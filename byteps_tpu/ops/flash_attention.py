"""Flash attention — Pallas TPU kernels for the transformer hot op.

No reference analog: the reference delegates attention math to torch/TF
kernels (its models live in example scripts, e.g.
``example/pytorch/benchmark_byteps.py``); on TPU the attention inner loop
is OURS to own, and it is the one op in the model families where the
naive form materializes a ``(B, H, S, S)`` score tensor in HBM.

Design (flash-attention-2 schedule, TPU-shaped):

* Layout ``(B*H, S, D)`` — batch×heads is the embarrassingly parallel
  grid axis; ``S`` is tiled into (bq, bk) blocks sized to the MXU
  (128 where the sequence allows); ``D`` (head_dim ≤ 256) stays whole so
  every matmul in the kernel is an MXU op on full tiles.
* Forward: grid ``(BH, nq, nk)``, innermost ``nk`` sequential
  ("arbitrary") with the online-softmax state ``(m, l, acc)`` carried in
  VMEM scratch — scores for one ``(bq, bk)`` tile only ever exist in
  VMEM. Emits the per-row logsumexp for the backward and for cross-shard
  combination.
* Backward: two kernels — ``dq`` (grid ``(BH, nq, nk)``) and ``dkv``
  (grid ``(BH, nk, nq)``) — each recomputing ``P = exp(S − lse)`` per
  tile, so the backward reads O(S·D) and never stores P.
  ``delta = rowsum(dO ∘ O)`` is one fused jnp pass. The lse output's own
  cotangent folds in exactly (``dS = P ∘ (dP − Δ + dlse)``), which is
  what lets ring attention differentiate through the cross-shard merge.
* Causal masking compares *global* positions: the q/k sequence offsets
  are runtime scalars (SMEM), so the same compiled kernel serves the
  single-device case (offsets 0), and every step of ring attention —
  diagonal (part-masked), below-diagonal (all-live), above-diagonal
  (all-masked, skipped tile-by-tile by ``pl.when``). Rows with no live
  key yield ``o = 0, lse = −1e30`` and drop out of the ring merge.

Numerics: all accumulation in float32 regardless of input dtype (bf16
in, bf16 out, f32 state) — same contract as
:func:`byteps_tpu.parallel.ring_attention.plain_attention`, which is the
golden for the tests and the jnp fallback for shapes/platforms the
kernel doesn't cover.

Known jax limitation: ``BYTEPS_KERNEL_BACKEND=pallas`` off-TPU runs the
kernels in interpret mode, which jax cannot evaluate inside
``shard_map(check_vma=True)`` (its error suggests ``check_vma=False``;
kernel-internal program_id math can't be pvaried to the SMEM scalars'
varying axes). Compiled TPU kernels are unaffected — only the boundary
is vma-typed there (:func:`_out_struct` / :func:`_unify_vma`). Off-TPU
the default backend is jnp, so the check_vma=True train factories are
only incompatible with *forcing* pallas interpret mode under them.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30
_MAX_HEAD_DIM = 256     # D beyond this spills VMEM tile budgets → fallback


def _pick_block(S: int, prefer: Tuple[int, ...] = ()) -> Optional[int]:
    """Largest tile from ``prefer + (256..8)`` dividing S (None → jnp
    fallback). The default list keeps the 8..256 contract that
    ``supported()``/``flash_decode`` are documented and tuned against;
    the train kernels pass explicit larger preferences (below)."""
    cands = prefer + (256, 128, 64, 32, 16, 8)
    for b in cands:
        if S % b == 0 and S >= b:
            return b
    return None


# Block size is the dominant throughput knob on this kernel family —
# per-tile pipeline overhead (the sequential online-softmax revisit
# chain through VMEM scratch) swamps the VPU/MXU work at 256² tiles.
# Measured on v5e, gpt2m shapes (BH=32, S=1024, D=64, bf16), fwd+bwd
# via the train-loss path: 256-tiles 24.0 ms, 512 14.6 ms, 1024
# 14.9 ms (fwd alone: 8.2 / 4.6 / 3.7 ms) — the forward prefers
# whole-sequence k-tiles, the backward 512. BYTEPS_FLASH_BLOCK=N,...
# prepends experiment overrides (train kernels only).
_FWD_PREFER = (1024, 512)
_BWD_PREFER = (512,)
_VMEM_BUDGET = 12 * 1024 * 1024   # leave headroom under the ~16MB VMEM


def _env_prefer() -> Tuple[int, ...]:
    force = os.environ.get("BYTEPS_FLASH_BLOCK")
    return tuple(int(x) for x in force.split(",")) if force else ()


def _train_blocks(Sq: int, Sk: int, D: int, itemsize: int,
                  prefer: Tuple[int, ...],
                  n_inter: int = 2) -> Optional[Tuple[int, int]]:
    """(bq, bk) for the train kernels — or None when either sequence has
    no dividing tile (the documented None→jnp-fallback contract that
    ``_pick_block``/``supported()`` establish; callers not pre-gated by
    ``supported()`` must get the same None, not a TypeError). Otherwise:
    the preferred large tiles, walked back down the candidate list until
    the tile set fits VMEM — the big-tile retune was measured at
    bf16/D=64; f32 or D→256 shapes must degrade gracefully instead of
    blowing the Mosaic budget.

    ``n_inter`` models the kernel's live (bq, bk) f32 intermediates:
    2 for the forward (s, p), 4 for the backwards (s, p, dp, ds) — the
    backward call sites pass 4, which is what steers them to 512 tiles
    while the forward keeps whole-sequence k-tiles."""
    def fits(bq: int, bk: int) -> bool:
        inter = n_inter * bq * bk * 4
        # q,(k,v)(,do) blocks double-buffered by the pallas pipeline
        io = 2 * 2 * (2 * bq + 2 * bk) * D * itemsize
        scratch = (bq + 2 * bk) * D * 4             # f32 accumulators
        return inter + io + scratch <= _VMEM_BUDGET

    prefer = _env_prefer() + prefer
    bq = _pick_block(Sq, prefer)
    bk = _pick_block(Sk, prefer)
    if bq is None or bk is None:
        return None
    while not fits(bq, bk):
        # shrink the larger tile first (s/p cost is the bq·bk product)
        nxt_q = _pick_block(Sq, tuple(p for p in prefer if p < bq))
        nxt_k = _pick_block(Sk, tuple(p for p in prefer if p < bk))
        if bq >= bk and nxt_q is not None and nxt_q < bq:
            bq = nxt_q
        elif nxt_k is not None and nxt_k < bk:
            bk = nxt_k
        elif nxt_q is not None and nxt_q < bq:
            bq = nxt_q
        else:
            break   # smallest divisible tiles; let Mosaic have it
    return bq, bk


from byteps_tpu.ops.backend import use_pallas  # noqa: E402 (re-export)
from byteps_tpu.ops.backend import tpu_compiler_params as _compiler_params  # noqa: E402


def supported(Sq: int, Sk: int, D: int) -> bool:
    return (_pick_block(Sq) is not None and _pick_block(Sk) is not None
            and D <= _MAX_HEAD_DIM)


# --------------------------------------------------------------------------
# jnp fallback (also the numerics golden; mirrors ring_attention._block_attn)
# --------------------------------------------------------------------------
def attention_jnp(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  causal: bool = True) -> jnp.ndarray:
    """Single-device softmax attention, (B, S, H, D) layout, f32 softmax."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.arange(Sq)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


def _out_struct(shape, dtype, *args):
    """ShapeDtypeStruct whose vma is the union of the inputs' — required
    for pallas_call under ``shard_map(check_vma=True)`` (outputs vary over
    whatever mesh axes the inputs vary over)."""
    try:
        vma = frozenset().union(*(jax.typeof(a).vma for a in args))
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _unify_vma(*xs):
    """pvary every array to the union of the group's varying axes, so the
    pallas_call boundary sees one consistent vma. (Interpret mode under
    check_vma=True still rejects kernel-internal program_id mixing — a
    known jax limitation whose error message recommends check_vma=False;
    the compiled TPU path only type-checks the boundary.)"""
    try:
        vmas = [jax.typeof(x).vma for x in xs]
    except AttributeError:
        return xs
    union = frozenset().union(*vmas)
    return tuple(
        jax.lax.pvary(x, tuple(union - v)) if union - v else x
        for x, v in zip(xs, vmas)
    )


def _read_offsets(qoff_ref, koff_ref):
    """Scalar SMEM loads (the only form mosaic allows)."""
    return (qoff_ref[0, 0].astype(jnp.int32),
            koff_ref[0, 0].astype(jnp.int32))


def _mask_tile(s, q_off, k_off, q_start, k_start, bq, bk):
    rows = q_off + q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = k_off + k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(rows >= cols, s, _NEG)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------
def _fwd_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    q_start, k_start = qi * bq, ki * bk
    q_off, k_off = _read_offsets(qoff_ref, koff_ref)

    def _tile(masked: bool):
        # operands stay in the INPUT dtype (bf16 in → MXU-native bf16
        # matmuls); preferred_element_type=f32 keeps the accumulation
        # exact, so s is bit-identical to an f32-operand dot for bf16
        # inputs (bf16→f32 casts are exact, the MXU multiplies bf16
        # pairs into an f32 accumulator either way)
        q = q_ref[0]                                         # (bq, D)
        k = k_ref[0]                                         # (bk, D)
        v = v_ref[0]                                         # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # (bq, bk)
        if masked:
            s = _mask_tile(s, q_off, k_off, q_start, k_start, bq, bk)
        m_prev = m_scr[:]                                    # (bq, 1)
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                               # (bq, bk)
        if masked:
            # exp(_NEG - m) underflows to 0 except when the whole row is
            # masked (m == _NEG) — zero those lanes explicitly
            p = jnp.where(s > _NEG / 2, p, 0.0)
        l_scr[:] = l_scr[:] * alpha + p.sum(axis=-1, keepdims=True)
        # p rounds to the input dtype for the MXU (standard flash-on-TPU
        # practice; p ∈ [0,1] so bf16 rounding is ≤ 2⁻⁸ relative — the
        # same order as the bf16 output rounding); f32 inputs keep f32 p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, D)
        m_scr[:] = m_new

    if causal:
        # tile live iff some global q_pos >= some global k_pos; INTERIOR
        # (min q_pos ≥ max k_pos, every pair live) skips the mask iotas
        # and the underflow where() — with big tiles the diagonal is a
        # 1/nk fraction, so most tiles take the cheap path
        live = q_off + q_start + bq - 1 >= k_off + k_start
        interior = q_off + q_start >= k_off + k_start + bk - 1

        @pl.when(live & interior)
        def _():
            _tile(False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _tile(True)
    else:
        _tile(False)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:]                                          # (bq, 1)
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(l > 0.0, m_scr[:] + jnp.log(l_safe), _NEG)


def _kv_index(heads: int, kv_heads: int):
    """Grid-index map from a (batch·H) query row to its (batch·Hkv) kv
    row — the GQA head-group association done by pure index arithmetic,
    so grouped attention reads the NARROW k/v (no repeated copies
    anywhere). Identity when heads == kv_heads."""
    if heads == kv_heads:
        return lambda b: b
    g = heads // kv_heads
    return lambda b: (b // heads) * kv_heads + (b % heads) // g


@functools.partial(jax.jit, static_argnames=("causal", "interpret",
                                             "heads", "kv_heads"))
def _fwd(q3, k3, v3, qoff, koff, causal: bool, interpret: bool,
         heads: int, kv_heads: int):
    """q3: (B·H, S, D), k3/v3: (B·Hkv, S, D) →
    (o (B·H, Sq, D), lse (B·H, Sq, 1) f32)."""
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    blocks = _train_blocks(Sq, Sk, D, q3.dtype.itemsize, _FWD_PREFER)
    if blocks is None:
        raise ValueError(
            f"flash forward kernel has no dividing tile for Sq={Sq}, "
            f"Sk={Sk} — gate call sites with supported() (jnp fallback)")
    bq, bk = blocks
    nq, nk = Sq // bq, Sk // bk
    scale = 1.0 / (D ** 0.5)
    kv = _kv_index(heads, kv_heads)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (kv(b), ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (kv(b), ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            _out_struct((BH, Sq, D), q3.dtype, q3, k3, v3, qoff, koff),
            _out_struct((BH, Sq, 1), jnp.float32, q3, k3, v3, qoff, koff),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),    # m (row max)
            pltpu.VMEM((bq, 1), jnp.float32),    # l (row sum)
            pltpu.VMEM((bq, D), jnp.float32),    # acc
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qoff, koff, q3, k3, v3)


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------
def _dq_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               dl_ref, dlse_ref, dq_ref, dq_scr,
               *, scale, causal, bq, bk, nk):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    q_start, k_start = qi * bq, ki * bk
    q_off, k_off = _read_offsets(qoff_ref, koff_ref)

    def _tile(masked: bool):
        # input-dtype operands on every MXU dot (see _fwd_kernel note);
        # s/p/ds math stays f32, ds rounds to the input dtype only at
        # the dq GEMM boundary
        q = q_ref[0]                                         # (bq, D)
        k = k_ref[0]                                         # (bk, D)
        v = v_ref[0]                                         # (bk, D)
        do = do_ref[0]                                       # (bq, D)
        lse = lse_ref[0]                                     # (bq, 1)
        delta = dl_ref[0]                                    # (bq, 1)
        dlse = dlse_ref[0]                                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if masked:
            s = _mask_tile(s, q_off, k_off, q_start, k_start, bq, bk)
        p = jnp.exp(s - lse)                                  # (bq, bk)
        if masked:
            p = jnp.where(s > _NEG / 2, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, bk)
        ds = (p * (dp - delta + dlse)).astype(k_ref.dtype)
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, D)

    if causal:
        live = q_off + q_start + bq - 1 >= k_off + k_start
        interior = q_off + q_start >= k_off + k_start + bk - 1

        @pl.when(live & interior)
        def _():
            _tile(False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _tile(True)
    else:
        _tile(False)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(qoff_ref, koff_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                dl_ref, dlse_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, bq, bk, nq, group=1):
    ki = pl.program_id(1)
    j = pl.program_id(2)            # (group member, q block) flattened
    qi = j % nq

    @pl.when(j == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    q_start, k_start = qi * bq, ki * bk
    q_off, k_off = _read_offsets(qoff_ref, koff_ref)

    def _tile(masked: bool):
        # input-dtype operands on every MXU dot (see _fwd_kernel note)
        q = q_ref[0]                                         # (bq, D)
        k = k_ref[0]                                         # (bk, D)
        v = v_ref[0]                                         # (bk, D)
        do = do_ref[0]                                       # (bq, D)
        lse = lse_ref[0]                                     # (bq, 1)
        delta = dl_ref[0]                                    # (bq, 1)
        dlse = dlse_ref[0]                                   # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if masked:
            s = _mask_tile(s, q_off, k_off, q_start, k_start, bq, bk)
        p = jnp.exp(s - lse)                                  # (bq, bk)
        if masked:
            p = jnp.where(s > _NEG / 2, p, 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, D)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, bk)
        ds = (p * (dp - delta + dlse)).astype(q_ref.dtype)
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bk, D)

    if causal:
        live = q_off + q_start + bq - 1 >= k_off + k_start
        interior = q_off + q_start >= k_off + k_start + bk - 1

        @pl.when(live & interior)
        def _():
            _tile(False)

        @pl.when(live & jnp.logical_not(interior))
        def _():
            _tile(True)
    else:
        _tile(False)

    @pl.when(j == nq * group - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "interpret",
                                             "heads", "kv_heads"))
def _bwd(q3, k3, v3, o3, lse, qoff, koff, do3, dlse,
         causal: bool, interpret: bool, heads: int, kv_heads: int):
    BH, Sq, D = q3.shape
    BHkv, Sk = k3.shape[0], k3.shape[1]
    blocks = _train_blocks(Sq, Sk, D, q3.dtype.itemsize, _BWD_PREFER,
                           n_inter=4)
    if blocks is None:
        raise ValueError(
            f"flash backward kernel has no dividing tile for Sq={Sq}, "
            f"Sk={Sk} — gate call sites with supported() (jnp fallback)")
    bq, bk = blocks
    nq, nk = Sq // bq, Sk // bk
    group = heads // kv_heads
    kv = _kv_index(heads, kv_heads)
    scale = 1.0 / (D ** 0.5)
    # delta_i = Σ_d dO_id · O_id  (one fused elementwise pass, f32)
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # (BH, Sq, 1)
    q3, k3, v3, do3, lse, delta, dlse, qoff, koff = _unify_vma(
        q3, k3, v3, do3, lse, delta, dlse, qoff, koff)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (kv(b), ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, qi, ki: (kv(b), ki, 0)),
            pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, qi, ki: (b, qi, 0)),
        out_shape=_out_struct((BH, Sq, D), q3.dtype,
                              q3, k3, v3, do3, lse, delta, dlse, qoff, koff),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, do3, lse, delta, dlse)

    # dkv iterates every (group member, q block) for its kv head: the q
    # row for grid point (b, ki, j) is the (j // nq)-th member of kv row
    # b's group, q block j % nq — one scratch accumulation covers the
    # whole group, so dk/dv come out kv-narrow with no reduction pass
    def qrow(b, j):
        return (b // kv_heads) * heads + (b % kv_heads) * group + j // nq

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, group=group),
        grid=(BHkv, nk, nq * group),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, bq, D), lambda b, ki, j: (qrow(b, j), j % nq, 0)),
            pl.BlockSpec((1, bk, D), lambda b, ki, j: (b, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, ki, j: (b, ki, 0)),
            pl.BlockSpec((1, bq, D), lambda b, ki, j: (qrow(b, j), j % nq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, ki, j: (qrow(b, j), j % nq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, ki, j: (qrow(b, j), j % nq, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, ki, j: (qrow(b, j), j % nq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, ki, j: (b, ki, 0)),
            pl.BlockSpec((1, bk, D), lambda b, ki, j: (b, ki, 0)),
        ],
        out_shape=[
            _out_struct((BHkv, Sk, D), k3.dtype,
                        q3, k3, v3, do3, lse, delta, dlse, qoff, koff),
            _out_struct((BHkv, Sk, D), v3.dtype,
                        q3, k3, v3, do3, lse, delta, dlse, qoff, koff),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qoff, koff, q3, k3, v3, do3, lse, delta, dlse)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom-VJP core on the (BH, S, D) layout
# --------------------------------------------------------------------------
# qoff/koff are (1, 1) float32 on purpose: they are *traced* values (ring
# attention passes axis_index-derived offsets), and float avoids the
# symbolic-zero cotangent dance custom_vjp requires for int-dtype
# arguments — their gradient is identically zero.
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_core(q3, k3, v3, qoff, koff, causal: bool, interpret: bool,
                heads: int, kv_heads: int):
    return _fwd(q3, k3, v3, qoff, koff, causal, interpret, heads, kv_heads)


def _flash_core_fwd(q3, k3, v3, qoff, koff, causal, interpret, heads,
                    kv_heads):
    o, lse = _fwd(q3, k3, v3, qoff, koff, causal, interpret, heads,
                  kv_heads)
    return (o, lse), (q3, k3, v3, o, lse, qoff, koff)


def _flash_core_bwd(causal, interpret, heads, kv_heads, res, cts):
    q3, k3, v3, o3, lse, qoff, koff = res
    do3, dlse = cts
    dlse = jnp.asarray(dlse, jnp.float32)
    dq, dk, dv = _bwd(q3, k3, v3, o3, lse, qoff, koff, do3, dlse,
                      causal, interpret, heads, kv_heads)
    zero = jnp.zeros((1, 1), jnp.float32)
    return dq, dk, dv, zero, zero


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def _to3(x: jnp.ndarray) -> jnp.ndarray:
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _from3(x3: jnp.ndarray, B: int, H: int) -> jnp.ndarray:
    BH, S, D = x3.shape
    return x3.reshape(B, H, S, D).transpose(0, 2, 1, 3)


def flash_attention_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        q_offset, k_offset,
                        causal: bool = True
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Flash attention with logsumexp, for cross-shard combination.

    q/k/v: (B, S, H, D); offsets are (possibly traced) global sequence
    positions of element 0 of the q/k blocks — causal masking compares
    ``q_offset + i >= k_offset + j``. Returns ``(o (B, Sq, H, D),
    lse (B, Sq, H) f32)``; rows with no live key give ``o = 0,
    lse = −1e30`` so a ring merge drops them. Callers must check
    :func:`supported` / :func:`use_pallas` first.
    """
    B, Sq, H, D = q.shape
    Hkv = k.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"q heads ({H}) not a multiple of kv heads "
                         f"({Hkv})")
    if v.shape[2] != Hkv:
        raise ValueError(f"k has {Hkv} heads but v has {v.shape[2]} — "
                         "GQA narrows k and v together")
    if not supported(Sq, k.shape[1], D):
        raise ValueError(
            f"flash_attention_lse: unsupported shape Sq={Sq} Sk={k.shape[1]} "
            f"head_dim={D} — sequence lengths must divide into 8..256 tiles "
            f"and head_dim must be ≤ {_MAX_HEAD_DIM}; gate on "
            "byteps_tpu.ops.flash_attention.supported() or use "
            "flash_attention()/attention_jnp() which fall back")
    qoff = jnp.asarray(q_offset, jnp.float32).reshape(1, 1)
    koff = jnp.asarray(k_offset, jnp.float32).reshape(1, 1)
    interpret = jax.default_backend() != "tpu"
    q3, k3, v3, qoff, koff = _unify_vma(_to3(q), _to3(k), _to3(v),
                                        qoff, koff)
    o3, lse3 = _flash_core(q3, k3, v3, qoff, koff, causal, interpret,
                           H, Hkv)
    o = _from3(o3, B, H)
    lse = lse3.reshape(B, H, Sq).transpose(0, 2, 1)           # (B, Sq, H)
    return o, lse


def attention_lse_jnp(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      q_offset, k_offset, causal: bool = True
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """jnp twin of :func:`flash_attention_lse` — same (o, lse) contract,
    same global-offset causal masking and −1e30 ≡ no-live-keys signal, any
    shape. The golden for the kernel and the fallback for ring schedules
    off-TPU. Grouped-query attention is native: when q carries G× the
    k/v head count, each kv head serves its group through the einsum —
    no materialized head repeat (the GQA decode hot path).

    ``q_offset`` may be a per-batch ``(B,)`` vector: row ``b``'s queries
    sit at global positions ``q_offset[b] + arange(Sq)``. That is the
    serve tier's packed-decode contract — one device batch holds
    requests at heterogeneous sequence positions (serve/paged_cache.py),
    and each row masks against its own fill level."""
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    scale = 1.0 / (D ** 0.5)
    if Hkv != H:
        if H % Hkv != 0:
            raise ValueError(f"q heads ({H}) not a multiple of kv heads "
                             f"({Hkv})")
        g = H // Hkv
        qg = q.reshape(B, Sq, Hkv, g, D)
        s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        s = s.reshape(B, H, Sq, Sk)
    else:
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
    if causal:
        if jnp.ndim(q_offset) == 1:
            # per-batch offsets: (B, Sq, Sk) mask broadcast over heads
            rows = (jnp.asarray(q_offset)[:, None, None]
                    + jnp.arange(Sq)[None, :, None])
            cols = k_offset + jnp.arange(Sk)[None, None, :]
            s = jnp.where((rows >= cols)[:, None], s, _NEG)
        else:
            rows = q_offset + jnp.arange(Sq)[:, None]
            cols = k_offset + jnp.arange(Sk)[None, :]
            s = jnp.where((rows >= cols)[None, None], s, _NEG)
    m = s.max(axis=-1)                                   # (B, H, Sq)
    live = m > _NEG / 2
    m_safe = jnp.where(live, m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    if causal:
        p = jnp.where(s > _NEG / 2, p, 0.0)
    l = p.sum(axis=-1)
    l_safe = jnp.where(l > 0.0, l, 1.0)
    pn = p / l_safe[..., None]
    if Hkv != H:
        pn = pn.reshape(B, Hkv, H // Hkv, Sq, Sk)
        o = jnp.einsum("bhgqk,bkhd->bqhgd", pn, v.astype(jnp.float32))
        o = o.reshape(B, Sq, H, D)
    else:
        o = jnp.einsum("bhqk,bkhd->bqhd", pn, v.astype(jnp.float32))
    o = jnp.where(live.transpose(0, 2, 1)[..., None], o, 0.0)
    lse = jnp.where(live, m_safe + jnp.log(l_safe), _NEG)
    return o.astype(q.dtype), lse.transpose(0, 2, 1)     # (B, Sq, H)


def attention_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  q_offset, k_offset, causal: bool = True
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Backend-dispatching (o, lse) attention with global offsets — the
    building block ring schedules merge with :func:`merge_attention`.
    Grouped-query attention (q heads a multiple of k/v heads) is native
    on both backends — the kernel associates each query head with its kv
    head by grid-index arithmetic, so the narrow k/v is read directly.
    A per-batch ``(B,)`` ``q_offset`` vector (the serve tier's packed
    decode) always takes the jnp twin — the kernel's grid masking is
    scalar-offset only."""
    if (jnp.ndim(q_offset) == 0 and use_pallas()
            and supported(q.shape[1], k.shape[1], q.shape[-1])):
        return flash_attention_lse(q, k, v, q_offset, k_offset,
                                   causal=causal)
    return attention_lse_jnp(q, k, v, q_offset, k_offset, causal=causal)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True) -> jnp.ndarray:
    """Softmax attention, (B, S, H, D) layout, flash kernel when possible.

    Drop-in numerics-equivalent of :func:`attention_jnp` (f32 accumulate,
    output in input dtype); falls back to it off-TPU (unless
    ``BYTEPS_KERNEL_BACKEND=pallas`` forces interpret mode) and for
    sequence lengths not divisible into MXU tiles. Differentiable via the
    flash backward kernels — O(S·D) memory in both passes.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    if not (use_pallas() and supported(Sq, Sk, D)):
        if k.shape[2] != H:
            o, _ = attention_lse_jnp(q, k, v, 0, 0, causal=causal)
            return o
        return attention_jnp(q, k, v, causal=causal)
    o, _ = flash_attention_lse(q, k, v, 0, 0, causal=causal)
    return o


def merge_attention(o_a, lse_a, o_b, lse_b):
    """Combine two attention partials over disjoint key sets.

    o: (B, S, H, D) normalized outputs; lse: (B, S, H) logsumexps
    (−1e30 ≡ no live keys). Returns the merged (o, lse). Exact (not an
    approximation) and differentiable — gradients flow into both o's and
    both lse's, which the flash backward folds into dS.
    """
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)
    wb = jnp.exp(lse_b - m)
    denom = wa + wb
    safe = jnp.where(denom > 0.0, denom, 1.0)
    o = (o_a.astype(jnp.float32) * wa[..., None]
         + o_b.astype(jnp.float32) * wb[..., None]) / safe[..., None]
    lse = jnp.where(denom > 0.0, m + jnp.log(safe), _NEG)
    return o.astype(o_a.dtype), lse
