"""byteps_tpu.ops — device kernels (Pallas TPU + jnp fallbacks).

The reference implements compressors as hand-written CPU C++
(``byteps/common/compressor/impl/*``); the TPU-native equivalents are
Pallas kernels for the hot wire ops, with jnp fallbacks that share the
exact wire layout so either backend can decode the other's payloads.
Backend selection: Pallas on TPU, jnp elsewhere; override with
``BYTEPS_KERNEL_BACKEND=pallas|jnp``.
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.ops.chunked_ce import chunked_ce_nll, dense_ce_nll
from byteps_tpu.ops.flash_attention import (
    attention_jnp,
    flash_attention,
    flash_attention_lse,
    merge_attention,
)
from byteps_tpu.ops.onebit_kernels import (
    onebit_pack,
    onebit_unpack,
    onebit_unpack_sum,
    packed_words,
)

__all__ = [
    "attention_jnp", "chunked_ce_nll", "dense_ce_nll", "flash_attention",
    "flash_attention_lse", "merge_attention",
    "onebit_pack", "onebit_unpack", "onebit_unpack_sum", "packed_words",
]
