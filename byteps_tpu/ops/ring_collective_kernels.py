"""Ring transport kernels for the ``ici-compressed`` wire tier (Pallas
TPU + ``lax.ppermute`` jnp twins).

The staged compressed collective (`comm/ici.py`) moves payloads with one
monolithic ``all_to_all`` and one ``all_gather``: codec compute and wire
time serialize, and every hop pays the full-exchange latency. These
kernels replace the *transport* with a ring — ``n−1`` pipelined hops, one
segment-payload per link per hop, each hop's DMA overlapping the next
block's codec work — while the aggregation arithmetic stays byte-for-byte
the staged path's (that is what makes the ring tier pinnable BIT-exact
against it; see ``comm/ici.py`` tier notes).

Three primitives, each a Pallas TPU kernel (``make_async_remote_copy`` +
DMA semaphores, double-buffered — SNIPPETS [1] ring-permute idiom) with a
``lax.ppermute`` twin that runs everywhere:

* ``ring_collect``: per-device ``(n, ...)`` stack whose row ``j`` is the
  payload bound for owner ``j`` → ``(n, ...)`` stack on each device whose
  row ``w`` is worker ``w``'s payload for *this* owner —
  ``lax.all_to_all`` semantics over rotation hops (hop ``t`` moves row
  ``(d+t) mod n`` directly to device ``(d+t) mod n``; on hardware that is
  ``t`` neighbor hops, and all ``n−1`` hops are mutually independent so
  the DMAs pipeline).
* ``ring_allgather``: per-device block → ``(n, ...)`` owner-ordered stack
  (``lax.all_gather(tiled=False)`` semantics), same rotation.
* ``ring_presum``: the genuinely fused per-hop form for PRESUMMABLE
  payloads (seed-synced randomk: payloads sum positionally, so adding
  payloads IS compressing the running partial): a serial chain where each
  hop receives the neighbor's partial, adds the local contribution
  in-kernel while the next DMA is in flight, and forwards — compressed
  bytes on every hop, ``n−1`` single-payload hops total (the
  bandwidth-optimal ring reduce-scatter). Chain accumulation order is
  arrival order, NOT the staged stack order, so the ici tier routes only
  *stochastic* presummable codecs here (their pin is statistical);
  deterministic codecs take ``ring_collect`` + the staged sum to keep the
  bit-exact contract.

Backend selection follows ``ops/backend.py``: Pallas on TPU, jnp twin
elsewhere (``BYTEPS_KERNEL_BACKEND`` override; off-TPU the pallas path
runs in interpret mode, which the parity tests use — the interpreter's
DMA discharge rule performs real cross-device transfers). The kernels
want a lane-aligned plane (trailing-dim product % 128 == 0) and a 1-D
mesh axis (logical device id == axis index); anything else takes the
twin, per-leaf.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from byteps_tpu.ops.backend import kernel_backend as _backend

_LANES = 128


def kernels_supported(shape, n: int) -> bool:
    """Pallas path wants >1 device, a lane-aligned flat plane, AND the
    ring axis spanning every device in mesh order: the remote DMAs
    address ``DeviceIdType.LOGICAL`` ids computed as axis-index
    arithmetic, which only equals the logical device id on an
    effectively 1-D mesh (on a ('dp','mp') mesh, device (i, j) has
    logical id i·|mp|+j ≠ i — the DMA would land on the wrong chip).
    Anything else takes the ppermute twin, which addresses by axis name
    and is correct on any mesh."""
    flat = 1
    for s in shape:
        flat *= int(s)
    return (n > 1 and flat % _LANES == 0 and flat > 0
            and n == jax.device_count())


def _axis_my_id(axis: str):
    return jax.lax.axis_index(axis)


# --- jnp twins (the goldens and the CPU/off-TPU path) ------------------------
def _collect_jnp(x: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
    """all_to_all-equivalent rotation: out row w = worker w's row my_id.

    Hop ``t`` ppermutes row ``(d+t) mod n`` of every device ``d`` to
    device ``(d+t) mod n`` — a shift-``t`` rotation (``t`` neighbor hops
    on a physical ring). The hops carry ORIGINAL payload rows and are
    mutually independent, so XLA dispatches them concurrently; the
    assembled stack is bitwise the ``all_to_all`` result."""
    my = _axis_my_id(axis)
    own = jax.lax.dynamic_index_in_dim(x, my, 0, keepdims=True)
    out = jnp.zeros_like(x)
    out = jax.lax.dynamic_update_slice_in_dim(out, own, my, 0)
    for t in range(1, n):
        perm = [(s, (s + t) % n) for s in range(n)]
        dest = jax.lax.rem(my + t, n)
        send = jax.lax.dynamic_index_in_dim(x, dest, 0, keepdims=True)
        recv = jax.lax.ppermute(send, axis, perm)
        src = jax.lax.rem(my - t + n, n)
        out = jax.lax.dynamic_update_slice_in_dim(out, recv, src, 0)
    return out


def _allgather_jnp(x: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
    """Owner-ordered stack of every device's block (all_gather
    tiled=False semantics): hop ``t`` rotates the own block by ``t``."""
    my = _axis_my_id(axis)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x[None], my, 0)
    for t in range(1, n):
        perm = [(s, (s + t) % n) for s in range(n)]
        recv = jax.lax.ppermute(x, axis, perm)
        src = jax.lax.rem(my - t + n, n)
        out = jax.lax.dynamic_update_slice_in_dim(out, recv[None], src, 0)
    return out


def _presum_jnp(x: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
    """Serial partial-sum chain (the classic ring reduce-scatter): at hop
    ``t`` device ``d`` forwards the running partial for segment
    ``(d−t) mod n`` to its right neighbor, which adds its own
    contribution — per-hop positional accumulation in payload space.
    Device ``d`` ends with the complete sum of segment ``d``, accumulated
    in chain order ``p_{d+1}, p_{d+2}, …, p_{d−1}, p_d``."""
    my = _axis_my_id(axis)
    perm = [(s, (s + 1) % n) for s in range(n)]
    cur = jax.lax.dynamic_index_in_dim(
        x, jax.lax.rem(my + n - 1, n), 0, keepdims=False)
    for t in range(1, n):
        recv = jax.lax.ppermute(cur, axis, perm)
        mine = jax.lax.dynamic_index_in_dim(
            x, jax.lax.rem(my + n - 1 - t, n), 0, keepdims=False)
        cur = recv + mine
    return cur


# --- pallas kernels ----------------------------------------------------------
def _rotate_kernel(src_ref, dst_ref, local_sem, send_sems, recv_sems, *,
                   n: int, axis: str, gather: bool):
    """Shared rotation body: deliver to device ``(my+t) mod n`` the row it
    expects from me — row ``(my+t) mod n`` of my stack (collect) or my own
    block (gather) — written at remote row ``my`` (worker/owner order).
    Double-buffered on semaphore parity: hop ``t`` starts before hop
    ``t−1`` is waited, so two DMAs are always in flight."""
    my = jax.lax.axis_index(axis)
    # own row: a local DMA, overlapped with the remote hops
    own_src = src_ref if gather else src_ref.at[my]
    own_cp = pltpu.make_async_copy(own_src, dst_ref.at[my], local_sem)
    own_cp.start()
    ops = []
    for t in range(1, n):
        dest = jax.lax.rem(my + t, n)
        op = pltpu.make_async_remote_copy(
            src_ref=src_ref if gather else src_ref.at[dest],
            dst_ref=dst_ref.at[my],
            send_sem=send_sems.at[t % 2],
            recv_sem=recv_sems.at[t % 2],
            device_id=dest,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        ops.append(op)
        if len(ops) >= 2:
            ops[-2].wait()
    if ops:
        ops[-1].wait()
    own_cp.wait()


@functools.partial(jax.jit,
                   static_argnames=("n", "axis", "gather", "interpret"))
def _rotate_pallas(x: jnp.ndarray, n: int, axis: str, gather: bool,
                   interpret: bool = False) -> jnp.ndarray:
    out_shape = ((n,) + x.shape) if gather else x.shape
    return pl.pallas_call(
        functools.partial(_rotate_kernel, n=n, axis=axis, gather=gather),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(out_shape, x.dtype),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA,          # local own-row copy
            pltpu.SemaphoreType.DMA((2,)),    # send, double-buffer parity
            pltpu.SemaphoreType.DMA((2,)),    # recv, double-buffer parity
        ],
        interpret=interpret,
    )(x)


def _presum_kernel(src_ref, out_ref, comm_ref, acc_ref, stage_ref,
                   local_sems, send_sems, recv_sems, *, n: int, axis: str):
    """Fused per-hop accumulate: while hop ``t``'s partial is on the wire
    (remote DMA out of ``comm_ref``), the next local contribution row
    DMAs HBM→VMEM; the add (the presummable codec's whole per-hop
    "decompress + accumulate + recompress", since payload sum == compress
    of the partial sum) runs the moment both land.

    Flow control: ring skew lets a fast upstream neighbor run up to
    ``n−1`` hops ahead of a slow device, so hop ``t``'s arrival gets its
    OWN landing slot (``comm_ref`` row ``t``) and its own recv semaphore
    (``recv_sems[t]``) — a counting parity pair could be satisfied by a
    later hop's arrival while the earlier slot is still unwritten.
    Slot 0 is the local send stage, reused only after ``send_sems[t]``
    confirms the previous send drained."""
    my = jax.lax.axis_index(axis)
    # seed the chain with the contribution for segment (my+n-1) mod n
    first = jax.lax.rem(my + n - 1, n)
    cp = pltpu.make_async_copy(src_ref.at[first], acc_ref, local_sems.at[0])
    cp.start()
    cp.wait()
    right = jax.lax.rem(my + 1, n)
    for t in range(1, n):
        # stage the partial for the wire (remote DMAs move HBM-resident
        # buffers; acc lives in VMEM for the adds)
        st = pltpu.make_async_copy(acc_ref, comm_ref.at[0], local_sems.at[0])
        st.start()
        st.wait()
        op = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[0],
            dst_ref=comm_ref.at[t],
            send_sem=send_sems.at[t],
            recv_sem=recv_sems.at[t],
            device_id=right,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        # overlap: prefetch my contribution for the incoming segment
        mine = jax.lax.rem(my + n - 1 - t, n)
        pf = pltpu.make_async_copy(src_ref.at[mine], stage_ref,
                                   local_sems.at[1])
        pf.start()
        op.wait()
        # land the received partial in VMEM and accumulate
        ld = pltpu.make_async_copy(comm_ref.at[t], acc_ref, local_sems.at[0])
        ld.start()
        ld.wait()
        pf.wait()
        acc_ref[...] = acc_ref[...] + stage_ref[...]
    wr = pltpu.make_async_copy(acc_ref, out_ref, local_sems.at[0])
    wr.start()
    wr.wait()


@functools.partial(jax.jit, static_argnames=("n", "axis", "interpret"))
def _presum_pallas(x: jnp.ndarray, n: int, axis: str,
                   interpret: bool = False) -> jnp.ndarray:
    rowshape = x.shape[1:]
    out, _comm = pl.pallas_call(
        functools.partial(_presum_kernel, n=n, axis=axis),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),
            # wire buffers (send stage row 0, per-hop landing rows 1..n-1)
            # — outputs only because pallas scratch has no HBM space;
            # discarded
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(rowshape, x.dtype),
            jax.ShapeDtypeStruct((n,) + rowshape, x.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM(rowshape, x.dtype),      # accumulator
            pltpu.VMEM(rowshape, x.dtype),      # own-contribution stage
            pltpu.SemaphoreType.DMA((2,)),      # local copies
            pltpu.SemaphoreType.DMA((n,)),      # per-hop send
            pltpu.SemaphoreType.DMA((n,)),      # per-hop recv
        ],
        interpret=interpret,
    )(x)
    return out


# --- public API (called INSIDE shard_map over a 1-D ``axis``) ----------------
def ring_collect(x: jnp.ndarray, axis: str, n: int,
                 backend=None) -> jnp.ndarray:
    """(n, ...) owner-major rows → (n, ...) worker-major rows (all_to_all
    semantics): exact, moves bits only."""
    backend = backend or _backend()
    if n == 1:
        return x
    if backend == "jnp" or not kernels_supported(x.shape[1:], n):
        return _collect_jnp(x, axis, n)
    return _rotate_pallas(x, n, axis, gather=False,
                          interpret=jax.default_backend() != "tpu")


def ring_allgather(x: jnp.ndarray, axis: str, n: int,
                   backend=None) -> jnp.ndarray:
    """per-device block → (n, ...) owner-ordered stack (all_gather
    tiled=False semantics): exact, moves bits only."""
    backend = backend or _backend()
    if n == 1:
        return x[None]
    if backend == "jnp" or not kernels_supported(x.shape, n):
        return _allgather_jnp(x, axis, n)
    return _rotate_pallas(x, n, axis, gather=True,
                          interpret=jax.default_backend() != "tpu")


def ring_presum(x: jnp.ndarray, axis: str, n: int,
                backend=None) -> jnp.ndarray:
    """(n, ...) owner-major rows → this device's summed row (ring
    reduce-scatter with per-hop payload accumulation). Chain-ordered fp
    adds: positionally exact for presummable payloads, NOT bitwise equal
    to the staged stack sum — callers route stochastic codecs only."""
    backend = backend or _backend()
    if n == 1:
        return x[0]
    if backend == "jnp" or not kernels_supported(x.shape[1:], n):
        return _presum_jnp(x, axis, n)
    return _presum_pallas(x, n, axis,
                          interpret=jax.default_backend() != "tpu")
