"""Segmented/gathered LoRA matmul — the batched heterogeneous-adapter
delta behind multi-tenant serving (Pallas TPU + jnp twin).

Reference shape: Punica's SGMV / S-LoRA's batched gather — R packed
decode rows each carry a per-row adapter *slot* into a device-resident
slab pool, and one fused op computes every row's low-rank delta
``(x_r @ A[slot_r]) @ B[slot_r]`` without materializing per-row weight
copies. Slot 0 is the pool's reserved all-zero slot (base-model rows,
padded batch rows): its delta is exactly 0.0, so heterogeneous batches
never branch.

Exactness contract: the jnp twin's per-row arithmetic is the packed
form of ``models/lora.lora_delta`` — same contraction order over the
input dim, same rank-bucket zero padding (a zero A column times a zero
B row adds exactly 0.0) — so a pooled tenant's greedy tokens stay
BIT-identical to a solo ``make_generate_fn`` run on its grafted params
(pinned in tests/test_serve_multitenant.py). The Pallas kernel is the
TPU fast path behind the shared ``ops/backend.py`` rule; it gathers
each row's A/B slabs by scalar-prefetched slot index so the weight DMA
overlaps the row's two thin matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from byteps_tpu.models.lora import _fence
from byteps_tpu.ops.backend import use_pallas

__all__ = ["segmented_lora_delta"]


def _delta_jnp(x: jnp.ndarray, a_slab: jnp.ndarray, b_slab: jnp.ndarray,
               slots: jnp.ndarray,
               tp_axis: Optional[str] = None,
               row_parallel: bool = False) -> jnp.ndarray:
    """(R, S, d_in) x (n_slots, d_in, rb) x (n_slots, rb, d_out) →
    (R, S, d_out): scan over rows, each body gathering its slot's slabs
    and running the SAME ``(1, S, d_in) @ (d_in, rb)`` / ``(1, S, rb) @
    (rb, d_out)`` dots the solo ``lora_delta`` emits on a grafted tree.
    A batched einsum (or a lax.scan) would be the obvious packed form,
    but XLA's accumulation is context-dependent — a gathered R-batched
    dot, a dot inside a scan-loop fusion, and R separate solo dots can
    each disagree by 1 ulp on some inputs. R is static at trace time,
    so the twin UNROLLS: each row emits its own standalone
    ``(1, S, d) @ (d, rb)`` / ``(1, S, rb) @ (rb, d_out)`` dot pair —
    HLO-identical to the solo path's ops — which is what makes the
    BIT-identical multi-tenant contract hold. The slabs are cast to
    ``x.dtype`` exactly like ``lora_delta`` casts the grafted leaves;
    the rank deltas are thin (R × targets × layers extra small dots is
    noise next to the step's base matmuls and a one-time trace cost the
    factory lru-cache amortizes)."""
    rows = []
    for i in range(x.shape[0]):
        sl = slots[i]
        a = jnp.take(a_slab, sl, axis=0).astype(x.dtype)
        b = jnp.take(b_slab, sl, axis=0).astype(x.dtype)
        # the same barrier fence lora_delta uses: each row's dot pair
        # becomes an isolated island with the solo path's exact HLO, so
        # XLA can neither merge the R rows into a batched dot nor fold
        # a row into a consumer fusion — either would change the
        # accumulation order and break bit-identity with the solo run
        xi, a, b = _fence((x[i:i + 1], a, b))
        u = xi @ a                                       # (1, S, rb)
        if row_parallel and tp_axis is not None:
            u = jax.lax.psum(u, tp_axis)
        rows.append(_fence(u @ b))
    return jnp.concatenate(rows, axis=0)


def _delta_pallas(x, a_slab, b_slab, slots):
    """One grid step per packed row; the row's A/B slabs are gathered
    by the scalar-prefetched slot index (the BlockSpec index maps read
    ``slots`` before the body runs, so the slab DMA is a plain block
    fetch — no in-kernel gather)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, S, d_in = x.shape
    _, _, rb = a_slab.shape
    d_out = b_slab.shape[-1]

    def kernel(slots_ref, x_ref, a_ref, b_ref, o_ref):
        xv = x_ref[0].astype(jnp.float32)          # (S, d_in)
        av = a_ref[0].astype(jnp.float32)          # (d_in, rb)
        bv = b_ref[0].astype(jnp.float32)          # (rb, d_out)
        u = jnp.dot(xv, av, preferred_element_type=jnp.float32)
        o_ref[0] = jnp.dot(
            u, bv, preferred_element_type=jnp.float32).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(R,),
        in_specs=[
            pl.BlockSpec((1, S, d_in), lambda r, slots: (r, 0, 0)),
            pl.BlockSpec((1, d_in, rb),
                         lambda r, slots: (slots[r], 0, 0)),
            pl.BlockSpec((1, rb, d_out),
                         lambda r, slots: (slots[r], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, d_out), lambda r, slots: (r, 0, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, S, d_out), x.dtype),
    )(slots, x, a_slab, b_slab)


def segmented_lora_delta(x: jnp.ndarray, a_slab: jnp.ndarray,
                         b_slab: jnp.ndarray, slots: jnp.ndarray,
                         row_parallel: bool = False,
                         tp_axis: Optional[str] = None) -> jnp.ndarray:
    """Per-row LoRA delta for a packed batch of heterogeneous adapters.

    x: ``(R, S, d_in)`` activations (S = 1 in the packed decode step);
    a_slab/b_slab: the pool's ``(n_slots, d_in, rank_bucket)`` /
    ``(n_slots, rank_bucket, d_out)`` slot arrays; slots: ``(R,)``
    int32 per-row slot indices. Returns ``(R, S, d_out)``.

    ``row_parallel`` mirrors ``lora_delta``'s tp contract for wo/w2:
    the thin ``(R, S, rank)`` intermediate is psum'd over ``tp_axis``
    before the second matmul — which also rules the Pallas fast path
    out for row-parallel targets (the psum must sit BETWEEN the two
    matmuls; the fused kernel has no collective seam), so those take
    the jnp twin on every backend.
    """
    if row_parallel and tp_axis is not None:
        return _delta_jnp(x, a_slab, b_slab, slots,
                          tp_axis=tp_axis, row_parallel=True)
    if use_pallas():
        return _delta_pallas(x, a_slab, b_slab, slots)
    return _delta_jnp(x, a_slab, b_slab, slots)
