"""Kernel-backend selection shared by every op module.

One dispatch rule for the whole ops package (the reference's analog is its
compile-time CUDA/CPU split; here it's a runtime choice): Pallas on TPU,
jnp elsewhere, overridable with ``BYTEPS_KERNEL_BACKEND=pallas|jnp``
(``pallas`` off-TPU means interpret mode — see docs/env.md for the
``check_vma`` caveat).
"""

from __future__ import annotations

import os

import jax


def kernel_backend() -> str:
    env = os.environ.get("BYTEPS_KERNEL_BACKEND", "")
    if env in ("pallas", "jnp"):
        return env
    return "pallas" if jax.default_backend() == "tpu" else "jnp"


def use_pallas() -> bool:
    return kernel_backend() == "pallas"


# --- pallas-TPU API compat (jax renamed TPUCompilerParams →
# CompilerParams and TPUMemorySpace → MemorySpace): resolve whichever
# name this jax ships so the kernels run on both sides of the rename.
def tpu_compiler_params(**kwargs):
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def tpu_smem():
    from jax.experimental.pallas import tpu as pltpu

    ms = getattr(pltpu, "MemorySpace", None) or getattr(
        pltpu, "TPUMemorySpace")
    return ms.SMEM
