"""Flash-decode — the Pallas kernel for single-token cached attention.

No reference analog (the reference is a training system; its models
delegate attention to torch/TF). On TPU, autoregressive decode is
HBM-bandwidth-bound: every generated token reads the whole KV cache
once. The jnp fallback leaves that op to XLA's fusion of a ``(1, S)``
einsum/softmax chain over the full static cache (int8 reads stay fused
— see ``models/generate.py _cache_read``); this kernel makes the
schedule explicit instead of hoping the fusion holds: one VMEM-resident
online-softmax pass over the stored cache with no intermediate
score/probability arrays in HBM, compute skipped block-by-block past
the fill level (the jnp chain always computes all of ``S_max``), and
the dequantized view never materialized anywhere (the pallas *prefill*
path must materialize it once per prefill, taking concrete operands):

* Grid ``(B, nk)`` — one program per sequence, ``nk`` sequential key
  blocks with flash-style online-softmax state ``(m, l, acc)`` in VMEM
  scratch. Each program carries ALL kv heads of its sequence, unrolled
  as per-head 2-d MXU ops — every cache block is DMA'd exactly once,
  and the ``G = H/Hkv`` query heads of each group ride their kv head's
  block (GQA native, narrow cache read).
* The cache AND its scales are read IN PLACE via BlockSpecs on their
  stored layouts (``(B, S, Hkv, D)`` / ``(B, S, Hkv)`` — trailing block
  dims equal the array's, satisfying the mosaic minor-dim rules), so
  there is no per-step transpose/copy of anything.
* int8 dequantization happens in VMEM, block by block: each head's
  ``(bk, D)`` int8 tile is multiplied by its ``(bk, 1)`` scale column
  and rounded through the model dtype — bit-identical to
  ``_cache_read``'s semantics — so the int8 cache is read from HBM at
  half the bf16 bandwidth by construction, not by fusion luck. The
  dense (non-quantized) signature carries no scale operands at all.
* Fill-level masking: keys at global positions ``> pos`` (the query's
  position) are dead — whole dead blocks skip compute via ``pl.when``,
  the boundary block masks by global column index. ``pos`` is a runtime
  SMEM scalar, so one compiled kernel serves every decode step.

Numerics contract: identical to ``attention_lse_jnp(q, _cache_read(k),
_cache_read(v), pos, 0, causal=True)`` restricted to its live prefix —
dequant rounded to model dtype, f32 accumulation, output in q.dtype —
for EVERY dtype/quantization combination (pinned per-op and
token-for-token across backends in ``tests/test_flash_decode.py``).
Prefill (T>1) keeps the existing flash/jnp paths: its cache read is
amortized over T tokens and the (bq, bk)-tiled forward kernel already
covers it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from byteps_tpu.ops.backend import use_pallas  # noqa: F401 (re-export)
from byteps_tpu.ops.backend import tpu_compiler_params as _compiler_params
from byteps_tpu.ops.flash_attention import (
    _MAX_HEAD_DIM,
    _NEG,
    _out_struct,
    _pick_block,
    _unify_vma,
)

__all__ = ["flash_decode", "decode_supported", "use_pallas"]


def decode_supported(S: int, D: int) -> bool:
    """Cache length must tile into 8..256 key blocks; head_dim ≤ 256.
    (Every block layout keeps its trailing dims mosaic-legal: the cache
    blocks end in the full (Hkv, D) planes, the scale blocks in
    (bk, Hkv) with bk a multiple of 8.)"""
    return _pick_block(S) is not None and D <= _MAX_HEAD_DIM


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, bk, nk):
    """ks_ref/vs_ref are None on the dense (non-quantized) path — the
    pallas signature then simply has no scale operands."""
    ki = pl.program_id(1)
    quantized = ks_ref is not None

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    pos = pos_ref[0, 0].astype(jnp.int32)     # query's global position
    k_start = ki * bk

    @pl.when(k_start <= pos)                  # dead blocks: no compute
    def _tile():
        # static unroll over kv heads: mosaic's matmul doesn't take the
        # stored layout's batch-dim placement, so each head runs plain
        # 2-d MXU ops on ref-sliced tiles; the block DMA happens ONCE —
        # slices read VMEM.
        Hkv = q_ref.shape[1]
        model_dt = q_ref.dtype
        for h in range(Hkv):
            qh = q_ref[0, h].astype(jnp.float32)          # (G, D)
            kh = k_ref[0, :, h, :]                        # (bk, D)
            vh = v_ref[0, :, h, :]
            if quantized:
                # VMEM dequant, rounded through the model dtype —
                # bit-identical to _cache_read's HBM materialization
                kh = (kh.astype(jnp.float32)
                      * ks_ref[0, :, h:h + 1]).astype(model_dt)
                vh = (vh.astype(jnp.float32)
                      * vs_ref[0, :, h:h + 1]).astype(model_dt)
            kh = kh.astype(jnp.float32)
            vh = vh.astype(jnp.float32)
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale   # (G, bk)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(cols <= pos, s, _NEG)
            m_prev = m_scr[h]                             # (G, 1)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            p = jnp.where(s > _NEG / 2, p, 0.0)           # masked lanes
            l_scr[h] = l_scr[h] * alpha + p.sum(axis=-1, keepdims=True)
            acc_scr[h] = acc_scr[h] * alpha + jax.lax.dot_general(
                p, vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)       # (G, D)
            m_scr[h] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_scr[:]
        l_safe = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _decode(q4, k4, v4, ks, vs, pos, interpret: bool):
    """q4: (B, Hkv, G, D); k4/v4: (B, S, Hkv, D) stored layout;
    ks/vs: (B, S, Hkv) f32 stored layout, or None → o (B, Hkv, G, D)."""
    B, Hkv, G, D = q4.shape
    S = k4.shape[1]
    bk = _pick_block(S)
    nk = S // bk
    quantized = ks is not None
    base = functools.partial(
        _decode_kernel, scale=1.0 / (D ** 0.5), bk=bk, nk=nk)
    pos2 = jnp.asarray(pos, jnp.float32).reshape(1, 1)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, Hkv, G, D), lambda b, ki: (b, 0, 0, 0)),
        pl.BlockSpec((1, bk, Hkv, D), lambda b, ki: (b, ki, 0, 0)),
        pl.BlockSpec((1, bk, Hkv, D), lambda b, ki: (b, ki, 0, 0)),
    ]
    if quantized:
        in_specs += [pl.BlockSpec((1, bk, Hkv), lambda b, ki: (b, ki, 0)),
                     pl.BlockSpec((1, bk, Hkv), lambda b, ki: (b, ki, 0))]
        operands = _unify_vma(pos2, q4, k4, v4, ks, vs)
        kern = base
    else:
        # dense: no scale operands in the signature at all
        operands = _unify_vma(pos2, q4, k4, v4)

        def kern(pos_ref, q_ref, k_ref, v_ref, o_ref, m, l, acc):
            base(pos_ref, q_ref, k_ref, v_ref, None, None, o_ref,
                 m, l, acc)

    out = pl.pallas_call(
        kern,
        grid=(B, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hkv, G, D), lambda b, ki: (b, 0, 0, 0)),
        out_shape=_out_struct((B, Hkv, G, D), q4.dtype, *operands),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, 1), jnp.float32),    # m
            pltpu.VMEM((Hkv, G, 1), jnp.float32),    # l
            pltpu.VMEM((Hkv, G, D), jnp.float32),    # acc
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    return out


def flash_decode(q, k_cache, v_cache, pos, k_scale=None, v_scale=None):
    """Single-token cached attention: ``q (B, 1, H, D)`` against the
    stored cache ``k/v (B, S, Hkv, D)`` (int8 when ``k_scale/v_scale
    (B, S, Hkv)`` are given, else any float dtype), attending to global
    key positions ``≤ pos`` (the query's position, a runtime scalar).
    Returns ``o (B, 1, H, D)`` in q.dtype. Callers gate on
    :func:`decode_supported` / :func:`use_pallas`.
    """
    B, T, H, D = q.shape
    if T != 1:
        raise ValueError(f"flash_decode is the T=1 step; got T={T}")
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    if H % Hkv != 0:
        raise ValueError(f"q heads ({H}) not a multiple of kv heads "
                         f"({Hkv})")
    if not decode_supported(S, D):
        raise ValueError(
            f"flash_decode: unsupported S={S} head_dim={D} — cache length "
            f"must divide into 8..256 blocks and head_dim ≤ "
            f"{_MAX_HEAD_DIM}; gate on decode_supported()")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    q4 = q.reshape(B, Hkv, H // Hkv, D)   # group-major head order
    ks = vs = None
    if k_scale is not None:
        ks = k_scale.astype(jnp.float32)      # stored (B, S, Hkv) layout
        vs = v_scale.astype(jnp.float32)
    interpret = jax.default_backend() != "tpu"
    o = _decode(q4, k_cache, v_cache, ks, vs, pos, interpret)
    return o.reshape(B, 1, H, D)
