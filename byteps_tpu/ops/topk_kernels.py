"""Block-topk selection/reconstruction kernels (Pallas TPU + jnp twins).

Reference analog: the select/pack loops of
``byteps/common/compressor/impl/topk.cc`` — but TPU-shaped: the round-5
xprof attribution showed the XLA form of blockwise selection (argmax +
value gather + one-hot reconstruct, chunked per partition) costing ~60 ms
of a 111 ms GPT-2-medium compressed step in mid-size elementwise ops and
layout changes. These kernels collapse that to three streaming passes.

Layout: a chunk of ``n = block·rows`` elements is viewed as
``(block, rows)`` — winner LANES on the minor axis (``rows ≈ k``, lane
aligned at real partition sizes), one winner per lane's strided element
set ``{c, c+rows, ...}`` (``compression/topk.py`` round-5 contract):

* ``block_select``: per lane, the first-max-|x| row index and its signed
  value — max/min reduces over the short sublane axis, no gather.
* ``block_reconstruct_sum``: Σ_k of K payloads rebuilt dense — an iota
  compare against each payload's winner rows, accumulated in VMEM; the
  aggregation tier's decompress-then-sum inner loop (reference server
  ``SumRecvBuff``) without materializing K dense arrays.

Tie-break matches ``jnp.argmax`` (first max) exactly: the kernel computes
``min(row where |x| == rowmax)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from byteps_tpu.ops.backend import kernel_backend as _backend

_LANES = 128


def _lane_block(rows: int) -> int:
    for bl in (1024, 512, 256, _LANES):
        if rows % bl == 0:
            return bl
    return rows


def kernels_supported(block: int, rows: int) -> bool:
    """The kernels want a lane-aligned winner axis; anything else (tiny
    test chunks, ragged tails) takes the jnp twin."""
    return rows % _LANES == 0 and block > 1


# --- jnp twins (the pre-round-5 XLA forms; also the goldens) -----------------
def _select_jnp(x2d: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    block, rows = x2d.shape
    xa = jnp.abs(x2d)
    local = jnp.argmax(xa, axis=0)                           # (rows,) int32
    rr = jax.lax.broadcasted_iota(jnp.int32, (block, rows), 0)
    vals = jnp.where(rr == local[None, :], x2d, 0.0).sum(axis=0)
    return local.astype(jnp.int32), vals


def _reconstruct_sum_jnp(locals_: jnp.ndarray, vals: jnp.ndarray,
                         block: int) -> jnp.ndarray:
    K, rows = locals_.shape
    rr = jax.lax.broadcasted_iota(jnp.int32, (block, rows), 0)
    acc = jnp.zeros((block, rows), jnp.float32)
    for k in range(K):
        acc = acc + jnp.where(rr == locals_[k][None, :], vals[k][None, :],
                              0.0)
    return acc


# --- pallas kernels ----------------------------------------------------------
def _select_kernel(x_ref, local_ref, vals_ref, *, block: int, bl: int):
    x = x_ref[...].astype(jnp.float32)                       # (block, bl)
    xa = jnp.abs(x)
    am = xa.max(axis=0, keepdims=True)                       # (1, bl)
    rr = jax.lax.broadcasted_iota(jnp.int32, (block, bl), 0)
    # first-max row per lane == jnp.argmax tie-break
    local = jnp.where(xa == am, rr, block).min(
        axis=0, keepdims=True)                               # (1, bl)
    vals = jnp.where(rr == local, x, 0.0).sum(
        axis=0, keepdims=True)                               # (1, bl)
    local_ref[...] = local
    vals_ref[...] = vals


@functools.partial(jax.jit, static_argnames=("interpret",))
def _select_pallas(x2d: jnp.ndarray, interpret: bool = False):
    block, rows = x2d.shape
    bl = _lane_block(rows)
    return pl.pallas_call(
        functools.partial(_select_kernel, block=block, bl=bl),
        grid=(rows // bl,),
        in_specs=[pl.BlockSpec((block, bl), lambda i: (0, i))],
        out_specs=[
            pl.BlockSpec((1, bl), lambda i: (0, i)),
            pl.BlockSpec((1, bl), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, rows), jnp.int32),
            jax.ShapeDtypeStruct((1, rows), jnp.float32),
        ],
        interpret=interpret,
    )(x2d)


def _reconstruct_kernel(local_ref, vals_ref, out_ref, *, K: int, block: int,
                        bl: int):
    rr = jax.lax.broadcasted_iota(jnp.int32, (block, bl), 0)
    acc = jnp.zeros((block, bl), jnp.float32)
    for k in range(K):
        lo = jnp.broadcast_to(local_ref[k:k + 1, :], (block, bl))
        va = jnp.broadcast_to(vals_ref[k:k + 1, :], (block, bl))
        acc = acc + jnp.where(rr == lo, va, 0.0)
    out_ref[...] = acc


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _reconstruct_pallas(locals_: jnp.ndarray, vals: jnp.ndarray, block: int,
                        interpret: bool = False) -> jnp.ndarray:
    K, rows = locals_.shape
    bl = _lane_block(rows)
    return pl.pallas_call(
        functools.partial(_reconstruct_kernel, K=K, block=block, bl=bl),
        grid=(rows // bl,),
        in_specs=[
            pl.BlockSpec((K, bl), lambda i: (0, i)),
            pl.BlockSpec((K, bl), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((block, bl), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((block, rows), jnp.float32),
        interpret=interpret,
    )(locals_, vals)


def _roundtrip_kernel(x_ref, *rest, jt: int, g: int, with_e: bool):
    """One streaming pass of the single-worker block-topk round trip,
    optionally with the EF add fused in: tiles → dense D(C(x[+e])) and
    residual (x[+e]) − D(C(x[+e])). Winner rule: strict FIRST-max per
    group — min group index where |x| equals the group max, exactly
    ``jnp.argmax``'s tie-break and what ``_select_kernel``/the wire
    payload path keep — so the fused n==1 path retains exactly one
    element per group even when bf16-derived gradients tie routinely."""
    if with_e:
        e_ref, out_ref, res_ref = rest
        x = (x_ref[...].astype(jnp.float32)
             + e_ref[...].astype(jnp.float32)).reshape(jt, g, 128)
    else:
        out_ref, res_ref = rest
        x = x_ref[...].astype(jnp.float32).reshape(jt, g, 128)
    xa = jnp.abs(x)
    am = xa.max(axis=1, keepdims=True)                       # (jt,1,128)
    ii = jax.lax.broadcasted_iota(jnp.int32, (jt, g, 128), 1)
    local = jnp.where(xa == am, ii, g).min(
        axis=1, keepdims=True)                               # (jt,1,128)
    dense = jnp.where(ii == local, x, 0.0)
    out_ref[...] = dense.reshape(jt * g, 128)
    res_ref[...] = (x - dense).reshape(jt * g, 128)


@functools.partial(jax.jit, static_argnames=("J", "g", "interpret"))
def _roundtrip_pallas(x2d: jnp.ndarray, e2d, J: int, g: int,
                      interpret: bool = False):
    M = x2d.shape[0]                                         # = J * g
    jt = 1
    for c in (16, 8, 4, 2):                                  # rows ≤ ~2k
        if J % c == 0 and c * g <= 2048:
            jt = c
            break
    with_e = e2d is not None
    spec = pl.BlockSpec((jt * g, 128), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_roundtrip_kernel, jt=jt, g=g, with_e=with_e),
        grid=(M // (jt * g),),
        in_specs=[spec, spec] if with_e else [spec],
        out_specs=[spec, spec],
        out_shape=[
            jax.ShapeDtypeStruct((M, 128), jnp.float32),
            jax.ShapeDtypeStruct((M, 128), jnp.float32),
        ],
        interpret=interpret,
    )(*((x2d, e2d) if with_e else (x2d,)))


def block_roundtrip(x: jnp.ndarray, J: int, g: int,
                    e: Optional[jnp.ndarray] = None,
                    backend: Optional[str] = None):
    """Flat (n = J·g·128,) f32 (+ optional EF residual e, added in-VMEM)
    → (D(C(x+e)), (x+e) − D(C(x+e))) flat, in ONE fused streaming pass.
    The single-worker compressed aggregation body — EF add, selection,
    reconstruction, and the new residual — with no payload
    materialization, no intermediate dense arrays, and no layout
    changes (1-D in, 1-D out). Tie-break is strict first-max (min group
    index at the group max |x|), matching the payload/wire paths
    exactly, so n==1 and n>1 select identical supports."""
    backend = backend or _backend()
    xf = x.astype(jnp.float32)
    if backend == "jnp":
        # same strict first-max winner rule as the kernel (see
        # _roundtrip_kernel) — the twin may never diverge on ties
        x3 = (xf if e is None
              else xf + e.astype(jnp.float32)).reshape(J, g, 128)
        xa = jnp.abs(x3)
        am = xa.max(axis=1, keepdims=True)
        ii = jax.lax.broadcasted_iota(jnp.int32, (J, g, 128), 1)
        local = jnp.where(xa == am, ii, g).min(axis=1, keepdims=True)
        dense = jnp.where(ii == local, x3, 0.0)
        return dense.reshape(-1), (x3 - dense).reshape(-1)
    out, res = _roundtrip_pallas(
        xf.reshape(J * g, 128),
        None if e is None else e.astype(jnp.float32).reshape(J * g, 128),
        J, g, interpret=jax.default_backend() != "tpu")
    return out.reshape(-1), res.reshape(-1)


# --- public API --------------------------------------------------------------
def block_select(x2d: jnp.ndarray,
                 backend: Optional[str] = None
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(block, rows) f32 → per-lane (local row (rows,) i32, value (rows,))."""
    backend = backend or _backend()
    block, rows = x2d.shape
    if backend == "jnp" or not kernels_supported(block, rows):
        return _select_jnp(x2d)
    lo, va = _select_pallas(x2d, interpret=jax.default_backend() != "tpu")
    return lo[0], va[0]


def block_reconstruct_sum(locals_: jnp.ndarray, vals: jnp.ndarray,
                          block: int,
                          backend: Optional[str] = None) -> jnp.ndarray:
    """(K, rows) winner rows + values → Σ_k dense (block, rows) f32."""
    backend = backend or _backend()
    K, rows = locals_.shape
    if backend == "jnp" or not kernels_supported(block, rows):
        return _reconstruct_sum_jnp(locals_, vals, block)
    return _reconstruct_pallas(
        locals_.astype(jnp.int32), vals.astype(jnp.float32), block,
        interpret=jax.default_backend() != "tpu")
