"""Fused readout→cross-entropy: the [B, S, V] logits never exist in HBM.

The round-5 xprof attribution (docs/performance.md §attribution) measured
the flagship's f32 ``[8, 512, 32768]`` CE-loss chain at 21.7% of the step
— ~5.4 ms of pure HBM streaming through logits + softmax intermediates
(the readout matmul itself already runs at MXU rate). The remedy is the
same trick the flash kernels use for attention: process the readout GEMM
and the softmax **blockwise** with online max/sum-exp accumulation, so
only one row-block's logits are live at a time, and **recompute** them in
the backward instead of saving them.

:func:`chunked_ce_nll` is the drop-in for
``_nll(head_dot(h, head), targets)`` (models/gpt.py): per-token NLL with
a custom VJP that

* scans the flattened ``(N, d)`` hidden states in row blocks
  (``row_block`` rows at a time; ≤64 MiB of f32 logits live per block by
  default — see ``_default_row_block`` — instead of the full N·V array),
* optionally sub-chunks the vocab axis inside each row block
  (``vocab_block``) with online max/sum-exp accumulation — the long-V
  memory lever,
* recomputes each block's logits in the backward from the saved
  ``(h, head)`` residuals + the per-row logsumexp (an (N,) f32 vector —
  the only extra forward output),
* keeps the ``head_dot`` precision contract: dot operands in the
  ACTIVATION dtype, f32 accumulation, activation-dtype ``dh``, f32
  ``dhead`` (the optimizer's master-weight gradient loses nothing).

**Vocab-parallel (tp) variant**: with ``tp_axis`` set, each device
computes only its ``V/ntp`` column slice of the readout (riding the same
col-parallel split the block matmuls use — the head weight stays
replicated, sliced at ``axis_index(tp)``), and the per-block row
max / sum-exp / target-logit are combined over tp (pmax + psum) before
the log-partition. FLOPs and live logits both drop by ntp; the backward
assembles ``dh``/``dhead`` with one psum each, so gradients keep the
replicated-weight contract the dense path has (VMA and no-VMA modes both
— see models/train.py's grad-assembly notes).

Numerics: the single-device, single-vocab-chunk path mirrors
``log_softmax``'s exact operation order (max, exp-shift, sum, log) and is
**bit-exact** with the dense ``_nll(head_dot(...))`` chain at f32; vocab
sub-chunking and the tp combine change the sum-exp association order and
are pinned to f32-roundoff tolerance instead
(tests/test_chunked_ce.py). The dense twin :func:`dense_ce_nll` is the
golden and the ``chunked_ce=False`` escape hatch on every train-step
factory routes production back to it.

Design note — why lax.scan blocks, not a Mosaic kernel: the measured
cost was the *materialization* (N·V f32 arrays streamed ~8×/step), not
the per-element math. Blockwise XLA already deletes that — the per-block
softmax stats and dlogits are elementwise/reduce consumers XLA fuses
onto the block GEMM's output, so the remaining traffic is the ~4 passes
a hand kernel would also pay for the GEMM operands/results it spills at
these shapes (one (512, 32768) f32 tile is 32× VMEM — a Pallas CE kernel
still round-trips HBM per vocab tile, saving ~1 pass). The scan form
keeps the path portable (CPU tier-1 pins it bit-exactly), VJP-exact
under remat/pipeline, and free of Mosaic compile risk on backends this
repo can't test against; if a future attribution shows the residual
passes matter, the flash kernels' (fwd, dq, dkv)-style split is the
shape a kernel port would take.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()


def _f32_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """`a @ b` with f32 accumulation — the head_dot contract's dot."""
    from byteps_tpu.ops.flash_attention import _unify_vma

    au, bu = _unify_vma(a, b)
    return jax.lax.dot_general(
        au, bu, (((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _vma(x) -> frozenset:
    try:
        return frozenset(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return frozenset()


def _default_row_block(n_rows: int, v_loc: int) -> int:
    """Largest power-of-two row count keeping one block's f32 logits
    ≤ 64 MiB — small enough that the full (B, S, V) chain never exists
    (the flagship's was 537 MB ×~8 HBM passes), large enough that the
    per-block readout GEMM keeps an MXU-efficient row dimension and the
    scan stays at ~8 steps (flagship V=32768 → 512 rows; gpt2m V=50304 →
    256). Clamped to [16, n_rows]."""
    budget = (64 * 1024 * 1024) // 4         # f32 elements per block
    if n_rows * max(v_loc, 1) <= budget:
        # whole batch in one block: no padding and no block-level
        # reassociation, so per-device numerics cannot depend on how a
        # mesh happens to split N — the cross-mesh equivalence pins
        # (dp vs dp×tp, etc.) see exactly the dense path's GEMM shapes
        return max(n_rows, 1)
    rb = 16
    while rb * 2 * max(v_loc, 1) <= budget:
        rb *= 2
    return rb


def _vocab_slices(v_loc: int, vocab_block: Optional[int]):
    """Static (start, width) slices covering the local vocab."""
    if not vocab_block or vocab_block >= v_loc:
        return [(0, v_loc)]
    return [(s, min(vocab_block, v_loc - s))
            for s in range(0, v_loc, vocab_block)]


def _local_head(head: jnp.ndarray, bias, tp_axis: Optional[str]):
    """This device's column slice of the (replicated) head/bias plus its
    vocab offset: the whole head when ``tp_axis`` is None or V doesn't
    split evenly; otherwise the ``V/ntp`` slice at ``axis_index(tp)``."""
    V = head.shape[1]
    if tp_axis is None:
        return head, bias, jnp.int32(0), V
    ntp = jax.lax.axis_size(tp_axis)
    if ntp == 1 or V % ntp != 0:
        return head, bias, jnp.int32(0), V
    v_loc = V // ntp
    off = (jax.lax.axis_index(tp_axis) * v_loc).astype(jnp.int32)
    head_loc = jax.lax.dynamic_slice(head, (jnp.int32(0), off),
                                     (head.shape[0], v_loc))
    bias_loc = (None if bias is None
                else jax.lax.dynamic_slice(bias, (off,), (v_loc,)))
    return head_loc, bias_loc, off, v_loc


def _block_stats(h_blk, head_loc, bias_loc, tgt_blk, off, vocab_block):
    """One row block's (m, s, t): running row max, sum-exp at that max,
    and the (shift-free) target logit masked to this vocab shard.

    Single vocab slice → exactly log_softmax's op order (bit-exact with
    the dense chain); multiple slices → online max/sum-exp accumulation.
    """
    rows = h_blk.shape[0]
    v_loc = head_loc.shape[1]
    head_c = head_loc.astype(h_blk.dtype)
    local_t = tgt_blk.astype(jnp.int32) - off
    in_range = (local_t >= 0) & (local_t < v_loc)
    slices = _vocab_slices(v_loc, vocab_block)
    if len(slices) == 1:
        z = _f32_dot(h_blk, head_c)
        if bias_loc is not None:
            z = z + bias_loc
        m = z.max(axis=-1)
        s = jnp.exp(z - m[:, None]).sum(axis=-1)
        tv = jnp.take_along_axis(
            z, jnp.clip(local_t, 0, v_loc - 1)[:, None], axis=-1)[:, 0]
        t = jnp.where(in_range, tv, 0.0)
        return m, s, t
    m = jnp.full((rows,), -jnp.inf, jnp.float32)
    s = jnp.zeros((rows,), jnp.float32)
    t = jnp.zeros((rows,), jnp.float32)
    for start, width in slices:
        z = _f32_dot(h_blk, head_c[:, start:start + width])
        if bias_loc is not None:
            z = z + bias_loc[start:start + width]
        m_new = jnp.maximum(m, z.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(z - m_new[:, None]).sum(axis=-1)
        m = m_new
        sel = local_t - start
        hit = in_range & (sel >= 0) & (sel < width)
        tv = jnp.take_along_axis(
            z, jnp.clip(sel, 0, width - 1)[:, None], axis=-1)[:, 0]
        t = t + jnp.where(hit, tv, 0.0)
    return m, s, t


def _pad_rows(x, rb: int):
    n = x.shape[0]
    nb = -(-n // rb)
    pad = nb * rb - n
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    return x, nb


def _fwd_scan(h2, head, bias, tgt, tp_axis, row_block, vocab_block):
    """(nll (N,), lse (N,)) via a row-block scan; collectives over tp
    combine the per-shard stats before the log-partition."""
    N = h2.shape[0]
    head_loc, bias_loc, off, v_loc = _local_head(head, bias, tp_axis)
    tp_split = v_loc != head.shape[1]   # vocab-parallel actually active
    rb = row_block or _default_row_block(N, head_loc.shape[1])
    h_pad, nb = _pad_rows(h2, rb)
    t_pad, _ = _pad_rows(tgt, rb)
    h_blks = h_pad.reshape(nb, rb, h2.shape[1])
    t_blks = t_pad.reshape(nb, rb)

    def body(carry, blk):
        h_blk, tgt_blk = blk
        m, s, t = _block_stats(h_blk, head_loc, bias_loc, tgt_blk, off,
                               vocab_block)
        if tp_split:
            m_g = jax.lax.pmax(m, tp_axis)
            s = jax.lax.psum(s * jnp.exp(m - m_g), tp_axis)
            t = jax.lax.psum(t, tp_axis)
            m = m_g
        # nll = logsumexp − target logit, associated exactly as
        # -log_softmax[target] is: log(Σexp(z−m)) − (z_t − m)
        lse = m + jnp.log(s)
        nll = jnp.log(s) - (t - m)
        return carry, (nll, lse)

    if nb == 1:
        _, (nll, lse) = body(None, (h_blks[0], t_blks[0]))
        return nll[:N], lse[:N]
    _, (nll, lse) = jax.lax.scan(body, None, (h_blks, t_blks))
    return nll.reshape(-1)[:N], lse.reshape(-1)[:N]


def _bwd_scan(h2, head, bias, tgt, lse, g, tp_axis, row_block, vocab_block):
    """Recompute-in-backward: per row block, rebuild the logits from
    (h, head), form ``dlogits = (softmax − onehot(target)) · g`` and
    accumulate ``dh`` (stacked) and ``dhead``/``dbias`` (f32 carries)."""
    N, d = h2.shape
    head_loc, bias_loc, off, v_loc = _local_head(head, bias, tp_axis)
    head_c = head_loc.astype(h2.dtype)
    rb = row_block or _default_row_block(N, v_loc)
    h_pad, nb = _pad_rows(h2, rb)
    t_pad, _ = _pad_rows(tgt, rb)
    lse_pad, _ = _pad_rows(lse, rb)
    g_pad, _ = _pad_rows(g.astype(jnp.float32), rb)
    h_blks = h_pad.reshape(nb, rb, d)
    t_blks = t_pad.reshape(nb, rb)
    lse_blks = lse_pad.reshape(nb, rb)
    g_blks = g_pad.reshape(nb, rb)
    slices = _vocab_slices(v_loc, vocab_block)

    def body(carry, blk):
        dhead_acc, dbias_acc = carry
        h_blk, tgt_blk, lse_blk, g_blk = blk
        local_t = tgt_blk.astype(jnp.int32) - off
        in_range = (local_t >= 0) & (local_t < v_loc)
        dh_blk = jnp.zeros((rb, d), jnp.float32)
        dhs, dbs = [], []
        for start, width in slices:
            z = _f32_dot(h_blk, head_c[:, start:start + width])
            if bias_loc is not None:
                z = z + bias_loc[start:start + width]
            p = jnp.exp(z - lse_blk[:, None])
            sel = local_t - start
            hit = in_range & (sel >= 0) & (sel < width)
            onehot = (jax.nn.one_hot(jnp.clip(sel, 0, width - 1), width,
                                     dtype=jnp.float32)
                      * hit[:, None].astype(jnp.float32))
            dz = ((p - onehot) * g_blk[:, None]).astype(h_blk.dtype)
            # dh accumulates over vocab slices; dhead/dbias over row blocks
            dh_blk = dh_blk + jax.lax.dot_general(
                dz, head_c[:, start:start + width],
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dhs.append(jax.lax.dot_general(
                h_blk, dz, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
            if bias_loc is not None:
                dbs.append(dz.astype(jnp.float32).sum(axis=0))
        dhead_acc = dhead_acc + jnp.concatenate(dhs, axis=1)
        if dbias_acc is not None:
            dbias_acc = dbias_acc + jnp.concatenate(dbs, axis=0)
        return (dhead_acc, dbias_acc), dh_blk

    # the f32 accumulators must carry the union vma of everything the body
    # touches or the scan carry would not be a type fixed point
    from byteps_tpu.ops.flash_attention import _unify_vma

    zeros_head = jnp.zeros((d, v_loc), jnp.float32)
    zeros_bias = jnp.zeros((v_loc,), jnp.float32)
    zeros_head, zeros_bias, *_rest = _unify_vma(
        zeros_head, zeros_bias, h_blks, t_blks, lse_blks, g_blks, head_c)
    init = (zeros_head, zeros_bias if bias_loc is not None else None)
    if nb == 1:
        (dhead_loc, dbias_loc), dh = body(
            init, (h_blks[0], t_blks[0], lse_blks[0], g_blks[0]))
        dh2 = dh[:N]
    else:
        (dhead_loc, dbias_loc), dh = jax.lax.scan(
            body, init, (h_blks, t_blks, lse_blks, g_blks))
        dh2 = dh.reshape(-1, d)[:N]

    tp_split = v_loc != head.shape[1]       # vocab-parallel actually active
    if tp_split:
        # each device computed only its vocab slice's contribution to dh —
        # the sum over the full vocab needs the tp psum (the row-parallel
        # adjoint); dhead slices scatter into the full (d, V) then psum
        dh2 = jax.lax.psum(dh2, tp_axis)
        zf, dhead_loc = _unify_vma(
            jnp.zeros((d, head.shape[1]), jnp.float32), dhead_loc)
        dhead = jax.lax.dynamic_update_slice(zf, dhead_loc,
                                             (jnp.int32(0), off))
        if dbias_loc is not None:
            zb, dbias_loc = _unify_vma(
                jnp.zeros((head.shape[1],), jnp.float32), dbias_loc)
            dbias = jax.lax.dynamic_update_slice(zb, dbias_loc, (off,))
        else:
            dbias = None
    else:
        dhead, dbias = dhead_loc, dbias_loc

    # replicated-weight adjoint: psum the head/bias grads over every axis
    # the activations vary on that the head doesn't (head_dot's contract),
    # plus tp when the vocab split was active
    extra = _vma(h2) - _vma(head)
    if tp_split:
        extra = extra | {tp_axis}
    sum_axes = tuple(sorted(extra))
    if sum_axes:
        dhead = jax.lax.psum(dhead, sum_axes)
        if dbias is not None:
            dbias = jax.lax.psum(dbias, sum_axes)
    return dh2.astype(h2.dtype), dhead, dbias


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _chunked_ce(h2, head, bias, tgt, tp_axis, row_block, vocab_block):
    nll, _lse = _fwd_scan(h2, head, bias, tgt, tp_axis, row_block,
                          vocab_block)
    return nll


def _chunked_ce_fwd(h2, head, bias, tgt, tp_axis, row_block, vocab_block):
    nll, lse = _fwd_scan(h2, head, bias, tgt, tp_axis, row_block,
                         vocab_block)
    return nll, (h2, head, bias, tgt, lse)


def _chunked_ce_bwd(tp_axis, row_block, vocab_block, res, g):
    h2, head, bias, tgt, lse = res
    dh2, dhead, dbias = _bwd_scan(h2, head, bias, tgt, lse, g, tp_axis,
                                  row_block, vocab_block)
    if bias is None:
        dbias = None
    # int targets take a symbolic-zero (float0) cotangent
    dtgt = np.zeros(tgt.shape, jax.dtypes.float0)
    return dh2, dhead.astype(head.dtype), dbias, dtgt


_chunked_ce.defvjp(_chunked_ce_fwd, _chunked_ce_bwd)


def dense_ce_nll(h: jnp.ndarray, head: jnp.ndarray,
                 targets: jnp.ndarray,
                 bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The jnp golden twin: per-token NLL through the dense
    ``head_dot`` readout + ``log_softmax`` chain (materializes the full
    f32 (..., V) logits). Identical numerics contract, used by the
    ``chunked_ce=False`` factory escape hatch and every parity pin."""
    from byteps_tpu.models.gpt import head_dot

    logits = head_dot(h, head)
    if bias is not None:
        logits = logits + bias
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def chunked_ce_nll(h: jnp.ndarray, head: jnp.ndarray, targets: jnp.ndarray,
                   bias: Optional[jnp.ndarray] = None,
                   tp_axis: Optional[str] = None,
                   row_block: Optional[int] = None,
                   vocab_block: Optional[int] = None) -> jnp.ndarray:
    """Per-token cross-entropy of the fused readout, logits never
    materialized.

    ``h (..., d)`` activations (any float dtype), ``head (d, V)`` f32
    readout weight (tied ``wte.T`` or untied ``lm_head``), ``targets
    (...)`` int ids, optional ``bias (V,)`` f32 logit bias (BERT's
    ``mlm_bias``). Returns f32 NLL shaped like ``targets``; equals
    ``dense_ce_nll(h, head, targets, bias)`` bit-exactly on the
    single-device single-vocab-chunk path and to f32 roundoff otherwise.

    ``tp_axis`` (inside shard_map) activates the vocab-parallel variant:
    per-device V/ntp column slices with tp-combined max/sum-exp — requires
    V divisible by the tp size (falls back to replicated compute
    otherwise). ``row_block``/``vocab_block`` override the block sizes
    (defaults: ≤64 MiB of live f32 logits per row block, no vocab
    sub-chunking).
    """
    if h.shape[:-1] != targets.shape:
        raise ValueError(
            f"h leading dims {h.shape[:-1]} must match targets shape "
            f"{targets.shape}")
    if head.ndim != 2 or h.shape[-1] != head.shape[0]:
        raise ValueError(
            f"head must be (d, V) with d == h.shape[-1]; got {head.shape} "
            f"vs d={h.shape[-1]}")
    if bias is not None and bias.shape != (head.shape[1],):
        raise ValueError(
            f"bias must be (V,) = ({head.shape[1]},); got {bias.shape}")
    lead = targets.shape
    h2 = h.reshape(-1, h.shape[-1])
    tgt = targets.reshape(-1)
    nll = _chunked_ce(h2, head, bias, tgt, tp_axis, row_block, vocab_block)
    return nll.reshape(lead)
