"""Onebit pack/unpack kernels (Pallas TPU + layout-identical jnp fallback).

Reference analog: the bit pack/unpack loops of
``byteps/common/compressor/impl/onebit.cc``. TPU-first layout: the flat
input is padded and viewed as ``(32, L)`` — bit-position k along the
*sublane* axis, word j along the *lane* axis — so packing is a 32-row
reduction over full 128-lane vectors and unpacking is a broadcast+shift,
both pure VPU ops with no cross-lane shuffles. (Packing 32 *consecutive*
elements per word, as the reference does on CPU, would need strided lane
gathers on TPU.) Wire format: element ``e`` (of the padded array) is bit
``e // L`` of word ``e % L``.

The fused ``onebit_unpack_sum`` is the aggregation-tier hot op — the
server's decompress→sum loop (``byteps/server/server.cc`` SumRecvBuff on
compressed pushes) done in one VMEM pass without materializing K dense
arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_BITS = 32


def _block(L: int) -> int:
    """Largest lane-multiple block size dividing L (L is always a multiple
    of 128, so this never falls through)."""
    for bl in (1024, 512, 256, 128):
        if L % bl == 0:
            return bl
    return L


from byteps_tpu.ops.backend import kernel_backend as _backend
from byteps_tpu.ops.backend import tpu_smem as _smem  # noqa: E402


def packed_words(n: int) -> int:
    """Words on the wire for n elements: ceil(n/32), lane-padded to 128."""
    m = -(-n // _BITS)
    return -(-m // _LANES) * _LANES


def _pad_len(n: int) -> int:
    return packed_words(n) * _BITS


# --- jnp fallback (same (32, L) layout) -------------------------------------
def _pack_jnp(x: jnp.ndarray) -> jnp.ndarray:
    L = packed_words(x.shape[0])
    xp = jnp.pad(x.astype(jnp.float32), (0, L * _BITS - x.shape[0]))
    bits = (xp.reshape(_BITS, L) >= 0).astype(jnp.uint32)
    shifts = jnp.arange(_BITS, dtype=jnp.uint32)[:, None]
    return (bits << shifts).sum(axis=0, dtype=jnp.uint32)


def _unpack_sum_jnp(words: jnp.ndarray, scales: jnp.ndarray,
                    n: int) -> jnp.ndarray:
    # words: (K, L) uint32, scales: (K,) f32 → Σ_k signs_k * scale_k, (n,)
    K, L = words.shape
    shifts = jnp.arange(_BITS, dtype=jnp.uint32)[None, :, None]
    bits = (words[:, None, :] >> shifts) & jnp.uint32(1)     # (K, 32, L)
    signs = bits.astype(jnp.float32) * 2.0 - 1.0
    acc = (signs * scales[:, None, None]).sum(axis=0)        # (32, L)
    return acc.reshape(-1)[:n]


# --- pallas kernels ----------------------------------------------------------
# Kernel arithmetic runs in int32 (Mosaic has no unsigned reductions);
# pack sums are exact bitwise under two's-complement wraparound (each word
# sums 32 distinct powers of two), and bit-k extraction `(w >> k) & 1`
# is shift-kind agnostic. uint32 lives only at the wire boundary.
def _pack_kernel(x_ref, out_ref):
    x = x_ref[...]                                           # (32, bl) f32
    bits = (x >= 0).astype(jnp.int32)
    shifts = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    out_ref[...] = jnp.sum(bits << shifts, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pack_pallas(x2d: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    _, L = x2d.shape
    bl = _block(L)
    out = pl.pallas_call(
        _pack_kernel,
        grid=(L // bl,),
        in_specs=[pl.BlockSpec((_BITS, bl), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, bl), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, L), jnp.int32),
        interpret=interpret,
    )(x2d)
    return jax.lax.bitcast_convert_type(out[0], jnp.uint32)


# Above this K the unpack-sum switches from a fully unrolled body to a
# grid axis over K: unrolling is fastest for mesh-axis-sized K (one VMEM
# pass, no revisits) but its program size — and Mosaic compile time —
# grows linearly with K, which is unbounded at pod scale (K = worker
# count on the server decompress-sum path).
_UNROLL_K_MAX = 32


def _rows_unpack_acc(words_ref, scales_ref, rows: int, bl: int):
    """Σ_r signs(words[r]) · scale[r] over ``rows`` block rows — the one
    copy of the bit-unpack arithmetic both unpack-sum kernels share."""
    shifts = jax.lax.broadcasted_iota(jnp.int32, (_BITS, bl), 0)
    acc = jnp.zeros((_BITS, bl), jnp.float32)
    for r in range(rows):
        w = jnp.broadcast_to(words_ref[r:r + 1, :], (_BITS, bl))
        bits = (w >> shifts) & jnp.int32(1)
        signs = bits.astype(jnp.float32) * 2.0 - 1.0
        acc = acc + signs * scales_ref[r, 0]
    return acc


def _make_unpack_sum_kernel(K: int, bl: int):
    def kernel(words_ref, scales_ref, out_ref):
        out_ref[...] = _rows_unpack_acc(words_ref, scales_ref, K, bl)

    return kernel


_GRID_K_BLOCK = 8  # sublane-dim blocks must be divisible by 8 on TPU


def _make_unpack_sum_grid_kernel(bl: int):
    """K as the innermost grid axis in blocks of 8 rows: constant program
    size for any K; the output block is revisited across consecutive k
    steps (legal revisit order on TPU), accumulating in place. Padded rows
    carry scale 0 and contribute nothing."""

    def kernel(words_ref, scales_ref, out_ref):
        kb = pl.program_id(1)
        acc = _rows_unpack_acc(words_ref, scales_ref, _GRID_K_BLOCK, bl)

        @pl.when(kb == 0)
        def _init():
            out_ref[...] = acc

        @pl.when(kb > 0)
        def _accumulate():
            out_ref[...] = out_ref[...] + acc

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def _unpack_sum_pallas(words: jnp.ndarray, scales: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    K, L = words.shape
    bl = _block(L)
    words_i32 = jax.lax.bitcast_convert_type(words, jnp.int32)
    if K <= _UNROLL_K_MAX:
        return pl.pallas_call(
            _make_unpack_sum_kernel(K, bl),
            grid=(L // bl,),
            in_specs=[
                pl.BlockSpec((K, bl), lambda i: (0, i)),
                pl.BlockSpec((K, 1), lambda i: (0, 0),
                             memory_space=_smem()),
            ],
            out_specs=pl.BlockSpec((_BITS, bl), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((_BITS, L), jnp.float32),
            interpret=interpret,
        )(words_i32, scales.reshape(K, 1))
    kp = -(-K // _GRID_K_BLOCK) * _GRID_K_BLOCK
    if kp != K:
        # pod worker counts are usually 8-multiples, so this copy of the
        # (already 32x-compressed) payload is the uncommon case; padded
        # rows are zero-scaled in the kernel
        words_i32 = jnp.pad(words_i32, ((0, kp - K), (0, 0)))
        scales_p = jnp.pad(scales.reshape(K, 1), ((0, kp - K), (0, 0)))
    else:
        scales_p = scales.reshape(K, 1)
    return pl.pallas_call(
        _make_unpack_sum_grid_kernel(bl),
        grid=(L // bl, kp // _GRID_K_BLOCK),
        in_specs=[
            pl.BlockSpec((_GRID_K_BLOCK, bl), lambda j, k: (k, j)),
            pl.BlockSpec((_GRID_K_BLOCK, 1), lambda j, k: (k, 0),
                         memory_space=_smem()),
        ],
        out_specs=pl.BlockSpec((_BITS, bl), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((_BITS, L), jnp.float32),
        interpret=interpret,
    )(words_i32, scales_p)


# --- public API --------------------------------------------------------------
def onebit_pack(x: jnp.ndarray,
                backend: Optional[str] = None) -> jnp.ndarray:
    """Flat f32 (n,) → (L,) uint32 sign words (L = packed_words(n))."""
    backend = backend or _backend()
    if backend == "jnp":
        return _pack_jnp(x)
    n = x.shape[0]
    L = packed_words(n)
    xp = jnp.pad(x.astype(jnp.float32), (0, L * _BITS - n))
    return _pack_pallas(xp.reshape(_BITS, L),
                        interpret=jax.default_backend() != "tpu")


def onebit_unpack_sum(words: jnp.ndarray, scales: jnp.ndarray, n: int,
                      backend: Optional[str] = None) -> jnp.ndarray:
    """(K, L) sign words + (K,) scales → Σ_k signs_k·scale_k as f32 (n,)."""
    backend = backend or _backend()
    if backend == "jnp":
        return _unpack_sum_jnp(words, scales, n)
    out = _unpack_sum_pallas(words, scales,
                             interpret=jax.default_backend() != "tpu")
    return out.reshape(-1)[:n]


def onebit_unpack(words: jnp.ndarray, scale: jnp.ndarray, n: int,
                  backend: Optional[str] = None) -> jnp.ndarray:
    """Single-payload decompress: (L,) words + scalar scale → (n,) f32."""
    return onebit_unpack_sum(words[None], scale.reshape(1), n, backend)
