"""Multi-replica request routing with lease/epoch replica liveness.

The PR 5 elastic-membership layer taught this repo one lesson worth
repeating at the serving tier: **death is detected by silence, never by
exception identity**. The summation servers there lease every worker —
one silent past the lease is evicted, the membership epoch bumps, and
open work re-targets the live set. :class:`Router` mirrors exactly
those semantics over serve replicas:

* every completed ``Scheduler.step()`` is the replica's lease renewal
  (the serve analog of the push/pull/kPing heartbeat);
* a replica silent past ``serve_replica_lease_ms`` — crashed, wedged,
  or deterministically killed by a ``worker:kill`` fault rule — is
  EVICTED: the routing epoch bumps (stamped on every completed
  result), and its in-flight requests re-queue to the survivors;
* re-queued requests keep their committed tokens and recompute their
  KV on the survivor (the scheduler's recompute-on-resume path), so a
  greedy request's final output is bit-identical to an undisturbed run
  — failover moves work, never content (pinned in tests/test_serve.py
  under the deterministic ``worker:kill`` fault scope).

Dispatch is least-loaded over the live set. The router is
single-threaded by design (one ``run()`` loop steps every replica
round-robin): replica parallelism in a real deployment is process- or
host-level, and this in-process form is what the bench and the chaos
pins drive deterministically.

The router also closes the scale-UP loop (docs/robustness.md §scale-up
elasticity): construct it with a
:class:`~byteps_tpu.common.autoscaler.ScalingPolicy` and a ``spawn``
callback and it runs one policy tick per step — the SAME policy class
that drives train-worker admission observes per-replica queue depth +
TTFT-SLO pressure, spawns replicas on ``admit`` and drains the
least-loaded one on ``evict``; every decision (the lease sweep's
evictions included) flows through the shared ``autoscaler.decisions``
event path, so train and serve share one elasticity story.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from byteps_tpu.common.autoscaler import (
    ScalingPolicy,
    record_decision,
    serve_sample,
)
from byteps_tpu.common.config import get_config
from byteps_tpu.common.faults import WorkerKilledError
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.serve.scheduler import Request, Scheduler

log = get_logger("serve.router")


class NoLiveReplicasError(RuntimeError):
    """Every replica is dead or evicted — nothing can serve."""


class Router:
    """Lease/epoch routing over a set of :class:`Scheduler` replicas."""

    def __init__(self, replicas: List[Scheduler],
                 lease_ms: Optional[int] = None,
                 clock=time.monotonic,
                 policy: Optional[ScalingPolicy] = None,
                 spawn: Optional[Callable[[], Scheduler]] = None,
                 ttft_slo_ms: Optional[float] = None):
        """``policy``/``spawn`` arm replica AUTOSCALING: the same
        :class:`~byteps_tpu.common.autoscaler.ScalingPolicy` class that
        drives train-worker admit/evict observes per-replica queue depth
        (+ TTFT-SLO pressure when ``ttft_slo_ms`` is set, off the
        ``serve.ttft_ms`` histogram, WINDOWED per tick — see
        :meth:`_autoscale`) once per :meth:`step`; an ``admit`` spawns a
        replica via ``spawn()``, an ``evict`` DRAINS the least-loaded
        one (its unfinished requests re-queue to the survivors — the
        lease-eviction mechanics, minus the death). A policy without a
        ``spawn`` callback — or one allowed to evict the last replica —
        would RECORD decisions the router cannot execute (phantom
        admits in the post-mortem, cooldowns armed for nothing), so
        both are rejected up front."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy is not None:
            if spawn is None:
                raise ValueError(
                    "a Router policy needs a spawn callback: the policy "
                    "records every decision it makes, and an admit the "
                    "router cannot execute would be a phantom event")
            if policy.min_units < 1:
                raise ValueError(
                    "Router policy min_units must be >= 1: the router "
                    "cannot drain its last replica")
        self.replicas = list(replicas)
        self.lease_ms = lease_ms if lease_ms is not None \
            else get_config().serve_replica_lease_ms
        self._clock = clock
        now = clock()
        self._beat: Dict[int, float] = {i: now
                                        for i in range(len(replicas))}
        self._live = set(range(len(replicas)))
        self.epoch = 0
        self.results: Dict[Any, Dict[str, Any]] = {}
        self._policy = policy
        self._spawn = spawn
        self._ttft_slo_ms = ttft_slo_ms
        # (count, sum) of serve.ttft_ms at the previous autoscale tick:
        # SLO pressure is computed over the DELTA, not the process-
        # lifetime histogram — a cold-start spike must stop inflating
        # the load signal as soon as fresh traffic is healthy
        self._ttft_mark = (0, 0.0)
        _reg = get_registry()
        self._m_dispatch = _reg.counter("serve.router.dispatched")
        self._m_evict = _reg.counter("serve.router.evictions")
        self._m_requeued = _reg.counter("serve.router.requeued")
        self._g_epoch = _reg.gauge("serve.router.epoch")
        self._g_live = _reg.gauge("serve.router.live_replicas")
        self._h_ttft = _reg.histogram("serve.ttft_ms")
        self._g_live.set(len(self._live))

    # -- dispatch -----------------------------------------------------------
    def live_replicas(self) -> List[int]:
        return sorted(self._live)

    def submit(self, req: Request,
               resume_tokens: Optional[List[int]] = None) -> int:
        """Route to the least-loaded live replica; returns its index."""
        if not self._live:
            raise NoLiveReplicasError("no live replica to route to")
        target = min(self._live, key=lambda i: (self.replicas[i].load, i))
        self.replicas[target].submit(req, resume_tokens=resume_tokens)
        self._m_dispatch.inc()
        return target

    # -- liveness -----------------------------------------------------------
    def step(self) -> bool:
        """Step every live replica once (its completed step renews the
        lease), then sweep expired leases. Returns True when any
        replica made progress."""
        progress = False
        completed = []
        for i in sorted(self._live):
            sched = self.replicas[i]
            try:
                if sched.step():
                    progress = True
                completed.append(i)
            except WorkerKilledError:
                # a dead replica renews nothing — eviction happens by
                # silence in sweep(), exactly like a real crash (the
                # PR 5 lease philosophy: no exception-identity paths)
                pass
        # renew every completed step at the SAME post-round timestamp:
        # this harness steps replicas serially, so a sibling's slow step
        # (first-call jit compile) must not age a healthy replica's
        # lease — a replica that completed its step this round is alive
        # NOW. Only true silence (kill/crash/wedge) accumulates.
        now = self._clock()
        for i in completed:
            self._beat[i] = now
        self._collect()
        self.sweep()
        self._autoscale()
        return progress

    def sweep(self) -> None:
        """Evict replicas silent past the lease: epoch bump + re-queue
        of their entire unfinished load onto the survivors."""
        now = self._clock()
        expired = [i for i in sorted(self._live)
                   if (now - self._beat[i]) * 1e3 > self.lease_ms]
        for i in expired:
            self._live.discard(i)
            self.epoch += 1
            self._m_evict.inc()
            self._g_epoch.set(self.epoch)
            self._g_live.set(len(self._live))
            incomplete = self.replicas[i].drain_incomplete()
            get_flight_recorder().record_event(
                "serve.replica_evicted",
                {"replica": i, "epoch": self.epoch,
                 "requeued": len(incomplete)})
            # the ONE shared decision path (common/autoscaler.py): lease
            # evictions and policy decisions land in the same counters/
            # FAULT instants, so a post-mortem shows WHY a replica left
            record_decision(
                "serve", "evict",
                f"lease-expired ({self.lease_ms} ms silent)",
                target=i, live=len(self._live))
            log.warning(
                "serve router: replica %d lease expired (epoch -> %d), "
                "re-queueing %d request(s)", i, self.epoch,
                len(incomplete))
            for req, emitted in incomplete:
                if not self._live:
                    raise NoLiveReplicasError(
                        f"replica {i} died holding {len(incomplete)} "
                        "request(s) and no survivor remains")
                self.submit(req, resume_tokens=emitted)
                self._m_requeued.inc()

    # -- replica autoscaling (common/autoscaler.py) --------------------------
    def add_replica(self, sched: Scheduler) -> int:
        """Bring a freshly spawned replica into the routing set (the
        serve-side JOIN: epoch bump so results stamp the new topology,
        lease seeded now). Returns its index."""
        self.replicas.append(sched)
        i = len(self.replicas) - 1
        self._beat[i] = self._clock()
        self._live.add(i)
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        self._g_live.set(len(self._live))
        log.info("serve router: replica %d admitted (epoch -> %d)", i,
                 self.epoch)
        return i

    def drain_replica(self, i: int) -> int:
        """Voluntarily retire replica ``i``: remove it from the live set
        (epoch bump) and re-queue its unfinished requests onto the
        survivors — the lease-eviction mechanics without the death, so
        drained requests keep their committed tokens (recompute-on-
        resume). Returns how many requests moved. The CALLER records the
        decision (policy evictions already did via ``observe``)."""
        if i not in self._live:
            raise ValueError(f"replica {i} is not live")
        if len(self._live) <= 1:
            raise NoLiveReplicasError(
                f"cannot drain replica {i}: it is the last live replica")
        self._live.discard(i)
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        self._g_live.set(len(self._live))
        incomplete = self.replicas[i].drain_incomplete()
        for req, emitted in incomplete:
            self.submit(req, resume_tokens=emitted)
            self._m_requeued.inc()
        log.info(
            "serve router: replica %d drained (epoch -> %d), "
            "%d request(s) re-queued", i, self.epoch, len(incomplete))
        return len(incomplete)

    def _autoscale(self) -> None:
        """One policy tick per router step: observe per-replica queue
        depth (+ TTFT-SLO pressure over the ticks' DELTA of the
        ``serve.ttft_ms`` histogram — the registry histogram is
        process-cumulative, and a lifetime p99 would carry a cold-start
        spike forever; the windowed mean resets with the traffic) and
        execute the decision."""
        if self._policy is None:
            return
        depth = sum(self.replicas[i].load for i in self._live)
        snap = self._h_ttft.snapshot()
        count = int(snap.get("count", 0))
        total = float(snap.get("sum", 0.0))
        dc = count - self._ttft_mark[0]
        ds = total - self._ttft_mark[1]
        self._ttft_mark = (count, total)
        ttft_ms = ds / dc if dc > 0 else 0.0
        d = self._policy.observe(serve_sample(
            live=len(self._live), queue_depth=depth,
            ttft_p99_ms=ttft_ms,
            ttft_slo_ms=self._ttft_slo_ms))
        if d.action == "admit":
            self.add_replica(self._spawn())
        elif d.action == "evict" and len(self._live) > 1:
            # drain the LEAST-loaded live replica (cheapest to move);
            # ties break toward the newest index
            target = min(sorted(self._live, reverse=True),
                         key=lambda i: self.replicas[i].load)
            self.drain_replica(target)

    def _collect(self) -> None:
        """DRAIN newly completed results up to the router (stamped with
        the epoch they completed under, like PR 5's response headers).
        Popping — not copying — keeps each replica's results dict and
        this loop sized by new completions, not lifetime traffic."""
        for i, sched in enumerate(self.replicas):
            while sched.results:
                rid, res = sched.results.popitem()
                res = dict(res)
                res["epoch"] = self.epoch
                res["replica"] = i
                self.results[rid] = res

    # -- convenience --------------------------------------------------------
    def finished(self, rids) -> bool:
        return all(r in self.results for r in rids)

    def run(self, requests: List[Request],
            max_idle_iters: int = 10000) -> Dict[Any, Dict[str, Any]]:
        """Dispatch ``requests`` (arrival-ordered) and drive the replica
        set until every one completes. Requests whose ``arrival_s`` is
        in the future are held back and dispatched on time — continuous
        admission, not a batch."""
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rids = [r.rid for r in requests]
        idle = 0
        while not self.finished(rids):
            now = self._clock()
            while pending and pending[0].arrival_s <= now:
                self.submit(pending.pop(0))
            if self.step():
                idle = 0
            else:
                idle += 1
                # idle wall time is what expires a dead replica's lease
                # — spinning without sleeping would burn the iteration
                # budget before the silence gets long enough to matter
                time.sleep(max(1e-4, self.lease_ms / 20e3))
                if idle > max_idle_iters:
                    raise RuntimeError(
                        "router made no progress with "
                        f"{len(rids) - len(self.results)} request(s) "
                        "outstanding")
        return self.results
