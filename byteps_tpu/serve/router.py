"""Multi-replica request routing with lease/epoch replica liveness.

The PR 5 elastic-membership layer taught this repo one lesson worth
repeating at the serving tier: **death is detected by silence, never by
exception identity**. The summation servers there lease every worker —
one silent past the lease is evicted, the membership epoch bumps, and
open work re-targets the live set. :class:`Router` mirrors exactly
those semantics over serve replicas:

* every completed ``Scheduler.step()`` is the replica's lease renewal
  (the serve analog of the push/pull/kPing heartbeat);
* a replica silent past ``serve_replica_lease_ms`` — crashed, wedged,
  or deterministically killed by a ``worker:kill`` fault rule — is
  EVICTED: the routing epoch bumps (stamped on every completed
  result), and its in-flight requests re-queue to the survivors;
* re-queued requests keep their committed tokens and recompute their
  KV on the survivor (the scheduler's recompute-on-resume path), so a
  greedy request's final output is bit-identical to an undisturbed run
  — failover moves work, never content (pinned in tests/test_serve.py
  under the deterministic ``worker:kill`` fault scope).

Dispatch is least-loaded over the live set. The router is
single-threaded by design (one ``run()`` loop steps every replica
round-robin): replica parallelism in a real deployment is process- or
host-level, and this in-process form is what the bench and the chaos
pins drive deterministically.

The router also closes the scale-UP loop (docs/robustness.md §scale-up
elasticity): construct it with a
:class:`~byteps_tpu.common.autoscaler.ScalingPolicy` and a ``spawn``
callback and it runs one policy tick per step — the SAME policy class
that drives train-worker admission observes per-replica queue depth +
TTFT-SLO pressure, spawns replicas on ``admit`` and drains the
least-loaded one on ``evict``; every decision (the lease sweep's
evictions included) flows through the shared ``autoscaler.decisions``
event path, so train and serve share one elasticity story.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from byteps_tpu.common.autoscaler import (
    ScalingPolicy,
    record_decision,
    serve_sample,
)
from byteps_tpu.common.config import get_config
from byteps_tpu.common.faults import WorkerKilledError
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.serve.scheduler import Request, Scheduler

log = get_logger("serve.router")


class NoLiveReplicasError(RuntimeError):
    """Every replica is dead or evicted — nothing can serve."""


class Router:
    """Lease/epoch routing over a set of :class:`Scheduler` replicas."""

    def __init__(self, replicas: List[Scheduler],
                 lease_ms: Optional[int] = None,
                 clock=time.monotonic,
                 policy: Optional[ScalingPolicy] = None,
                 spawn: Optional[Callable[[], Scheduler]] = None,
                 ttft_slo_ms: Optional[float] = None,
                 prefill_replicas: Optional[List[Scheduler]] = None,
                 wire_mbps: Optional[float] = None,
                 wire_credit: Optional[int] = None,
                 prompt_threshold: Optional[int] = None,
                 migrate_preempt: Optional[bool] = None,
                 kv_target_wrap: Optional[Callable[[Scheduler], Any]]
                 = None):
        """``policy``/``spawn`` arm replica AUTOSCALING: the same
        :class:`~byteps_tpu.common.autoscaler.ScalingPolicy` class that
        drives train-worker admit/evict observes per-replica queue depth
        (+ TTFT-SLO pressure when ``ttft_slo_ms`` is set, off the
        ``serve.ttft_ms`` histogram, WINDOWED per tick — see
        :meth:`_autoscale`) once per :meth:`step`; an ``admit`` spawns a
        replica via ``spawn()``, an ``evict`` DRAINS the least-loaded
        one (its unfinished requests re-queue to the survivors — the
        lease-eviction mechanics, minus the death). A policy without a
        ``spawn`` callback — or one allowed to evict the last replica —
        would RECORD decisions the router cannot execute (phantom
        admits in the post-mortem, cooldowns armed for nothing), so
        both are rejected up front.

        ``prefill_replicas`` arms DISAGGREGATION (docs/serving.md
        §disaggregation): dedicated ``role="prefill"`` replicas whose
        finished KV blocks stream to a decode target over per-replica
        :class:`~byteps_tpu.serve.kv_wire.KVWire` NICs (token-bucket
        paced at ``wire_mbps`` ≡ ``BYTEPS_SERVE_DISAGG_MBPS``).
        Admission classifies on prompt length × decode-pool pressure:
        inputs of ``prompt_threshold``+ tokens (the knee shrinks 4×
        when the decode pools run ≤25% free) route to the prefill tier
        and MIGRATE to their decode target as their blocks commit;
        shorter prompts prefill in place on a decode replica (one cheap
        chunk beats a migration round-trip). ``migrate_preempt``
        additionally turns pool-pressure preemption into
        migrate-don't-evict wherever ≥2 decode replicas live: the
        victim's committed blocks MOVE to the roomiest sibling instead
        of being freed and recomputed.

        ``kv_target_wrap`` swaps the migration wire's DELIVERY surface:
        the wrap maps a resolved decode Scheduler to whatever should
        receive its ``ingest_block`` calls — e.g. a
        :class:`~byteps_tpu.serve.kv_socket.SocketKVTarget` so the
        block bytes cross a real TCP link. Only the resolve callback
        handed to :class:`~byteps_tpu.serve.kv_wire.KVWire` is wrapped;
        the router's own adoption bookkeeping (``staged_blocks``/
        ``pop_staged``/``submit_migrated``) still talks to the local
        scheduler object."""
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy is not None:
            if spawn is None:
                raise ValueError(
                    "a Router policy needs a spawn callback: the policy "
                    "records every decision it makes, and an admit the "
                    "router cannot execute would be a phantom event")
            if policy.min_units < 1:
                raise ValueError(
                    "Router policy min_units must be >= 1: the router "
                    "cannot drain its last replica")
        c = get_config()
        self.replicas = list(replicas) + list(prefill_replicas or [])
        self._prefill_ids = set(range(len(replicas), len(self.replicas)))
        for i in self._prefill_ids:
            if self.replicas[i].role != "prefill":
                raise ValueError(
                    f"prefill_replicas[{i - len(replicas)}] has role "
                    f"{self.replicas[i].role!r} — construct it with "
                    "Scheduler(..., role='prefill')")
        self.lease_ms = lease_ms if lease_ms is not None \
            else c.serve_replica_lease_ms
        self._clock = clock
        now = clock()
        self._beat: Dict[int, float] = {i: now
                                        for i in range(len(self.replicas))}
        self._live = set(range(len(self.replicas)))
        # -- disaggregation / migration plane -------------------------------
        self._wire_mbps = wire_mbps if wire_mbps is not None \
            else c.serve_disagg_mbps
        self._wire_credit = wire_credit if wire_credit is not None \
            else c.serve_disagg_credit
        self._prompt_threshold = prompt_threshold \
            if prompt_threshold is not None \
            else c.serve_disagg_prompt_threshold
        self._migrate_preempt = migrate_preempt \
            if migrate_preempt is not None else c.serve_disagg_migrate
        # rid -> decode-target index; re-resolved (remapped) when the
        # target dies — read by KVWire PUSH threads, hence the lock
        self._mig_lock = threading.Lock()
        self._assignment: Dict[Any, int] = {}
        # rid -> in-flight migration: ticket, source index, whether the
        # source still PINS the blocks (prefill handoff) or already
        # freed them (migrate-out), full payload store, per-block wire
        # handles. The payload store is the retransmit source: a dead
        # target mid-migration costs a re-send, never the request.
        self._migrations: Dict[Any, Dict[str, Any]] = {}
        self._stream_store: Dict[Any, Dict[int, Any]] = {}
        self._stream_handles: Dict[Any, Dict[int, Any]] = {}
        self._stream_src: Dict[Any, int] = {}
        self._wires: Dict[int, Any] = {}
        self._kv_target_wrap = kv_target_wrap
        if self._prefill_ids or (self._migrate_preempt
                                 and len(self.replicas) > 1):
            # every migration-capable replica must share one pool
            # layout — the wire codec frames the pool's own bytes, so a
            # mismatch is a construction error, not a retryable one.
            # Duck-typed test stubs without a pool sit the check (and
            # the migrate hooks) out.
            keys = {i: self._codec_key(self.replicas[i])
                    for i in range(len(self.replicas))
                    if hasattr(self.replicas[i], "cache")}
            if len(set(keys.values())) > 1:
                raise ValueError(
                    "migration needs every replica on one pool layout "
                    f"(block_size, kv shape, dtype, quant); got {keys}")
        for i in self._prefill_ids:
            self.replicas[i].stream_blocks = self._make_stream_cb(i)
        if self._migrate_preempt:
            for i in range(len(self.replicas)):
                if i not in self._prefill_ids \
                        and hasattr(self.replicas[i], "cache"):
                    self.replicas[i].migrate_out = self._migrate_out
        self.epoch = 0
        self.results: Dict[Any, Dict[str, Any]] = {}
        self._policy = policy
        self._spawn = spawn
        self._ttft_slo_ms = ttft_slo_ms
        # (count, sum) of serve.ttft_ms at the previous autoscale tick:
        # SLO pressure is computed over the DELTA, not the process-
        # lifetime histogram — a cold-start spike must stop inflating
        # the load signal as soon as fresh traffic is healthy
        self._ttft_mark = (0, 0.0)
        _reg = get_registry()
        self._m_dispatch = _reg.counter("serve.router.dispatched")
        self._m_evict = _reg.counter("serve.router.evictions")
        self._m_requeued = _reg.counter("serve.router.requeued")
        self._m_mig_done = _reg.counter("serve.migration.adopted")
        self._m_mig_fallback = _reg.counter(
            "serve.migration.fallback_recompute")
        self._m_mig_retarget = _reg.counter("serve.migration.retargets")
        self._g_epoch = _reg.gauge("serve.router.epoch")
        self._g_live = _reg.gauge("serve.router.live_replicas")
        self._h_ttft = _reg.histogram("serve.ttft_ms")
        self._g_live.set(len(self._live))

    # -- dispatch -----------------------------------------------------------
    def live_replicas(self) -> List[int]:
        return sorted(self._live)

    def _live_decode(self) -> List[int]:
        return [i for i in sorted(self._live)
                if i not in self._prefill_ids]

    def _live_prefill(self) -> List[int]:
        return [i for i in sorted(self._live) if i in self._prefill_ids]

    def _effective_threshold(self) -> int:
        """Prompt-length classification knee, scaled by decode-pool
        pressure: when the decode tier runs low on (free + reclaimable)
        blocks, even shorter prompts are worth shipping to the prefill
        tier — their prefill would otherwise land ON the pressured
        pools and force preemptions there."""
        thr = self._prompt_threshold
        dec = self._live_decode()
        if not dec:
            return thr
        frac = min((self.replicas[i].cache.free_blocks
                    + self.replicas[i].cache.reclaimable_blocks())
                   / max(1, self.replicas[i].cache.pool_blocks - 1)
                   for i in dec)
        return max(1, thr // 4) if frac <= 0.25 else thr

    def _pick_decode_for(self, req: Request, dec: List[int]) -> int:
        """Least-loaded live decode replica, with ADAPTER AFFINITY for
        tenanted requests: replicas whose adapter pool already holds
        the request's adapter RESIDENT win first (admission's acquire
        is then a residency hit — no host->device slab load on the
        critical path), then replicas that at least have it registered
        (reloadable from their host registry); plain least-loaded
        otherwise. Ties always break by load then index."""
        # getattr: router duck-types requests/replicas (stub schedulers
        # in the autoscaling tests predate the adapter surface)
        adapter = getattr(req, "adapter", None)
        if adapter is not None:
            def _pool(i):
                return getattr(self.replicas[i], "adapter_pool", None)
            warm = [i for i in dec
                    if _pool(i) is not None
                    and _pool(i).resident(adapter)]
            if warm:
                return min(warm,
                           key=lambda i: (self.replicas[i].load, i))
            able = [i for i in dec
                    if _pool(i) is not None
                    and _pool(i).registered(adapter)]
            if able:
                return min(able,
                           key=lambda i: (self.replicas[i].load, i))
        return min(dec, key=lambda i: (self.replicas[i].load, i))

    def submit(self, req: Request,
               resume_tokens: Optional[List[int]] = None) -> int:
        """Route to the least-loaded live replica; returns its index.
        With the prefill tier armed, admissions classify on prompt
        length × decode-pool pressure: long inputs go to a prefill
        replica (their decode target reserved now, streamed to as
        blocks commit), short ones prefill in place on a decode
        replica. With every prefill replica dead the tier degrades to
        colocated routing — decode replicas can always prefill.
        Adapter-tagged requests add pool affinity (see
        :meth:`_pick_decode_for`); they only classify to a prefill
        replica that can graft their adapter."""
        dec = self._live_decode()
        if not dec:
            raise NoLiveReplicasError(
                "no live decode-capable replica to route to")
        pre = self._live_prefill()
        if pre and getattr(req, "adapter", None) is not None:
            pre = [i for i in pre
                   if (getattr(self.replicas[i], "adapter_pool", None)
                       is not None
                       and self.replicas[i].adapter_pool.registered(
                           req.adapter))]
        if pre:
            n_in = (np.asarray(req.prompt).size
                    + len(resume_tokens or ()))
            if n_in >= self._effective_threshold():
                target = min(pre,
                             key=lambda i: (self.replicas[i].load, i))
                self.replicas[target].submit(
                    req, resume_tokens=resume_tokens)
                # decode target reserved only AFTER the prefill replica
                # accepted the request — a rejected submit must not
                # leave a phantom pending assignment skewing future
                # target picks
                with self._mig_lock:
                    self._assignment[req.rid] = \
                        self._pick_decode_locked(dec)
                self._m_dispatch.inc()
                return target
        target = self._pick_decode_for(req, dec)
        self.replicas[target].submit(req, resume_tokens=resume_tokens)
        self._m_dispatch.inc()
        return target

    # -- liveness -----------------------------------------------------------
    def step(self) -> bool:
        """Step every live replica once (its completed step renews the
        lease), then sweep expired leases. Returns True when any
        replica made progress."""
        progress = False
        completed = []
        for i in sorted(self._live):
            sched = self.replicas[i]
            try:
                if sched.step():
                    progress = True
                completed.append(i)
            except WorkerKilledError:
                # a dead replica renews nothing — eviction happens by
                # silence in sweep(), exactly like a real crash (the
                # PR 5 lease philosophy: no exception-identity paths)
                pass
        # renew every completed step at the SAME post-round timestamp:
        # this harness steps replicas serially, so a sibling's slow step
        # (first-call jit compile) must not age a healthy replica's
        # lease — a replica that completed its step this round is alive
        # NOW. Only true silence (kill/crash/wedge) accumulates.
        now = self._clock()
        for i in completed:
            self._beat[i] = now
        self._collect()
        self.sweep()
        if self._migrations or self._prefill_ids:
            if self._pump_migrations():
                progress = True
        self._autoscale()
        return progress

    def sweep(self) -> None:
        """Evict replicas silent past the lease: epoch bump + re-queue
        of their entire unfinished load onto the survivors. A dead
        PREFILL replica's load re-classifies through ``submit`` (a
        surviving prefill sibling, else colocated on the decode tier);
        handoffs it was mid-migration on are cancelled — their runs
        ride the drain — while migrate-OUT transfers it sourced keep
        going (the payload store and wire outlive the source's lease)."""
        now = self._clock()
        expired = [i for i in sorted(self._live)
                   if (now - self._beat[i]) * 1e3 > self.lease_ms]
        for i in expired:
            self._live.discard(i)
            self._cancel_sourced_migrations(i)
            self.epoch += 1
            self._m_evict.inc()
            self._g_epoch.set(self.epoch)
            self._g_live.set(len(self._live))
            incomplete = self.replicas[i].drain_incomplete()
            get_flight_recorder().record_event(
                "serve.replica_evicted",
                {"replica": i, "epoch": self.epoch,
                 "requeued": len(incomplete)})
            # the ONE shared decision path (common/autoscaler.py): lease
            # evictions and policy decisions land in the same counters/
            # FAULT instants, so a post-mortem shows WHY a replica left
            record_decision(
                "serve", "evict",
                f"lease-expired ({self.lease_ms} ms silent)",
                target=i, live=len(self._live))
            log.warning(
                "serve router: replica %d lease expired (epoch -> %d), "
                "re-queueing %d request(s)", i, self.epoch,
                len(incomplete))
            for req, emitted in incomplete:
                if not self._live:
                    raise NoLiveReplicasError(
                        f"replica {i} died holding {len(incomplete)} "
                        "request(s) and no survivor remains")
                self.submit(req, resume_tokens=emitted)
                self._m_requeued.inc()

    # -- replica autoscaling (common/autoscaler.py) --------------------------
    def add_replica(self, sched: Scheduler) -> int:
        """Bring a freshly spawned replica into the routing set (the
        serve-side JOIN: epoch bump so results stamp the new topology,
        lease seeded now). Returns its index."""
        self.replicas.append(sched)
        i = len(self.replicas) - 1
        if (self._migrate_preempt and hasattr(sched, "cache")
                and getattr(sched, "role", "both") != "prefill"):
            sched.migrate_out = self._migrate_out
        self._beat[i] = self._clock()
        self._live.add(i)
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        self._g_live.set(len(self._live))
        log.info("serve router: replica %d admitted (epoch -> %d)", i,
                 self.epoch)
        return i

    def drain_replica(self, i: int) -> int:
        """Voluntarily retire replica ``i``: remove it from the live set
        (epoch bump) and re-queue its unfinished requests onto the
        survivors — the lease-eviction mechanics without the death, so
        drained requests keep their committed tokens (recompute-on-
        resume). Returns how many requests moved. The CALLER records the
        decision (policy evictions already did via ``observe``)."""
        if i not in self._live:
            raise ValueError(f"replica {i} is not live")
        if len(self._live) <= 1:
            raise NoLiveReplicasError(
                f"cannot drain replica {i}: it is the last live replica")
        if (i not in self._prefill_ids
                and len(self._live_decode()) <= 1):
            raise NoLiveReplicasError(
                f"cannot drain replica {i}: it is the last live "
                "decode-capable replica")
        self._live.discard(i)
        self._cancel_sourced_migrations(i)
        self.epoch += 1
        self._g_epoch.set(self.epoch)
        self._g_live.set(len(self._live))
        incomplete = self.replicas[i].drain_incomplete()
        for req, emitted in incomplete:
            self.submit(req, resume_tokens=emitted)
            self._m_requeued.inc()
        log.info(
            "serve router: replica %d drained (epoch -> %d), "
            "%d request(s) re-queued", i, self.epoch, len(incomplete))
        return len(incomplete)

    def _autoscale(self) -> None:
        """One policy tick per router step: observe per-replica queue
        depth (+ TTFT-SLO pressure over the ticks' DELTA of the
        ``serve.ttft_ms`` histogram — the registry histogram is
        process-cumulative, and a lifetime p99 would carry a cold-start
        spike forever; the windowed mean resets with the traffic) and
        execute the decision."""
        if self._policy is None:
            return
        depth = sum(self.replicas[i].load for i in self._live)
        snap = self._h_ttft.snapshot()
        count = int(snap.get("count", 0))
        total = float(snap.get("sum", 0.0))
        dc = count - self._ttft_mark[0]
        ds = total - self._ttft_mark[1]
        self._ttft_mark = (count, total)
        ttft_ms = ds / dc if dc > 0 else 0.0
        d = self._policy.observe(serve_sample(
            live=len(self._live), queue_depth=depth,
            ttft_p99_ms=ttft_ms,
            ttft_slo_ms=self._ttft_slo_ms))
        if d.action == "admit":
            self.add_replica(self._spawn())
        elif d.action == "evict":
            # drain the LEAST-loaded live DECODE replica (cheapest to
            # move; the prefill tier is not the policy's to shrink);
            # ties break toward the newest index
            dec = self._live_decode()
            if len(dec) > 1:
                target = min(sorted(dec, reverse=True),
                             key=lambda i: self.replicas[i].load)
                self.drain_replica(target)

    # -- KV migration plane (serve/kv_wire.py, docs/serving.md) -------------
    @staticmethod
    def _codec_key(sched: Scheduler):
        st = sched.cache.state
        return (sched.cache.block_size, sched.cache.quant,
                st.k.shape[0], st.k.shape[2:], str(st.k.dtype))

    def _wire_for(self, i: int):
        """The source replica's outbound migration NIC (lazy: colocated
        routers never build one)."""
        w = self._wires.get(i)
        if w is None:
            from byteps_tpu.serve.kv_wire import KVWire

            resolve = self._resolve_target
            if self._kv_target_wrap is not None:
                # wrap ONLY the wire's delivery surface — adoption
                # bookkeeping elsewhere still needs the local object
                wrap = self._kv_target_wrap

                def resolve(rid, _r=self._resolve_target, _w=wrap):
                    t = _r(rid)
                    return None if t is None else _w(t)

            w = KVWire(self.replicas[i].kv_codec, resolve,
                       mbps=self._wire_mbps, credit=self._wire_credit)
            self._wires[i] = w
        return w

    def _pick_decode_locked(self, dec: List[int]) -> int:
        """Least-loaded live decode replica, counting PENDING migration
        assignments as load — a decode replica's `.load` only moves at
        adoption, so without this every concurrent migration would pile
        onto one target. Callers hold ``_mig_lock``."""
        pending: Dict[int, int] = {}
        for t in self._assignment.values():
            pending[t] = pending.get(t, 0) + 1
        return min(dec, key=lambda i: (self.replicas[i].load
                                       + pending.get(i, 0), i))

    def _resolve_target(self, rid):
        """The CURRENT decode target for a migrating rid — called by
        KVWire PUSH threads per delivery attempt, so a dead target is a
        remap (the stage retry lands on the live sibling), never a
        loss. Returns None when no decode-capable replica lives (the
        push retries until the autoscaler/operator brings one back or
        the retry budget trips — the payload store re-sends either
        way), and for rids with no ACTIVE migration/stream: a straggler
        push task whose migration was cancelled (dead source) or whose
        request already completed must die quietly, not resurrect an
        assignment and stage orphan payloads nobody will reclaim."""
        with self._mig_lock:
            t = self._assignment.get(rid)
            if t is not None and t in self._live \
                    and t not in self._prefill_ids:
                return self.replicas[t]
            if (t is None and rid not in self._migrations
                    and rid not in self._stream_src):
                return None
            dec = self._live_decode()
            if not dec:
                return None
            nt = self._pick_decode_locked(dec)
            if t is not None:
                self._m_mig_retarget.inc()
                get_flight_recorder().record_event(
                    "serve.migration.retarget",
                    {"rid": str(rid), "from": t, "to": nt})
            self._assignment[rid] = nt
            return self.replicas[nt]

    def _make_stream_cb(self, i: int):
        """Prefill replica ``i``'s block-commit hook: every newly full
        block goes onto the wire NOW (overlapping the next chunk's
        compute) and into the payload store (the retransmit source
        until adoption)."""
        def cb(sched, run, payloads):
            rid = run.req.rid
            wire = self._wire_for(i)
            store = self._stream_store.setdefault(rid, {})
            handles = self._stream_handles.setdefault(rid, {})
            self._stream_src[rid] = i
            for bi, p in payloads.items():
                store[bi] = p
                handles[bi] = wire.send_block(rid, bi, p)
        return cb

    def _migrate_out(self, sched: Scheduler, run) -> bool:
        """Migrate-don't-evict: scheduler ``sched`` is about to preempt
        ``run`` — move its committed blocks to the roomiest live
        sibling instead, when one can hold them. Returns False (the
        classic evict proceeds) when no sibling fits or the wire is
        not armed."""
        src = self.replicas.index(sched)
        need = sched.cache.blocks_for(run.cache_len + 1)
        with self._mig_lock:
            sibs = [i for i in self._live_decode()
                    if i != src and self.replicas[i].cache.free_blocks
                    + self.replicas[i].cache.reclaimable_blocks()
                    >= need]
            if not sibs:
                return False
            target = max(sibs,
                         key=lambda i: self.replicas[i].cache.free_blocks
                         - self.replicas[i].load)
            rid = run.req.rid
            self._assignment[rid] = target
        ticket = sched.extract_for_migration(rid)
        wire = self._wire_for(src)
        handles = {bi: wire.send_block(rid, bi, p)
                   for bi, p in ticket.payloads.items()}
        self._migrations[rid] = {
            "ticket": ticket, "source": src, "src_holds": False,
            "payloads": dict(ticket.payloads), "handles": handles}
        get_flight_recorder().record_event(
            "serve.migration.start",
            {"rid": str(rid), "kind": "preempt", "from": src,
             "to": target, "blocks": ticket.n_blocks})
        return True

    def _begin_handoff(self, src: int, ticket) -> None:
        rid = ticket.req.rid
        payloads = self._stream_store.pop(rid, {})
        payloads.update(ticket.payloads)
        handles = self._stream_handles.pop(rid, {})
        self._stream_src.pop(rid, None)
        wire = self._wire_for(src)
        for bi, p in ticket.payloads.items():
            handles[bi] = wire.send_block(rid, bi, p)
        self._migrations[rid] = {
            "ticket": ticket, "source": src, "src_holds": True,
            "payloads": payloads, "handles": handles}
        get_flight_recorder().record_event(
            "serve.migration.start",
            {"rid": str(rid), "kind": "handoff", "from": src,
             "blocks": ticket.n_blocks})

    def _cancel_sourced_migrations(self, i: int) -> None:
        """Source replica ``i`` left the live set: its HANDOFF
        migrations cancel (the parked runs ride its drain and
        re-classify — recompute, the pre-migration behavior), while
        migrate-OUT transfers keep going: their blocks were already
        extracted, and the payload store + wire outlive the source."""
        gone = [r for r, m in self._migrations.items()
                if m["source"] == i and m["src_holds"]]
        # mid-prefill streams from the dead source cancel the same way
        # (their runs re-classify through the drain, recompute clean)
        gone += [r for r, s in self._stream_src.items()
                 if s == i and r not in gone]
        for rid in gone:
            self._migrations.pop(rid, None)
            self._stream_store.pop(rid, None)
            self._stream_handles.pop(rid, None)
            self._stream_src.pop(rid, None)
            with self._mig_lock:
                t = self._assignment.pop(rid, None)
            if t is not None and t < len(self.replicas):
                self.replicas[t].drop_staged(rid)

    def _pump_migrations(self) -> bool:
        """One migration tick: collect fresh prefill handoffs, then
        push every pending migration forward (re-send what failed or
        landed on a since-dead target; adopt once the target staged the
        full block set). Returns True when anything moved."""
        progress = False
        for i in self._live_prefill():
            for ticket in self.replicas[i].pop_handoffs():
                self._begin_handoff(i, ticket)
                progress = True
        for rid in list(self._migrations):
            if self._advance_migration(rid):
                progress = True
        return progress

    def _advance_migration(self, rid) -> bool:
        m = self._migrations[rid]
        ticket = m["ticket"]
        target = self._resolve_target(rid)
        if target is None:
            return False          # no decode tier right now; keep waiting
        wire = self._wire_for(m["source"])
        waiting = False
        for bi in range(ticket.n_blocks):
            h = m["handles"].get(bi)
            if h is not None and h.failed():
                cause = getattr(h.error(), "cause", None)
                if cause is not None and not getattr(
                        cause, "retryable", True):
                    # layout mismatch or similar construction bug:
                    # re-sending the same bytes can never fix it —
                    # surface it instead of looping on the wire
                    raise RuntimeError(
                        f"KV migration for {rid!r} failed terminally: "
                        f"{cause}") from cause
                # retry budget exhausted (e.g. every attempt hit a dead
                # target before the remap): re-send from the payload
                # store as a fresh task
                wire.abandon(1)
                h = None
            if h is None:
                m["handles"][bi] = wire.send_block(rid, bi,
                                                   m["payloads"][bi])
                waiting = True
            elif not h.done():
                waiting = True
        if waiting:
            return False
        staged = target.staged_blocks(rid)
        missing = [bi for bi in range(ticket.n_blocks)
                   if bi not in staged]
        if missing:
            # delivered to a target that died before adoption — the
            # payload store re-sends to the current one
            for bi in missing:
                m["handles"][bi] = wire.send_block(rid, bi,
                                                   m["payloads"][bi])
            return True
        ok = target.submit_migrated(ticket, target.pop_staged(rid))
        if ok:
            self._m_mig_done.inc()
            if m["src_holds"]:
                self.replicas[m["source"]].finish_handoff(rid)
            get_flight_recorder().record_event(
                "serve.migration.adopted",
                {"rid": str(rid), "blocks": ticket.n_blocks})
        else:
            # the target cannot hold it even after preemption: fall
            # back to recompute-on-resume — slower, never wrong
            self._m_mig_fallback.inc()
            get_flight_recorder().record_event(
                "serve.migration.fallback",
                {"rid": str(rid), "blocks": ticket.n_blocks})
            if m["src_holds"]:
                self.replicas[m["source"]].finish_handoff(rid)
            target.submit(ticket.req, resume_tokens=ticket.emitted)
        del self._migrations[rid]
        with self._mig_lock:
            self._assignment.pop(rid, None)
        return True

    def close(self) -> None:
        """Tear down the migration wires (their stage pools own
        threads); idempotent, and a colocated router has nothing to
        do."""
        for w in self._wires.values():
            w.shutdown()
        self._wires.clear()

    def _collect(self) -> None:
        """DRAIN newly completed results up to the router (stamped with
        the epoch they completed under, like PR 5's response headers).
        Popping — not copying — keeps each replica's results dict and
        this loop sized by new completions, not lifetime traffic."""
        for i, sched in enumerate(self.replicas):
            while sched.results:
                rid, res = sched.results.popitem()
                res = dict(res)
                res["epoch"] = self.epoch
                res["replica"] = i
                self.results[rid] = res
                if self._prefill_ids or self._migrations:
                    # a cancelled/retargeted migration can strand
                    # staged host payloads for this rid — reclaim them
                    # now that the request is done
                    with self._mig_lock:
                        self._assignment.pop(rid, None)
                    for other in self.replicas:
                        other.drop_staged(rid)

    # -- convenience --------------------------------------------------------
    def finished(self, rids) -> bool:
        return all(r in self.results for r in rids)

    def run(self, requests: List[Request],
            max_idle_iters: int = 10000) -> Dict[Any, Dict[str, Any]]:
        """Dispatch ``requests`` (arrival-ordered) and drive the replica
        set until every one completes. Requests whose ``arrival_s`` is
        in the future are held back and dispatched on time — continuous
        admission, not a batch."""
        pending = sorted(requests, key=lambda r: r.arrival_s)
        rids = [r.rid for r in requests]
        idle = 0
        idle_since = None
        while not self.finished(rids):
            now = self._clock()
            while pending and pending[0].arrival_s <= now:
                self.submit(pending.pop(0))
            if self.step():
                idle = 0
                idle_since = None
            else:
                idle += 1
                if idle_since is None:
                    idle_since = self._clock()
                # idle wall time is what expires a dead replica's lease
                # — spinning without sleeping would burn the iteration
                # budget before the silence gets long enough to matter.
                # The per-step sleep is capped at 50 ms (a huge lease
                # must not turn one idle step — e.g. waiting on an
                # in-flight KV migration — into a multi-second stall);
                # the no-progress abort is therefore WALL-CLOCK gated
                # past twice the lease, so a dead replica always gets
                # evicted before the loop gives up, whatever the lease
                time.sleep(min(0.05, max(1e-4, self.lease_ms / 20e3)))
                if (idle > max_idle_iters
                        and (self._clock() - idle_since) * 1e3
                        > 2 * self.lease_ms):
                    raise RuntimeError(
                        "router made no progress with "
                        f"{len(rids) - len(self.results)} request(s) "
                        "outstanding")
        return self.results
