"""Iteration-level request scheduler — Orca's continuous batching over
the block-paged KV cache.

One :class:`Scheduler` is one model replica: it owns a
:class:`~byteps_tpu.serve.paged_cache.PagedKVCache` pool and drives a
four-phase iteration (``step()``):

1. **Admission** — requests whose arrival time has passed join the
   running set as soon as a decode slot AND enough free KV blocks
   exist. Per-tenant FIFO in arrival order, deficit-weighted fair
   queuing ACROSS tenants (``serve_fair_queue``; single-tenant
   traffic reduces exactly to the historical global FIFO); preempted
   requests re-queue at the FRONT (they are the oldest work). With the prefix cache on
   (``BYTEPS_SERVE_PREFIX_CACHE``, default), admission first consults
   the pool's radix index: a hit maps the request's leading table
   entries to shared read-only pages (committed by earlier prefills),
   CoWs the divergence block when the match ends mid-block, and starts
   chunked prefill at the divergence — the shared chunks are skipped
   entirely, which is where the shared-prefix TTFT headline comes from
   (``bench.py --mode serve``, prefix leg).
2. **Prefill** — one prompt chunk (``serve_prefill_chunk`` tokens) per
   iteration through the per-request paged prefill, so a long prompt
   interleaves with everyone else's decode steps instead of stalling
   them (the Orca observation). The final chunk's last-position logits
   yield the request's first generated token — that commit is TTFT.
3. **Speculative lane** — every spec-policy request runs one
   draft-propose/verify round per iteration instead of a plain decode
   step: ``spec_len`` proposed tokens verified in ONE forward,
   committed through ``speculative._verify_commit`` (the same
   exactness-critical arithmetic as ``make_speculative_generate_fn``
   — greedy output is identical to plain greedy decoding at any
   accept rate, the draft only moves speed). Spec requests never join
   the packed batch: a plain decode step would commit tokens the
   per-request draft cache never saw, silently desyncing it and
   collapsing acceptance. Fill-level rewind is the paged twin of the
   dense cache rewind: ``cache_len`` advances only by the committed
   count, later writes overwrite the rest.
4. **Packed decode** — every non-speculative decoding request joins
   ONE jitted device batch (static ``serve_max_batch`` rows, padded
   rows scatter into the reserved scratch block): one token per
   request per iteration at heterogeneous positions.

**Preemption** — when a block allocation fails, the youngest admitted
request is evicted: its blocks free immediately, its committed tokens
are kept, and it re-queues with ``prompt + emitted`` as the recompute
prefill input (recompute-on-resume; the vLLM policy that beats
swapping when recompute is one chunked prefill). Continuation tokens
are unchanged — the resume prefill's last logits ARE the logits the
uninterrupted decode step would have produced at that position.

**Exactness contract** — greedy (``temperature == 0``) requests emit
token-for-token what a solo ``make_generate_fn`` run emits, regardless
of batch composition, admission order, chunking, preemption, or
speculation (pinned in tests/test_serve.py). Sampled requests draw
per-request fold_in keys — deterministic per (seed, position) but
intentionally NOT the solo sampler's batched key sequence.

Replica death is deterministic chaos: a ``worker:kill`` (or
serve-scoped ``replica<N>:kill``) rule in the request's
:class:`~byteps_tpu.common.faults.FaultPlan` kills the replica at an
exact step; the router's lease sweep then evicts it — the same
death-by-silence semantics the PR 5 membership layer pins.

**Multi-tenant LoRA multiplexing** (docs/serving.md §multi-tenant) —
with an :class:`~byteps_tpu.serve.adapter_pool.AdapterPool` attached,
one replica serves MANY fine-tuned variants of its base model:
adapter-tagged requests pin their adapter's pool slot at admission
(all-or-nothing with the KV blocks), single-request forwards (chunked
prefill, spec verify) run on the tenant's grafted tree, and the packed
decode step gathers each row's A/B slabs by slot inside one jitted
program (the S-LoRA/Punica shape; ``ops/segmented_lora.py``) — every
tenant's greedy tokens bit-identical to a solo run on its grafted
params. Per-tenant KV quotas make a flooding tenant preempt ITS OWN
youngest runs and queue behind its own wall instead of starving
siblings; ``serve.tenant<T>.*`` metrics carry the per-tenant view.

**Disaggregation** (docs/serving.md §disaggregation) — a Scheduler
can be a dedicated ``role="prefill"`` or ``role="decode"`` replica:
prefill replicas run chunked prefill only, stream committed KV blocks
to their decode target over the ``serve/kv_wire.py`` transport as each
chunk fills them, and park finished requests for the router to
migrate; decode replicas adopt migrated requests through the
refcount/radix path (``submit_migrated``), so prefix sharing survives
the wire. The same transport gives migrate-don't-evict preemption
(``extract_for_migration``): a pressured victim's blocks move to a
sibling instead of being freed and recomputed.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.config import get_config
from byteps_tpu.common.faults import FaultPlan, WorkerKilledError, plan_from_env
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.models.generate import gpt_apply_cached, init_cache
from byteps_tpu.models.gpt import GPTConfig
from byteps_tpu.models.speculative import _verify_commit
from byteps_tpu.serve.paged_cache import (
    PagedKVCache,
    PoolExhausted,
    make_paged_decode_fn,
    make_paged_prefill_fn,
)

log = get_logger("serve.scheduler")

# global replica instance sequence for per-replica gauge series (the
# PR 6 scheduler.s<N> pattern — replica_id is caller-chosen and two
# fresh replicas may both say 0)
_REPLICA_SEQ = itertools.count()


@functools.lru_cache(maxsize=16)
def _make_pick_fn(vocab_size: int):
    """Process-wide jitted token pick, one per vocab size (jit's own
    shape cache handles the batch dimension). The greedy/sampled select
    arm IS generate.make_pick — the serve layer only adds per-row keys
    (fold_in by absolute position, invariant to batch packing), so the
    bit-exact greedy contract can never drift from make_generate_fn's.
    lru-cached like the paged-step factories: fresh replicas (bench
    reps, failover respawns) must reuse the compiled programs."""
    from byteps_tpu.models.generate import make_pick, make_truncate

    pick1 = make_pick(make_truncate(None, None, vocab_size))

    def pick(logits, seeds, pos, temps):
        keys = jax.vmap(
            lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p))(
                seeds, pos)
        return jax.vmap(lambda l, k, t: pick1(l[None], k, t)[0])(
            logits, keys, temps)

    return jax.jit(pick)


@dataclasses.dataclass
class SpecPolicy:
    """Per-request speculative decoding policy.

    ``kind="lookup"`` — prompt-lookup drafting (model-free): propose
    the ``spec_len`` tokens that followed the most recent earlier
    occurrence of the current bigram in the committed context (the
    ``make_lookup_generate_fn`` trick, host-side).
    ``kind="draft"`` — a draft MODEL (any GPT-family config sharing
    the target's vocab): ``spec_len`` greedy draft steps against a
    per-request dense draft cache, the
    ``make_speculative_generate_fn`` proposal semantics in-loop.
    Greedy-only (verification compares greedy argmax)."""

    kind: str = "lookup"
    spec_len: int = 0              # 0 = BYTEPS_SERVE_SPEC_LEN
    draft_params: Any = None
    draft_cfg: Optional[GPTConfig] = None

    def __post_init__(self):
        if self.kind not in ("lookup", "draft"):
            raise ValueError(f"unknown spec kind {self.kind!r}")
        if self.kind == "draft" and (self.draft_params is None
                                     or self.draft_cfg is None):
            raise ValueError("draft policy needs draft_params + draft_cfg")


@dataclasses.dataclass
class Request:
    """One generation request. ``prompt`` is a 1-D int32 token array;
    the scheduler emits up to ``max_new`` tokens (stopping early at
    ``eos_id`` when set). ``temperature == 0`` is the bit-pinned greedy
    path; sampled requests use per-request ``seed``."""

    rid: Any
    prompt: np.ndarray
    max_new: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    spec: Optional[SpecPolicy] = None
    arrival_s: float = 0.0
    # multi-tenant multiplexing (docs/serving.md §multi-tenant):
    # ``tenant`` keys fair queuing, KV quotas, and the per-tenant
    # metric series (None = untenanted legacy traffic, exempt from
    # quotas); ``adapter`` names a LoRA adapter registered in the
    # replica's AdapterPool — the request decodes through that
    # adapter's pool slot, bit-identical to a solo run on its grafted
    # params (None = the bare base model).
    tenant: Any = None
    adapter: Any = None


class _Run:
    """Scheduler-internal per-request state."""

    __slots__ = ("req", "full_input", "emitted", "pending", "cache_len",
                 "prefill_done", "state", "t_submit", "t_origin", "t_admit",
                 "t_first", "t_last", "preemptions", "spec_rounds",
                 "draft_cache", "tok_s", "idx_seq", "streamed", "tenant",
                 "slot")

    def __init__(self, req: Request, resume_tokens: List[int],
                 t_submit: float):
        self.req = req
        self.emitted: List[int] = list(resume_tokens)
        self.full_input = np.concatenate(
            [np.asarray(req.prompt, np.int32),
             np.asarray(self.emitted, np.int32)])
        self.pending: Optional[int] = None
        self.cache_len = 0
        self.prefill_done = 0
        self.state = "queued"
        self.t_submit = t_submit
        # latency origin: the request's ARRIVAL, not the (possibly
        # earlier) submit call — offered-load benches submit ahead of
        # time and TTFT must not credit queue-building as waiting
        self.t_origin = max(t_submit, req.arrival_s)
        self.t_admit = 0.0
        self.t_first: Optional[float] = None
        self.t_last = self.t_origin
        self.preemptions = 0
        self.spec_rounds = 0
        self.draft_cache = None
        self.tok_s: List[float] = []
        # prefix-index version this run last matched against: the
        # mid-prefill re-match is skipped until a new commit bumps it
        self.idx_seq = -1
        # full blocks already streamed to the decode target (prefill
        # replicas only): the stream callback sends [streamed, full)
        # after each chunk, so each block crosses the wire exactly once
        self.streamed = 0
        self.tenant = req.tenant
        # adapter-pool slot held while admitted (None = base model or
        # not admitted); acquired at admission, released on finish,
        # preempt, drain, and migration — mirrors the KV block table
        self.slot: Optional[int] = None


class NoProgressError(RuntimeError):
    """The drain loop spun without any request advancing — a scheduler
    bug or an impossible pool configuration; raised instead of hanging
    (the serve twin of the PR 5 StallError philosophy)."""


class Scheduler:
    """One serving replica: continuous admission, chunked prefill,
    packed decode, preemption, per-request speculation. See the module
    docstring for the iteration anatomy and docs/serving.md for the
    operator view."""

    def __init__(self, params, cfg: GPTConfig, *,
                 tp_axis: Optional[str] = None,
                 max_batch: Optional[int] = None,
                 block_size: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 quant_cache: Optional[bool] = None,
                 prefix_cache: Optional[bool] = None,
                 fault_plan: Optional[FaultPlan] = None,
                 replica_id: int = 0,
                 role: str = "both",
                 adapter_pool=None,
                 tenant_quota_blocks: Optional[int] = None,
                 fair_queue: Optional[bool] = None,
                 tenant_weights: Optional[Dict[Any, float]] = None,
                 clock=time.monotonic):
        """``role`` (disaggregation, docs/serving.md §disaggregation):
        ``"both"`` — the colocated default, admission through decode on
        one replica. ``"prefill"`` — a dedicated prefill replica: runs
        chunked prefill only, streams committed KV blocks to its decode
        target as they fill (router-installed ``stream_blocks``
        callback), parks a finished request in the ``handoff`` state
        (first token already committed — TTFT is stamped HERE) for the
        router to migrate, and never touches the packed decode step.
        ``"decode"`` — receives migrated requests (``submit_migrated``)
        and decodes; it can still prefill (short prompts routed
        directly, recompute-on-resume fallbacks), but in the pure
        migration flow it never builds a prefill chunk program. The
        jit factories are built LAZILY per role so a dedicated replica
        never compiles — or holds HBM for — the other role's step."""
        c = get_config()
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown scheduler role {role!r} "
                             "(expected both|prefill|decode)")
        self.params = params
        self.cfg = cfg
        self.tp_axis = tp_axis
        self.role = role
        self.replica_id = replica_id
        self.max_batch = max_batch if max_batch is not None \
            else c.serve_max_batch
        self.prefill_chunk = prefill_chunk if prefill_chunk is not None \
            else c.serve_prefill_chunk
        self.default_spec_len = c.serve_spec_len
        self._prefix_on = prefix_cache if prefix_cache is not None \
            else c.serve_prefix_cache
        # multi-tenant plane (docs/serving.md §multi-tenant): the
        # AdapterPool is caller-built and caller-shared (one pool per
        # replica; the router wires it), quotas/fair-queue default from
        # config so env knobs reach bench/tests
        self.adapter_pool = adapter_pool
        self._quota = tenant_quota_blocks if tenant_quota_blocks \
            is not None else c.serve_tenant_quota_blocks
        if self._quota < 0:
            raise ValueError(
                f"tenant_quota_blocks must be >= 0; got {self._quota}")
        self._fair = fair_queue if fair_queue is not None \
            else c.serve_fair_queue
        self._weights: Dict[Any, float] = dict(tenant_weights or {})
        for t, w in self._weights.items():
            if w <= 0:
                raise ValueError(
                    f"tenant weight must be > 0; got {w} for {t!r}")
        # DWFQ deficit credits, one per tenant with waiting work; the
        # max over active tenants is renormalized to 0 after every
        # admission so an idle tenant can't bank credit while away
        self._credits: Dict[Any, float] = {}
        self._tm: Dict[Any, Dict[str, Any]] = {}
        quant = quant_cache if quant_cache is not None \
            else c.serve_quant_cache
        bs = block_size if block_size is not None else c.serve_block_size
        nb = pool_blocks if pool_blocks is not None else c.serve_pool_blocks
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1; got {self.max_batch}")
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1; got {self.prefill_chunk}")
        if cfg.max_seq % bs != 0:
            log.warning(
                "serve: block_size %d does not divide max_seq %d — the "
                "gathered views carry a zero tail past max_seq (correct, "
                "slightly wasteful)", bs, cfg.max_seq)
        kv_loc = params["blocks"][0]["wk"].shape[-1] // cfg.head_dim
        self.cache = PagedKVCache(cfg, block_size=bs, pool_blocks=nb,
                                  max_batch=self.max_batch, h_loc=kv_loc,
                                  quant=quant)
        # the packed decode step is built LAZILY (first decode touch):
        # a prefill-only replica must never trace/compile it — that is
        # the dedicated replica's cold-start and HBM win, asserted in
        # tests/test_serve_disagg.py
        self._decode_fn = None
        self._pick = _make_pick_fn(cfg.vocab_size)
        self._draft_steps: Dict[int, Any] = {}
        self._plan = fault_plan if fault_plan is not None \
            else plan_from_env(worker_id=replica_id)
        self._dead = False
        self._clock = clock
        # disaggregation hooks (router-installed; None = colocated):
        # stream_blocks(sched, run, {block_idx: BlockPayload}) pushes
        # newly committed prefill blocks onto the migration wire;
        # migrate_out(sched, run) -> bool moves a preemption victim's
        # blocks to a sibling instead of evicting (True = extracted)
        self.stream_blocks = None
        self.migrate_out = None
        # wire-delivered block payloads staged until adoption, keyed
        # (rid -> {block_idx: BlockPayload}); written by KVWire push
        # threads via ingest_block, drained on this thread at adoption
        self._staging: Dict[Any, Dict[int, Any]] = {}
        self._staging_lock = threading.Lock()
        self._kv_codec = None
        self._prefill_built = False
        self._waiting: deque = deque()
        self._running: List[_Run] = []
        self._runs: Dict[Any, _Run] = {}
        self.results: Dict[Any, Dict[str, Any]] = {}
        # admit a little past the decode-slot count so a finished
        # request's slot refills from a PREFILLED standby instead of
        # waiting a prompt's worth of prefill chunks with the batch
        # underfull (the pool pressure valve is preemption either way)
        self._admit_cap = self.max_batch + max(1, self.max_batch // 4)
        _reg = get_registry()
        self._m = {
            "admitted": _reg.counter("serve.admitted"),
            "completed": _reg.counter("serve.completed"),
            "preempted": _reg.counter("serve.preempted"),
            "resumed": _reg.counter("serve.resumed"),
            "prefill_tokens": _reg.counter("serve.prefill_tokens"),
            "decode_tokens": _reg.counter("serve.decode_tokens"),
            "spec_rounds": _reg.counter("serve.spec_rounds"),
            "spec_tokens": _reg.counter("serve.spec_tokens"),
            "prefix_hits": _reg.counter("serve.prefix_hits"),
            "prefix_misses": _reg.counter("serve.prefix_misses"),
            "prefix_saved": _reg.counter("serve.prefix_saved_tokens"),
            # migration plane (docs/observability.md): requests that
            # left/arrived over the KV wire, KV tokens that moved
            # instead of being recomputed, and the recompute bill the
            # evict path still charges — migrate-vs-recompute reads
            # straight off these two
            "migrated_out": _reg.counter("serve.migration.out_requests"),
            "migrated_in": _reg.counter("serve.migration.in_requests"),
            "migrated_tokens": _reg.counter("serve.migration.tokens"),
            "recompute_tokens": _reg.counter(
                "serve.migration.recompute_tokens"),
            "iterations": _reg.counter("serve.iterations"),
            "ttft_ms": _reg.histogram("serve.ttft_ms"),
            "token_ms": _reg.histogram("serve.token_ms"),
            "request_ms": _reg.histogram("serve.request_ms"),
            "batch_occupancy": _reg.histogram("serve.batch_occupancy"),
            # per-replica series (global instance sequence): two
            # replicas' queues must not mask each other
            "queue_depth": _reg.gauge(
                f"serve.r{next(_REPLICA_SEQ)}.queue_depth"),
        }

    # -- client surface -----------------------------------------------------
    def submit(self, req: Request,
               resume_tokens: Optional[List[int]] = None) -> None:
        """Enqueue a request (idempotence is the caller's problem: rids
        must be unique per replica lifetime). ``resume_tokens`` is the
        router's failover path — tokens already committed on a dead
        replica, kept verbatim and recomputed into fresh KV."""
        prompt = np.asarray(req.prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if req.max_new < 1:
            raise ValueError(f"max_new must be >= 1; got {req.max_new}")
        spec_k = 0
        if req.spec is not None:
            if req.temperature != 0.0:
                raise ValueError(
                    "speculative policies are greedy-only "
                    "(verification compares greedy argmax)")
            spec_k = req.spec.spec_len or self.default_spec_len
            if spec_k < 1:
                raise ValueError(
                    f"effective spec_len must be >= 1; got {spec_k} "
                    "(policy spec_len or BYTEPS_SERVE_SPEC_LEN)")
        total = prompt.size + req.max_new + spec_k
        if total > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({req.max_new})"
                + (f" + spec_len ({spec_k})" if spec_k else "")
                + f" exceeds cfg.max_seq ({self.cfg.max_seq})")
        if self.cache.blocks_for(total) > self.cache.pool_blocks - 1:
            raise ValueError(
                f"request needs {self.cache.blocks_for(total)} KV blocks "
                f"but the pool holds {self.cache.pool_blocks - 1} — it "
                "could never be scheduled")
        if (self._quota and req.tenant is not None
                and self.cache.blocks_for(total) > self._quota):
            raise ValueError(
                f"request needs {self.cache.blocks_for(total)} KV blocks "
                f"but tenant {req.tenant!r}'s quota is {self._quota} — "
                "it could never run under the quota")
        if req.adapter is not None:
            if self.adapter_pool is None:
                raise ValueError(
                    f"request names adapter {req.adapter!r} but this "
                    "replica has no adapter pool "
                    "(BYTEPS_SERVE_ADAPTER_SLOTS=0)")
            if not self.adapter_pool.registered(req.adapter):
                raise ValueError(
                    f"adapter {req.adapter!r} is not registered in the "
                    "pool")
        if req.rid in self._runs:
            raise ValueError(f"duplicate request id {req.rid!r}")
        if req.adapter is not None:
            # prefetch-on-admission: warm a FREE slot now (never evicts
            # a cached sibling) so the admission-time acquire is a
            # residency hit instead of a host->device load on the
            # critical path
            self.adapter_pool.prefetch(req.adapter)
        run = _Run(req, list(resume_tokens or []), self._clock())
        self._runs[req.rid] = run
        if resume_tokens:
            self._waiting.appendleft(run)   # failover work is oldest
            self._m["resumed"].inc()
        else:
            self._waiting.append(run)
        self._m["queue_depth"].set(len(self._waiting))

    @property
    def load(self) -> int:
        """Routing weight: queued + running requests."""
        return len(self._waiting) + len(self._running)

    @property
    def finished(self) -> bool:
        return not self._waiting and not self._running

    @property
    def dead(self) -> bool:
        return self._dead

    def result(self, rid) -> Dict[str, Any]:
        return self.results[rid]

    def drain_incomplete(self):
        """Pop every unfinished request (queued AND running), freeing
        their blocks; returns ``[(Request, emitted_tokens), ...]`` for
        the router to re-queue on a survivor. Completed results stay
        readable — they were already delivered."""
        out = []
        for run in list(self._running):
            self.cache.release(run.req.rid)
            self._release_adapter(run)
            out.append((run.req, list(run.emitted)))
            del self._runs[run.req.rid]
        self._running.clear()
        while self._waiting:
            run = self._waiting.popleft()
            out.append((run.req, list(run.emitted)))
            del self._runs[run.req.rid]
        self._m["queue_depth"].set(0)
        return out

    # -- disaggregation / migration surface (docs/serving.md) ---------------
    def ingest_block(self, rid, block_idx: int, buf) -> None:
        """KV-wire delivery (called on KVWire PUSH threads): decode the
        frame (CRC verified — corruption raises back into the wire's
        stage retry) and stage the payload until adoption. Idempotent
        per (rid, block): a retried delivery overwrites the identical
        payload. Device state is never touched here — adoption scatters
        on the scheduler's own thread."""
        payload = self.kv_codec.decode(buf)
        with self._staging_lock:
            self._staging.setdefault(rid, {})[int(block_idx)] = payload

    def staged_blocks(self, rid) -> set:
        with self._staging_lock:
            return set(self._staging.get(rid, ()))

    def pop_staged(self, rid) -> Dict[int, Any]:
        with self._staging_lock:
            return self._staging.pop(rid, {})

    def drop_staged(self, rid) -> None:
        with self._staging_lock:
            self._staging.pop(rid, None)

    def _cut_ticket(self, run: _Run, nb: int, payloads):
        from byteps_tpu.serve.kv_wire import MigrationTicket

        return MigrationTicket(
            req=run.req, emitted=list(run.emitted), pending=run.pending,
            cache_len=run.cache_len,
            full_input=np.concatenate(
                [np.asarray(run.req.prompt, np.int32),
                 np.asarray(run.emitted, np.int32)]),
            n_blocks=nb, payloads=payloads, t_origin=run.t_origin,
            t_submit=run.t_submit, t_first=run.t_first,
            tok_s=list(run.tok_s), preemptions=run.preemptions,
            spec_rounds=run.spec_rounds)

    def pop_handoffs(self):
        """Prefill replicas: cut a :class:`MigrationTicket` for every
        request whose prefill (and first token) completed. The ticket
        carries the blocks NOT yet streamed (the partial tail); the run
        parks in the ``migrating`` state — blocks pinned — until the
        router confirms adoption via :meth:`finish_handoff` (so a
        mid-migration failure can always re-stream from live pages)."""
        out = []
        for run in self._running:
            if run.state != "handoff":
                continue
            nb = self.cache.blocks_for(run.cache_len)
            out.append(self._cut_ticket(
                run, nb,
                self.cache.snapshot_blocks(run.req.rid, run.streamed,
                                           nb)))
            run.state = "migrating"
        return out

    def finish_handoff(self, rid) -> None:
        """Adoption confirmed on the decode target: release the parked
        run's blocks (shared prefix pages stay resident for the next
        sharer — the refcount path, as everywhere)."""
        run = self._runs.pop(rid)
        self._running.remove(run)
        self.cache.release(rid)
        self._release_adapter(run)

    def extract_for_migration(self, rid):
        """Migrate-don't-evict: pull a decoding victim OUT of this
        replica — snapshot ALL its committed blocks, free them, and
        return the ticket the router ships to a sibling. Unlike
        :meth:`_preempt` nothing is recomputed: the tokens move, the
        pool pressure drops NOW."""
        run = self._runs.pop(rid)
        self._running.remove(run)
        nb = self.cache.blocks_for(run.cache_len)
        ticket = self._cut_ticket(
            run, nb, self.cache.snapshot_blocks(rid, 0, nb))
        self.cache.release(rid)
        self._release_adapter(run)
        run.state = "migrated"
        self._m["migrated_out"].inc()
        get_flight_recorder().record_event(
            "serve.migrate_out",
            {"replica": self.replica_id, "rid": str(rid),
             "blocks": nb, "tokens": run.cache_len})
        return ticket

    def submit_migrated(self, ticket, payloads) -> bool:
        """Adopt a migrated request: its KV blocks (delivered over the
        wire into ``payloads``) enter THIS pool through the refcount/
        radix path — leading blocks the local index already holds are
        shared instead of duplicated (prefix sharing survives
        migration), the rest scatter bit-exact, and the whole context
        is committed to the index so later sharers (and this request's
        own preemption resume) hit it. Returns False — allocating
        nothing — when the pool cannot fit the request even after
        preemption (the router then falls back to recompute-on-resume
        via a plain ``submit``)."""
        req = ticket.req
        rid = req.rid
        if rid in self._runs:
            raise ValueError(f"duplicate request id {rid!r}")
        if req.adapter is not None and (
                self.adapter_pool is None
                or not self.adapter_pool.registered(req.adapter)):
            raise ValueError(
                f"migrated request {rid!r} names adapter {req.adapter!r} "
                "but this replica's pool does not hold it — the router "
                "must register every adapter on every decode-capable "
                "replica")
        missing = [bi for bi in range(ticket.n_blocks)
                   if bi not in payloads]
        if missing:
            raise ValueError(
                f"migration for {rid!r} is missing block(s) {missing}")
        run = _Run(req, list(ticket.emitted),
                   ticket.t_submit or self._clock())
        ctx = run.full_input           # prompt + emitted == rows [0, len)
        self.cache.register(rid)
        hit_blocks: List[int] = []
        if self._prefix_on:
            hit_blocks, hit_tokens = self.cache.match_prefix(
                ctx[:ticket.cache_len], full_blocks_only=True)
            if hit_blocks:
                self.cache.adopt_prefix(rid, hit_blocks)
                self._m["prefix_hits"].inc()
                self._m["prefix_saved"].inc(hit_tokens)
        hit_n = len(hit_blocks)
        while True:
            try:
                self.cache.ensure(rid, ticket.cache_len + 1)
                break
            except PoolExhausted:
                victim = None
                for cand in reversed(self._running):
                    if cand.state in ("prefill", "decode"):
                        victim = cand
                        break
                if victim is None:
                    # cannot fit even with the pool drained: roll back
                    # losslessly; the router recomputes instead
                    self.cache.release(rid)
                    return False
                if (self.migrate_out is not None
                        and victim.state == "decode"
                        and victim.req.spec is None
                        and self.migrate_out(self, victim)):
                    continue
                self._preempt(victim)
        if req.adapter is not None:
            try:
                run.slot = self.adapter_pool.acquire(req.adapter, rid)
            except PoolExhausted:
                # every adapter slot is pinned by live requests: roll
                # back losslessly, the router falls back to recompute
                # (or a sibling) exactly like the block-fit failure
                self.cache.release(rid)
                return False
        row = self.cache.table_row(rid)
        self.cache.write_payloads(
            [int(b) for b in row[hit_n:ticket.n_blocks]],
            [payloads[bi] for bi in range(hit_n, ticket.n_blocks)])
        if self._prefix_on:
            self.cache.commit_prefix(rid, ctx, ticket.cache_len)
        run.cache_len = ticket.cache_len
        run.prefill_done = ticket.cache_len
        run.pending = ticket.pending
        run.t_origin = ticket.t_origin
        run.t_first = ticket.t_first
        run.t_last = ticket.tok_s[-1] if ticket.tok_s else ticket.t_origin
        run.tok_s = list(ticket.tok_s)
        run.preemptions = ticket.preemptions
        run.spec_rounds = ticket.spec_rounds
        run.state = "decode"
        if req.spec is not None and req.spec.kind == "draft":
            # rebuild the per-request draft cache over everything but
            # the pending token (the draft proposes FROM pending) —
            # drafts only move speed, never content, so the rebuild
            # cannot touch exactness
            self._build_draft_cache(run, tokens=ctx[:-1])
        self._runs[rid] = run
        self._running.append(run)
        self._m["migrated_in"].inc()
        self._m["migrated_tokens"].inc(ticket.cache_len)
        get_flight_recorder().record_event(
            "serve.migrate_in",
            {"replica": self.replica_id, "rid": str(rid),
             "blocks": ticket.n_blocks, "shared": hit_n,
             "tokens": ticket.cache_len})
        return True

    # -- jit caches ---------------------------------------------------------
    def _prefill_fn(self, C: int, with_readout: bool = True):
        # the factory is lru-cached process-wide — every replica shares
        # one jit wrapper per (cfg, block_size, C, readout)
        self._prefill_built = True
        return make_paged_prefill_fn(self.cfg, self.cache.block_size, C,
                                     self.tp_axis, with_readout)

    def _decode_step(self):
        """The packed decode step, built on first decode touch. A
        prefill-only replica must never get here — reaching it would
        mean the role split leaked decode work onto the prefill tier
        (and would silently re-grow its cold-start/HBM bill)."""
        if self._decode_fn is None:
            if self.role == "prefill":
                raise RuntimeError(
                    "prefill-only replica asked for the packed decode "
                    "step — the router's role split is broken")
            lora_sig = None
            if self.adapter_pool is not None:
                # (targets, rank bucket, n_slots) joins the factory's
                # lru key: two replicas with different pool shapes get
                # different compiled steps instead of silently
                # retracing each other's per iteration (the compile-
                # count pin in tests/test_serve_multitenant.py)
                ap = self.adapter_pool
                lora_sig = (tuple(ap.targets), ap.rank_bucket,
                            ap.n_slots)
            self._decode_fn = make_paged_decode_fn(
                self.cfg, self.cache.block_size, self.tp_axis, lora_sig)
        return self._decode_fn

    def _params_for(self, run: _Run):
        """The parameter tree a single-request forward (chunked
        prefill, spec verify) runs on: the tenant's grafted tree —
        built from the pool's canonical padded host slabs and cached
        per adapter — when the request carries one, else the bare
        base. Grafting from the SAME rank-bucket-padded slabs the
        packed decode gathers is what keeps prefill logits, packed
        decode logits, and the solo baseline bit-identical."""
        if run.req.adapter is None:
            return self.params
        return self.adapter_pool.graft(self.params, run.req.adapter)

    @property
    def kv_codec(self):
        """This replica's KV-block wire codec (lazy; both ends of a
        migration must agree — KVBlockCodec.decode validates)."""
        if self._kv_codec is None:
            from byteps_tpu.serve.kv_wire import KVBlockCodec

            self._kv_codec = KVBlockCodec.from_pool(self.cache)
        return self._kv_codec

    def _width(self, rid) -> int:
        """Power-of-two bucket of the request's live table: the jitted
        steps retrace once per bucket instead of once per length, and a
        short request never pays a max_seq-wide gather."""
        n = self.cache.table_len(rid)
        w = 1
        while w < n:
            w <<= 1
        return min(w, self.cache.blocks_per_req)


    def _draft_step(self, draft_cfg: GPTConfig):
        key = id(draft_cfg)
        fn = self._draft_steps.get(key)
        if fn is None:
            fn = jax.jit(_make_draft_apply(draft_cfg, self.tp_axis))
            self._draft_steps[key] = fn
        return fn

    # -- multi-tenant policy (docs/serving.md §multi-tenant) ----------------
    def _tenant_m(self, tenant) -> Dict[str, Any]:
        """Lazy per-tenant metric family (``serve.tenant<T>.*``) —
        only tenanted requests pay the extra series, so legacy
        single-model traffic keeps its historical metric surface."""
        m = self._tm.get(tenant)
        if m is None:
            _reg = get_registry()
            p = f"serve.tenant{tenant}"
            m = {
                "admitted": _reg.counter(f"{p}.admitted"),
                "tokens": _reg.counter(f"{p}.tokens"),
                "quota_hits": _reg.counter(f"{p}.quota_hits"),
                "ttft_ms": _reg.histogram(f"{p}.ttft_ms"),
            }
            self._tm[tenant] = m
        return m

    def _tenant_usage(self, tenant) -> int:
        """KV blocks the tenant's admitted requests hold right now
        (table lengths — shared prefix pages charge every sharer,
        which is conservative and keeps the accounting O(running))."""
        return sum(self.cache.table_len(r.req.rid)
                   for r in self._running if r.tenant == tenant)

    def _quota_blocked(self, run: _Run) -> bool:
        """Would admitting ``run`` push its tenant past the KV quota?
        Untenanted requests are exempt (the quota is tenant isolation,
        not a pool limit — the pool has its own)."""
        if not self._quota or run.tenant is None:
            return False
        L = len(run.full_input)
        reserve = L if self.role == "prefill" else L + 1
        return (self._tenant_usage(run.tenant)
                + self.cache.blocks_for(reserve) > self._quota)

    def _next_admission(self, now: float, deferred=()) -> Optional[_Run]:
        """The admission selector. Candidates are each tenant's OLDEST
        waiting request (per-tenant order is always FIFO) that has
        arrived, is not quota-blocked, and whose tenant is not
        fault-deferred — a blocked tenant is skipped WITHOUT
        head-blocking its siblings. With fair queuing off, or when
        every candidate is the same (possibly None) tenant, the
        earliest queue position wins — exactly the historical FIFO.
        With it on, the max-credit tenant wins (deficit-weighted fair
        queuing; ties break to the earliest queue position)."""
        seen = set()
        cands = []                       # (queue position, run)
        for pos, run in enumerate(self._waiting):
            t = run.tenant
            if t in seen:
                continue
            seen.add(t)                  # younger same-tenant work waits
            if run.req.arrival_s > now:
                continue
            if t is not None and str(t) in deferred:
                continue
            if self._quota_blocked(run):
                self._tenant_m(t)["quota_hits"].inc()
                continue
            cands.append((pos, run))
        if not cands:
            return None
        if not self._fair:
            return min(cands)[1]
        for _, run in cands:
            self._credits.setdefault(run.tenant, 0.0)
        return max(cands, key=lambda pr: (self._credits[pr[1].tenant],
                                          -pr[0]))[1]

    def _charge_admission(self, run: _Run, reserve: int) -> None:
        """DWFQ accounting for one successful admission: the winner's
        tenant pays its block reservation over its weight, then the
        max credit over tenants that still have waiting work (plus the
        payer) renormalizes to 0 — a tenant idle for an hour returns
        at credit 0, equal to the current leaders, instead of having
        banked an hour of unfairness."""
        if not self._fair:
            return
        t = run.tenant
        w = float(self._weights.get(t, 1.0))
        self._credits[t] = (self._credits.get(t, 0.0)
                            - self.cache.blocks_for(reserve) / w)
        active = {r.tenant for r in self._waiting}
        active.add(t)
        mx = max(self._credits.get(a, 0.0) for a in active)
        self._credits = {a: self._credits.get(a, 0.0) - mx
                         for a in active}

    def _release_adapter(self, run: _Run) -> None:
        """Unpin the run's adapter slot (idempotent). The adapter
        stays RESIDENT at refcount 0 — cached-but-idle, LRU — so the
        tenant's next request is a residency hit."""
        if run.slot is not None:
            self.adapter_pool.release(run.req.adapter, run.req.rid)
            run.slot = None

    # -- internals ----------------------------------------------------------
    def _commit_token(self, run: _Run, tok: int, now: float) -> None:
        """Append one generated token, stamp latencies, finish when the
        request is done (max_new reached or eos emitted)."""
        run.emitted.append(tok)
        run.pending = tok
        run.tok_s.append(now)
        if run.tenant is not None:
            self._tenant_m(run.tenant)["tokens"].inc()
        if run.t_first is None:
            run.t_first = now
            self._m["ttft_ms"].observe((now - run.t_origin) * 1e3)
            if run.tenant is not None:
                self._tenant_m(run.tenant)["ttft_ms"].observe(
                    (now - run.t_origin) * 1e3)
        else:
            self._m["token_ms"].observe((now - run.t_last) * 1e3)
        run.t_last = now
        if (len(run.emitted) >= run.req.max_new
                or (run.req.eos_id is not None
                    and tok == run.req.eos_id)):
            self._finish(run, now)

    def _finish(self, run: _Run, now: float) -> None:
        self.cache.release(run.req.rid)
        self._release_adapter(run)
        self._running.remove(run)
        # the run record is done — drop it so a long-lived replica's
        # memory tracks its LIVE load, not its lifetime request count
        # (results stay until the caller/router consumes them)
        del self._runs[run.req.rid]
        run.state = "done"
        prompt = np.asarray(run.req.prompt, np.int32).reshape(-1)
        emitted = np.asarray(run.emitted[:run.req.max_new], np.int32)
        self.results[run.req.rid] = {
            "tokens": np.concatenate([prompt, emitted]),
            "emitted": emitted,
            "ttft_s": (run.t_first - run.t_origin
                       if run.t_first is not None else None),
            "total_s": now - run.t_origin,
            "token_s": np.asarray(run.tok_s[:run.req.max_new]),
            "preemptions": run.preemptions,
            "spec_rounds": run.spec_rounds,
        }
        self._m["completed"].inc()
        self._m["request_ms"].observe((now - run.t_origin) * 1e3)

    def _preempt(self, run: _Run) -> None:
        """Evict ``run`` under pool pressure: free its blocks, keep its
        committed tokens, re-queue at the FRONT for recompute-on-resume
        (its next prefill input is prompt + emitted)."""
        # the recompute bill: every committed KV row thrown away here
        # must be re-prefilled on resume (the request's own prefix
        # commits may refund part of it if they survive the pressure
        # that caused this evict) — the migrate-vs-recompute headline's
        # "recompute" side (bench.py --mode serve, migrate leg)
        self._m["recompute_tokens"].inc(run.cache_len)
        self.cache.release(run.req.rid)
        self._release_adapter(run)
        run.state = "queued"
        run.preemptions += 1
        run.pending = None
        run.cache_len = 0
        run.prefill_done = 0
        run.streamed = 0
        run.draft_cache = None
        run.full_input = np.concatenate(
            [np.asarray(run.req.prompt, np.int32),
             np.asarray(run.emitted, np.int32)])
        self._running.remove(run)
        self._waiting.appendleft(run)
        self._m["preempted"].inc()
        self._m["queue_depth"].set(len(self._waiting))
        get_flight_recorder().record_event(
            "serve.preempt",
            {"replica": self.replica_id, "rid": str(run.req.rid),
             "emitted": len(run.emitted)})

    def _ensure_or_preempt(self, run: _Run, n_tokens: int,
                           write_lo: Optional[int] = None,
                           write_hi: Optional[int] = None) -> bool:
        """Grow ``run``'s block table to ``n_tokens`` — and, when a
        write span is given, CoW any shared page inside it — preempting
        the youngest admitted request as often as needed. Returns False
        when ``run`` itself became the victim (the caller skips it).
        The write span is belt-and-braces: scheduler writes only ever
        target fresh or admission-CoW'd private blocks, but a shared
        page must NEVER be scattered into, so the invariant is enforced
        here rather than assumed."""
        # per-tenant KV quota: growth past the tenant's cap preempts
        # the OFFENDER's own youngest run — never a sibling's — so a
        # noisy tenant pays its own recompute bill. Terminates: each
        # preempt frees at least one same-tenant table, and submit()
        # guarantees a single request fits the quota alone.
        if self._quota and run.tenant is not None:
            while True:
                need = (self.cache.blocks_for(n_tokens)
                        - self.cache.table_len(run.req.rid))
                if (need <= 0 or self._tenant_usage(run.tenant) + need
                        <= self._quota):
                    break
                self._tenant_m(run.tenant)["quota_hits"].inc()
                victim = None
                for cand in reversed(self._running):
                    if (cand.tenant == run.tenant and cand is not run
                            and cand.state in ("prefill", "decode")):
                        victim = cand
                        break
                if victim is None:
                    victim = run             # its own youngest is itself
                self._preempt(victim)
                if victim is run:
                    return False
        while True:
            try:
                self.cache.ensure(run.req.rid, n_tokens)
                if write_lo is not None:
                    self.cache.ensure_writable(run.req.rid, write_lo,
                                               write_hi)
                return True
            except PoolExhausted:
                victim = None
                for cand in reversed(self._running):
                    if cand.state in ("prefill", "decode"):
                        victim = cand
                        break
                if victim is None:
                    raise RuntimeError(
                        "KV pool exhausted with no preemptible request — "
                        "pool sizing bug (submit() validates single-"
                        "request fit)")
                # migrate-don't-evict: a decoding victim's committed
                # blocks can MOVE to a sibling replica over the KV wire
                # instead of being freed and recomputed — the router's
                # hook extracts it (blocks freed here, adopted there).
                # The victim may be the REQUESTER itself (symmetric
                # pressure grows every table in lockstep, so the
                # youngest decoder is usually the one asking): that is
                # cross-replica load shedding, and the caller's False
                # return already means "this run is no longer mine".
                # Mid-prefill and spec victims take the classic evict
                # path (their partial/draft state doesn't travel).
                if (self.migrate_out is not None
                        and victim.state == "decode"
                        and victim.req.spec is None
                        and self.migrate_out(self, victim)):
                    if victim is run:
                        return False
                    continue
                self._preempt(victim)
                if victim is run:
                    return False

    # -- speculative lane ---------------------------------------------------
    def _lookup_propose(self, run: _Run, K: int) -> np.ndarray:
        """Host-side prompt-lookup draft: the continuation of the most
        recent earlier occurrence of the committed context's last
        bigram (speculative.make_lookup_generate_fn's propose(), numpy).
        No match → junk proposals (they just accept 0)."""
        ctx = np.concatenate(
            [np.asarray(run.req.prompt, np.int32),
             np.asarray(run.emitted, np.int32)])
        n = ctx.size
        if n < 2:
            return np.zeros(K, np.int32)
        prev, last = int(ctx[-2]), int(ctx[-1])
        match = np.flatnonzero(
            (ctx[:-1] == prev) & (ctx[1:] == last))
        match = match[match <= n - 3]   # strictly earlier than the bigram
        if match.size == 0:
            return np.zeros(K, np.int32)
        p = int(match[-1])
        idx = np.clip(p + 2 + np.arange(K), 0, n - 1)
        return ctx[idx].astype(np.int32)

    def _draft_propose(self, run: _Run, K: int):
        """K greedy draft-model steps (make_speculative_generate_fn's
        dstep scan, in-loop with a per-request dense draft cache).
        Returns ``(proposals (K,), draft fill level before the round)``
        — the rewind anchor."""
        pol = run.req.spec
        step = self._draft_step(pol.draft_cfg)
        dc = run.draft_cache
        len0 = int(dc.length)
        tok = run.pending
        d = []
        for _ in range(K):
            lg, dc = step(pol.draft_params,
                          jnp.asarray([[tok]], jnp.int32), dc)
            tok = int(np.argmax(np.asarray(lg)[0, -1]))
            d.append(tok)
        run.draft_cache = dc
        return np.asarray(d, np.int32), len0

    def _spec_round(self, run: _Run, now: float) -> None:
        """One propose→verify→commit round for a spec-policy request.
        Exactness rides on speculative._verify_commit — the identical
        accept/commit arithmetic of make_speculative_generate_fn."""
        pol = run.req.spec
        K = pol.spec_len or self.default_spec_len
        pos0 = run.cache_len
        if not self._ensure_or_preempt(run, pos0 + K, pos0, pos0 + K):
            return
        draft_len0 = None
        if pol.kind == "draft":
            d, draft_len0 = self._draft_propose(run, K)
        else:
            d = self._lookup_propose(run, K)
        feed = np.concatenate([[run.pending], d[:K - 1]]).astype(np.int32)
        logits, self.cache.state = self._prefill_fn(K)(
            self._params_for(run), self.cache.state,
            jnp.asarray(feed)[None],
            jnp.int32(pos0),
            jnp.asarray(self.cache.table_row(run.req.rid,
                                             self._width(run.req.rid))))
        out = jnp.zeros((1, K + 1), jnp.int32)
        out, n_emitted, next_tok, committed = _verify_commit(
            jnp.asarray(d)[None], logits, out, jnp.int32(0), K)
        n = int(n_emitted)
        block = np.asarray(out)[0, :n]
        committed = int(committed)
        run.cache_len = pos0 + committed
        if pol.kind == "draft":
            run.draft_cache = run.draft_cache._replace(
                length=jnp.asarray(draft_len0 + committed, jnp.int32))
        run.spec_rounds += 1
        self._m["spec_rounds"].inc()
        self._m["spec_tokens"].inc(n)
        # the round emits [d_1..d_m (, correction)] then the NEXT round's
        # pending token; commit them one by one so eos/max_new stop
        # mid-block exactly like the dense sampler's output truncation
        for t in block:
            if run.state != "decode":
                return                       # finished mid-block
            self._commit_token(run, int(t), now)
        if run.state == "decode":
            run.pending = int(np.asarray(next_tok)[0])

    # -- the iteration ------------------------------------------------------
    def step(self) -> bool:
        """One scheduler iteration; returns True when any request made
        progress (admission, a prefill chunk, a spec round, or at least
        one decoded token)."""
        if self._dead:
            raise WorkerKilledError(
                f"serve replica {self.replica_id} is dead")
        if self._plan is not None:
            inj = self._plan.intercept("serve", -1)
            if inj is not None:
                if inj.kind == "kill":
                    self._dead = True
                    get_flight_recorder().record_event(
                        "serve.replica_killed",
                        {"replica": self.replica_id,
                         "step": self._plan.step})
                    raise WorkerKilledError(
                        f"serve replica {self.replica_id} killed by fault "
                        f"plan at op {self._plan.step}")
                if inj.kind == "hang":
                    time.sleep(inj.rule.latency_ms / 1e3)
        self._m["iterations"].inc()
        now = self._clock()
        progress = False

        # tenant-scoped fault rules (tenant<T>:slow|hang): one
        # attributed intercept per waiting tenant per iteration —
        # made ONLY when the plan carries tenant rules, so tenant-free
        # specs keep their historical step-window alignment. A slow
        # rule sleeps inline inside intercept (the tenant's admission
        # pays the latency); a hang defers the tenant's admission for
        # the iteration without sleeping.
        deferred: set = set()
        if (self._plan is not None and self._plan.has_tenant_rules()
                and self._waiting):
            for t in sorted({str(r.tenant) for r in self._waiting
                             if r.tenant is not None}):
                inj = self._plan.intercept("serve", -1, tenant=t)
                if inj is not None and inj.kind == "hang":
                    deferred.add(t)

        # 1. admission (per-tenant FIFO in arrival order, DWFQ across
        # tenants when fair queuing is on — single-tenant traffic is
        # exactly the historical global FIFO; head-blocked on blocks so
        # latecomers can't starve the selected request, but a quota- or
        # fault-blocked tenant is skipped, never head-blocking
        # siblings)
        while self._waiting and len(self._running) < self._admit_cap:
            run = self._next_admission(now, deferred)
            if run is None:
                break
            L = len(run.full_input)
            # a prefill-only replica writes exactly L rows (the decode
            # slot L+1 belongs to the decode target's pool)
            reserve = L if self.role == "prefill" else L + 1
            hit_blocks: List[int] = []
            hit_tokens = 0
            if self._prefix_on:
                # consult the radix index — capped at L-1 tokens so the
                # final prefill chunk always runs (its last-position
                # logits yield the first generated token / TTFT commit)
                hit_blocks, hit_tokens = self.cache.match_prefix(
                    run.full_input[:L - 1])
                run.idx_seq = self.cache.index_version
            partial = 1 if hit_tokens % self.cache.block_size else 0
            need = (self.cache.blocks_for(reserve) - len(hit_blocks)
                    + partial)
            if partial and need > (self.cache.free_blocks
                                   + self.cache.reclaimable_blocks(
                                       exclude=hit_blocks)):
                # a partial-divergence hit costs one extra block (the
                # CoW copy) AND pins an otherwise-evictable page — on a
                # tight pool that can make admission infeasible where a
                # cold admission would fit, forever (nothing running to
                # free blocks). Drop the partial adoption; the
                # full-block hit alone is never worse than cold.
                hit_blocks = hit_blocks[:-1]
                hit_tokens -= hit_tokens % self.cache.block_size
                partial = 0
                need = self.cache.blocks_for(reserve) - len(hit_blocks)
            if need > (self.cache.free_blocks
                       + self.cache.reclaimable_blocks(
                           exclude=hit_blocks)):
                break
            self._waiting.remove(run)
            self.cache.register(run.req.rid)
            try:
                if hit_blocks:
                    self.cache.adopt_prefix(run.req.rid, hit_blocks)
                self.cache.ensure(run.req.rid, reserve)
                if partial:
                    # the match ends mid-block: CoW the divergence
                    # block so the request owns a private copy carrying
                    # the shared KV below hit_tokens
                    self.cache.ensure_writable(run.req.rid, hit_tokens,
                                               hit_tokens + 1)
                if run.req.adapter is not None:
                    # pin the tenant's adapter slot for the run's
                    # lifetime (all-or-nothing with the KV blocks: a
                    # PoolExhausted here — every slot pinned by live
                    # requests — rolls the whole admission back)
                    run.slot = self.adapter_pool.acquire(
                        run.req.adapter, run.req.rid)
            except PoolExhausted:
                # the reclaimable estimate can be beaten by pathological
                # tree shapes (and the adapter pool can be pinned out);
                # roll the admission back losslessly and retry next
                # iteration
                self.cache.release(run.req.rid)
                self._waiting.appendleft(run)
                break
            if self._prefix_on:
                if hit_tokens:
                    self._m["prefix_hits"].inc()
                    self._m["prefix_saved"].inc(hit_tokens)
                else:
                    self._m["prefix_misses"].inc()
            # a hit starts chunked prefill at the divergence — the
            # shared chunks are never recomputed
            run.prefill_done = hit_tokens
            run.cache_len = hit_tokens
            run.state = "prefill"
            run.t_admit = now
            self._running.append(run)
            self._charge_admission(run, reserve)
            self._m["admitted"].inc()
            if run.tenant is not None:
                self._tenant_m(run.tenant)["admitted"].inc()
            self._m["queue_depth"].set(len(self._waiting))
            progress = True

        # 2. prefill lane: ONE chunk for the oldest prefilling request
        for run in list(self._running):
            if run.state != "prefill":
                continue
            L = len(run.full_input)
            if (self._prefix_on and run.prefill_done < L - 1
                    and run.idx_seq != self.cache.index_version):
                # re-consult the index mid-prefill: at saturation every
                # request admits before ANY has committed the shared
                # prefix, so the admission lookup misses — but the
                # oldest sibling prefills first and commits, and this
                # jump maps its pages instead of recomputing them. The
                # block at the watermark swaps too when matched (its
                # written-so-far rows are content-identical by
                # construction); prefill resumes at the match end.
                # Gated on the index VERSION (bumped per commit) and
                # matched full-blocks-only, so an unchanged index costs
                # nothing and a re-match never pays the divergence scan.
                bs = self.cache.block_size
                run.idx_seq = self.cache.index_version
                hit_blocks, hit_tokens = self.cache.match_prefix(
                    run.full_input[:L - 1], full_blocks_only=True)
                jump = hit_tokens
                if jump > run.prefill_done:
                    bp = run.prefill_done // bs
                    self.cache.readopt_prefix(
                        run.req.rid, hit_blocks[bp:jump // bs], bp)
                    self._m["prefix_hits"].inc()
                    self._m["prefix_saved"].inc(jump - run.prefill_done)
                    run.prefill_done = jump
                    run.cache_len = jump
            C = min(self.prefill_chunk,
                    len(run.full_input) - run.prefill_done)
            toks = run.full_input[run.prefill_done:run.prefill_done + C]
            final = run.prefill_done + C == len(run.full_input)
            # the chunk scatters C rows — CoW any shared page in its
            # span (a no-op by construction: admission already CoW'd
            # the divergence block; enforced, not assumed)
            self.cache.ensure_writable(run.req.rid, run.prefill_done,
                                       run.prefill_done + C)
            # intermediate chunks skip the vocab readout — only the
            # final chunk's last-position logits are ever read
            logits, self.cache.state = self._prefill_fn(C, final)(
                self._params_for(run), self.cache.state,
                jnp.asarray(toks)[None],
                jnp.int32(run.prefill_done),
                jnp.asarray(self.cache.table_row(run.req.rid,
                                                 self._width(run.req.rid))))
            run.prefill_done += C
            run.cache_len = run.prefill_done
            self._m["prefill_tokens"].inc(C)
            if self._prefix_on:
                # publish the newly fully-written leading blocks so the
                # NEXT request sharing this prefix maps them instead of
                # recomputing (refcount +1 per node keeps them resident
                # after this request finishes — cached-but-idle, LRU)
                self.cache.commit_prefix(run.req.rid, run.full_input,
                                         run.prefill_done)
            if self.role == "prefill" and self.stream_blocks is not None:
                # disaggregation: newly FULL blocks stream to the decode
                # target NOW, so their wire time (codec + pacer on the
                # KVWire's stage threads) overlaps the next chunk's
                # compute on this thread — the partial tail travels
                # with the handoff ticket
                full = run.prefill_done // self.cache.block_size
                if full > run.streamed:
                    self.stream_blocks(
                        self, run,
                        self.cache.snapshot_blocks(run.req.rid,
                                                   run.streamed, full))
                    run.streamed = full
            progress = True
            if run.prefill_done == len(run.full_input):
                # device-side last-position slice: only vocab floats
                # cross to host, not the whole (1, C, vocab) chunk
                picked = self._pick(
                    logits[:, -1],
                    jnp.asarray([run.req.seed], jnp.int32),
                    jnp.asarray([run.cache_len], jnp.int32),
                    jnp.asarray([run.req.temperature], jnp.float32))
                run.state = "decode"
                if (run.req.spec is not None
                        and run.req.spec.kind == "draft"
                        and self.role != "prefill"):
                    self._build_draft_cache(run)
                self._commit_token(run, int(np.asarray(picked)[0]),
                                   self._clock())
                if run.state == "decode" and self.role == "prefill":
                    # prefill is this replica's whole job: the request
                    # parks (blocks pinned) until the router migrates
                    # it — its first token is already committed, so
                    # TTFT was stamped here, untouched by wire time
                    run.state = "handoff"
            break                                 # one chunk per iteration

        # 3. speculative lane: one round per spec request — they never
        # take plain decode steps (a token committed outside the round
        # would desync the per-request draft cache)
        for run in [r for r in self._running
                    if r.state == "decode" and r.req.spec is not None]:
            if run.state == "decode":   # an earlier round may preempt
                self._spec_round(run, self._clock())
                progress = True

        # 4. packed decode for the non-speculative decoders
        packed: List[_Run] = []
        for run in list(self._running):
            if run.state != "decode" or run.req.spec is not None:
                continue
            if len(packed) >= self.max_batch:
                break
            if self._ensure_or_preempt(run, run.cache_len + 1,
                                       run.cache_len, run.cache_len + 1):
                if run.state == "decode":     # survived any preemptions
                    packed.append(run)
        packed = [r for r in packed if r.state == "decode"]
        if packed:
            R = self.max_batch
            W = max(self._width(r.req.rid) for r in packed)
            toks = np.zeros(R, np.int32)
            pos = np.zeros(R, np.int32)
            tables = np.zeros((R, W), np.int32)
            seeds = np.zeros(R, np.int32)
            temps = np.zeros(R, np.float32)
            for i, run in enumerate(packed):
                toks[i] = run.pending
                pos[i] = run.cache_len
                tables[i] = self.cache.table_row(run.req.rid, W)
                seeds[i] = run.req.seed
                temps[i] = run.req.temperature
            if self.adapter_pool is not None:
                # heterogeneous-adapter decode: each row gathers its
                # adapter's A/B slabs by pool slot inside the ONE
                # jitted step (ops/segmented_lora.py); padded rows and
                # base-model runs ride slot 0, the reserved all-zero
                # slot, so batch composition never branches the program
                slots = np.zeros(R, np.int32)
                for i, run in enumerate(packed):
                    if run.slot is not None:
                        slots[i] = run.slot
                logits, self.cache.state = self._decode_step()(
                    self.params, self.cache.state, jnp.asarray(toks),
                    jnp.asarray(pos), jnp.asarray(tables),
                    self.adapter_pool.slabs, jnp.asarray(slots))
            else:
                logits, self.cache.state = self._decode_step()(
                    self.params, self.cache.state, jnp.asarray(toks),
                    jnp.asarray(pos), jnp.asarray(tables))
            picked = np.asarray(self._pick(
                logits, jnp.asarray(seeds), jnp.asarray(pos + 1),
                jnp.asarray(temps)))
            now = self._clock()
            for i, run in enumerate(packed):
                run.cache_len += 1
                self._commit_token(run, int(picked[i]), now)
            self._m["decode_tokens"].inc(len(packed))
            self._m["batch_occupancy"].observe(len(packed))
            progress = True
        return progress

    def _build_draft_cache(self, run: _Run,
                           tokens: Optional[np.ndarray] = None) -> None:
        """Prefill the per-request dense draft cache over the full
        committed context (prompt + resumed tokens; a migrated-in run
        passes its context minus the pending token explicitly)."""
        pol = run.req.spec
        kv_d = (pol.draft_params["blocks"][0]["wk"].shape[-1]
                // pol.draft_cfg.head_dim)
        dc = init_cache(pol.draft_cfg, 1, h_loc=kv_d)
        _, dc = self._draft_step(pol.draft_cfg)(
            pol.draft_params,
            jnp.asarray(run.full_input if tokens is None
                        else tokens)[None], dc)
        run.draft_cache = dc

    def serve(self, requests: List[Request], max_idle_iters: int = 10000):
        """Submit + drain convenience for tests/bench: runs ``step()``
        until every request finished. Arrival times are honored against
        this scheduler's clock."""
        for r in requests:
            self.submit(r)
        idle = 0
        while not self.finished:
            if self.step():
                idle = 0
            else:
                idle += 1
                if self._waiting and all(
                        r.req.arrival_s > self._clock()
                        for r in self._waiting):
                    time.sleep(1e-4)
                elif idle > max_idle_iters:
                    raise NoProgressError(
                        f"{len(self._waiting)} queued / "
                        f"{len(self._running)} running requests made no "
                        f"progress for {max_idle_iters} iterations")
        return self.results


def _make_draft_apply(draft_cfg: GPTConfig, tp_axis):
    """A named closure (not functools.partial) so jit caches by draft
    config identity and the traceback names the draft step."""
    def _draft_apply(p, t, c):
        return gpt_apply_cached(p, t, c, draft_cfg, tp_axis)
    return _draft_apply
