"""byteps_tpu.serve — the continuous-batching inference tier.

The training side of this repo already had every serving-shaped piece
(`models/generate.py` KV cache + cached apply, `models/speculative.py`,
flash decode) but served exactly one request at a time with a
fixed-shape cache. This subsystem is the vLLM/Orca-shaped completion:

* ``paged_cache`` — a block-paged KV pool: fixed-size KV blocks
  preallocated once, per-request block tables, so sequences of wildly
  different lengths pack one device batch (PagedAttention's memory
  model). Pages are refcounted and shareable: a radix prefix index
  over committed prefill blocks (RadixAttention's organization) lets
  requests with a common prompt prefix map the SAME physical pages,
  with copy-on-write at the divergence block and LRU eviction of
  cached-but-idle pages under pool pressure
  (``BYTEPS_SERVE_PREFIX_CACHE``, default-on).
* ``scheduler`` — iteration-level request scheduling: continuous
  admission from a queue, chunked prefill so long prompts can't starve
  decoders, preemption under block-pool pressure with
  recompute-on-resume, and speculative decoding as a per-request
  policy (Orca's per-step admission instead of run-to-completion
  batches).
* ``router`` — multi-replica routing with lease/epoch replica
  liveness mirroring the PR 5 elastic-membership layer: a dead
  replica's in-flight requests re-queue to survivors.
* ``adapter_pool`` — multi-tenant LoRA multiplexing (docs/serving.md
  §multi-tenant): LoRA A/B weights paged into a fixed device-resident
  slot pool exactly like KV blocks (refcounts, LRU eviction of idle
  adapters, host registry as the reload source), so ONE replica
  serves 32+ fine-tuned variants of its base model; the packed decode
  step gathers each row's adapter by slot index
  (``ops/segmented_lora.py`` — the S-LoRA/Punica shape) with
  per-tenant fair queuing and KV quotas in the scheduler.
* ``kv_wire`` — disaggregated prefill/decode (docs/serving.md
  §disaggregation): dedicated prefill replicas stream committed KV
  blocks to their decode target over a KVCOMPRESS→KVPUSH stage
  pipeline (wire-scoped credits, token-bucket pacer, CRC + stage
  retry — the gradient tier's wire machinery reused as a KV-migration
  transport), and the same wire turns pool-pressure preemption into
  migrate-don't-evict: committed blocks MOVE to a sibling instead of
  being freed and recomputed.

Greedy outputs are pinned BIT-identical (token-for-token) to
single-request ``make_generate_fn`` runs — batching and paging are
pure throughput levers, never content changes (tests/test_serve.py).
Measured by ``bench.py --mode serve`` (docs/serving.md).
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.serve.adapter_pool import AdapterPool  # noqa: E402,F401
from byteps_tpu.serve.kv_wire import (  # noqa: E402,F401
    BlockPayload,
    KVBlockCodec,
    KVWire,
    MigrationTicket,
)
from byteps_tpu.serve.paged_cache import (  # noqa: E402,F401
    PagedKVCache,
    PoolState,
    make_paged_decode_fn,
    make_paged_prefill_fn,
)
from byteps_tpu.serve.router import Router  # noqa: E402,F401
from byteps_tpu.serve.scheduler import (  # noqa: E402,F401
    Request,
    Scheduler,
    SpecPolicy,
)
