"""KV-block wire codec + migration transport for disaggregated serving.

The serve tier's disaggregation story (docs/serving.md §disaggregation)
is the BytePS thesis — "use every link" — applied to inference: prefill
and decode stop sharing a replica, and finished KV blocks STREAM from
the prefill replica to their decode target over the same wire machinery
the gradient tier built:

* **Codec** — :class:`KVBlockCodec` turns one physical KV block (every
  layer's k/v rows, plus the int8 ``_QuantSlot`` scales in quant mode)
  into self-describing wire bytes and back BYTE-IDENTICAL. There is no
  lossy re-encode: the int8 pool is already the compressed form (the
  ``_QuantSlot`` absmax codec), and the dense pool ships its dtype raw
  — so migration can never move a request's numerics (the serve tier's
  bit-exactness contract extends across the wire, pinned in
  tests/test_serve_disagg.py).
* **Transport** — :class:`KVWire` is one emulated outbound NIC per
  source replica: a two-stage
  :class:`~byteps_tpu.common.scheduler.PipelineScheduler` pipeline
  (KVCOMPRESS → KVPUSH) with wire-scoped PUSH credits, so block ``i``'s
  bytes ride the wire while block ``i+1`` encodes — and both overlap
  the source replica's NEXT prefill chunk, which runs on the caller's
  thread. Payload bytes are paced through a
  :class:`~byteps_tpu.server.pacer.DcnPacer` token bucket
  (``BYTEPS_SERVE_DISAGG_MBPS``), the PR 1 emulated-NIC philosophy:
  loopback behaves like the DCN tier migration would actually cross.
* **Self-healing** — the frame carries a CRC32 verified at decode
  (the PR 3 chaos-stack contract: corruption is detected, never
  adopted), KVPUSH is ``Stage.retryable``, and the push resolves its
  TARGET per attempt through a router-provided callback — a dead
  decode target is a stage-retryable REMAP (the router re-points the
  request at a live sibling), not a loss.

The same transport serves migrate-don't-evict preemption: a pressured
victim's committed blocks move to a sibling replica instead of being
freed and recomputed (serve/scheduler.py ``extract_for_migration`` →
router ``_migrate_out`` → sibling ``submit_migrated``).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import zlib
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import numpy as np

from byteps_tpu.common.faults import (
    FaultPlan,
    InjectedConnectionError,
    InjectedTimeout,
)
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.common.partition import Partition
from byteps_tpu.common.scheduler import (
    Handle,
    PartitionTask,
    PipelineScheduler,
    Stage,
)
from byteps_tpu.server.pacer import DcnPacer

log = get_logger("serve.kv_wire")

_MAGIC = 0x4B564231  # "KVB1"
_FLAG_QUANT = 0x1

# global NIC sequence: one KVWire per source replica, and the registry
# in-flight gauge must be a per-wire series (the PR 6 pacer.p<N> rule)
_WIRE_SEQ = itertools.count()


class KVWireError(RuntimeError):
    """Malformed/incompatible KV wire frame — not retryable (re-sending
    the same bytes cannot fix a shape/config mismatch)."""

    retryable = False


class KVWireCorruption(RuntimeError):
    """CRC mismatch on a received KV block — the frame was damaged in
    flight. Retryable: the source re-sends from its pristine payload."""

    retryable = True


class DeadTargetError(ConnectionError):
    """The resolved decode target is dead/evicted. Retryable: the stage
    retry re-resolves the target, and the router's remap points the
    request at a live sibling."""

    retryable = True


class BlockPayload(NamedTuple):
    """One physical KV block's host-side contents, every layer at once.

    k/v: ``(n_layers, block_size, h_kv, head_dim)`` in the pool dtype
    (int8 in quant mode); k_scale/v_scale: ``(n_layers, block_size,
    h_kv)`` fp32 (quant mode only, else None). These are exactly the
    pool slices ``state.k[:, b]`` etc. — the codec round-trips them
    byte-identical.
    """

    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None


@dataclasses.dataclass
class MigrationTicket:
    """Everything a decode replica needs to CONTINUE a request whose KV
    lives (or is arriving) in its pool: the request, the committed
    tokens, the decode cursor, and latency provenance. Block contents
    travel separately (streamed over the :class:`KVWire`); ``payloads``
    carries only the blocks NOT yet streamed when the ticket was cut
    (the partial tail at prefill handoff; everything for a
    migrate-don't-evict extraction).

    ``full_input`` is the token CONTEXT backing cache rows
    ``[0, cache_len)`` (prompt + any resume/emitted tokens) — what the
    receiving pool's radix index matches and commits against, so prefix
    sharing survives migration."""

    req: Any                       # serve.scheduler.Request
    emitted: List[int]
    pending: Optional[int]
    cache_len: int
    full_input: np.ndarray
    n_blocks: int
    payloads: Dict[int, BlockPayload]
    t_origin: float = 0.0
    t_submit: float = 0.0
    t_first: Optional[float] = None
    tok_s: List[float] = dataclasses.field(default_factory=list)
    preemptions: int = 0
    spec_rounds: int = 0


class KVBlockCodec:
    """Encode/decode one KV block for the migration wire.

    Frame: ``[u32 magic][u32 flags][u32 body_len][u32 crc32]`` + body,
    body = k ‖ v (‖ k_scale ‖ v_scale in quant mode), raw array bytes
    in the pool's own dtype. Shapes/dtype are bound at construction
    (both ends of a wire must agree — validated loudly at decode), so
    the frame stays self-checking without shipping shape metadata per
    block. Round-trip is BYTE-identical by construction: the body is a
    view, never a cast.
    """

    def __init__(self, n_layers: int, block_size: int, h_kv: int,
                 head_dim: int, dtype, quant: bool):
        self.n_layers = int(n_layers)
        self.block_size = int(block_size)
        self.h_kv = int(h_kv)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        self.quant = bool(quant)
        self._kv_shape = (self.n_layers, self.block_size, self.h_kv,
                          self.head_dim)
        self._sc_shape = self._kv_shape[:-1]
        kv_bytes = int(np.prod(self._kv_shape)) * self.dtype.itemsize
        sc_bytes = (int(np.prod(self._sc_shape)) * 4 if self.quant else 0)
        self.body_bytes = 2 * kv_bytes + 2 * sc_bytes
        self._kv_bytes = kv_bytes
        self._sc_bytes = sc_bytes

    @classmethod
    def from_pool(cls, cache) -> "KVBlockCodec":
        """Codec matching a :class:`~byteps_tpu.serve.paged_cache.
        PagedKVCache`'s pool layout."""
        L, _, bs, h, D = cache.state.k.shape
        return cls(L, bs, h, D, np.dtype(cache.state.k.dtype), cache.quant)

    @property
    def frame_bytes(self) -> int:
        return 16 + self.body_bytes

    def encode(self, p: BlockPayload) -> np.ndarray:
        """BlockPayload → uint8 wire frame (CRC32-stamped)."""
        parts = [np.ascontiguousarray(p.k).view(np.uint8).ravel(),
                 np.ascontiguousarray(p.v).view(np.uint8).ravel()]
        if self.quant:
            if p.k_scale is None or p.v_scale is None:
                raise KVWireError("quant codec needs k_scale/v_scale")
            parts.append(np.ascontiguousarray(
                p.k_scale, np.float32).view(np.uint8).ravel())
            parts.append(np.ascontiguousarray(
                p.v_scale, np.float32).view(np.uint8).ravel())
        body = np.concatenate(parts)
        if body.nbytes != self.body_bytes:
            raise KVWireError(
                f"payload is {body.nbytes} B, codec expects "
                f"{self.body_bytes} B — pool layout mismatch")
        out = np.empty(16 + body.nbytes, np.uint8)
        hdr = np.asarray(
            [_MAGIC, _FLAG_QUANT if self.quant else 0, body.nbytes,
             zlib.crc32(body.tobytes()) & 0xFFFFFFFF], np.uint32)
        out[:16] = hdr.view(np.uint8)
        out[16:] = body
        return out

    def decode(self, buf: np.ndarray) -> BlockPayload:
        """uint8 wire frame → BlockPayload (CRC-verified)."""
        buf = np.ascontiguousarray(buf, np.uint8)
        if buf.nbytes < 16:
            raise KVWireError(f"short KV frame ({buf.nbytes} B)")
        magic, flags, body_len, crc = (int(x) for x in
                                       buf[:16].view(np.uint32))
        if magic != _MAGIC:
            raise KVWireError(f"bad KV frame magic {magic:#x}")
        want_flags = _FLAG_QUANT if self.quant else 0
        if flags != want_flags or body_len != self.body_bytes:
            raise KVWireError(
                f"KV frame flags/len ({flags:#x}, {body_len}) do not "
                f"match this codec ({want_flags:#x}, {self.body_bytes}) "
                "— source and target pool layouts differ")
        body = buf[16:16 + body_len]
        if body.nbytes != body_len:
            raise KVWireError(
                f"truncated KV frame: {body.nbytes}/{body_len} body B")
        if (zlib.crc32(body.tobytes()) & 0xFFFFFFFF) != crc:
            raise KVWireCorruption(
                "KV block CRC mismatch — frame damaged in flight")
        kb, sb = self._kv_bytes, self._sc_bytes
        k = body[:kb].view(self.dtype).reshape(self._kv_shape).copy()
        v = body[kb:2 * kb].view(self.dtype).reshape(self._kv_shape).copy()
        if not self.quant:
            return BlockPayload(k, v)
        ks = body[2 * kb:2 * kb + sb].view(np.float32) \
            .reshape(self._sc_shape).copy()
        vs = body[2 * kb + sb:].view(np.float32) \
            .reshape(self._sc_shape).copy()
        return BlockPayload(k, v, ks, vs)


class KVWire:
    """One source replica's outbound migration NIC.

    ``send_block`` enqueues one block: KVCOMPRESS encodes the payload to
    CRC-stamped frame bytes on a pool thread, KVPUSH (credited,
    wire-scoped release, retryable) pays the token-bucket wire time and
    delivers into the CURRENT target's staging via
    ``Scheduler.ingest_block`` — the target is re-resolved through
    ``resolve(rid)`` on every attempt, so a stage retry after
    :class:`DeadTargetError` lands on whatever live sibling the router
    remapped the request to. Credits bound in-flight encoded frames
    (COMPRESS may run ahead of a throttled wire by at most ``credit``
    blocks), exactly the PR 1 COMPRESS→PUSH overlap discipline.

    An optional :class:`~byteps_tpu.common.faults.FaultPlan` intercepts
    each push attempt (op ``"push"``): ``corrupt`` flips a byte of a
    COPY of the frame (the CRC detects it, the retry re-sends pristine
    bytes), ``timeout`` delivers then loses the ack (the re-delivery is
    idempotent — staging is keyed by (rid, block)), ``kill``/``down``
    fail the attempt outright.
    """

    def __init__(self, codec: KVBlockCodec,
                 resolve: Callable[[Any], Any], *,
                 mbps: float = 0.0, credit: int = 4,
                 fault_plan: Optional[FaultPlan] = None,
                 max_attempts: int = 10):
        self.codec = codec
        self._resolve = resolve
        self._plan = fault_plan
        self._pacer = DcnPacer(mbps) if mbps and mbps > 0 else None
        self._key_seq = itertools.count()
        _reg = get_registry()
        self._m_blocks = _reg.counter("serve.migration.blocks")
        self._m_bytes = _reg.counter("serve.migration.bytes")
        self._g_inflight = _reg.gauge(
            f"serve.kvwire{next(_WIRE_SEQ)}.inflight_blocks")
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._sched = PipelineScheduler(
            stages=[
                Stage(name="KVCOMPRESS", fn=self._compress, pool_size=2),
                Stage(name="KVPUSH", fn=self._push, credited=True,
                      releases_credit=True, retryable=True,
                      pool_size=2, max_attempts=max_attempts,
                      retry_backoff_s=0.02),
            ],
            credit=max(1, credit),
        )

    # -- stage bodies (pool threads) ----------------------------------------
    def _compress(self, task: PartitionTask) -> np.ndarray:
        return self.codec.encode(task.payload)

    def _push(self, task: PartitionTask) -> int:
        buf = task.payload
        rid = task.context["rid"]
        bi = task.context["block"]
        deliver = buf
        inj = self._plan.intercept("push", -1) if self._plan else None
        if inj is not None:
            if inj.kind in ("kill", "down"):
                raise InjectedConnectionError(
                    f"injected {inj.kind} on KV push {rid!r}.{bi}")
            if inj.kind == "corrupt":
                deliver = buf.copy()
                FaultPlan.corrupt(deliver, inj.corrupt_at)
        if self._pacer is not None:
            self._pacer.throttle_send(int(buf.nbytes))
        target = self._resolve(rid)
        if target is None or getattr(target, "dead", False):
            raise DeadTargetError(
                f"decode target for {rid!r} is dead/unassigned")
        # decode runs target-side inside this push (CRC verified before
        # anything is staged); KVWireCorruption is retryable and the
        # retry re-sends the pristine frame
        target.ingest_block(rid, bi, deliver)
        if inj is not None and inj.kind == "timeout":
            # delivered, ack lost: the retry's re-delivery overwrites
            # the identical staged payload (idempotent by key)
            raise InjectedTimeout(
                f"injected timeout on KV push {rid!r}.{bi}")
        self._m_blocks.inc()
        self._m_bytes.inc(int(buf.nbytes))
        self._note_inflight(-1)
        return int(buf.nbytes)

    def _note_inflight(self, d: int) -> None:
        with self._inflight_lock:
            self._inflight += d
            self._g_inflight.set(self._inflight)

    # -- client surface ------------------------------------------------------
    def send_block(self, rid, block_idx: int,
                   payload: BlockPayload) -> Handle:
        """Enqueue one block; the returned handle completes when the
        target staged it (or fails after the retry budget)."""
        key = next(self._key_seq)
        part = Partition(key=key, tensor_id=key, part_idx=int(block_idx),
                         offset=0, length=self.codec.body_bytes // 4,
                         priority=0)
        handle = Handle(f"kv.{rid}.{block_idx}", 1)
        task = PartitionTask(partition=part, name=f"kv.{rid}",
                             handle=handle, payload=payload,
                             context={"rid": rid, "block": int(block_idx)})
        self._note_inflight(1)
        self._sched.enqueue([task])
        return handle

    def abandon(self, n: int = 1) -> None:
        """Router bookkeeping: ``n`` permanently-failed sends left the
        wire (their blocks will be re-sent as fresh tasks)."""
        self._note_inflight(-n)

    def shutdown(self) -> None:
        self._sched.shutdown()
