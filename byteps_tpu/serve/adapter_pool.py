"""Paged LoRA adapter pool — ``paged_cache.py``'s memory model applied
to adapter *parameters* (S-LoRA's weight paging over this repo's
refcount/LRU machinery).

One base model, many tenants: each tenant's LoRA A/B weights live in a
fixed device-resident slot pool (``{target: {"a": (n_slots, L, d_in,
rank_bucket), "b": (n_slots, L, rank_bucket, d_out)}}`` float32), and
the packed decode step gathers each row's slabs by its *slot index*
(``ops/segmented_lora.py``) — N dedicated replicas collapse into one
replica with N-way weight sharing and full batch occupancy.

The allocator is deliberately the KV pool's design, re-applied:

* **Slot 0 is reserved** and all-zero forever: base-model rows and
  padded batch rows gather it and pick up an exactly-0.0 delta — no
  branches in the packed step.
* **Refcounted residency** — ``acquire`` pins an adapter for one
  holder (a request id); an adapter with live holders is NEVER evicted.
  ``release`` at refcount 0 keeps the adapter resident (cached-idle) so
  the next burst of its tenant's traffic pays no reload.
* **All-or-nothing** — a failed ``acquire`` changes nothing; when every
  slot is pinned by live adapters it raises
  :class:`~byteps_tpu.serve.paged_cache.PoolExhausted` with the
  adapter-pool occupancy breakdown (live vs cached-idle vs free,
  LEAKED if nonzero) — the KV breakdown's twin, and the scheduler's
  cue to defer the admission.
* **LRU eviction of idle adapters** — under slot pressure the
  least-recently-used cached-idle adapter loses its slot first; the
  host-side registry (the numpy slab copies ``register`` keeps) is the
  reload source, so eviction is always safe.
* **Ground-truth leak accounting** — ``leaked_slots()`` computes
  occupancy from the residency map itself, ``check_refcounts()`` pins
  the per-adapter refcounts against the holder sets (the
  ``test_serve_prefix.py`` randomized-schedule pattern, applied to
  params).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Sequence, Set

import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.metrics import get_registry
from byteps_tpu.models.gpt import GPTConfig
from byteps_tpu.models.lora import (
    _check_targets,
    _target_dims,
    lora_pool_slabs,
    lora_rank,
)
from byteps_tpu.serve.paged_cache import PoolExhausted

__all__ = ["AdapterPool"]

# global pool instance sequence for per-pool gauge series (the
# serve.pool<N> pattern — two replicas' adapter pools must not mask
# each other last-writer-wins)
_APOOL_SEQ = itertools.count()


class AdapterPool:
    """Device-resident LoRA slot pool + host-side adapter registry.

    ``n_slots`` counts the reserved zero slot 0; ``rank_bucket`` is the
    pool-wide padded rank (mixed-rank tenants share ONE compiled packed
    step — satellite of the lru-cache key contract in
    ``make_paged_decode_fn``); ``targets`` is the pool-wide target set
    every registered adapter must cover. Omitted sizing falls back to
    ``BYTEPS_SERVE_ADAPTER_SLOTS`` / ``BYTEPS_SERVE_ADAPTER_RANK_BUCKET``
    (the former defaults to 0 = multiplexing off, so an env-sized pool
    must be explicitly enabled).
    """

    def __init__(self, cfg: GPTConfig, *, n_slots: Optional[int] = None,
                 rank_bucket: Optional[int] = None,
                 targets: Sequence[str] = ("wq", "wv")):
        from byteps_tpu.common.config import get_config

        c = get_config()
        if n_slots is None:
            n_slots = c.serve_adapter_slots
        if rank_bucket is None:
            rank_bucket = c.serve_adapter_rank_bucket
        if n_slots < 2:
            raise ValueError(
                f"n_slots ({n_slots}) must hold the reserved zero slot "
                "plus at least one loadable slot")
        if rank_bucket < 1:
            raise ValueError(
                f"rank_bucket must be >= 1; got {rank_bucket}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.rank_bucket = rank_bucket
        self.targets = _check_targets(cfg, targets)
        L = cfg.n_layers
        self.slabs: Dict[str, Dict[str, jnp.ndarray]] = {}
        for t in self.targets:
            d_in, d_out = _target_dims(cfg, t)
            self.slabs[t] = {
                "a": jnp.zeros((n_slots, L, d_in, rank_bucket),
                               jnp.float32),
                "b": jnp.zeros((n_slots, L, rank_bucket, d_out),
                               jnp.float32),
            }
        # host-side registry: the reload source (numpy slab copies) +
        # the raw adapter tree/scale for per-request grafted prefill
        self._registry: Dict[Any, Dict[str, Any]] = {}
        self._graft_cache: Dict[Any, Any] = {}
        # LIFO free list over slots 1..n_slots-1 (0 = zero, reserved)
        self._free: List[int] = list(range(n_slots - 1, 0, -1))
        self._slot: Dict[Any, int] = {}      # resident adapter -> slot
        self._ref: Dict[Any, int] = {}       # resident adapter -> pins
        self._holders: Dict[Any, Set[Any]] = {}   # ground truth for _ref
        self._lru_tick = 0
        self._last_used: Dict[Any, int] = {}
        _reg = get_registry()
        seq = next(_APOOL_SEQ)
        self._g_live = _reg.gauge(f"serve.apool{seq}.live_adapters")
        self._g_cached = _reg.gauge(f"serve.apool{seq}.cached_adapters")
        self._c_loads = _reg.counter("serve.adapter_loads")
        self._c_evict = _reg.counter("serve.adapter_evictions")
        self._c_fail = _reg.counter("serve.adapter_alloc_failures")

    # -- registry ------------------------------------------------------------
    def register(self, adapter_id, adapters: Dict[str, Any],
                 scale: float = 1.0) -> None:
        """Admit an adapter to the host registry (NOT the device pool —
        residency is demand-paged by :meth:`acquire`/:meth:`prefetch`).
        Validates rank against the pool bucket and target coverage up
        front, so a bad adapter fails here instead of at first use."""
        if adapter_id in self._registry:
            raise ValueError(f"adapter {adapter_id!r} already registered")
        slabs = lora_pool_slabs(adapters, self.cfg, self.rank_bucket,
                                scale, self.targets)
        host = {t: {"a": np.asarray(ts["a"]), "b": np.asarray(ts["b"])}
                for t, ts in slabs.items()}
        self._registry[adapter_id] = {
            "slabs": host,
            "rank": lora_rank(adapters),
            "adapters": adapters,
            "scale": scale,
        }

    def unregister(self, adapter_id) -> None:
        """Drop an adapter from the registry (and its slot, when
        cached-idle). Refuses while the adapter has live holders."""
        if self._ref.get(adapter_id, 0) > 0:
            raise ValueError(
                f"adapter {adapter_id!r} has {self._ref[adapter_id]} live "
                "holder(s) — release them before unregistering")
        if adapter_id in self._slot:
            self._evict(adapter_id)
        del self._registry[adapter_id]
        self._graft_cache.pop(adapter_id, None)

    def registered(self, adapter_id) -> bool:
        return adapter_id in self._registry

    def rank_of(self, adapter_id) -> int:
        return self._registry[adapter_id]["rank"]

    def graft(self, base_params, adapter_id):
        """The adapter's solo grafted tree (base + scaled A/B under the
        ``"lora"`` key) built from the pool's CANONICAL form — the
        rank-bucket-padded, scale-folded slabs — not the raw registered
        tree. Zero-padding is mathematically inert (the extra rank
        columns contribute exact 0.0) but it widens the thin GEMMs, and
        XLA's accumulation order is width-dependent, so a width-r graft
        and the width-bucket pool can disagree by 1 ulp on some inputs.
        Grafting the padded slabs pins ONE width everywhere: prefill
        chunks (this tree), packed decode (the device slabs), and the
        solo ``make_generate_fn`` exactness baseline all run identical
        arithmetic — the BIT-identical contract the tests enforce.
        Cached per adapter (the tree shares every base leaf by
        reference; only the thin adapter leaves are new)."""
        p = self._graft_cache.get(adapter_id)
        if p is None:
            host = self._registry[adapter_id]["slabs"]
            blocks = []
            for li, bp in enumerate(base_params["blocks"]):
                blk = dict(bp)
                # slabs already carry b * scale (lora_pool_slabs), so
                # the graft folds scale=1 — graft_lora's output format
                blk["lora"] = {
                    t: {"a": jnp.asarray(host[t]["a"][li]),
                        "b": jnp.asarray(host[t]["b"][li])}
                    for t in self.targets
                }
                blocks.append(blk)
            p = dict(base_params)
            p["blocks"] = blocks
            self._graft_cache[adapter_id] = p
        return p

    # -- accounting ----------------------------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_adapters(self) -> int:
        return sum(1 for r in self._ref.values() if r > 0)

    @property
    def cached_adapters(self) -> int:
        return sum(1 for r in self._ref.values() if r == 0)

    def leaked_slots(self) -> int:
        """Slots neither free nor occupied by a resident adapter — must
        be 0 at drain, computed from the residency map itself (not the
        refcounts) so the pin stays truthful against bookkeeping
        drift."""
        return (self.n_slots - 1) - len(self._free) \
            - len(set(self._slot.values()))

    def check_refcounts(self) -> None:
        """Debug/test invariant: per-adapter refcounts must equal the
        holder-set ground truth; the slot map and free list must
        partition the allocatable slots. Raises AssertionError on
        drift."""
        for aid, r in self._ref.items():
            assert r == len(self._holders.get(aid, ())), (
                f"refcount drift for adapter {aid!r}: "
                f"{r} != {len(self._holders.get(aid, ()))}")
            assert r >= 0
        assert set(self._ref) == set(self._slot), (
            "resident map / refcount map diverged")
        slots = list(self._slot.values())
        assert len(slots) == len(set(slots)), "two adapters share a slot"
        assert not (set(slots) & set(self._free)), (
            "free list overlaps resident slots")
        assert 0 not in slots and 0 not in self._free, (
            "reserved zero slot was allocated")
        assert self.leaked_slots() == 0, (
            f"{self.leaked_slots()} leaked adapter slot(s)")

    def _exhausted_msg(self, adapter_id) -> str:
        """Adapter-pool occupancy breakdown — the KV pool's
        ``_exhausted_msg`` twin, so a slot-pressure post-mortem is
        diagnosable straight off the flight recorder."""
        leaked = self.leaked_slots()
        return (
            f"adapter {adapter_id!r} needs a slot, pool has "
            f"{len(self._free)} free — occupancy: "
            f"{self.n_slots - 1} allocatable = "
            f"{self.live_adapters} live adapter(s) + "
            f"{self.cached_adapters} cached-idle + "
            f"{len(self._free)} free"
            + (f" + {leaked} LEAKED" if leaked else ""))

    # -- residency -----------------------------------------------------------
    def _touch(self, adapter_id) -> None:
        self._lru_tick += 1
        self._last_used[adapter_id] = self._lru_tick

    def _load(self, adapter_id, slot: int) -> None:
        host = self._registry[adapter_id]["slabs"]
        for t in self.targets:
            ts = self.slabs[t]
            self.slabs[t] = {
                "a": ts["a"].at[slot].set(jnp.asarray(host[t]["a"])),
                "b": ts["b"].at[slot].set(jnp.asarray(host[t]["b"])),
            }
        self._c_loads.inc()

    def _evict(self, adapter_id) -> None:
        """Drop a cached-idle adapter's slot (LRU pressure, explicit
        evict, unregister). The slot's device rows go stale rather than
        zeroed — no live row can gather a freed slot, exactly like the
        KV pool's recycled blocks."""
        assert self._ref.get(adapter_id, 0) == 0
        self._free.append(self._slot.pop(adapter_id))
        del self._ref[adapter_id]
        self._holders.pop(adapter_id, None)
        self._last_used.pop(adapter_id, None)
        self._c_evict.inc()

    def _alloc_slot(self, adapter_id) -> int:
        if not self._free:
            idle = sorted(
                (aid for aid, r in self._ref.items() if r == 0),
                key=lambda aid: self._last_used.get(aid, 0))
            if idle:
                self._evict(idle[0])
        if not self._free:
            self._c_fail.inc()
            raise PoolExhausted(self._exhausted_msg(adapter_id))
        return self._free.pop()

    def acquire(self, adapter_id, holder) -> int:
        """Pin ``adapter_id`` for ``holder`` (a request id), loading it
        into a slot if it isn't resident (prefetch-on-admission: the
        scheduler acquires at admission, so the slabs are on device
        before the first packed decode touch). Returns the slot index.
        All-or-nothing: on :class:`PoolExhausted` nothing changed."""
        if adapter_id not in self._registry:
            raise KeyError(f"adapter {adapter_id!r} is not registered")
        holders = self._holders.setdefault(adapter_id, set())
        if holder in holders:
            raise ValueError(
                f"holder {holder!r} already pinned adapter "
                f"{adapter_id!r}")
        if adapter_id not in self._slot:
            slot = self._alloc_slot(adapter_id)   # may raise; no state yet
            self._slot[adapter_id] = slot
            self._ref[adapter_id] = 0
            self._load(adapter_id, slot)
        holders.add(holder)
        self._ref[adapter_id] += 1
        self._touch(adapter_id)
        self._update_gauges()
        return self._slot[adapter_id]

    def release(self, adapter_id, holder) -> None:
        """Unpin one holder. At refcount 0 the adapter STAYS resident
        (cached-idle, LRU-evictable) — the param twin of the KV pool's
        cached-but-idle prefix pages."""
        holders = self._holders.get(adapter_id)
        if not holders or holder not in holders:
            raise ValueError(
                f"holder {holder!r} does not pin adapter {adapter_id!r}")
        holders.remove(holder)
        self._ref[adapter_id] -= 1
        if self._ref[adapter_id] < 0:
            raise RuntimeError(
                f"refcount underflow on adapter {adapter_id!r}")
        self._update_gauges()

    def prefetch(self, adapter_id) -> bool:
        """Best-effort residency warm-up: load into a FREE slot only
        (never evicts — prefetch must not fight live traffic for
        slots). Returns True when the adapter is resident after the
        call."""
        if adapter_id not in self._registry:
            raise KeyError(f"adapter {adapter_id!r} is not registered")
        if adapter_id in self._slot:
            self._touch(adapter_id)
            return True
        if not self._free:
            return False
        slot = self._free.pop()
        self._slot[adapter_id] = slot
        self._ref[adapter_id] = 0
        self._load(adapter_id, slot)
        self._touch(adapter_id)
        self._update_gauges()
        return True

    def evict_idle(self, adapter_id) -> None:
        """Explicitly drop a cached-idle adapter's slot (tests, tenant
        offboarding). Refuses for live adapters — an adapter with
        running requests is NEVER evicted."""
        if adapter_id not in self._slot:
            raise KeyError(f"adapter {adapter_id!r} is not resident")
        if self._ref[adapter_id] > 0:
            raise ValueError(
                f"adapter {adapter_id!r} has {self._ref[adapter_id]} live "
                "holder(s) — live adapters are never evicted")
        self._evict(adapter_id)
        self._update_gauges()

    def slot_of(self, adapter_id) -> int:
        """The resident slot index (the packed step's per-row gather
        key). KeyError when not resident — callers acquire first."""
        return self._slot[adapter_id]

    def resident(self, adapter_id) -> bool:
        return adapter_id in self._slot

    def _update_gauges(self) -> None:
        self._g_live.set(self.live_adapters)
        self._g_cached.set(self.cached_adapters)
