"""KV-block migration over a REAL socket: the kv_wire seam, cross-process.

:class:`~byteps_tpu.serve.kv_wire.KVWire` delivers each block by calling
``target.ingest_block(rid, bi, frame)`` on whatever ``resolve(rid)``
returns — in a colocated router that is the decode
:class:`~byteps_tpu.serve.scheduler.Scheduler` itself. This module puts
a real TCP link inside that seam without KVWire noticing:

* :class:`KVSocketEndpoint` — the DECODE side. Owns a
  :class:`~byteps_tpu.common.socknic.SocketNicListener`, unpacks each
  ``CH_KV_BLOCK`` frame and feeds the local scheduler's
  ``ingest_block`` (which decodes through the KV codec — CRC verified
  — and stages idempotently by ``(rid, block)``, so a retry's
  re-delivery is harmless). A codec/CRC failure raises out of the
  handler and crosses BACK over the wire as a typed error reply.
* :class:`SocketKVTarget` — the SOURCE side's proxy for that endpoint:
  the same ``ingest_block``/``dead`` duck type the in-process target
  has, delivery by framed request over a
  :class:`~byteps_tpu.common.socknic.SocketNicClient`. Failures keep
  the existing retryable/wire-death taxonomy KVWire's retryable KVPUSH
  stage already classifies: a reset/refused link raises
  ``ConnectionError``, a recv deadline ``TimeoutError``, on-wire
  damage :class:`~byteps_tpu.common.socknic.SockWireCorruption`, and a
  remote codec rejection is re-raised as the ORIGINAL
  ``KVWireCorruption``/``KVWireError`` type — so what is retryable
  in-process is retryable cross-process, for real reasons.

Routers opt in per-target via ``Router(kv_target_wrap=...)``: the wrap
is applied to the resolve callback handed to KVWire only, so the
router's own migration bookkeeping (``staged_blocks``/``pop_staged``/
``submit_migrated``) keeps talking to the local scheduler object while
the BYTES cross the kernel's TCP stack. Request ids must be strings on
this path (they are serialized into the frame).
"""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.common.socknic import (
    CH_KV_BLOCK,
    SocketNicClient,
    SocketNicListener,
)
from byteps_tpu.serve.kv_wire import (
    DeadTargetError,
    KVWireCorruption,
    KVWireError,
)

log = get_logger("serve.kv_socket")

__all__ = ["KVSocketEndpoint", "SocketKVTarget"]

_BODY_HDR = struct.Struct("<II")  # rid_len, block_idx


def _pack(rid: str, block_idx: int, frame: np.ndarray) -> bytes:
    rb = rid.encode("utf-8")
    return (_BODY_HDR.pack(len(rb), int(block_idx)) + rb
            + np.ascontiguousarray(frame, np.uint8).tobytes())


def _unpack(body: bytes):
    rid_len, block_idx = _BODY_HDR.unpack_from(body)
    off = _BODY_HDR.size
    rid = body[off:off + rid_len].decode("utf-8")
    frame = np.frombuffer(body, np.uint8, offset=off + rid_len)
    return rid, block_idx, frame


class KVSocketEndpoint:
    """Decode-side ingest listener in front of a local scheduler."""

    def __init__(self, target, port: int = 16200, attempts: int = 16,
                 stride: int = 1):
        self._target = target
        self._listener = SocketNicListener(port, attempts=attempts,
                                           stride=stride)
        self._listener.register(CH_KV_BLOCK, self._on_block)
        self._m_ingested = get_registry().counter(
            "serve.kv_socket.blocks_ingested")
        log.info("KV socket endpoint listening on :%d", self.port)

    @property
    def port(self) -> int:
        return self._listener.port

    @property
    def host(self) -> str:
        return self._listener.host

    def _on_block(self, body: bytes) -> bytes:
        if getattr(self._target, "dead", False):
            # same refusal the in-process path makes BEFORE delivery;
            # crossing back as DeadTargetError keeps it retryable (the
            # source's next attempt re-resolves)
            raise DeadTargetError("decode target behind this endpoint "
                                  "is dead")
        rid, bi, frame = _unpack(body)
        # ingest_block decodes (CRC verified) + stages idempotently;
        # KVWireCorruption/KVWireError raise back across the wire typed
        self._target.ingest_block(rid, bi, frame)
        self._m_ingested.inc()
        return b""

    def close(self) -> None:
        self._listener.close()


class SocketKVTarget:
    """Source-side proxy: KVWire's target duck type over a real link."""

    # the wire surfaces its own liveness (ConnectionError per attempt,
    # re-resolved by the retry) — a proxy has no local lease to check
    dead = False

    def __init__(self, host: str, port: int,
                 timeout_ms: Optional[int] = None, pacer=None,
                 fault_plan=None):
        self._client = SocketNicClient(
            host, port, timeout_ms=timeout_ms, pacer=pacer,
            fault_plan=fault_plan,
            error_types={
                "KVWireCorruption": KVWireCorruption,
                "KVWireError": KVWireError,
                "DeadTargetError": DeadTargetError,
            })

    def ingest_block(self, rid: Any, block_idx: int,
                     frame: np.ndarray) -> None:
        self._client.request(CH_KV_BLOCK, _pack(str(rid), block_idx,
                                                frame))

    def close(self) -> None:
        self._client.close()
