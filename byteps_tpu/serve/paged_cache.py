"""Block-paged KV cache — PagedAttention's memory model over this
repo's cache machinery.

``models/generate.py`` holds one contiguous ``(B, max_seq, h, D)``
cache per batch: every request pays max_seq slots whether it uses 10
tokens or 1000, and a batch must share one fill level. Here the cache
is a preallocated pool of fixed-size KV *blocks* plus a per-request
*block table* mapping logical position ``p`` to physical slot
``(table[p // bs], p % bs)`` — heterogeneous sequence lengths pack one
device batch, memory is allocated block-at-a-time as requests grow,
and a freed request's blocks immediately serve the next admission.

Numerics are the point, not just memory: the paged views reproduce the
dense cache's contract exactly. A gathered per-request view zero-fills
every position at or past the request's fill level (the dense cache is
zero-initialized and written only below ``length``), attention masks
with the same global-offset causal rule through the SAME
``attention_lse`` twin (extended to per-batch offset vectors), and
quantized pools reuse ``_quantize_block``'s absmax arithmetic — so a
request served out of the paged pool emits tokens bit-identical to a
solo ``make_generate_fn`` run (pinned in tests/test_serve.py).

Pages are SHARED, not owned: every physical block carries a refcount
and a radix/prefix index maps token content → committed prefill blocks
(SGLang's RadixAttention organized over vLLM's paged pool). Requests
whose prompts share a leading prefix — the dominant traffic shape at
"millions of users" (one long system prompt, short unique tails) — map
their leading table entries to the SAME physical pages and skip the
shared prefill entirely. Divergence inside a block is copy-on-write: a
writer whose table entry has refcount > 1 gets a fresh block with the
shared contents copied (dense and int8 ``_QuantSlot`` paths), so
sharing changes where bytes live, never what attention reads — hot-
cache greedy outputs stay BIT-identical to cold runs (pinned). Cached-
but-idle prefix pages are evicted LRU under pool pressure before any
allocation fails: the prefix cache can never cause
:class:`PoolExhausted` for live traffic.

Three layers:

* :class:`PagedKVCache` — the host-side allocator: pool arrays, block
  tables + per-block refcounts, the radix prefix index,
  alloc/adopt/CoW/free/defrag, leak accounting. Block 0 is a reserved
  scratch block: inactive decode rows scatter there and no table ever
  references it, so a padded batch slot can't corrupt live state.
* :func:`make_paged_decode_fn` — ONE jitted packed decode step:
  R requests at heterogeneous positions, per-row rope/masks, scatter
  the new token's K/V into the pool, gather per-request views, attend.
* :func:`make_paged_prefill_fn` — chunked prefill/verify for one
  request: gather its blocks into a dense :class:`KVCache` view, run
  the stock ``gpt_apply_cached`` (bit-identical to the single-request
  prefill by construction), scatter the newly written rows back.
"""

from __future__ import annotations

import functools
import itertools
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.metrics import get_registry
from byteps_tpu.models.generate import (
    KVCache,
    _quantize_block,
    gpt_apply_cached,
)
from byteps_tpu.models.gpt import (
    GPTConfig,
    _bias,
    _mlp,
    _readout,
    resolve_norm,
    resolve_rope,
    rope_rotate,
)
from byteps_tpu.ops.flash_attention import attention_lse
from byteps_tpu.ops.segmented_lora import segmented_lora_delta
from byteps_tpu.parallel.tp import col_parallel_matmul, row_parallel_matmul


class PoolState(NamedTuple):
    """The device half of the paged cache — a pytree so the jitted
    decode/prefill steps thread it functionally.

    k/v: ``(n_layers, num_blocks, block_size, h_kv, head_dim)`` in
    ``cfg.dtype``, or int8 with ``k_scale``/``v_scale``
    ``(n_layers, num_blocks, block_size, h_kv)`` fp32 absmax scales
    (generate.py's _QuantSlot layout, block-paged).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


class PoolExhausted(RuntimeError):
    """A block allocation could not be satisfied — the scheduler's cue
    to preempt (it should never escape to callers)."""


# global pool instance sequence for per-pool gauge series
_POOL_SEQ = itertools.count()


class _PrefixNode:
    """One committed KV block in the radix prefix index.

    The index is a block-granular radix tree: a node's edge label is
    the EXACT ``block_size`` token ids its block holds (content-
    addressed — children are keyed by the raw token bytes, chained
    through the parent, so two different contexts can never collide
    the way a rolling hash could). ``tick`` is the LRU clock stamped on
    every lookup touch; eviction takes the least-recently-used
    reclaimable subtree first."""

    __slots__ = ("key", "tokens", "block", "parent", "children", "tick")

    def __init__(self, key: bytes, tokens: np.ndarray, block: int,
                 parent: "_PrefixNode"):
        self.key = key
        self.tokens = tokens
        self.block = block
        self.parent = parent
        self.children: Dict[bytes, "_PrefixNode"] = {}
        self.tick = 0


class PagedKVCache:
    """Host-side block allocator + per-request block tables.

    The pool is sized once (``pool_blocks``); block 0 is reserved as
    the scratch target for padded decode rows and is never allocated.
    ``blocks_per_req`` (``ceil(max_seq / block_size)``) caps a table;
    the compute steps take width-bucketed table rows (powers of two,
    see ``Scheduler._width``) so a short request's gather/attention
    width tracks its actual length instead of max_seq — the zero-mask
    keeps every width bit-comparable to the solo dense run.
    """

    def __init__(self, cfg: GPTConfig, *, block_size: int,
                 pool_blocks: int, max_batch: int,
                 h_loc: Optional[int] = None, quant: bool = False):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1; got {block_size}")
        self.cfg = cfg
        self.block_size = block_size
        self.blocks_per_req = -(-cfg.max_seq // block_size)
        if pool_blocks <= 0:   # auto: no oversubscription
            pool_blocks = 1 + max_batch * self.blocks_per_req
        if pool_blocks < 2:
            raise ValueError(
                f"pool_blocks ({pool_blocks}) must hold the reserved "
                "scratch block plus at least one allocatable block "
                "(per-request fit is validated at Scheduler.submit)")
        self.pool_blocks = pool_blocks
        self.quant = quant
        h = h_loc if h_loc is not None else cfg.kv_heads
        shape = (cfg.n_layers, pool_blocks, block_size, h, cfg.head_dim)
        if quant:
            self.state = PoolState(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32),
            )
        else:
            self.state = PoolState(
                k=jnp.zeros(shape, cfg.dtype),
                v=jnp.zeros(shape, cfg.dtype),
            )
        # LIFO free list over blocks 1..NB-1 (0 = scratch, reserved)
        self._free: List[int] = list(range(pool_blocks - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        # per-block refcount: one ref per table entry referencing the
        # block plus one for its prefix-index node (if any). A shared
        # block frees only at refcount 0.
        self._ref: List[int] = [0] * pool_blocks
        self._in_use = 0                  # distinct blocks with ref > 0
        # radix prefix index over committed prefill blocks
        self._root = _PrefixNode(b"", np.zeros(0, np.int32), -1, None)  # type: ignore[arg-type]
        self._node_of_block: Dict[int, _PrefixNode] = {}
        self._lru_tick = 0
        # bumped on every commit_prefix insert: lets the scheduler's
        # mid-prefill re-match skip the walk when nothing new committed
        self.index_version = 0
        # blocks adopted from the migration wire over this pool's
        # lifetime (disaggregation / migrate-don't-evict): surfaced in
        # the PoolExhausted breakdown so a pressure post-mortem shows
        # how much of the occupancy migrated in rather than grew here
        self.migrated_in_blocks = 0
        _reg = get_registry()
        # per-POOL gauge series (global instance sequence, the PR 6
        # scheduler.s<N>/pacer.p<N> pattern): two replicas' pools must
        # not mask each other last-writer-wins
        seq = next(_POOL_SEQ)
        self._g_in_use = _reg.gauge(f"serve.pool{seq}.kv_blocks_in_use")
        self._g_prefix = _reg.gauge(f"serve.pool{seq}.prefix_blocks")
        self._c_alloc_fail = _reg.counter("serve.kv_alloc_failures")
        self._c_prefix_evict = _reg.counter("serve.prefix_evictions")

    # -- accounting ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def _live_blocks(self) -> set:
        """Ground-truth occupancy: the DISTINCT physical blocks
        referenced by any live table or the prefix index — computed
        from the references themselves, not ``_ref``, so the leak pin
        stays truthful even against a refcount bookkeeping bug."""
        live = {b for t in self._tables.values() for b in t}
        live.update(self._node_of_block)
        return live

    @property
    def blocks_in_use(self) -> int:
        """Distinct physical blocks occupied (shared pages count ONCE —
        the whole point of sharing). Maintained incrementally: it moves
        only when a refcount crosses 0<->1 (_alloc_block/_decref), so
        the per-mutation gauge update stays O(1) instead of walking
        every table (check_refcounts pins it against the ground
        truth)."""
        return self._in_use

    @property
    def prefix_blocks(self) -> int:
        """Blocks held by the radix prefix index."""
        return len(self._node_of_block)

    def leaked_blocks(self) -> int:
        """Blocks neither free nor referenced by a live table or the
        prefix index — must be 0 at drain (the CI smoke's leak pin)."""
        return (self.pool_blocks - 1) - len(self._free) \
            - len(self._live_blocks())

    def reclaimable_blocks(self, exclude=()) -> int:
        """Blocks LRU eviction could actually return to the free list:
        prefix-index blocks no live table references (refcount 1 —
        cached-but-idle). ``exclude`` masks blocks the caller is about
        to adopt (adoption pins them, so they stop being reclaimable
        the moment the admission that counted them proceeds)."""
        ex = set(exclude)
        return sum(1 for b in self._node_of_block
                   if self._ref[b] == 1 and b not in ex)

    def check_refcounts(self) -> None:
        """Debug/test invariant: ``_ref`` must equal the reference
        ground truth (table entries + index nodes) for every block, and
        never go negative. Raises ``AssertionError`` on drift."""
        want = [0] * self.pool_blocks
        for t in self._tables.values():
            for b in t:
                want[b] += 1
        for b in self._node_of_block:
            want[b] += 1
        assert self._ref == want, (
            f"refcount drift: {[(b, self._ref[b], want[b]) for b in range(self.pool_blocks) if self._ref[b] != want[b]]}")
        assert all(r >= 0 for r in self._ref)
        assert self._in_use == len(self._live_blocks()), (
            self._in_use, len(self._live_blocks()))
        assert self.leaked_blocks() >= 0

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def table_len(self, rid) -> int:
        """Live blocks allocated to ``rid`` (the width buckets key)."""
        return len(self._tables[rid])

    # -- allocation ---------------------------------------------------------
    def register(self, rid) -> None:
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already registered")
        self._tables[rid] = []

    def _alloc_block(self) -> int:
        b = self._free.pop()
        self._ref[b] = 1
        self._in_use += 1
        return b

    def _decref(self, b: int) -> None:
        r = self._ref[b] - 1
        if r < 0:
            raise RuntimeError(
                f"refcount underflow on block {b} — a release/evict "
                "path double-freed a shared page")
        self._ref[b] = r
        if r == 0:
            self._free.append(b)
            self._in_use -= 1

    def _exhausted_msg(self, rid, need: int) -> str:
        """Occupancy breakdown so a preemption-storm post-mortem is
        diagnosable straight off the flight recorder: live (table-
        referenced) vs cached-but-idle shared-prefix vs free blocks."""
        live = {b for t in self._tables.values() for b in t}
        cached_idle = sum(1 for b in self._node_of_block if b not in live)
        leaked = self.leaked_blocks()
        return (
            f"request {rid!r} needs {need} more block(s), pool has "
            f"{len(self._free)} free — occupancy: "
            f"{self.pool_blocks - 1} allocatable = {len(live)} live + "
            f"{cached_idle} cached-prefix + {len(self._free)} free"
            + (f" + {leaked} LEAKED" if leaked else "")
            + (f"; {self.migrated_in_blocks} block(s) migrated in over "
               "this pool's lifetime"
               if self.migrated_in_blocks else ""))

    def ensure(self, rid, n_tokens: int) -> None:
        """Grow ``rid``'s table to cover ``n_tokens`` positions with
        FRESH (refcount-1, private) blocks; raises
        :class:`PoolExhausted` (allocating nothing) when the pool can't
        — all-or-nothing so a failed grow never strands blocks.
        Cached-but-idle prefix pages are LRU-evicted first: the prefix
        cache must never cause :class:`PoolExhausted` for live
        traffic."""
        table = self._tables[rid]
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return
        if need > len(self._free):
            self._evict_prefix(need - len(self._free))
        if need > len(self._free):
            self._c_alloc_fail.inc()
            raise PoolExhausted(self._exhausted_msg(rid, need))
        for _ in range(need):
            table.append(self._alloc_block())
        self._g_in_use.set(self.blocks_in_use)

    def release(self, rid) -> None:
        """Drop ``rid``'s table, decrementing each block's refcount
        (request completion, preemption, replica drain). A shared block
        returns to the pool only at refcount 0 — pages still backing
        the prefix index (or a sibling's table) stay resident."""
        table = self._tables.pop(rid)
        for b in reversed(table):
            self._decref(b)
        self._g_in_use.set(self.blocks_in_use)

    def adopt_prefix(self, rid, blocks: List[int]) -> None:
        """Seed ``rid``'s (empty) table with shared prefix pages from a
        :meth:`match_prefix` hit — each gains a reference and becomes
        read-only for this request until :meth:`ensure_writable` CoWs
        it."""
        table = self._tables[rid]
        if table:
            raise ValueError(
                f"adopt_prefix needs an empty table; {rid!r} holds "
                f"{len(table)} block(s)")
        for b in blocks:
            self._ref[b] += 1
            table.append(b)
        self._g_in_use.set(self.blocks_in_use)

    def readopt_prefix(self, rid, blocks: List[int],
                       first_block: int) -> int:
        """Mid-prefill adoption: swap ``rid``'s table entries
        ``[first_block, first_block + len(blocks))`` for shared pages a
        SIBLING committed after this request was admitted — the
        saturation shape, where everyone admits before anyone commits,
        so the admission-time lookup alone would miss almost every
        share. The displaced private blocks free immediately (or drop a
        reference if they were themselves shared). The caller only
        swaps entries at/above its prefill watermark: everything below
        is already written and stays put."""
        table = self._tables[rid]
        swapped = 0
        for i, b in enumerate(blocks):
            bi = first_block + i
            old = table[bi]
            if old == b:
                continue
            self._ref[b] += 1
            self._decref(old)
            table[bi] = b
            swapped += 1
        if swapped:
            self._g_in_use.set(self.blocks_in_use)
        return swapped

    def ensure_writable(self, rid, lo: int, hi: int) -> int:
        """Copy-on-write every block covering token positions
        ``[lo, hi)``: a table entry with refcount > 1 gets a fresh
        block with the shared contents copied (dense and int8
        ``_QuantSlot`` paths — k/v and their scales), the shared page's
        refcount drops, and the table points at the private copy.
        Returns the number of blocks copied. Raises
        :class:`PoolExhausted` when no fresh block can be found even
        after LRU eviction."""
        if hi <= lo:
            return 0
        table = self._tables[rid]
        copied = 0
        for bi in range(lo // self.block_size,
                        -(-hi // self.block_size)):
            b = table[bi]
            if self._ref[b] <= 1:
                continue
            if not self._free:
                self._evict_prefix(1)
            if not self._free:
                self._c_alloc_fail.inc()
                raise PoolExhausted(self._exhausted_msg(rid, 1))
            nb = self._alloc_block()
            st = self.state
            self.state = PoolState(
                k=st.k.at[:, nb].set(st.k[:, b]),
                v=st.v.at[:, nb].set(st.v[:, b]),
                k_scale=(None if st.k_scale is None
                         else st.k_scale.at[:, nb].set(st.k_scale[:, b])),
                v_scale=(None if st.v_scale is None
                         else st.v_scale.at[:, nb].set(st.v_scale[:, b])),
            )
            self._decref(b)
            table[bi] = nb
            copied += 1
        if copied:
            self._g_in_use.set(self.blocks_in_use)
        return copied

    # -- radix prefix index -------------------------------------------------
    def _touch(self) -> int:
        self._lru_tick += 1
        return self._lru_tick

    def match_prefix(self, tokens,
                     full_blocks_only: bool = False
                     ) -> "tuple[List[int], int]":
        """Longest committed prefix of ``tokens`` in the radix index.

        Returns ``(blocks, n_tokens)``: a chain of full-block hits plus
        optionally ONE divergence block matched on a partial leading
        run (``n_tokens % block_size != 0`` then) — the caller adopts
        the chain, CoWs the partial tail, and starts chunked prefill at
        ``n_tokens``. Touches every matched node's LRU tick.
        ``full_blocks_only`` skips the divergence scan (a numpy compare
        over the deepest node's children) — the mid-prefill jump only
        swaps whole blocks, so it never pays for a partial match."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        node = self._root
        blocks: List[int] = []
        matched = 0
        tick = self._touch()
        while matched + bs <= tokens.size:
            child = node.children.get(tokens[matched:matched + bs]
                                      .tobytes())
            if child is None:
                break
            child.tick = tick
            blocks.append(child.block)
            matched += bs
            node = child
        rem = tokens[matched:]
        if rem.size and not full_blocks_only:
            # divergence block: the child sharing the longest leading
            # run with the remaining tokens (>= 1 token to be worth a
            # CoW copy)
            best, best_n = None, 0
            for child in node.children.values():
                m = min(rem.size, child.tokens.size)
                n = int(np.cumprod(child.tokens[:m] == rem[:m]).sum())
                if n > best_n:
                    best, best_n = child, n
            if best is not None:
                best.tick = tick
                blocks.append(best.block)
                matched += best_n
        return blocks, matched

    def commit_prefix(self, rid, tokens, n_tokens: int) -> int:
        """Publish ``rid``'s fully-written leading blocks (covering
        ``tokens[:n_tokens]``) into the radix index; each inserted node
        takes one reference on its block, keeping the page resident
        after the request finishes (cached-but-idle, LRU-evictable).
        Only FULL blocks are committed — a partial tail block is still
        being written and never enters the index. Returns the number of
        nodes inserted."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        bs = self.block_size
        table = self._tables[rid]
        node = self._root
        inserted = 0
        tick = self._touch()
        for bi in range(n_tokens // bs):
            seg = tokens[bi * bs:(bi + 1) * bs]
            key = seg.tobytes()
            child = node.children.get(key)
            if child is None:
                b = table[bi]
                if b in self._node_of_block:
                    # this physical page already backs a node on another
                    # chain — cannot happen for content-addressed private
                    # blocks; stop rather than alias two chains
                    break
                child = _PrefixNode(key, seg.copy(), b, node)
                node.children[key] = child
                self._node_of_block[b] = child
                self._ref[b] += 1
                inserted += 1
            # an existing node may be backed by a DIFFERENT physical
            # block (this request recomputed a prefix that was cached
            # after its admission); the chain continues through the
            # index's block — content-identical by construction
            child.tick = tick
            node = child
        if inserted:
            self.index_version += 1
            self._g_prefix.set(len(self._node_of_block))
        return inserted

    def _evict_node(self, node: _PrefixNode) -> None:
        """Drop one node (and its subtree, depth-first) from the index:
        each dropped block loses the index's reference and frees at
        refcount 0."""
        for child in list(node.children.values()):
            self._evict_node(child)
        del node.parent.children[node.key]
        del self._node_of_block[node.block]
        self._decref(node.block)
        self._c_prefix_evict.inc()

    def _evict_prefix(self, want_free: int) -> int:
        """LRU-evict cached-but-idle prefix subtrees until
        ``want_free`` blocks came back to the free list or nothing
        reclaimable remains. Victims are nodes whose block only the
        index holds (refcount 1 — evicting anything else frees no
        memory); a victim's descendants go with it (they are
        unreachable without the parent edge), shared ones merely
        leaving the index."""
        freed0 = len(self._free)
        # one snapshot, tick-sorted: eviction only ever REMOVES nodes
        # (it can't mint new refcount-1 candidates with older ticks),
        # so rescanning the whole index per evicted subtree would be
        # O(k * index) for nothing — re-check each candidate instead
        victims = sorted((n for n in self._node_of_block.values()
                          if self._ref[n.block] == 1),
                         key=lambda n: n.tick)
        for n in victims:
            if len(self._free) - freed0 >= want_free:
                break
            if self._node_of_block.get(n.block) is not n:
                continue      # went down with an ancestor's subtree
            self._evict_node(n)
        self._g_prefix.set(len(self._node_of_block))
        return len(self._free) - freed0

    def drop_prefix_cache(self) -> int:
        """Release every cached prefix page (tests, replica teardown,
        the ``BYTEPS_SERVE_PREFIX_CACHE=0`` escape hatch); live tables
        keep their references. Returns the number of nodes dropped."""
        n = len(self._node_of_block)
        for child in list(self._root.children.values()):
            self._evict_node(child)
        self._g_prefix.set(0)
        self._g_in_use.set(self.blocks_in_use)
        return n

    def table_row(self, rid, width: Optional[int] = None) -> np.ndarray:
        """``(width,)`` int32 physical-block row for the packed step
        (default ``blocks_per_req``); the unallocated tail points at
        scratch block 0 (those positions are always at/past the fill
        level, so the gather's zero-mask keeps whatever lives there out
        of the math). ``width`` must cover the live table — callers
        bucket it to a power of two so the jitted steps see a handful
        of gather shapes instead of one per request length."""
        w = self.blocks_per_req if width is None else width
        t = self._tables[rid]
        if w < len(t):
            raise ValueError(f"width {w} < live table {len(t)}")
        row = np.zeros(w, np.int32)
        row[:len(t)] = t
        return row

    # -- migration payloads (serve/kv_wire.py) -------------------------------
    def snapshot_blocks(self, rid, lo: int, hi: int):
        """Host snapshots of ``rid``'s table blocks ``[lo, hi)`` as
        ``{block_idx: BlockPayload}`` — ONE device gather per call (not
        one per block). This is the migration wire's read side: the
        bytes are copied out verbatim (rows at/past the fill level
        carry whatever the recycled block held — the receiving gather's
        zero-mask keeps them out of the math, exactly as it does
        locally)."""
        from byteps_tpu.serve.kv_wire import BlockPayload

        if hi <= lo:
            return {}
        blocks = self._tables[rid][lo:hi]
        idx = jnp.asarray(blocks, jnp.int32)
        st = self.state
        k = jax.device_get(st.k[:, idx])          # (L, n, bs, h, D)
        v = jax.device_get(st.v[:, idx])
        ks = vs = None
        if st.k_scale is not None:
            ks = jax.device_get(st.k_scale[:, idx])
            vs = jax.device_get(st.v_scale[:, idx])
        return {lo + i: BlockPayload(
                    k[:, i], v[:, i],
                    None if ks is None else ks[:, i],
                    None if vs is None else vs[:, i])
                for i in range(len(blocks))}

    def write_payloads(self, block_ids, payloads) -> None:
        """Scatter migrated block contents into physical ``block_ids``
        (the adoption write side) — one device scatter per pool array
        regardless of block count. Payload dtypes are the pool's own
        (the wire codec round-trips bytes, never values), so this write
        is bit-exact by construction."""
        if not block_ids:
            return
        idx = jnp.asarray(list(block_ids), jnp.int32)
        k = jnp.asarray(np.stack([np.asarray(p.k) for p in payloads],
                                 axis=1))
        v = jnp.asarray(np.stack([np.asarray(p.v) for p in payloads],
                                 axis=1))
        st = self.state
        if st.k_scale is not None:
            ks = jnp.asarray(np.stack(
                [np.asarray(p.k_scale) for p in payloads], axis=1))
            vs = jnp.asarray(np.stack(
                [np.asarray(p.v_scale) for p in payloads], axis=1))
            self.state = PoolState(
                k=st.k.at[:, idx].set(k), v=st.v.at[:, idx].set(v),
                k_scale=st.k_scale.at[:, idx].set(ks),
                v_scale=st.v_scale.at[:, idx].set(vs))
        else:
            self.state = PoolState(
                k=st.k.at[:, idx].set(k.astype(st.k.dtype)),
                v=st.v.at[:, idx].set(v.astype(st.v.dtype)))
        self.migrated_in_blocks += len(block_ids)

    def defrag(self) -> int:
        """Compact live blocks to the lowest physical ids (one device
        gather per pool array), rewriting every table, the prefix
        index, and the refcounts. A SHARED page moves once and every
        alias follows it — table aliasing and shared-page contents are
        preserved exactly (pinned in tests/test_serve_prefix.py).
        Correctness never needs this — tables make fragmentation
        invisible — but a long-lived replica's pool walks toward high
        ids and compaction restores allocation locality for the gather.
        Returns the number of blocks moved."""
        live = sorted(self._live_blocks())
        perm = np.arange(self.pool_blocks)
        moved = 0
        for new_id, old_id in enumerate(live, start=1):
            perm[new_id] = old_id
            if new_id != old_id:
                moved += 1
        if moved == 0:
            # already compact (free-list order may still differ; reset it)
            self._free = list(range(self.pool_blocks - 1, len(live), -1))
            return 0
        remap = {old: new for new, old in enumerate(live, start=1)}
        src = jnp.asarray(perm)
        self.state = PoolState(
            k=self.state.k[:, src],
            v=self.state.v[:, src],
            k_scale=(None if self.state.k_scale is None
                     else self.state.k_scale[:, src]),
            v_scale=(None if self.state.v_scale is None
                     else self.state.v_scale[:, src]),
        )
        for t in self._tables.values():
            t[:] = [remap[b] for b in t]
        ref = [0] * self.pool_blocks
        for old, new in remap.items():
            ref[new] = self._ref[old]
        self._ref = ref
        self._node_of_block = {remap[b]: n
                               for b, n in self._node_of_block.items()}
        for new, node in self._node_of_block.items():
            node.block = new
        self._free = list(range(self.pool_blocks - 1, len(live), -1))
        return moved


def _gather_view(pool_l, scale_l, table, length, dtype, block_size):
    """One layer's attention-ready per-request view(s).

    pool_l: (NB, bs, h, D); table: (..., n_blocks) int32; length:
    broadcastable per-row fill level. Returns (..., n_blocks*bs, h, D)
    in ``dtype`` with positions >= length zeroed — exactly the dense
    cache's state (zero-init, written only below the fill level), so
    freed-block garbage can never reach the masked lanes and the packed
    view is bit-comparable to a solo run's cache."""
    g = pool_l[table]                       # (..., nb, bs, h, D)
    S = g.shape[-4] * g.shape[-3]
    g = g.reshape(g.shape[:-4] + (S,) + g.shape[-2:])
    if scale_l is not None:
        s = scale_l[table]
        s = s.reshape(s.shape[:-3] + (S,) + s.shape[-1:])
        g = (g.astype(jnp.float32) * s[..., None])   # _cache_read dequant
    g = g.astype(dtype)
    keep = jnp.arange(S) < jnp.asarray(length)[..., None]
    return jnp.where(keep[..., None, None], g, jnp.zeros((), dtype))


@functools.lru_cache(maxsize=64)
def make_paged_decode_fn(cfg: GPTConfig, block_size: int,
                         tp_axis: Optional[str] = None,
                         lora_sig: Optional[tuple] = None):
    """Build the jitted packed decode step.

    ``step(params, pool, toks, pos, tables) -> (logits (R, vocab) f32,
    new pool)``: R requests each feed one token at their OWN global
    position ``pos[r]`` (cache fill level — keys [0, pos) are live).
    Padded rows pass pos=0 with an all-scratch table row; their math is
    garbage-in/garbage-out into scratch block 0 and the caller ignores
    their logits. The gathered key width is ``tables.shape[1] *
    block_size`` — callers pass width-bucketed tables so short requests
    don't pay max_seq-wide gathers, and jit retraces once per bucket.
    Table rows may alias SHARED prefix pages (refcount > 1): those are
    read-only by host contract — the scheduler CoWs the write-target
    block (``ensure_writable``) before this step scatters into
    ``tables[r][pos // bs]``, so the scatter below only ever lands in a
    private block (or scratch).
    Dense-MLP GPT families only (the MoE block's no-drop capacity
    logic hasn't been paged yet — detected from the params and
    rejected loudly).

    Multi-tenant variant: ``lora_sig=(targets, rank_bucket,
    n_adapter_slots)`` makes the step accept two trailing arguments —
    the :class:`~byteps_tpu.serve.adapter_pool.AdapterPool`'s slab dict
    and a ``(R,)`` int32 per-row slot vector — and each row adds its
    OWN adapter's low-rank delta beside every frozen matmul via
    ``ops/segmented_lora.segmented_lora_delta`` (slot 0 is the pool's
    reserved zero adapter, so base-model and padded rows stay exact
    no-ops). The rank bucket and slot count sit in the factory cache
    key: mixed-rank tenants share ONE compiled step (they're padded to
    the bucket), while a pool-geometry change gets its own wrapper
    instead of silently colliding — the retrace-count tests pin this.

    lru-cached by (cfg, block_size, tp_axis, lora_sig): every Scheduler
    replica in the process shares ONE jit wrapper, so a fresh replica
    (bench rep, failover respawn) reuses the compiled steps instead of
    paying a full retrace."""
    resolve_rope(cfg)
    norm_fn, norm_eps = resolve_norm(cfg)
    rope_base = cfg.rope_base if cfg.pos_embedding == "rope" else 0.0
    head_dim, use_bias = cfg.head_dim, cfg.use_bias
    lora_targets = () if lora_sig is None else tuple(lora_sig[0])

    def _seg(name, xin, slabs, slots, li, row_parallel=False):
        # one layer's slab slice: (n_slots, d_in, rb) / (n_slots, rb, d_out)
        sl = slabs[name]
        return segmented_lora_delta(
            xin, sl["a"][:, li], sl["b"][:, li], slots,
            row_parallel=row_parallel, tp_axis=tp_axis)

    def _mlp_seg(x, p, slabs, slots, li):
        # gpt._mlp with per-row segmented deltas spliced in at the SAME
        # points (value path, gate path, row projection) so a pooled
        # tenant's MLP arithmetic is the solo grafted one exactly
        h = col_parallel_matmul(x, p["w1"].astype(x.dtype),
                                _bias(p, "b1", x, use_bias))
        if "w1" in lora_targets:
            h = h + _seg("w1", x, slabs, slots, li)
        if "w3" in p:
            g = col_parallel_matmul(x, p["w3"].astype(x.dtype),
                                    _bias(p, "b3", x, use_bias))
            if "w3" in lora_targets:
                g = g + _seg("w3", x, slabs, slots, li)
            h = jax.nn.silu(h) * g
        else:
            h = jax.nn.gelu(h)
        out = row_parallel_matmul(h, p["w2"].astype(x.dtype), tp_axis,
                                  _bias(p, "b2", x, use_bias))
        if "w2" in lora_targets:
            out = out + _seg("w2", h, slabs, slots, li, row_parallel=True)
        return out

    def _block(x, p, pool, li, blk, off, pos, tables,
               slabs=None, slots=None):
        from byteps_tpu.models.lora import lora_delta

        R = x.shape[0]
        h = norm_fn(x, p["ln1_g"], p.get("ln1_b"), norm_eps)
        q = col_parallel_matmul(h, p["wq"].astype(x.dtype),
                                _bias(p, "bq", x, use_bias))
        k = col_parallel_matmul(h, p["wk"].astype(x.dtype),
                                _bias(p, "bk", x, use_bias))
        v = col_parallel_matmul(h, p["wv"].astype(x.dtype),
                                _bias(p, "bv", x, use_bias))
        if "lora" in p:
            q = q + lora_delta(h, p, "wq")
            k = k + lora_delta(h, p, "wk")
            v = v + lora_delta(h, p, "wv")
        if slabs is not None:
            if "wq" in lora_targets:
                q = q + _seg("wq", h, slabs, slots, li)
            if "wk" in lora_targets:
                k = k + _seg("wk", h, slabs, slots, li)
            if "wv" in lora_targets:
                v = v + _seg("wv", h, slabs, slots, li)
        h_loc = q.shape[-1] // head_dim
        kv_loc = k.shape[-1] // head_dim
        q = q.reshape(R, 1, h_loc, head_dim)
        k = k.reshape(R, 1, kv_loc, head_dim)
        v = v.reshape(R, 1, kv_loc, head_dim)
        if rope_base > 0.0:
            q = rope_rotate(q, pos[:, None], rope_base)
            k = rope_rotate(k, pos[:, None], rope_base)
        # scatter the new token's K/V into each request's block slot
        # (quantizing first in quant mode, so attention reads the same
        # lossy values the dense _cache_write→_cache_read roundtrip
        # produces)
        if pool.k_scale is not None:
            kq, ks = _quantize_block(k)
            vq, vs = _quantize_block(v)
            pool = PoolState(
                k=pool.k.at[li, blk, off].set(kq[:, 0]),
                v=pool.v.at[li, blk, off].set(vq[:, 0]),
                k_scale=pool.k_scale.at[li, blk, off].set(ks[:, 0]),
                v_scale=pool.v_scale.at[li, blk, off].set(vs[:, 0]),
            )
        else:
            pool = PoolState(
                k=pool.k.at[li, blk, off].set(k[:, 0].astype(pool.k.dtype)),
                v=pool.v.at[li, blk, off].set(v[:, 0].astype(pool.v.dtype)),
            )
        length = pos + 1                       # new key included
        kk = _gather_view(pool.k[li],
                          None if pool.k_scale is None else pool.k_scale[li],
                          tables, length, x.dtype, block_size)
        vv = _gather_view(pool.v[li],
                          None if pool.v_scale is None else pool.v_scale[li],
                          tables, length, x.dtype, block_size)
        o, _ = attention_lse(q, kk, vv, pos, 0, causal=True)
        o = o.reshape(R, 1, h_loc * head_dim)
        attn_out = row_parallel_matmul(o, p["wo"].astype(x.dtype), tp_axis,
                                       _bias(p, "bo", x, use_bias))
        if "lora" in p:
            attn_out = attn_out + lora_delta(o, p, "wo", tp_axis)
        if slabs is not None and "wo" in lora_targets:
            attn_out = attn_out + _seg("wo", o, slabs, slots, li,
                                       row_parallel=True)
        x = x + attn_out
        h2 = norm_fn(x, p["ln2_g"], p.get("ln2_b"), norm_eps)
        if "moe" in p:
            raise NotImplementedError(
                "the paged decode step serves dense-MLP GPT families "
                "only — MoE routing hasn't been paged yet")
        if slabs is not None:
            return x + _mlp_seg(h2, p, slabs, slots, li), pool
        return x + _mlp(h2, p, tp_axis, use_bias=use_bias), pool

    # the pool is DONATED: the caller always rebinds its state to the
    # returned pool, and without aliasing XLA would copy the entire
    # (L, NB, bs, h, D) pool every step to honor functional semantics —
    # measured ~45 ms/step of pure memcpy at serving sizes on CPU
    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(params, pool, toks, pos, tables, slabs=None, slots=None):
        tok2 = toks[:, None]                                  # (R, 1)
        if cfg.pos_embedding == "rope":
            x = params["wte"][tok2].astype(cfg.dtype)
        else:
            x = (params["wte"][tok2]
                 + jnp.take(params["wpe"], pos[:, None],
                            axis=0)).astype(cfg.dtype)
        blk = jnp.take_along_axis(
            tables, (pos // block_size)[:, None], axis=1)[:, 0]
        off = pos % block_size
        for li, p in enumerate(params["blocks"]):
            x, pool = _block(x, p, pool, li, blk, off, pos, tables,
                             slabs, slots)
        logits = _readout(params, x, norm_fn, norm_eps)
        return logits[:, 0], pool

    return step


@functools.lru_cache(maxsize=256)
def make_paged_prefill_fn(cfg: GPTConfig, block_size: int, chunk_len: int,
                          tp_axis: Optional[str] = None,
                          with_readout: bool = True):
    """Build the jitted per-request prefill/verify chunk.

    ``chunk(params, pool, tokens (1, C), pos0, table (W,)) ->
    (logits (1, C, vocab) f32, new pool)``: gather the request's blocks
    into a dense :class:`KVCache` view (zero past ``pos0``, int8 +
    scales in quant mode), run the STOCK ``gpt_apply_cached`` — the
    same computation a solo ``make_generate_fn`` prefill performs — and
    scatter the C newly written cache rows back into the pool. The
    dense view's length is ``table.shape[0] * block_size`` (callers
    bucket W). Like the decode step, the table may alias shared prefix
    pages below ``pos0`` — read via the gather only; the C written rows
    land at/after ``pos0`` in blocks the host made private first. Also the speculative verify forward: C proposed tokens
    in, per-position logits out, and only the committed prefix of the
    written rows is ever counted live (the fill level rewinds exactly
    like ``speculative.py``'s cache contract). ``with_readout=False``
    skips the vocab projection (an intermediate prefill chunk's logits
    are never read — at real vocab sizes that projection is the
    biggest weight stream in the chunk) and returns ``(None, pool)``.
    lru-cached like :func:`make_paged_decode_fn`."""
    C = chunk_len
    L = cfg.n_layers

    # pool donated for the same reason as the decode step
    @functools.partial(jax.jit, donate_argnums=(1,))
    def chunk(params, pool, tokens, pos0, table):
        quant = pool.k_scale is not None
        S = table.shape[0] * block_size
        keep = (jnp.arange(S) < pos0)
        gk = pool.k[:, table].reshape(L, 1, S, *pool.k.shape[-2:])
        gv = pool.v[:, table].reshape(L, 1, S, *pool.v.shape[-2:])
        gk = jnp.where(keep[None, None, :, None, None], gk,
                       jnp.zeros((), gk.dtype))
        gv = jnp.where(keep[None, None, :, None, None], gv,
                       jnp.zeros((), gv.dtype))
        if quant:
            gks = pool.k_scale[:, table].reshape(L, 1, S, -1)
            gvs = pool.v_scale[:, table].reshape(L, 1, S, -1)
            gks = jnp.where(keep[None, None, :, None], gks, 0.0)
            gvs = jnp.where(keep[None, None, :, None], gvs, 0.0)
        cache = KVCache(k=gk, v=gv, length=pos0,
                        k_scale=gks if quant else None,
                        v_scale=gvs if quant else None)
        logits, cache = gpt_apply_cached(params, tokens, cache, cfg,
                                         tp_axis, readout=with_readout)
        # scatter the C newly written rows back into the pool
        positions = pos0 + jnp.arange(C)
        blk = jnp.take(table, positions // block_size)
        off = positions % block_size
        h = cache.k.shape[-2]
        newk = jax.lax.dynamic_slice(
            cache.k, (0, 0, pos0, 0, 0),
            (L, 1, C, h, cfg.head_dim))[:, 0]
        newv = jax.lax.dynamic_slice(
            cache.v, (0, 0, pos0, 0, 0),
            (L, 1, C, h, cfg.head_dim))[:, 0]
        if quant:
            newks = jax.lax.dynamic_slice(
                cache.k_scale, (0, 0, pos0, 0), (L, 1, C, h))[:, 0]
            newvs = jax.lax.dynamic_slice(
                cache.v_scale, (0, 0, pos0, 0), (L, 1, C, h))[:, 0]
            pool = PoolState(
                k=pool.k.at[:, blk, off].set(newk),
                v=pool.v.at[:, blk, off].set(newv),
                k_scale=pool.k_scale.at[:, blk, off].set(newks),
                v_scale=pool.v_scale.at[:, blk, off].set(newvs),
            )
        else:
            pool = PoolState(
                k=pool.k.at[:, blk, off].set(newk),
                v=pool.v.at[:, blk, off].set(newv),
            )
        return logits, pool

    return chunk
