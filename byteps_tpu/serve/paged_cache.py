"""Block-paged KV cache — PagedAttention's memory model over this
repo's cache machinery.

``models/generate.py`` holds one contiguous ``(B, max_seq, h, D)``
cache per batch: every request pays max_seq slots whether it uses 10
tokens or 1000, and a batch must share one fill level. Here the cache
is a preallocated pool of fixed-size KV *blocks* plus a per-request
*block table* mapping logical position ``p`` to physical slot
``(table[p // bs], p % bs)`` — heterogeneous sequence lengths pack one
device batch, memory is allocated block-at-a-time as requests grow,
and a freed request's blocks immediately serve the next admission.

Numerics are the point, not just memory: the paged views reproduce the
dense cache's contract exactly. A gathered per-request view zero-fills
every position at or past the request's fill level (the dense cache is
zero-initialized and written only below ``length``), attention masks
with the same global-offset causal rule through the SAME
``attention_lse`` twin (extended to per-batch offset vectors), and
quantized pools reuse ``_quantize_block``'s absmax arithmetic — so a
request served out of the paged pool emits tokens bit-identical to a
solo ``make_generate_fn`` run (pinned in tests/test_serve.py).

Three layers:

* :class:`PagedKVCache` — the host-side allocator: pool arrays, block
  tables, alloc/free/defrag, leak accounting. Block 0 is a reserved
  scratch block: inactive decode rows scatter there and no table ever
  references it, so a padded batch slot can't corrupt live state.
* :func:`make_paged_decode_fn` — ONE jitted packed decode step:
  R requests at heterogeneous positions, per-row rope/masks, scatter
  the new token's K/V into the pool, gather per-request views, attend.
* :func:`make_paged_prefill_fn` — chunked prefill/verify for one
  request: gather its blocks into a dense :class:`KVCache` view, run
  the stock ``gpt_apply_cached`` (bit-identical to the single-request
  prefill by construction), scatter the newly written rows back.
"""

from __future__ import annotations

import functools
import itertools
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from byteps_tpu.common.metrics import get_registry
from byteps_tpu.models.generate import (
    KVCache,
    _quantize_block,
    gpt_apply_cached,
)
from byteps_tpu.models.gpt import (
    GPTConfig,
    _bias,
    _mlp,
    _readout,
    resolve_norm,
    resolve_rope,
    rope_rotate,
)
from byteps_tpu.ops.flash_attention import attention_lse
from byteps_tpu.parallel.tp import col_parallel_matmul, row_parallel_matmul


class PoolState(NamedTuple):
    """The device half of the paged cache — a pytree so the jitted
    decode/prefill steps thread it functionally.

    k/v: ``(n_layers, num_blocks, block_size, h_kv, head_dim)`` in
    ``cfg.dtype``, or int8 with ``k_scale``/``v_scale``
    ``(n_layers, num_blocks, block_size, h_kv)`` fp32 absmax scales
    (generate.py's _QuantSlot layout, block-paged).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


class PoolExhausted(RuntimeError):
    """A block allocation could not be satisfied — the scheduler's cue
    to preempt (it should never escape to callers)."""


# global pool instance sequence for per-pool gauge series
_POOL_SEQ = itertools.count()


class PagedKVCache:
    """Host-side block allocator + per-request block tables.

    The pool is sized once (``pool_blocks``); block 0 is reserved as
    the scratch target for padded decode rows and is never allocated.
    ``blocks_per_req`` (``ceil(max_seq / block_size)``) caps a table;
    the compute steps take width-bucketed table rows (powers of two,
    see ``Scheduler._width``) so a short request's gather/attention
    width tracks its actual length instead of max_seq — the zero-mask
    keeps every width bit-comparable to the solo dense run.
    """

    def __init__(self, cfg: GPTConfig, *, block_size: int,
                 pool_blocks: int, max_batch: int,
                 h_loc: Optional[int] = None, quant: bool = False):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1; got {block_size}")
        self.cfg = cfg
        self.block_size = block_size
        self.blocks_per_req = -(-cfg.max_seq // block_size)
        if pool_blocks <= 0:   # auto: no oversubscription
            pool_blocks = 1 + max_batch * self.blocks_per_req
        if pool_blocks < 2:
            raise ValueError(
                f"pool_blocks ({pool_blocks}) must hold the reserved "
                "scratch block plus at least one allocatable block "
                "(per-request fit is validated at Scheduler.submit)")
        self.pool_blocks = pool_blocks
        self.quant = quant
        h = h_loc if h_loc is not None else cfg.kv_heads
        shape = (cfg.n_layers, pool_blocks, block_size, h, cfg.head_dim)
        if quant:
            self.state = PoolState(
                k=jnp.zeros(shape, jnp.int8),
                v=jnp.zeros(shape, jnp.int8),
                k_scale=jnp.zeros(shape[:-1], jnp.float32),
                v_scale=jnp.zeros(shape[:-1], jnp.float32),
            )
        else:
            self.state = PoolState(
                k=jnp.zeros(shape, cfg.dtype),
                v=jnp.zeros(shape, cfg.dtype),
            )
        # LIFO free list over blocks 1..NB-1 (0 = scratch, reserved)
        self._free: List[int] = list(range(pool_blocks - 1, 0, -1))
        self._tables: Dict[object, List[int]] = {}
        _reg = get_registry()
        # per-POOL gauge series (global instance sequence, the PR 6
        # scheduler.s<N>/pacer.p<N> pattern): two replicas' pools must
        # not mask each other last-writer-wins
        seq = next(_POOL_SEQ)
        self._g_in_use = _reg.gauge(f"serve.pool{seq}.kv_blocks_in_use")
        self._c_alloc_fail = _reg.counter("serve.kv_alloc_failures")

    # -- accounting ---------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return sum(len(t) for t in self._tables.values())

    def leaked_blocks(self) -> int:
        """Blocks neither free nor owned by a live table — must be 0 at
        drain (the CI smoke's leak pin)."""
        return (self.pool_blocks - 1) - len(self._free) - self.blocks_in_use

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.block_size)

    def table_len(self, rid) -> int:
        """Live blocks allocated to ``rid`` (the width buckets key)."""
        return len(self._tables[rid])

    # -- allocation ---------------------------------------------------------
    def register(self, rid) -> None:
        if rid in self._tables:
            raise ValueError(f"request {rid!r} already registered")
        self._tables[rid] = []

    def ensure(self, rid, n_tokens: int) -> None:
        """Grow ``rid``'s table to cover ``n_tokens`` positions; raises
        :class:`PoolExhausted` (allocating nothing) when the pool can't
        — all-or-nothing so a failed grow never strands blocks."""
        table = self._tables[rid]
        need = self.blocks_for(n_tokens) - len(table)
        if need <= 0:
            return
        if need > len(self._free):
            self._c_alloc_fail.inc()
            raise PoolExhausted(
                f"request {rid!r} needs {need} more block(s), pool has "
                f"{len(self._free)} free")
        for _ in range(need):
            table.append(self._free.pop())
        self._g_in_use.set(self.blocks_in_use)

    def release(self, rid) -> None:
        """Return every block of ``rid`` to the pool and drop its table
        (request completion, preemption, replica drain)."""
        table = self._tables.pop(rid)
        self._free.extend(reversed(table))
        self._g_in_use.set(self.blocks_in_use)

    def table_row(self, rid, width: Optional[int] = None) -> np.ndarray:
        """``(width,)`` int32 physical-block row for the packed step
        (default ``blocks_per_req``); the unallocated tail points at
        scratch block 0 (those positions are always at/past the fill
        level, so the gather's zero-mask keeps whatever lives there out
        of the math). ``width`` must cover the live table — callers
        bucket it to a power of two so the jitted steps see a handful
        of gather shapes instead of one per request length."""
        w = self.blocks_per_req if width is None else width
        t = self._tables[rid]
        if w < len(t):
            raise ValueError(f"width {w} < live table {len(t)}")
        row = np.zeros(w, np.int32)
        row[:len(t)] = t
        return row

    def defrag(self) -> int:
        """Compact live blocks to the lowest physical ids (one device
        gather per pool array), rewriting every table. Correctness
        never needs this — tables make fragmentation invisible — but a
        long-lived replica's pool walks toward high ids and compaction
        restores allocation locality for the gather. Returns the number
        of blocks moved."""
        live = [b for t in self._tables.values() for b in t]
        perm = np.arange(self.pool_blocks)
        moved = 0
        for new_id, old_id in enumerate(sorted(live), start=1):
            perm[new_id] = old_id
            if new_id != old_id:
                moved += 1
        if moved == 0:
            # already compact (free-list order may still differ; reset it)
            self._free = list(range(self.pool_blocks - 1, len(live), -1))
            return 0
        remap = {old: new for new, old in enumerate(sorted(live), start=1)}
        src = jnp.asarray(perm)
        self.state = PoolState(
            k=self.state.k[:, src],
            v=self.state.v[:, src],
            k_scale=(None if self.state.k_scale is None
                     else self.state.k_scale[:, src]),
            v_scale=(None if self.state.v_scale is None
                     else self.state.v_scale[:, src]),
        )
        for t in self._tables.values():
            t[:] = [remap[b] for b in t]
        self._free = list(range(self.pool_blocks - 1, len(live), -1))
        return moved


def _gather_view(pool_l, scale_l, table, length, dtype, block_size):
    """One layer's attention-ready per-request view(s).

    pool_l: (NB, bs, h, D); table: (..., n_blocks) int32; length:
    broadcastable per-row fill level. Returns (..., n_blocks*bs, h, D)
    in ``dtype`` with positions >= length zeroed — exactly the dense
    cache's state (zero-init, written only below the fill level), so
    freed-block garbage can never reach the masked lanes and the packed
    view is bit-comparable to a solo run's cache."""
    g = pool_l[table]                       # (..., nb, bs, h, D)
    S = g.shape[-4] * g.shape[-3]
    g = g.reshape(g.shape[:-4] + (S,) + g.shape[-2:])
    if scale_l is not None:
        s = scale_l[table]
        s = s.reshape(s.shape[:-3] + (S,) + s.shape[-1:])
        g = (g.astype(jnp.float32) * s[..., None])   # _cache_read dequant
    g = g.astype(dtype)
    keep = jnp.arange(S) < jnp.asarray(length)[..., None]
    return jnp.where(keep[..., None, None], g, jnp.zeros((), dtype))


@functools.lru_cache(maxsize=64)
def make_paged_decode_fn(cfg: GPTConfig, block_size: int,
                         tp_axis: Optional[str] = None):
    """Build the jitted packed decode step.

    ``step(params, pool, toks, pos, tables) -> (logits (R, vocab) f32,
    new pool)``: R requests each feed one token at their OWN global
    position ``pos[r]`` (cache fill level — keys [0, pos) are live).
    Padded rows pass pos=0 with an all-scratch table row; their math is
    garbage-in/garbage-out into scratch block 0 and the caller ignores
    their logits. The gathered key width is ``tables.shape[1] *
    block_size`` — callers pass width-bucketed tables so short requests
    don't pay max_seq-wide gathers, and jit retraces once per bucket.
    Dense-MLP GPT families only (the MoE block's no-drop capacity
    logic hasn't been paged yet — detected from the params and
    rejected loudly).

    lru-cached by (cfg, block_size, tp_axis): every Scheduler replica
    in the process shares ONE jit wrapper, so a fresh replica (bench
    rep, failover respawn) reuses the compiled steps instead of paying
    a full retrace."""
    resolve_rope(cfg)
    norm_fn, norm_eps = resolve_norm(cfg)
    rope_base = cfg.rope_base if cfg.pos_embedding == "rope" else 0.0
    head_dim, use_bias = cfg.head_dim, cfg.use_bias

    def _block(x, p, pool, li, blk, off, pos, tables):
        from byteps_tpu.models.lora import lora_delta

        R = x.shape[0]
        h = norm_fn(x, p["ln1_g"], p.get("ln1_b"), norm_eps)
        q = col_parallel_matmul(h, p["wq"].astype(x.dtype),
                                _bias(p, "bq", x, use_bias))
        k = col_parallel_matmul(h, p["wk"].astype(x.dtype),
                                _bias(p, "bk", x, use_bias))
        v = col_parallel_matmul(h, p["wv"].astype(x.dtype),
                                _bias(p, "bv", x, use_bias))
        if "lora" in p:
            q = q + lora_delta(h, p, "wq")
            k = k + lora_delta(h, p, "wk")
            v = v + lora_delta(h, p, "wv")
        h_loc = q.shape[-1] // head_dim
        kv_loc = k.shape[-1] // head_dim
        q = q.reshape(R, 1, h_loc, head_dim)
        k = k.reshape(R, 1, kv_loc, head_dim)
        v = v.reshape(R, 1, kv_loc, head_dim)
        if rope_base > 0.0:
            q = rope_rotate(q, pos[:, None], rope_base)
            k = rope_rotate(k, pos[:, None], rope_base)
        # scatter the new token's K/V into each request's block slot
        # (quantizing first in quant mode, so attention reads the same
        # lossy values the dense _cache_write→_cache_read roundtrip
        # produces)
        if pool.k_scale is not None:
            kq, ks = _quantize_block(k)
            vq, vs = _quantize_block(v)
            pool = PoolState(
                k=pool.k.at[li, blk, off].set(kq[:, 0]),
                v=pool.v.at[li, blk, off].set(vq[:, 0]),
                k_scale=pool.k_scale.at[li, blk, off].set(ks[:, 0]),
                v_scale=pool.v_scale.at[li, blk, off].set(vs[:, 0]),
            )
        else:
            pool = PoolState(
                k=pool.k.at[li, blk, off].set(k[:, 0].astype(pool.k.dtype)),
                v=pool.v.at[li, blk, off].set(v[:, 0].astype(pool.v.dtype)),
            )
        length = pos + 1                       # new key included
        kk = _gather_view(pool.k[li],
                          None if pool.k_scale is None else pool.k_scale[li],
                          tables, length, x.dtype, block_size)
        vv = _gather_view(pool.v[li],
                          None if pool.v_scale is None else pool.v_scale[li],
                          tables, length, x.dtype, block_size)
        o, _ = attention_lse(q, kk, vv, pos, 0, causal=True)
        o = o.reshape(R, 1, h_loc * head_dim)
        attn_out = row_parallel_matmul(o, p["wo"].astype(x.dtype), tp_axis,
                                       _bias(p, "bo", x, use_bias))
        if "lora" in p:
            attn_out = attn_out + lora_delta(o, p, "wo", tp_axis)
        x = x + attn_out
        h2 = norm_fn(x, p["ln2_g"], p.get("ln2_b"), norm_eps)
        if "moe" in p:
            raise NotImplementedError(
                "the paged decode step serves dense-MLP GPT families "
                "only — MoE routing hasn't been paged yet")
        return x + _mlp(h2, p, tp_axis, use_bias=use_bias), pool

    # the pool is DONATED: the caller always rebinds its state to the
    # returned pool, and without aliasing XLA would copy the entire
    # (L, NB, bs, h, D) pool every step to honor functional semantics —
    # measured ~45 ms/step of pure memcpy at serving sizes on CPU
    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(params, pool, toks, pos, tables):
        tok2 = toks[:, None]                                  # (R, 1)
        if cfg.pos_embedding == "rope":
            x = params["wte"][tok2].astype(cfg.dtype)
        else:
            x = (params["wte"][tok2]
                 + jnp.take(params["wpe"], pos[:, None],
                            axis=0)).astype(cfg.dtype)
        blk = jnp.take_along_axis(
            tables, (pos // block_size)[:, None], axis=1)[:, 0]
        off = pos % block_size
        for li, p in enumerate(params["blocks"]):
            x, pool = _block(x, p, pool, li, blk, off, pos, tables)
        logits = _readout(params, x, norm_fn, norm_eps)
        return logits[:, 0], pool

    return step


@functools.lru_cache(maxsize=256)
def make_paged_prefill_fn(cfg: GPTConfig, block_size: int, chunk_len: int,
                          tp_axis: Optional[str] = None,
                          with_readout: bool = True):
    """Build the jitted per-request prefill/verify chunk.

    ``chunk(params, pool, tokens (1, C), pos0, table (W,)) ->
    (logits (1, C, vocab) f32, new pool)``: gather the request's blocks
    into a dense :class:`KVCache` view (zero past ``pos0``, int8 +
    scales in quant mode), run the STOCK ``gpt_apply_cached`` — the
    same computation a solo ``make_generate_fn`` prefill performs — and
    scatter the C newly written cache rows back into the pool. The
    dense view's length is ``table.shape[0] * block_size`` (callers
    bucket W). Also the speculative verify forward: C proposed tokens
    in, per-position logits out, and only the committed prefix of the
    written rows is ever counted live (the fill level rewinds exactly
    like ``speculative.py``'s cache contract). ``with_readout=False``
    skips the vocab projection (an intermediate prefill chunk's logits
    are never read — at real vocab sizes that projection is the
    biggest weight stream in the chunk) and returns ``(None, pool)``.
    lru-cached like :func:`make_paged_decode_fn`."""
    C = chunk_len
    L = cfg.n_layers

    # pool donated for the same reason as the decode step
    @functools.partial(jax.jit, donate_argnums=(1,))
    def chunk(params, pool, tokens, pos0, table):
        quant = pool.k_scale is not None
        S = table.shape[0] * block_size
        keep = (jnp.arange(S) < pos0)
        gk = pool.k[:, table].reshape(L, 1, S, *pool.k.shape[-2:])
        gv = pool.v[:, table].reshape(L, 1, S, *pool.v.shape[-2:])
        gk = jnp.where(keep[None, None, :, None, None], gk,
                       jnp.zeros((), gk.dtype))
        gv = jnp.where(keep[None, None, :, None, None], gv,
                       jnp.zeros((), gv.dtype))
        if quant:
            gks = pool.k_scale[:, table].reshape(L, 1, S, -1)
            gvs = pool.v_scale[:, table].reshape(L, 1, S, -1)
            gks = jnp.where(keep[None, None, :, None], gks, 0.0)
            gvs = jnp.where(keep[None, None, :, None], gvs, 0.0)
        cache = KVCache(k=gk, v=gv, length=pos0,
                        k_scale=gks if quant else None,
                        v_scale=gvs if quant else None)
        logits, cache = gpt_apply_cached(params, tokens, cache, cfg,
                                         tp_axis, readout=with_readout)
        # scatter the C newly written rows back into the pool
        positions = pos0 + jnp.arange(C)
        blk = jnp.take(table, positions // block_size)
        off = positions % block_size
        h = cache.k.shape[-2]
        newk = jax.lax.dynamic_slice(
            cache.k, (0, 0, pos0, 0, 0),
            (L, 1, C, h, cfg.head_dim))[:, 0]
        newv = jax.lax.dynamic_slice(
            cache.v, (0, 0, pos0, 0, 0),
            (L, 1, C, h, cfg.head_dim))[:, 0]
        if quant:
            newks = jax.lax.dynamic_slice(
                cache.k_scale, (0, 0, pos0, 0), (L, 1, C, h))[:, 0]
            newvs = jax.lax.dynamic_slice(
                cache.v_scale, (0, 0, pos0, 0), (L, 1, C, h))[:, 0]
            pool = PoolState(
                k=pool.k.at[:, blk, off].set(newk),
                v=pool.v.at[:, blk, off].set(newv),
                k_scale=pool.k_scale.at[:, blk, off].set(newks),
                v_scale=pool.v_scale.at[:, blk, off].set(newvs),
            )
        else:
            pool = PoolState(
                k=pool.k.at[:, blk, off].set(newk),
                v=pool.v.at[:, blk, off].set(newv),
            )
        return logits, pool

    return chunk
