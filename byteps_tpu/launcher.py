"""bpslaunch — role dispatch and local process spawn.

Reference analog: ``launcher/launch.py`` (installed as ``bpslaunch``):
reads ``DMLC_ROLE``; scheduler/server roles run the summation service;
the worker role spawns ``BYTEPS_LOCAL_SIZE`` copies of the user command
with per-child rank env, monitors them, and tears the job down if any
child fails.

TPU deltas (SURVEY §5.8): one worker process drives all local TPU devices
(so the default local_size is 1, not the visible-device count), and there is
no separate scheduler node — rendezvous is ``jax.distributed`` or direct
worker→server TCP connects with retry. ``DMLC_ROLE=scheduler`` is accepted
for reference-script compatibility and runs an extra (idle) summation
endpoint only so the process exists and exits cleanly with the job.

Usage (same shape as the reference):
    DMLC_ROLE=server  DMLC_NUM_WORKER=2 ... python -m byteps_tpu.launcher
    DMLC_ROLE=worker  DMLC_WORKER_ID=0 ... python -m byteps_tpu.launcher \
        python train.py
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import get_logger

log = get_logger("launcher")


def _run_server() -> int:
    from byteps_tpu.server import serve_forever

    serve_forever()
    return 0


def _run_scheduler() -> int:
    # Compatibility shim: our design has no scheduler node (SURVEY §5.8 —
    # jax.distributed replaces ps-lite rendezvous). Block until SIGTERM so
    # reference launch scripts that expect a long-lived scheduler work.
    log.info("scheduler role is a no-op in byteps_tpu; idling until killed")
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    return 0


def _wrap_jax_distributed(cmd: List[str]) -> List[str]:
    """Interpose the jax.distributed bootstrap around a python command so
    the global mesh forms BEFORE user code touches any JAX backend
    (reference: ps-lite rendezvous precedes all CUDA work in byteps_init).
    Interpreter flags (``python -u train.py``) are kept ahead of the
    ``-m`` interposition. Commands that cannot be wrapped (non-python
    binaries, ``python -m pkg``, ``python -c ...``) run unwrapped with a
    warning — their own bps.init() still joins the group, just later."""
    exe = os.path.basename(cmd[0])
    if exe.startswith("python"):
        for i, arg in enumerate(cmd[1:], start=1):
            if arg in ("-m", "-c"):
                break  # module/inline form: runpy.run_path can't replay it
            if not arg.startswith("-"):
                return (cmd[:i] + ["-m", "byteps_tpu._jd_boot"] + cmd[i:])
    log.warning(
        "cannot interpose jax.distributed bootstrap around %r; the global "
        "mesh forms at bps.init() — make sure user code touches no JAX "
        "backend before that", " ".join(cmd),
    )
    return cmd


def _spawn_workers(cmd: List[str]) -> int:
    cfg = get_config()
    local_size = cfg.local_size
    procs: List[subprocess.Popen] = []
    single_host_sim = (
        local_size > 1 and cfg.num_worker == local_size and cfg.worker_id == 0
    )
    if cfg.jax_distributed:
        cmd = _wrap_jax_distributed(cmd)
    for i in range(local_size):
        env = dict(os.environ)
        env["BYTEPS_LOCAL_RANK"] = str(i)
        env["BYTEPS_LOCAL_SIZE"] = str(local_size)
        if single_host_sim:
            # localhost multi-worker simulation (reference test pattern:
            # N worker processes on one machine, each a full DMLC worker)
            env["DMLC_WORKER_ID"] = str(i)
        log.info("spawning worker local_rank=%d: %s", i, " ".join(cmd))
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    try:
        # fail-fast: first nonzero child exit kills the rest (reference
        # launch.py child monitoring)
        remaining = set(range(len(procs)))
        while remaining:
            for idx in list(remaining):
                p = procs[idx]
                try:
                    r = p.wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    continue
                remaining.discard(idx)
                if r != 0:
                    log.error("worker local_rank=%d exited rc=%d — "
                              "terminating job", idx, r)
                    rc = r
                    for j in remaining:
                        procs[j].terminate()
                    for j in remaining:
                        try:
                            procs[j].wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    remaining.clear()
                    # stop scanning this snapshot: the siblings we just
                    # SIGTERMed would otherwise report rc=-15 and
                    # overwrite the REAL failure's rc
                    break
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        rc = 130
    return rc


# --------------------------------------------------------------------------
# Supervisor: real OS-process membership under the elastic control plane
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _Child:
    """One supervised worker process and its restart bookkeeping."""

    wid: int
    proc: subprocess.Popen
    argv: List[str]
    env: Dict[str, str]
    plan: Any = None              # proc-scoped FaultPlan, or None
    auto_restart: bool = False
    restarts: int = 0
    retired: bool = False
    term_deadline: Optional[float] = None
    # armed by a proc:restart fault or a crash with restart budget left
    backoff_until: Optional[float] = None


class Supervisor:
    """Spawn/retire REAL worker processes off autoscaler decisions.

    Everything the elastic membership story proved so far executed
    against threads in one process; this class is the missing half of
    ROADMAP item 3 — the launcher grown into a supervisor so the
    lease/epoch machinery runs against processes that actually die:

    * :meth:`execute` maps a :class:`~byteps_tpu.common.autoscaler.
      Decision` to the real world: ``admit`` spawns a child that joins
      mid-stream via the kJoin protocol (``BYTEPS_CHILD_JOIN=1`` →
      ``PSWorker.join()``), ``evict`` retires one (SIGTERM → the child
      exits WITHOUT the shutdown goodbye → the server lease-evicts its
      id and bumps the epoch — scale-down IS the eviction path, as in
      the in-process churn harness). Both land on the shared
      ``autoscaler.decision`` event path (``domain="proc"``).
    * :meth:`poll` is the supervision tick: it ticks each child's
      ``proc:``-scoped :class:`~byteps_tpu.common.faults.FaultPlan`
      (``proc:kill@step=N`` → REAL ``SIGKILL``, ``proc:restart@p=...``
      → SIGKILL + respawn), reaps exits with STRUCTURED reasons
      (``clean`` / ``error:rc=N`` / ``signal:SIGKILL``) into the
      flight recorder + registry, escalates overdue retires
      (SIGTERM → grace → SIGKILL), and executes bounded
      restart-with-backoff for flapping children (delay doubles per
      consecutive restart; past ``restart_limit`` the child is given
      up with a ``supervisor.giveup`` event instead of a hot loop).
    * Crash-resume: a respawned child carries
      ``BYTEPS_SUPERVISOR_RESTARTS`` so the driver knows to
      ``rejoin()`` + restore from its ``Checkpointer`` directory
      (``BYTEPS_CHILD_CKPT``) before continuing the round sequence.

    The default child command is this module's own ``--child-worker``
    driver; tests/benches override ``argv``/``base_env`` to run any
    program. The supervisor is single-threaded by design — callers own
    the poll cadence (``cfg.supervisor_poll_ms`` between ticks), so
    chaos tests can single-step it deterministically.
    """

    def __init__(self, *, argv: Optional[List[str]] = None,
                 base_env: Optional[Dict[str, str]] = None,
                 restart_limit: Optional[int] = None,
                 backoff_ms: Optional[int] = None,
                 grace_ms: Optional[int] = None,
                 fault_spec: str = "", fault_seed: int = 0,
                 first_wid: int = 0):
        from byteps_tpu.common.faults import parse_fault_spec
        from byteps_tpu.common.metrics import get_registry

        cfg = get_config()
        self._argv = list(argv) if argv else [
            sys.executable, "-m", "byteps_tpu.launcher", "--child-worker"]
        self._base_env = dict(base_env or {})
        self.restart_limit = (restart_limit if restart_limit is not None
                              else cfg.supervisor_restart_limit)
        self._backoff_s = (backoff_ms if backoff_ms is not None
                           else cfg.supervisor_backoff_ms) / 1e3
        self._grace_s = (grace_ms if grace_ms is not None
                         else cfg.supervisor_grace_ms) / 1e3
        # proc:-scoped rules only: the supervision tick must never
        # consume (or fire) a child's own wire-weather rules — those
        # belong to the child process's in-process plan
        self._fault_rules = [r for r in parse_fault_spec(fault_spec)
                             if r.scope == "proc"]
        self._fault_seed = fault_seed
        self._children: Dict[int, _Child] = {}
        self._next_wid = first_wid
        self.exit_reasons: Dict[int, List[str]] = {}
        _reg = get_registry()
        self._m_spawns = _reg.counter("supervisor.spawns")
        self._m_exits = _reg.counter("supervisor.exits")
        self._m_exit_kind = {
            k: _reg.counter(f"supervisor.exit.{k}")
            for k in ("clean", "error", "signal")}
        self._m_restarts = _reg.counter("supervisor.restarts")
        self._m_giveups = _reg.counter("supervisor.giveups")
        self._m_retired = _reg.counter("supervisor.retired")

    # -- membership views ---------------------------------------------------
    def live(self) -> List[int]:
        """wids with a running (or backoff-pending) process."""
        return sorted(self._children)

    def child(self, wid: int) -> Optional[subprocess.Popen]:
        c = self._children.get(wid)
        return c.proc if c is not None else None

    # -- spawn / retire / kill ----------------------------------------------
    def _plan_for(self, wid: int):
        from byteps_tpu.common.faults import FaultPlan

        if not self._fault_rules:
            return None
        return FaultPlan(self._fault_rules, seed=self._fault_seed,
                         worker_id=wid)

    def spawn(self, wid: Optional[int] = None,
              extra_env: Optional[Dict[str, str]] = None,
              argv: Optional[List[str]] = None,
              auto_restart: bool = False,
              _restarts: int = 0,
              _env: Optional[Dict[str, str]] = None) -> int:
        """Start one child worker process; returns its wid."""
        from byteps_tpu.common.flight_recorder import get_flight_recorder

        if wid is None:
            wid = self._next_wid
        if wid in self._children:
            raise ValueError(f"worker {wid} is already supervised")
        self._next_wid = max(self._next_wid, wid + 1)
        cmd = list(argv) if argv else list(self._argv)
        if _env is not None:
            env = dict(_env)  # respawn: the dead child's env, verbatim
        else:
            env = dict(os.environ)
            env.update(self._base_env)
            env.update(extra_env or {})
            env["DMLC_WORKER_ID"] = str(wid)
        env["BYTEPS_SUPERVISOR_RESTARTS"] = str(_restarts)
        proc = subprocess.Popen(cmd, env=env)
        self._children[wid] = _Child(
            wid=wid, proc=proc, argv=cmd, env=env,
            plan=self._plan_for(wid), auto_restart=auto_restart,
            restarts=_restarts)
        self._m_spawns.inc()
        get_flight_recorder().record_event(
            "supervisor.spawn",
            {"wid": wid, "pid": proc.pid, "restarts": _restarts})
        log.info("supervisor: spawned worker %d (pid=%d, restarts=%d)",
                 wid, proc.pid, _restarts)
        return wid

    def kill(self, wid: int, sig: int = signal.SIGKILL) -> None:
        """REAL signal to a live child (the chaos tier's process-death
        instrument — no emulation, the PID dies)."""
        c = self._children.get(wid)
        if c is None or c.backoff_until is not None:
            return
        try:
            c.proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass

    def retire(self, wid: int) -> None:
        """Graceful scale-down: SIGTERM; the child driver exits 0
        WITHOUT the shutdown goodbye, so the server lease-evicts the id
        (epoch bump) exactly like the in-process churn harness. A child
        that ignores the grace window is SIGKILLed by :meth:`poll`."""
        c = self._children.get(wid)
        if c is None:
            return
        c.retired = True
        c.auto_restart = False
        c.term_deadline = time.monotonic() + self._grace_s
        self._m_retired.inc()
        try:
            c.proc.terminate()
        except (ProcessLookupError, OSError):
            pass

    def execute(self, decision,
                spawn_env: Optional[Dict[str, str]] = None
                ) -> Optional[int]:
        """Carry out one ScalingPolicy decision against real processes;
        returns the wid acted on (None for hold). The DECISION was
        already recorded by the policy's ``observe`` (the shared
        ``autoscaler.decision`` path); what lands here is the
        EXECUTION — which pid-owning wid the decision bound to."""
        from byteps_tpu.common.flight_recorder import get_flight_recorder

        wid: Optional[int] = None
        if decision.action == "admit":
            env = {"BYTEPS_CHILD_JOIN": "1"}
            env.update(spawn_env or {})
            wid = self.spawn(extra_env=env)
        elif decision.action == "evict":
            live = self.live()
            if not live:
                return None
            wid = live[-1]
            self.retire(wid)
        if wid is not None:
            get_flight_recorder().record_event(
                "supervisor.execute",
                {"action": decision.action, "reason": decision.reason,
                 "wid": wid, "live": len(self._children)})
        return wid

    # -- supervision tick ---------------------------------------------------
    @staticmethod
    def _classify(rc: int) -> str:
        if rc == 0:
            return "clean"
        if rc < 0:
            try:
                name = signal.Signals(-rc).name
            except ValueError:
                name = str(-rc)
            return f"signal:{name}"
        return f"error:rc={rc}"

    def poll(self) -> List[Dict[str, Any]]:
        """One supervision tick: proc-fault plans → real signals, reap
        exits (structured reasons), escalate overdue retires, respawn
        backoff-expired children. Returns this tick's exit records."""
        from byteps_tpu.common.flight_recorder import get_flight_recorder

        now = time.monotonic()
        rec = get_flight_recorder()
        exits: List[Dict[str, Any]] = []
        for wid, c in list(self._children.items()):
            if c.backoff_until is not None:
                # respawn once the (doubling) backoff elapsed
                if now >= c.backoff_until:
                    del self._children[wid]
                    self._m_restarts.inc()
                    rec.record_event("supervisor.restart",
                                     {"wid": wid,
                                      "restarts": c.restarts + 1})
                    self.spawn(wid, argv=c.argv,
                               auto_restart=c.auto_restart,
                               _restarts=c.restarts + 1, _env=c.env)
                continue
            if c.proc.poll() is None:
                # alive: tick its proc:-scoped plan — injections become
                # REAL signals, one plan step per poll per child
                inj = (c.plan.intercept("proc", -1)
                       if c.plan is not None else None)
                if inj is not None and inj.kind in ("kill", "restart"):
                    if inj.kind == "restart":
                        c.auto_restart = True
                    self.kill(wid)
                elif c.term_deadline is not None \
                        and now >= c.term_deadline:
                    log.warning("supervisor: worker %d ignored SIGTERM "
                                "for %.1fs — escalating to SIGKILL",
                                wid, self._grace_s)
                    self.kill(wid)
                continue
            # exited: classify, record, maybe respawn
            rc = c.proc.returncode
            reason = self._classify(rc)
            self._m_exits.inc()
            self._m_exit_kind[reason.split(":", 1)[0]].inc()
            self.exit_reasons.setdefault(wid, []).append(reason)
            rec.record_event("supervisor.exit",
                             {"wid": wid, "pid": c.proc.pid, "rc": rc,
                              "reason": reason, "retired": c.retired,
                              "restarts": c.restarts})
            log.info("supervisor: worker %d exited (%s)", wid, reason)
            exits.append({"wid": wid, "rc": rc, "reason": reason,
                          "retired": c.retired, "restarts": c.restarts})
            if c.auto_restart and not c.retired and reason != "clean":
                if c.restarts >= self.restart_limit:
                    self._m_giveups.inc()
                    rec.record_event("supervisor.giveup",
                                     {"wid": wid,
                                      "restarts": c.restarts})
                    log.error("supervisor: worker %d flapped past the "
                              "restart limit (%d) — giving up",
                              wid, self.restart_limit)
                    del self._children[wid]
                else:
                    c.backoff_until = (now + self._backoff_s
                                       * (2 ** c.restarts))
            else:
                del self._children[wid]
        return exits

    def wait_all(self, timeout_s: float = 60.0,
                 poll_ms: Optional[int] = None) -> bool:
        """Poll until every supervised child is gone; False on timeout
        (children are still the caller's to shut down)."""
        step = (poll_ms if poll_ms is not None
                else get_config().supervisor_poll_ms) / 1e3
        deadline = time.monotonic() + timeout_s
        while self._children:
            self.poll()
            if not self._children:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(step)
        return True

    def shutdown(self) -> None:
        """Terminate everything, escalating to SIGKILL after grace —
        the teardown path MUST leak zero child processes."""
        for c in self._children.values():
            c.auto_restart = False
            if c.backoff_until is None:
                try:
                    c.proc.terminate()
                except (ProcessLookupError, OSError):
                    pass
        deadline = time.monotonic() + self._grace_s
        for c in self._children.values():
            if c.backoff_until is not None:
                continue
            try:
                c.proc.wait(timeout=max(0.0,
                                        deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                c.proc.kill()
                c.proc.wait(timeout=10)
        self._children.clear()


# --------------------------------------------------------------------------
# --child-worker: the supervised worker process driver
# --------------------------------------------------------------------------


def _child_worker_main() -> int:
    """Supervised worker child: deterministic push/pull rounds (or an
    idle heartbeat) against the server tier, env-driven so the
    supervisor/bench/tests compose behaviors without a zoo of helper
    scripts:

    ``BYTEPS_CHILD_SERVERS``   host:port[,host:port...] (required)
    ``BYTEPS_CHILD_ROUNDS``    N push/pull rounds; 0 = idle heartbeat
                               until SIGTERM (scale-up probe child)
    ``BYTEPS_CHILD_JOIN``      1 = kJoin admission before the loop
    ``BYTEPS_CHILD_PIN``       1 = pin version r+1 on round r's push so
                               a crash-resume redo replay-dedupes
    ``BYTEPS_CHILD_CKPT``      Checkpointer dir: save state per round,
                               restore + rejoin on restart
    ``BYTEPS_CHILD_OUT``       final JSON path; per-round progress
                               lines stream to ``<out>.progress``
    ``BYTEPS_CHILD_ELEMS/SEED/KEY/ROUND_DELAY_MS`` shape the rounds.

    Round r's payload is ``default_rng((seed, wid, r))`` — recomputable
    after a crash, so bit-identity across death is assertable from the
    outside. SIGTERM means RETIRE: exit 0 WITHOUT the shutdown goodbye
    (the server lease-evicts this id); a completed round loop does say
    goodbye (``PSWorker.shutdown``) so the server can exit with the
    job."""
    import json
    import zlib

    import numpy as np

    from byteps_tpu.server import PSWorker

    wid = int(os.environ.get("DMLC_WORKER_ID", "0"))
    servers_env = os.environ.get("BYTEPS_CHILD_SERVERS", "")
    if not servers_env:
        log.error("--child-worker needs BYTEPS_CHILD_SERVERS=host:port")
        return 2
    servers = []
    for part in servers_env.split(","):
        host, _, port = part.strip().rpartition(":")
        servers.append((host or "127.0.0.1", int(port)))
    rounds = int(os.environ.get("BYTEPS_CHILD_ROUNDS", "0"))
    elems = int(os.environ.get("BYTEPS_CHILD_ELEMS", "256"))
    seed = int(os.environ.get("BYTEPS_CHILD_SEED", "1234"))
    key = int(os.environ.get("BYTEPS_CHILD_KEY", "7"))
    out_path = os.environ.get("BYTEPS_CHILD_OUT", "")
    do_join = os.environ.get("BYTEPS_CHILD_JOIN", "0") == "1"
    pin = os.environ.get("BYTEPS_CHILD_PIN", "0") == "1"
    ckpt_dir = os.environ.get("BYTEPS_CHILD_CKPT", "")
    delay_s = int(os.environ.get("BYTEPS_CHILD_ROUND_DELAY_MS",
                                 "0")) / 1e3
    restarts = int(os.environ.get("BYTEPS_SUPERVISOR_RESTARTS", "0"))

    stop = {"term": False}

    def _on_term(signum, frame):  # noqa: ARG001 - signal signature
        stop["term"] = True

    signal.signal(signal.SIGTERM, _on_term)

    w = PSWorker(servers=servers, worker_id=wid)
    ck = state = None
    start_round = 0
    if ckpt_dir:
        from byteps_tpu.checkpoint import Checkpointer

        ck = Checkpointer(ckpt_dir, max_to_keep=2, async_save=False)
        state = np.zeros(elems, np.float32)
        last = ck.latest_step()
        if last is not None:
            restored = ck.restore(
                {"state": state, "round": 0}, step=last)
            state = np.asarray(restored["state"], np.float32)
            start_round = int(restored["round"]) + 1
            log.info("child %d: resuming from checkpoint round %d",
                     wid, start_round - 1)
    if restarts > 0 or (ckpt_dir and start_round > 0):
        # crash-resume: re-admit the id + adopt the server's round
        # watermarks BEFORE minting anything
        w.rejoin()
    elif do_join:
        w.join()

    results: List[List[int]] = []
    progress = open(out_path + ".progress", "a",
                    buffering=1) if out_path else None
    try:
        if rounds <= 0:
            # idle probe: hold the lease by pinging until retired
            while not stop["term"]:
                for sidx in range(len(servers)):
                    try:
                        w.ping(sidx)
                    except Exception:  # noqa: BLE001 - probe only
                        pass
                time.sleep(0.1)
            return 0  # retire: NO goodbye → lease eviction
        w.init_key(key, elems * 4)
        for r in range(start_round, rounds):
            if stop["term"]:
                return 0  # retired mid-run: same no-goodbye contract
            data = np.random.default_rng(
                (seed, wid, r)).standard_normal(elems).astype(np.float32)
            buf = data.view(np.uint8)
            v = w.push_bytes(key, buf,
                             version=(r + 1) if pin else None)
            out = w.pull_bytes(key, buf.nbytes, v)
            crc = zlib.crc32(out.tobytes()) & 0xFFFFFFFF
            results.append([r, int(v), int(crc)])
            if progress is not None:
                progress.write(f"{r} {v} {crc}\n")
            if ck is not None:
                state = state + out.view(np.float32)
                ck.save(r, {"state": state, "round": r}, force=True)
            if delay_s:
                time.sleep(delay_s)
        w.shutdown()  # completed: goodbye so the server can exit
        if out_path:
            final: Dict[str, Any] = {
                "wid": wid, "rounds": results, "restarts": restarts,
                "resumed_from": start_round,
                "counters": dict(w.counters),
            }
            if state is not None:
                final["state_crc"] = int(
                    zlib.crc32(state.tobytes()) & 0xFFFFFFFF)
                final["state_sum"] = float(state.sum())
            with open(out_path, "w") as f:
                json.dump(final, f)
        return 0
    finally:
        if progress is not None:
            progress.close()


_USAGE = """\
bpslaunch — BytePS-TPU job launcher (reference: launcher/launch.py)

Usage:
  DMLC_ROLE=server  DMLC_NUM_WORKER=N ... bpslaunch
  DMLC_ROLE=worker  DMLC_WORKER_ID=i ... bpslaunch python train.py [args...]

Role comes from DMLC_ROLE (worker | server | scheduler | joint). The worker
role spawns BYTEPS_LOCAL_SIZE copies of the given command with per-child
rank env and tears the job down if any child fails; with
BYTEPS_JAX_DISTRIBUTED=1 it also interposes the jax.distributed bootstrap
so one global mesh spans all workers. See docs/env.md for every variable.

bpslaunch --child-worker runs the SUPERVISED worker driver (spawned by the
Supervisor class; see its docstring for the BYTEPS_CHILD_* contract).
"""


def main(argv: List[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(_USAGE)
        return 0
    if argv and argv[0] == "--child-worker":
        return _child_worker_main()
    cfg = get_config()
    role = cfg.role.lower()
    if role == "server":
        return _run_server()
    if role == "scheduler":
        return _run_scheduler()
    if role in ("worker", "joint"):
        if not argv:
            log.error("worker role needs a command to run")
            return 2
        return _spawn_workers(argv)
    log.error("unknown DMLC_ROLE=%r", role)
    return 2


if __name__ == "__main__":
    sys.exit(main())
