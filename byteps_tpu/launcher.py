"""bpslaunch — role dispatch and local process spawn.

Reference analog: ``launcher/launch.py`` (installed as ``bpslaunch``):
reads ``DMLC_ROLE``; scheduler/server roles run the summation service;
the worker role spawns ``BYTEPS_LOCAL_SIZE`` copies of the user command
with per-child rank env, monitors them, and tears the job down if any
child fails.

TPU deltas (SURVEY §5.8): one worker process drives all local TPU devices
(so the default local_size is 1, not the visible-device count), and there is
no separate scheduler node — rendezvous is ``jax.distributed`` or direct
worker→server TCP connects with retry. ``DMLC_ROLE=scheduler`` is accepted
for reference-script compatibility and runs an extra (idle) summation
endpoint only so the process exists and exits cleanly with the job.

Usage (same shape as the reference):
    DMLC_ROLE=server  DMLC_NUM_WORKER=2 ... python -m byteps_tpu.launcher
    DMLC_ROLE=worker  DMLC_WORKER_ID=0 ... python -m byteps_tpu.launcher \
        python train.py
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
from typing import List

from byteps_tpu.common.config import get_config
from byteps_tpu.common.logging import get_logger

log = get_logger("launcher")


def _run_server() -> int:
    from byteps_tpu.server import serve_forever

    serve_forever()
    return 0


def _run_scheduler() -> int:
    # Compatibility shim: our design has no scheduler node (SURVEY §5.8 —
    # jax.distributed replaces ps-lite rendezvous). Block until SIGTERM so
    # reference launch scripts that expect a long-lived scheduler work.
    log.info("scheduler role is a no-op in byteps_tpu; idling until killed")
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    return 0


def _wrap_jax_distributed(cmd: List[str]) -> List[str]:
    """Interpose the jax.distributed bootstrap around a python command so
    the global mesh forms BEFORE user code touches any JAX backend
    (reference: ps-lite rendezvous precedes all CUDA work in byteps_init).
    Interpreter flags (``python -u train.py``) are kept ahead of the
    ``-m`` interposition. Commands that cannot be wrapped (non-python
    binaries, ``python -m pkg``, ``python -c ...``) run unwrapped with a
    warning — their own bps.init() still joins the group, just later."""
    exe = os.path.basename(cmd[0])
    if exe.startswith("python"):
        for i, arg in enumerate(cmd[1:], start=1):
            if arg in ("-m", "-c"):
                break  # module/inline form: runpy.run_path can't replay it
            if not arg.startswith("-"):
                return (cmd[:i] + ["-m", "byteps_tpu._jd_boot"] + cmd[i:])
    log.warning(
        "cannot interpose jax.distributed bootstrap around %r; the global "
        "mesh forms at bps.init() — make sure user code touches no JAX "
        "backend before that", " ".join(cmd),
    )
    return cmd


def _spawn_workers(cmd: List[str]) -> int:
    cfg = get_config()
    local_size = cfg.local_size
    procs: List[subprocess.Popen] = []
    single_host_sim = (
        local_size > 1 and cfg.num_worker == local_size and cfg.worker_id == 0
    )
    if cfg.jax_distributed:
        cmd = _wrap_jax_distributed(cmd)
    for i in range(local_size):
        env = dict(os.environ)
        env["BYTEPS_LOCAL_RANK"] = str(i)
        env["BYTEPS_LOCAL_SIZE"] = str(local_size)
        if single_host_sim:
            # localhost multi-worker simulation (reference test pattern:
            # N worker processes on one machine, each a full DMLC worker)
            env["DMLC_WORKER_ID"] = str(i)
        log.info("spawning worker local_rank=%d: %s", i, " ".join(cmd))
        procs.append(subprocess.Popen(cmd, env=env))
    rc = 0
    try:
        # fail-fast: first nonzero child exit kills the rest (reference
        # launch.py child monitoring)
        remaining = set(range(len(procs)))
        while remaining:
            for idx in list(remaining):
                p = procs[idx]
                try:
                    r = p.wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    continue
                remaining.discard(idx)
                if r != 0:
                    log.error("worker local_rank=%d exited rc=%d — "
                              "terminating job", idx, r)
                    rc = r
                    for j in remaining:
                        procs[j].terminate()
                    for j in remaining:
                        try:
                            procs[j].wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    remaining.clear()
    except KeyboardInterrupt:
        for p in procs:
            p.terminate()
        rc = 130
    return rc


_USAGE = """\
bpslaunch — BytePS-TPU job launcher (reference: launcher/launch.py)

Usage:
  DMLC_ROLE=server  DMLC_NUM_WORKER=N ... bpslaunch
  DMLC_ROLE=worker  DMLC_WORKER_ID=i ... bpslaunch python train.py [args...]

Role comes from DMLC_ROLE (worker | server | scheduler | joint). The worker
role spawns BYTEPS_LOCAL_SIZE copies of the given command with per-child
rank env and tears the job down if any child fails; with
BYTEPS_JAX_DISTRIBUTED=1 it also interposes the jax.distributed bootstrap
so one global mesh spans all workers. See docs/env.md for every variable.
"""


def main(argv: List[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        print(_USAGE)
        return 0
    cfg = get_config()
    role = cfg.role.lower()
    if role == "server":
        return _run_server()
    if role == "scheduler":
        return _run_scheduler()
    if role in ("worker", "joint"):
        if not argv:
            log.error("worker role needs a command to run")
            return 2
        return _spawn_workers(argv)
    log.error("unknown DMLC_ROLE=%r", role)
    return 2


if __name__ == "__main__":
    sys.exit(main())
