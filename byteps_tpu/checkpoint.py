"""Sharded checkpoint/resume for distributed train state.

Reference behavior (SURVEY §5.4): in the reference, checkpointing belongs
to the host framework (torch `state_dict` in the examples) and BytePS
contributes the resume synchronization — `broadcast_parameters` /
`broadcast_optimizer_state` push rank 0's restored tensors to every
worker. The TPU-native redesign goes further, because on a device mesh
the state itself is *sharded*: each leaf of params/opt_state is a global
`jax.Array` laid out over (dp, tp, pp, ep, ...) axes, and a checkpoint
must round-trip that layout — including onto a DIFFERENT topology at
restore time (save on dp=8, resume on dp=4 x tp=2 after a pod
reconfiguration).

This module is that subsystem, built on orbax (the TPU-ecosystem
checkpointer) rather than a hand-rolled format:

- `Checkpointer` — step-numbered checkpoint directory with retention
  (`max_to_keep`), async device->host->disk saves (training continues
  while the write completes), and restore-with-resharding: pass any
  pytree of like-shaped arrays (e.g. the freshly-built state from a
  train-step factory on the NEW mesh) and each leaf comes back sharded
  for that target. Orbax writes per-shard files, so on a multi-host
  global mesh every process saves only its local shards and restore
  reads only what the target sharding needs.
- `abstract_like(tree)` — ShapeDtypeStruct skeleton carrying shardings,
  for restoring without materializing a throwaway state first.
- `save_checkpoint` / `restore_checkpoint` — one-shot conveniences.

Hybrid-PS mode note (multi-pod over DCN, SURVEY §2.7 flavor 2): each pod
is an independent JAX world, so exactly one pod should write
(`Checkpointer(..., should_save=bps.rank() == 0)`) and resumers follow
the reference recipe — restore on each pod controller, then
`bps.broadcast_parameters(...)` to pin every pod to pod 0's values
(`examples/jax/checkpoint_resume.py`). On a `BYTEPS_JAX_DISTRIBUTED=1`
global mesh no broadcast is needed: restore IS collective, every process
participates and holds consistent global arrays.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

__all__ = [
    "Checkpointer",
    "abstract_like",
    "save_checkpoint",
    "restore_checkpoint",
]


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def abstract_like(tree: Any) -> Any:
    """ShapeDtypeStruct skeleton of ``tree``, each leaf keeping its
    sharding — the restore target for "same layout as this state"
    without touching the state's buffers."""
    def _ab(x):
        if not hasattr(x, "shape"):        # python scalars (step counters)
            return x
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=getattr(x, "sharding", None))

    return jax.tree.map(_ab, tree)


class Checkpointer:
    """Step-numbered sharded checkpoints with retention and async save.

    directory: root path (created if missing). Each step lands in
    ``directory/<step>/state``.
    max_to_keep: retention window; older steps are deleted after a
    newer save commits (None keeps everything).
    save_interval_steps: ``save()`` calls for steps off this grid are
    no-ops returning False (lets the train loop call save(step) every
    step and centralize cadence here).
    should_save: gate for topologies where only one controller may
    write (hybrid-PS pod 0). When False, ``save`` is a no-op; restore
    still works everywhere.
    async_save: overlap the disk write with subsequent training steps;
    ``wait()``/``close()`` (or the next save) joins the writer. The
    device->host copy happens at save() time either way, so the saved
    values are the state as of the call.
    """

    def __init__(
        self,
        directory: os.PathLike | str,
        *,
        max_to_keep: Optional[int] = 3,
        save_interval_steps: int = 1,
        should_save: bool = True,
        async_save: bool = True,
    ) -> None:
        ocp = _ocp()
        self._should_save = bool(should_save)
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    # -- writing ---------------------------------------------------------
    def save(self, step: int, state: Any, *, force: bool = False) -> bool:
        """Checkpoint ``state`` (any pytree of jax.Arrays / scalars) as
        ``step``. Returns True if a save was actually started (cadence
        grid + should_save gate)."""
        if not self._should_save:
            return False
        ocp = _ocp()
        return bool(self._mgr.save(
            int(step), args=ocp.args.StandardSave(state), force=force))

    def wait(self) -> None:
        """Join any in-flight async save (call before exit/eval)."""
        self._mgr.wait_until_finished()

    # -- reading ---------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, like: Any = None, *, step: Optional[int] = None) -> Any:
        """Restore ``step`` (default: latest). ``like`` — a pytree of
        arrays or ShapeDtypeStructs (see ``abstract_like``) — gives the
        target structure/shardings; each restored leaf is laid out for
        its ``like`` leaf's sharding, which is how a checkpoint written
        on one mesh resumes on another. Without ``like`` the checkpoint
        restores with its saved layout (single-process only)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint steps under {self._mgr.directory}")
        ocp = _ocp()
        if like is None:
            return self._mgr.restore(int(step))
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore(abstract_like(like)))

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def save_checkpoint(directory, step: int, state: Any) -> None:
    """One-shot synchronous save of ``state`` as ``step``."""
    with Checkpointer(directory, max_to_keep=None, async_save=False) as ck:
        ck.save(step, state, force=True)
        ck.wait()


def restore_checkpoint(directory, like: Any = None,
                       step: Optional[int] = None) -> Any:
    """One-shot restore (latest step by default), resharded onto
    ``like``'s shardings when given."""
    with Checkpointer(directory, max_to_keep=None, async_save=False) as ck:
        return ck.restore(like, step=step)
