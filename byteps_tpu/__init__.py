"""byteps_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capabilities of joapolarbear/byteps (a fork of
bytedance/byteps; see SURVEY.md for the reference's structural analysis):

* Horovod-style ``push_pull`` / ``DistributedOptimizer`` APIs
  (reference: ``byteps/torch/__init__.py``, ``byteps/tensorflow/__init__.py``)
* tensor partitioning into ~4 MB chunks with priority = -declaration order and
  credit-limited in-flight partitions
  (reference: ``byteps/common/operations.cc``, ``byteps/common/scheduled_queue.cc``)
* pluggable gradient compression — onebit, topk, randomk, dithering, with
  error-feedback and Nesterov-momentum decorators
  (reference: ``byteps/common/compressor/``)
* hybrid parameter-server topology: intra-pod ICI collectives + a C++
  summation service over DCN
  (reference: ``byteps/server/server.cc``, ``3rdparty/ps-lite/``)

The compute path is JAX/XLA/Pallas over a ``jax.sharding.Mesh``; the host
runtime (DCN summation server, CPU reducer) is native C++.
"""

__version__ = "0.1.0"

from byteps_tpu.common.config import Config, get_config  # noqa: F401
