"""byteps_tpu — a TPU-native distributed training framework.

A ground-up re-design of the capabilities of joapolarbear/byteps (a fork of
bytedance/byteps; see SURVEY.md for the reference's structural analysis):

* Horovod-style ``push_pull`` / ``DistributedOptimizer`` APIs
  (reference: ``byteps/torch/__init__.py``, ``byteps/tensorflow/__init__.py``)
* tensor partitioning into ~4 MB chunks with priority = -declaration order and
  credit-limited in-flight partitions
  (reference: ``byteps/common/operations.cc``, ``byteps/common/scheduled_queue.cc``)
* pluggable gradient compression — onebit, topk, randomk, dithering, with
  error-feedback and Nesterov-momentum decorators
  (reference: ``byteps/common/compressor/``)
* hybrid parameter-server topology: intra-pod ICI collectives + a C++
  summation service over DCN
  (reference: ``byteps/server/server.cc``, ``3rdparty/ps-lite/``)

The compute path is JAX/XLA/Pallas over a ``jax.sharding.Mesh``; the host
runtime (DCN summation server, CPU reducer) is native C++.
"""

__version__ = "0.1.0"

import sys as _sys

if "jax" in _sys.modules:
    # jax is already loaded (an interactive session, a test harness):
    # install the API-rename aliases now, before any user code calls
    # jax.shard_map directly. Cold jax-less processes skip this — the
    # jax-consuming subpackages (comm/jax/ops/models/parallel) each call
    # ensure() at import, so nobody pays jax's import cost for the
    # server/torch-only paths. See common/jax_compat.py.
    from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

    _ensure_jax_compat()

from byteps_tpu.common.config import Config, get_config  # noqa: F401,E402


def metrics_snapshot() -> dict:
    """One JSON-safe view of the always-on telemetry plane
    (docs/observability.md): the unified metrics registry (scheduler
    stage dwell/run percentiles, per-NIC wire bytes/attempts/retries,
    pacer debt, ICI dispatch counts, fault injections, train-step
    walltime) plus the flight recorder's ring occupancy. The hook bench
    legs and tests assert against — and what ops would scrape."""
    from byteps_tpu.common.flight_recorder import get_flight_recorder
    from byteps_tpu.common.metrics import get_registry

    return {
        "metrics": get_registry().snapshot(),
        "flight_recorder": get_flight_recorder().summary(),
    }
