"""Ring attention — sequence/context parallelism over an sp mesh axis.

No reference analog (SURVEY §5.7: the reference predates long-context
training); this is the TPU-idiomatic form: the sequence is sharded over the
``sp`` axis, each device holds one Q/K/V block, and K/V blocks rotate
around the ring with ``jax.lax.ppermute`` while a flash-attention-style
online softmax accumulates the output. Wire traffic per step is one K/V
block over nearest-neighbour ICI links; compute of step t overlaps the
ppermute of step t+1 on real hardware (XLA async collective).

Two implementations share this ring schedule:

* **Pallas** (TPU, or forced via ``BYTEPS_KERNEL_BACKEND=pallas``): each
  step runs the flash kernel (:mod:`byteps_tpu.ops.flash_attention`) on
  the local Q against the visiting K/V block with *global* position
  offsets for causal masking, and the per-step ``(o, lse)`` partials are
  merged exactly with :func:`merge_attention` — O(S_loc·D) memory per
  device, scores never materialize even blockwise.
* **jnp fallback**: the same online softmax with per-step
  ``(m, l, o)`` carried at the jnp level (materializes one
  ``(B, H, S_loc, S_loc)`` score block per step).

Differentiable: the ppermute transposes to the reverse rotation, so the
backward pass is itself a ring; on the Pallas path the lse cotangent of
the merge folds into the flash backward's dS.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from byteps_tpu.ops.flash_attention import (
    flash_attention as _flash_attention,
    flash_attention_lse as _flash_attention_lse,
    merge_attention as _merge_attention,
    supported as _flash_supported,
    use_pallas as _use_pallas,
)

_NEG = -1e30  # masked-score value; avoids -inf NaN in the online softmax


def _block_attn(q, k, v, q_pos, k_pos, scale, causal, m, l, o):
    """One (q-block × k-block) online-softmax update.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D); m,l: (B, H, Sq); o: (B, Sq, H, D).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale          # (B,H,Sq,Sk)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]               # (Sq,Sk)
        s = jnp.where(mask[None, None], s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))                    # (B,H,Sq)
    # rescale previous accumulator, accumulate this block
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                         # (B,H,Sq,Sk)
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, o_new


def plain_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True) -> jnp.ndarray:
    """Single-device softmax attention, (B, S, H, D) layout — the
    entry()/single-chip path. Runs the flash kernel where supported;
    :func:`byteps_tpu.ops.attention_jnp` is the golden / fallback."""
    return _flash_attention(q, k, v, causal=causal)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   sp_axis: Optional[str], causal: bool = True) -> jnp.ndarray:
    """Sequence-parallel attention inside shard_map.

    q/k/v: (B, S_local, H, D) — this device's sequence block; the global
    sequence is the sp-axis concatenation of blocks in axis-index order.
    With ``sp_axis=None`` falls through to :func:`plain_attention`.
    """
    if sp_axis is None:
        return plain_attention(q, k, v, causal=causal)
    n = jax.lax.axis_size(sp_axis)
    if n == 1:
        return plain_attention(q, k, v, causal=causal)
    if _use_pallas() and _flash_supported(q.shape[1], k.shape[1],
                                          q.shape[-1]):
        return _ring_flash(q, k, v, sp_axis, n, causal)
    idx = jax.lax.axis_index(sp_axis)
    B, S_loc, H, D = q.shape
    scale = jnp.float32(1.0 / (D ** 0.5))
    qf = q.astype(jnp.float32)
    q_pos = idx * S_loc + jnp.arange(S_loc)

    m = jnp.full((B, H, S_loc), _NEG, jnp.float32)
    l = jnp.zeros((B, H, S_loc), jnp.float32)
    o = jnp.zeros((B, S_loc, H, D), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk = k.astype(jnp.float32)
    v_blk = v.astype(jnp.float32)
    # sp is small and static → unrolled python loop (one XLA program);
    # lax.scan would re-materialize the ring state each step for no gain.
    for step in range(n):
        src = (idx - step) % n                # owner of the block we hold
        k_pos = src * S_loc + jnp.arange(S_loc)
        m, l, o = _block_attn(qf, k_blk, v_blk, q_pos, k_pos, scale,
                              causal, m, l, o)
        if step + 1 < n:
            k_blk = jax.lax.ppermute(k_blk, sp_axis, perm)
            v_blk = jax.lax.ppermute(v_blk, sp_axis, perm)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _ring_flash(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                sp_axis: str, n: int, causal: bool) -> jnp.ndarray:
    """Flash-kernel ring: per-step flash partials merged by logsumexp.

    The visiting K/V block's global offset feeds the kernel's causal
    mask, so above-diagonal steps contribute (o=0, lse=−1e30) partials
    that the merge drops exactly; the merge itself runs in f32 at the
    jnp level (fused elementwise by XLA) and its lse gradients flow back
    through the flash backward kernels.
    """
    idx = jax.lax.axis_index(sp_axis)
    B, S_loc, H, D = q.shape
    q_off = idx * S_loc

    o = jnp.zeros((B, S_loc, H, D), jnp.float32)
    lse = jnp.full((B, S_loc, H), _NEG, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v
    for step in range(n):
        src = (idx - step) % n                # owner of the block we hold
        o_s, lse_s = _flash_attention_lse(
            q, k_blk, v_blk, q_off, src * S_loc, causal=causal)
        o, lse = _merge_attention(o, lse, o_s, lse_s)
        if step + 1 < n:
            k_blk = jax.lax.ppermute(k_blk, sp_axis, perm)
            v_blk = jax.lax.ppermute(v_blk, sp_axis, perm)
    return o.astype(q.dtype)
