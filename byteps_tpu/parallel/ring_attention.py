"""Ring attention — sequence/context parallelism over an sp mesh axis.

No reference analog (SURVEY §5.7: the reference predates long-context
training); this is the TPU-idiomatic form: the sequence is sharded over the
``sp`` axis, each device holds one Q/K/V block, and K/V blocks rotate
around the ring with ``jax.lax.ppermute`` while a flash-attention-style
online softmax accumulates the output. Wire traffic per step is one K/V
block over nearest-neighbour ICI links; compute of step t overlaps the
ppermute of step t+1 on real hardware (XLA async collective).

Two implementations share this ring schedule:

* **Pallas** (TPU, or forced via ``BYTEPS_KERNEL_BACKEND=pallas``): each
  step runs the flash kernel (:mod:`byteps_tpu.ops.flash_attention`) on
  the local Q against the visiting K/V block with *global* position
  offsets for causal masking, and the per-step ``(o, lse)`` partials are
  merged exactly with :func:`merge_attention` — O(S_loc·D) memory per
  device, scores never materialize even blockwise.
* **jnp fallback**: the same online softmax with per-step
  ``(m, l, o)`` carried at the jnp level (materializes one
  ``(B, H, S_loc, S_loc)`` score block per step).

Differentiable: the ppermute transposes to the reverse rotation, so the
backward pass is itself a ring; on the Pallas path the lse cotangent of
the merge folds into the flash backward's dS.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from byteps_tpu.ops.flash_attention import (
    attention_lse as _attention_lse,
    flash_attention as _flash_attention,
    flash_attention_lse as _flash_attention_lse,
    merge_attention as _merge_attention,
    supported as _flash_supported,
    use_pallas as _use_pallas,
)

_NEG = -1e30  # masked-score value; avoids -inf NaN in the online softmax


def _block_attn(q, k, v, q_pos, k_pos, scale, causal, m, l, o):
    """One (q-block × k-block) online-softmax update.

    q: (B, Sq, H, D), k/v: (B, Sk, H, D); m,l: (B, H, Sq); o: (B, Sq, H, D).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale          # (B,H,Sq,Sk)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]               # (Sq,Sk)
        s = jnp.where(mask[None, None], s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))                    # (B,H,Sq)
    # rescale previous accumulator, accumulate this block
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                         # (B,H,Sq,Sk)
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v
    )
    return m_new, l_new, o_new


def plain_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = True) -> jnp.ndarray:
    """Single-device softmax attention, (B, S, H, D) layout — the
    entry()/single-chip path. Runs the flash kernel where supported;
    :func:`byteps_tpu.ops.attention_jnp` is the golden / fallback."""
    return _flash_attention(q, k, v, causal=causal)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   sp_axis: Optional[str], causal: bool = True) -> jnp.ndarray:
    """Sequence-parallel attention inside shard_map.

    q/k/v: (B, S_local, H, D) — this device's sequence block; the global
    sequence is the sp-axis concatenation of blocks in axis-index order.
    With ``sp_axis=None`` falls through to :func:`plain_attention`.
    """
    if sp_axis is None:
        return plain_attention(q, k, v, causal=causal)
    n = jax.lax.axis_size(sp_axis)
    if n == 1:
        return plain_attention(q, k, v, causal=causal)
    if _use_pallas() and _flash_supported(q.shape[1], k.shape[1],
                                          q.shape[-1]):
        return _ring_flash(q, k, v, sp_axis, n, causal)
    # GQA: the ring rotates the NARROW (Hkv-head) k/v blocks — G× less
    # ICI wire per step — and widens only the in-hand block at compute
    # time (the flash ring's kernels consume narrow blocks directly).
    rep = q.shape[2] // k.shape[2]
    idx = jax.lax.axis_index(sp_axis)
    B, S_loc, H, D = q.shape
    scale = jnp.float32(1.0 / (D ** 0.5))
    qf = q.astype(jnp.float32)
    q_pos = idx * S_loc + jnp.arange(S_loc)

    m = jnp.full((B, H, S_loc), _NEG, jnp.float32)
    l = jnp.zeros((B, H, S_loc), jnp.float32)
    o = jnp.zeros((B, S_loc, H, D), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk = k.astype(jnp.float32)
    v_blk = v.astype(jnp.float32)
    # sp is small and static → unrolled python loop (one XLA program);
    # lax.scan would re-materialize the ring state each step for no gain.
    for step in range(n):
        src = (idx - step) % n                # owner of the block we hold
        # the k block's OWN length, not q's: cross-attention rings rotate
        # encoder-memory blocks under decoder queries (Sk_loc != S_loc)
        Sk_loc = k_blk.shape[1]
        k_pos = src * Sk_loc + jnp.arange(Sk_loc)
        k_use = k_blk if rep == 1 else jnp.repeat(k_blk, rep, axis=2)
        v_use = v_blk if rep == 1 else jnp.repeat(v_blk, rep, axis=2)
        m, l, o = _block_attn(qf, k_use, v_use, q_pos, k_pos, scale,
                              causal, m, l, o)
        if step + 1 < n:
            k_blk = jax.lax.ppermute(k_blk, sp_axis, perm)
            v_blk = jax.lax.ppermute(v_blk, sp_axis, perm)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def zigzag_permutation(S: int, n: int) -> jnp.ndarray:
    """Global index map for the zigzag sequence layout.

    The sequence splits into 2n chunks; device d owns chunks
    ``(d, 2n−1−d)`` — pairing an early chunk with a late one so every
    device carries the same causal-attention load (the contiguous layout
    gives device d work ∝ d+1; zigzag is the standard rebalancing).
    ``perm[i]`` = the global position stored at layout slot i; shard the
    permuted array ``P('sp')`` and slot order lines up with the ring's
    per-device (chunk_d, chunk_{2n−1−d}) convention.
    """
    if S % (2 * n) != 0:
        raise ValueError(f"zigzag needs S ({S}) divisible by 2·sp ({2 * n})")
    c = S // (2 * n)
    chunks = []
    for d in range(n):
        chunks.append(jnp.arange(d * c, (d + 1) * c))
        e = 2 * n - 1 - d
        chunks.append(jnp.arange(e * c, (e + 1) * c))
    return jnp.concatenate(chunks)


def zigzag_inverse(S: int, n: int) -> jnp.ndarray:
    """Inverse map: ``x_layout[zigzag_inverse(S, n)] == x_original``."""
    perm = zigzag_permutation(S, n)
    inv = jnp.zeros((S,), jnp.int32)
    return inv.at[perm].set(jnp.arange(S, dtype=jnp.int32))


def zigzag_local_positions(S_loc: int, sp_axis: str) -> jnp.ndarray:
    """This device's global positions under the zigzag layout (S_loc
    local tokens = two chunks of S_loc/2). Call inside shard_map —
    feeds position embeddings and loss masking."""
    n = jax.lax.axis_size(sp_axis)
    idx = jax.lax.axis_index(sp_axis)
    c = S_loc // 2
    a = idx * c + jnp.arange(c)
    b = (2 * n - 1 - idx) * c + jnp.arange(c)
    return jnp.concatenate([a, b])


def zigzag_ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                          sp_axis: Optional[str],
                          causal: bool = True) -> jnp.ndarray:
    """Load-balanced causal ring: inputs/outputs in the zigzag layout.

    Each device's S_loc tokens are its (chunk_d, chunk_{2n−1−d}) pair
    (see :func:`zigzag_permutation`); K/V pairs rotate around the ring
    and every (q-half, k-half) combination runs flash attention with its
    own global offsets, merged by logsumexp. Per ring step each device's
    live work is ~equal (one early + one late chunk), vs the contiguous
    ring where device d computes on only d+1 of n steps — ~2× utilization
    for causal attention at large n. Differentiable end-to-end (ppermute
    transpose + the flash/jnp lse VJPs).
    """
    if sp_axis is None:
        return plain_attention(q, k, v, causal=causal)
    n = jax.lax.axis_size(sp_axis)
    if n == 1:
        return plain_attention(q, k, v, causal=causal)
    B, S_loc, H, D = q.shape
    if S_loc % 2 != 0:
        raise ValueError(f"zigzag layout needs even local length; got "
                         f"{S_loc}")
    c = S_loc // 2
    idx = jax.lax.axis_index(sp_axis)
    my_offs = (idx * c, (2 * n - 1 - idx) * c)
    q_halves = (q[:, :c], q[:, c:])

    state = [
        (jnp.zeros((B, c, H, D), jnp.float32),
         jnp.full((B, c, H), _NEG, jnp.float32))
        for _ in range(2)
    ]
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v
    for step in range(n):
        src = (idx - step) % n
        k_offs = (src * c, (2 * n - 1 - src) * c)
        kh = (k_blk[:, :c], k_blk[:, c:])
        vh = (v_blk[:, :c], v_blk[:, c:])
        if not causal:
            # bidirectional: all four half-combos are live
            for ki in range(2):
                for qi in range(2):
                    o_s, lse_s = _attention_lse(
                        q_halves[qi], kh[ki], vh[ki], my_offs[qi],
                        k_offs[ki], causal=False)
                    state[qi] = _merge_attention(*state[qi], o_s, lse_s)
        elif step == 0:
            # diagonal step (src == idx): e_q×e_k and l_q×l_k carry their
            # own causal masks; l_q×e_k is fully live
            for qi, ki, cc in ((0, 0, True), (1, 0, False), (1, 1, True)):
                o_s, lse_s = _attention_lse(
                    q_halves[qi], kh[ki], vh[ki], my_offs[qi], k_offs[ki],
                    causal=cc)
                state[qi] = _merge_attention(*state[qi], o_s, lse_s)
        else:
            # off-diagonal: exactly TWO live half-combos, both UNMASKED.
            # l_q×e_k (late queries over early keys) is live at every
            # step; of e_q×e_k / l_q×l_k exactly one is live — e_q×e_k
            # when idx > src (early q block comes after the early k
            # block), l_q×l_k when idx < src (the LATE ordering flips) —
            # and the other is fully masked. Select the live combo's
            # operands branchlessly (scalar where; the matmul runs once)
            # and route its partial to the right half's accumulator by
            # giving the other half a neutral lse (−1e30 merges to a
            # no-op). This executes 2 block-matmuls per step instead of
            # the naive 4 (or the previous 3): the measured FLOP edge
            # over the contiguous ring grows from ~1.3× to ~1.8× at
            # sp=8, asymptotically 2×.
            o_s, lse_s = _attention_lse(
                q_halves[1], kh[0], vh[0], my_offs[1], k_offs[0],
                causal=False)
            state[1] = _merge_attention(*state[1], o_s, lse_s)
            sel = idx > src
            qB = jnp.where(sel, q_halves[0], q_halves[1])
            kB = jnp.where(sel, kh[0], kh[1])
            vB = jnp.where(sel, vh[0], vh[1])
            oB, lseB = _attention_lse(qB, kB, vB, 0, 0, causal=False)
            state[0] = _merge_attention(
                *state[0], oB, jnp.where(sel, lseB, _NEG))
            state[1] = _merge_attention(
                *state[1], oB, jnp.where(sel, _NEG, lseB))
        if step + 1 < n:
            k_blk = jax.lax.ppermute(k_blk, sp_axis, perm)
            v_blk = jax.lax.ppermute(v_blk, sp_axis, perm)
    out = jnp.concatenate([state[0][0], state[1][0]], axis=1)
    return out.astype(q.dtype)


def _ring_flash(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                sp_axis: str, n: int, causal: bool) -> jnp.ndarray:
    """Flash-kernel ring: per-step flash partials merged by logsumexp.

    The visiting K/V block's global offset feeds the kernel's causal
    mask, so above-diagonal steps contribute (o=0, lse=−1e30) partials
    that the merge drops exactly; the merge itself runs in f32 at the
    jnp level (fused elementwise by XLA) and its lse gradients flow back
    through the flash backward kernels.
    """
    idx = jax.lax.axis_index(sp_axis)
    B, S_loc, H, D = q.shape
    q_off = idx * S_loc

    o = jnp.zeros((B, S_loc, H, D), jnp.float32)
    lse = jnp.full((B, S_loc, H), _NEG, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v
    for step in range(n):
        src = (idx - step) % n                # owner of the block we hold
        # offset by the k block's own length (rectangular cross-attn rings)
        o_s, lse_s = _flash_attention_lse(
            q, k_blk, v_blk, q_off, src * k_blk.shape[1], causal=causal)
        o, lse = _merge_attention(o, lse, o_s, lse_s)
        if step + 1 < n:
            k_blk = jax.lax.ppermute(k_blk, sp_axis, perm)
            v_blk = jax.lax.ppermute(v_blk, sp_axis, perm)
    return o.astype(q.dtype)
