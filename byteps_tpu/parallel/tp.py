"""Tensor-parallel building blocks (Megatron-style, shard_map-first).

No reference analog (the reference is DP-only, SURVEY §2.7); these are the
TPU-idiomatic primitives for sharding a transformer's wide matmuls over the
innermost mesh axis. Called inside ``shard_map``; weights arrive already
sharded by the in_specs (column-parallel: out-features split; row-parallel:
in-features split), so the functions are plain matmuls plus the one psum
the row-parallel output needs — XLA overlaps it with the next layer's
compute.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def maybe_psum(x: jnp.ndarray, axis: Optional[str]) -> jnp.ndarray:
    """psum over ``axis`` when it names a mesh axis, identity when None
    (single-device / axis-disabled path shares the same model code)."""
    if axis is None:
        return x
    return jax.lax.psum(x, axis)


def col_parallel_matmul(x: jnp.ndarray, w: jnp.ndarray,
                        b: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """y_local = x @ w_local: ``w`` is split on its output dim; the result
    stays sharded (each device owns its slice of features). No collective."""
    y = x @ w
    if b is not None:
        y = y + b
    return y


def row_parallel_matmul(x_local: jnp.ndarray, w: jnp.ndarray,
                        axis: Optional[str],
                        b: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """y = psum_tp(x_local @ w_local): ``w`` is split on its input dim,
    matching a column-parallel producer; the psum makes the output
    replicated across tp. Bias is added AFTER the psum (it is replicated)."""
    y = maybe_psum(x_local @ w, axis)
    if b is not None:
        y = y + b
    return y
