"""Expert parallelism (ep axis): Mixture-of-Experts FFN with all_to_all
dispatch.

The reference is data-parallel only; ep is the last of the "beyond
reference" mesh axes (pp/tp/sp being the others). TPU-first design
(Switch/GShard style): top-1 or top-2 gating with a static per-expert
capacity (XLA needs static shapes — tokens beyond capacity are dropped,
their residual path passes through untouched), dispatch/combine as
einsums against a one-hot (token, expert, slot) tensor so the MXU does
the routing, and expert placement over the ``ep`` mesh axis with a pair
of ``lax.all_to_all`` collectives shipping token slots to their expert's
owner and back over ICI.

Inside ``shard_map`` each device owns ``E / ep_size`` experts
(expert-stacked weights sharded ``P('ep')`` on their leading axis) and
every device routes its OWN tokens to all E experts — dp and ep compose:
dp replicas each contribute their local batch's slots.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def topk_dispatch(gate_logits: jnp.ndarray, capacity: int, k: int = 1):
    """Top-k routing tensors from ``(T, E)`` gate logits (k=1: Switch;
    k=2: GShard-style, second choices take slots after first choices and
    the two gates renormalize to sum 1 per token).

    Returns ``(dispatch, combine, aux_loss)``: ``dispatch`` is a one-hot
    ``(T, E, C)`` float tensor mapping each kept (token, choice) to its
    (expert, slot); ``combine`` is ``dispatch`` scaled by the choice's
    gate weight; ``aux_loss`` is the Switch load-balancing loss on the
    FIRST choice (mean_e frac_tokens_e · mean_prob_e · E).
    """
    T, E = gate_logits.shape
    if not 1 <= k <= E:
        raise ValueError(f"router top-k must satisfy 1 <= k <= n_experts "
                         f"({E}); got k={k}")
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    remaining = probs
    onehots, gates = [], []
    for _ in range(k):
        expert = jnp.argmax(remaining, axis=-1)            # (T,)
        oh = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # (T, E)
        gates.append(jnp.sum(probs * oh, axis=-1))
        onehots.append(oh)
        remaining = remaining * (1.0 - oh)
    if k > 1:
        # renormalize so each token's kept choices sum to 1 (GShard).
        # NEVER for k=1: that would collapse every weight to exactly 1.0,
        # silencing the router's gradient through the task loss — Switch
        # keeps the raw softmax prob as the combine weight
        gate_sum = sum(gates)
        gates = [g / jnp.maximum(gate_sum, 1e-9) for g in gates]

    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    used = jnp.zeros((E,), jnp.float32)  # slots consumed by earlier ranks
    for oh, gate in zip(onehots, gates):
        # slot = rank among earlier tokens of this expert AT THIS CHOICE
        # rank, offset by slots used by earlier choice ranks
        slot = (jnp.cumsum(oh, axis=0) - 1.0) * oh + used[None, :] * oh
        kept = (slot < capacity) & (oh > 0)
        slot_oh = jax.nn.one_hot(
            jnp.sum(jnp.clip(slot, 0, capacity - 1),
                    axis=-1).astype(jnp.int32),
            capacity, dtype=jnp.float32,
        )
        d = kept.astype(jnp.float32)[:, :, None] * slot_oh[:, None, :]
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        used = used + jnp.sum(oh, axis=0)
    frac = onehots[0].mean(axis=0)
    aux = E * jnp.sum(frac * probs.mean(axis=0))
    return dispatch, combine, aux


def top1_dispatch(gate_logits: jnp.ndarray, capacity: int):
    """Switch-style top-1 routing (see :func:`topk_dispatch`)."""
    return topk_dispatch(gate_logits, capacity, k=1)


def moe_ffn(
    x: jnp.ndarray,
    params,
    capacity_factor: float = 1.25,
    ep_axis: Optional[str] = None,
    activation=jax.nn.gelu,
    router_topk: int = 1,
    tp_axis: Optional[str] = None,
    no_drop: bool = False,
):
    """MoE feed-forward over the trailing feature dim of ``x (..., d)``.

    ``params``: ``wg (d, E)`` gate; expert-stacked ``w1 (E_loc, d, ff)``,
    ``b1 (E_loc, ff)``, ``w2 (E_loc, ff, d)``, ``b2 (E_loc, d)`` — with
    ``ep_axis`` set these are THIS device's expert slab (global tensors
    sharded ``P('ep')``); without it they hold all experts. With
    ``tp_axis`` the experts are additionally Megatron-sharded: w1/b1
    column-parallel over the ff dim, w2 row-parallel with a psum over tp
    restoring the full output (`moe_specs(ep, tp)` gives the layout).

    Returns ``(y, aux_loss)`` with ``y`` shaped like ``x``. Dropped
    (over-capacity) tokens produce zero — add the residual outside, as the
    transformer block does. ``no_drop=True`` sets capacity so NO token can
    be dropped (``T`` slots per expert — the worst-case load, since a
    token's k choices are distinct experts) — decode-time routing, where
    a drop silently corrupts the sample. Memory note: that worst case
    allocates ``E × T × d`` dispatch slots per layer, so no-drop prefill
    of a long prompt spikes HBM roughly ``E×`` the dense activation;
    chunk long prefills (gpt_apply_cached accepts any T) if that
    pressure shows up in profiles.
    """
    ep = jax.lax.axis_size(ep_axis) if ep_axis is not None else 1
    e_loc = params["w1"].shape[0]
    E = e_loc * ep
    lead = x.shape[:-1]
    d = x.shape[-1]
    T = 1
    for s in lead:
        T *= s
    xt = x.reshape(T, d)
    # gating/dispatch in f32 (standard Switch practice); the expert
    # matmuls and the all_to_all payload run in x.dtype like the dense
    # family's _mlp — bf16 configs keep full MXU rate and half ICI bytes
    gate_logits = xt.astype(jnp.float32) @ params["wg"].astype(jnp.float32)
    # no-drop worst case is T (a token's k choices are DISTINCT experts,
    # so any one expert receives at most T assignments)
    cap = (T if no_drop
           else max(1, int(capacity_factor * router_topk * T / E)))
    dispatch, combine, aux = topk_dispatch(gate_logits, cap, k=router_topk)
    slots = jnp.einsum(
        "tec,td->ecd", dispatch.astype(x.dtype), xt
    )                                                      # (E, cap, d)
    if ep_axis is not None:
        # ship each expert's slots to its owner: (E, cap, d) →
        # (ep, E_loc, cap, d) → all_to_all → every device holds, for its
        # OWN experts, the slots from every peer: (ep, E_loc, cap, d)
        slots = slots.reshape(ep, e_loc, cap, d)
        slots = jax.lax.all_to_all(
            slots, ep_axis, split_axis=0, concat_axis=0, tiled=False
        )
        # (ep, E_loc, cap, d): axis 0 now indexes the SOURCE device; bring
        # the local-expert axis out front for the expert matmuls
        slots = slots.transpose(1, 0, 2, 3).reshape(e_loc, ep * cap, d)
    h = jnp.einsum("ecd,edf->ecf", slots, params["w1"].astype(x.dtype))
    h = h + params["b1"][:, None, :].astype(x.dtype)
    if "w3" in params:
        # gated experts (structural dispatch, like the dense _mlp):
        # silu(slots·w1) ∘ (slots·w3), per expert
        g = jnp.einsum("ecd,edf->ecf", slots, params["w3"].astype(x.dtype))
        g = g + params["b3"][:, None, :].astype(x.dtype)
        h = jax.nn.silu(h) * g
    else:
        h = activation(h)
    y = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(x.dtype))
    if tp_axis is not None:
        # row-parallel: each tp shard computed a partial over its ff slice
        y = jax.lax.psum(y, tp_axis)
    y = y + params["b2"][:, None, :].astype(x.dtype)
    if ep_axis is not None:
        y = y.reshape(e_loc, ep, cap, d).transpose(1, 0, 2, 3)
        y = jax.lax.all_to_all(
            y, ep_axis, split_axis=0, concat_axis=0, tiled=False
        )
        # axis 0 = expert-group owner: global expert e = owner*E_loc + local
        y = y.reshape(E, cap, d)
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), y)
    return out.reshape(*lead, d).astype(x.dtype), aux.astype(jnp.float32)


def moe_init(rng, d: int, ff: int, n_experts: int, std: float = 0.02,
             mlp: str = "gelu"):
    """Expert-stacked MoE FFN params (shard w1/b1/w2/b2 ``P('ep')``).
    ``mlp="swiglu"`` adds the per-expert gate stack ``w3/b3`` (llama-
    style gated experts — the FFN mirrors the dense family's
    ``_mlp`` structural dispatch)."""
    if mlp not in ("gelu", "swiglu"):
        raise ValueError(f"unknown mlp {mlp!r} — expected 'gelu' or "
                         "'swiglu'")
    k = jax.random.split(rng, 4)
    p = {
        "wg": jax.random.normal(k[0], (d, n_experts), jnp.float32) * std,
        "w1": jax.random.normal(k[1], (n_experts, d, ff), jnp.float32) * std,
        "b1": jnp.zeros((n_experts, ff), jnp.float32),
        "w2": jax.random.normal(k[2], (n_experts, ff, d), jnp.float32) * std,
        "b2": jnp.zeros((n_experts, d), jnp.float32),
    }
    if mlp == "swiglu":
        p["w3"] = jax.random.normal(k[3], (n_experts, d, ff),
                                    jnp.float32) * std
        p["b3"] = jnp.zeros((n_experts, ff), jnp.float32)
    return p


def moe_logical_specs(mlp: str = "gelu"):
    """Logical-axis dict for :func:`moe_init` output: experts over the
    expert axis, each expert's ff dim Megatron col/row over mlp."""
    return {
        "wg": (None, None),
        "w1": ("expert", "embed", "mlp"), "b1": ("expert", "mlp"),
        "w2": ("expert", "mlp", "embed"), "b2": ("expert",),
        **({"w3": ("expert", "embed", "mlp"), "b3": ("expert", "mlp")}
           if mlp == "swiglu" else {}),
    }


def moe_specs(ep_axis: Optional[str], tp_axis: Optional[str] = None,
              mlp: str = "gelu"):
    """PartitionSpec dict for :func:`moe_init` output: experts over ep,
    and (optionally) Megatron col/row sharding of each expert's ff dim
    over tp."""
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(moe_logical_specs(mlp),
                         rules_from_axes(tp_axis=tp_axis, ep_axis=ep_axis))
