"""Rematerialization helper shared by every model family and the pipeline."""

from __future__ import annotations

import jax


def maybe_remat(fn, remat: bool):
    """Wrap a per-layer block fn in ``jax.checkpoint`` when ``remat`` is on.

    Full-block remat trades HBM for FLOPs — and, on tp/sp-sharded meshes,
    for INTERCONNECT: the backward pass re-runs everything in the block,
    including tp psums and sp ring-attention ppermutes, roughly doubling
    per-layer collective traffic. If ICI is the bottleneck, switch to a
    ``jax.checkpoint`` policy that saves collective outputs (e.g.
    ``checkpoint_name`` on the collective results +
    ``save_only_these_names``) instead of flipping this helper off.
    """
    return jax.checkpoint(fn) if remat else fn
