"""Mesh factory for multi-axis parallelism.

The reference's topology is env-var process ranks (``DMLC_WORKER_ID`` ×
``BYTEPS_LOCAL_RANK``, SURVEY §5.6); on TPU the topology is a named
``jax.sharding.Mesh``. Axis convention (order matters — outermost first so
dp rides DCN across slices and tp/sp ride ICI within one):

    (pp, dp, sp, tp, ep)   — any axis of size 1 may be omitted.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """Named axis sizes for :func:`make_mesh`. Size 1 disables an axis."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def total(self) -> int:
        return self.dp * self.tp * self.sp * self.pp * self.ep

    def as_dict(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "sp": self.sp,
                "tp": self.tp, "ep": self.ep}


def make_mesh(axes: MeshAxes, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh with only the non-trivial axes of ``axes``.

    Axis order is (pp, dp, sp, tp, ep) outermost→innermost: tp needs the
    tightest coupling (per-matmul psum) so it gets the innermost (fastest
    ICI neighbourhood) placement; pp crosses the slowest links.
    """
    if devices is None:
        devices = jax.devices()
    if axes.total != len(devices):
        raise ValueError(
            f"mesh axes {axes.as_dict()} require {axes.total} devices, "
            f"have {len(devices)}"
        )
    names = []
    sizes = []
    for name, size in axes.as_dict().items():
        if size > 1:
            names.append(name)
            sizes.append(size)
    if not names:  # single device: degenerate 1-axis mesh so axis lookups work
        names, sizes = ["dp"], [1]
    return jax.make_mesh(tuple(sizes), tuple(names), devices=devices)


def factor_devices(n: int, want_tp: int = 2, want_sp: int = 2) -> MeshAxes:
    """Heuristic (dp, tp, sp) factorization of ``n`` devices.

    Used by the dry-run path and examples: carve off tp then sp (innermost
    first) when they divide ``n``, leave the rest to dp.
    """
    tp = want_tp if n % want_tp == 0 and n >= want_tp else 1
    rem = n // tp
    sp = want_sp if rem % want_sp == 0 and rem >= want_sp else 1
    dp = rem // sp
    return MeshAxes(dp=dp, tp=tp, sp=sp)
