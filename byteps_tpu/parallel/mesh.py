"""Mesh factory for multi-axis parallelism.

The reference's topology is env-var process ranks (``DMLC_WORKER_ID`` ×
``BYTEPS_LOCAL_RANK``, SURVEY §5.6); on TPU the topology is a named
``jax.sharding.Mesh``. Axis convention (order matters — outermost first so
slice_ rides DCN across slices and tp/sp ride ICI within one):

    (slice_, pp, dp, sp, tp, ep)   — any axis of size 1 may be omitted.

``slice_`` is the DCN axis: one entry per TPU slice (pod span). On real
multi-slice topologies :func:`make_mesh` builds it with
``mesh_utils.create_hybrid_device_mesh`` so the outer axis crosses the
data-center network and every inner axis stays on ICI. On CPU or a single
slice the boundary is emulated by contiguous grouping so tier-1 tests can
exercise the multi-slice code paths on fake devices.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """Named axis sizes for :func:`make_mesh`. Size 1 disables an axis."""

    dp: int = 1
    tp: int = 1
    sp: int = 1
    pp: int = 1
    ep: int = 1
    slice_: int = 1

    @property
    def total(self) -> int:
        return self.dp * self.tp * self.sp * self.pp * self.ep * self.slice_

    @property
    def per_slice(self) -> int:
        return self.dp * self.tp * self.sp * self.pp * self.ep

    def as_dict(self) -> Dict[str, int]:
        return {"slice_": self.slice_, "pp": self.pp, "dp": self.dp,
                "sp": self.sp, "tp": self.tp, "ep": self.ep}


def _device_slice_index(d) -> Optional[int]:
    """Real slice id of a device, or None when the runtime has no DCN
    topology (CPU, single slice)."""
    return getattr(d, "slice_index", None)


def make_mesh(axes: MeshAxes, devices: Optional[Sequence] = None) -> Mesh:
    """Build a mesh with only the non-trivial axes of ``axes``.

    Axis order is (slice_, pp, dp, sp, tp, ep) outermost→innermost: tp
    needs the tightest coupling (per-matmul psum) so it gets the innermost
    (fastest ICI neighbourhood) placement; pp crosses the slowest ICI
    links, and slice_ crosses DCN.

    With ``axes.slice_ > 1`` on a real multi-slice topology (devices carry
    distinct ``slice_index``) the device grid comes from
    ``mesh_utils.create_hybrid_device_mesh`` so slice_ is the DCN axis.
    Anywhere else the slice boundary is emulated: devices are grouped
    contiguously, ``axes.per_slice`` per emulated slice.
    """
    if devices is None:
        devices = jax.devices()
    if axes.total != len(devices):
        raise ValueError(
            f"mesh axes {axes.as_dict()} require {axes.total} devices, "
            f"have {len(devices)}"
        )
    names = []
    sizes = []
    for name, size in axes.as_dict().items():
        if size > 1:
            names.append(name)
            sizes.append(size)
    if not names:
        # Single device: expose every axis at size 1 so axis lookups
        # (tp/sp/... code asking mesh.shape["tp"]) work on the degenerate
        # mesh the same way they do on a real one.
        names = list(axes.as_dict().keys())
        sizes = [1] * len(names)
        import numpy as np

        grid = np.asarray(devices, dtype=object).reshape(tuple(sizes))
        return Mesh(grid, tuple(names))
    if axes.slice_ > 1:
        slice_ids = {_device_slice_index(d) for d in devices}
        if len(slice_ids) == axes.slice_ and None not in slice_ids:
            from jax.experimental import mesh_utils

            # names[0] is always slice_ here (first in as_dict, size > 1).
            grid = mesh_utils.create_hybrid_device_mesh(
                mesh_shape=(1,) + tuple(sizes[1:]),
                dcn_mesh_shape=(axes.slice_,) + (1,) * (len(sizes) - 1),
                devices=devices,
            )
            return Mesh(grid, tuple(names))
    return jax.make_mesh(tuple(sizes), tuple(names), devices=devices)


def factor_devices(n: int, want_tp: int = 2, want_sp: int = 2,
                   want_pp: int = 1, want_ep: int = 1,
                   n_slices: int = 1) -> MeshAxes:
    """Heuristic factorization of ``n`` devices onto (slice_, pp, dp, sp,
    tp, ep).

    Used by the dry-run path and examples. ``n_slices`` is carved off
    first (the DCN dimension must divide ``n`` exactly — a ragged slice
    count is a topology error, so it raises rather than rounding down).
    Within one slice, ep then tp then sp are carved off innermost-first
    when they divide the remainder, then pp, and dp absorbs what's left.
    Requested factors that don't divide evenly fall back to 1 (matching
    the historical tp/sp behaviour) instead of erroring.
    """
    if n_slices < 1 or n % n_slices != 0:
        raise ValueError(f"{n} devices cannot split into {n_slices} slices")
    per_slice = n // n_slices

    def carve(rem: int, want: int) -> int:
        return want if want > 1 and rem % want == 0 and rem >= want else 1

    rem = per_slice
    ep = carve(rem, want_ep)
    rem //= ep
    tp = carve(rem, want_tp)
    rem //= tp
    sp = carve(rem, want_sp)
    rem //= sp
    pp = carve(rem, want_pp)
    rem //= pp
    return MeshAxes(dp=rem, tp=tp, sp=sp, pp=pp, ep=ep, slice_=n_slices)
