"""Sharding-spec helpers for shard_map'd training steps.

The reference never shards state (each GPU process owns full replicas;
SURVEY §2.7) so none of this has a reference analog — it is the glue that
makes multi-axis meshes usable: given a params pytree and its PartitionSpec
tree, derive matching specs for arbitrary optax optimizer states.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import PartitionSpec as P


def opt_state_specs(opt_state: Any, params: Any, param_specs: Any) -> Any:
    """PartitionSpec tree for ``opt_state``.

    Rule: any subtree structurally identical to ``params`` (e.g. Adam's
    mu/nu) gets ``param_specs``; every other array leaf (step counts,
    EF/momentum flats handled separately by ``dp_state_specs``) is
    replicated.
    """
    pdef = jax.tree.structure(params)

    def is_param_tree(node: Any) -> bool:
        try:
            return jax.tree.structure(node) == pdef
        except Exception:  # noqa: BLE001 - unregistered nodes are not trees
            return False

    def mapper(node: Any) -> Any:
        if is_param_tree(node):
            return param_specs
        return P()

    return jax.tree.map(mapper, opt_state, is_leaf=is_param_tree)
