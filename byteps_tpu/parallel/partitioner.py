"""One Partitioner for the whole mesh: logical axes → mesh axes.

Model code names array dimensions by *meaning* — ``embed``, ``mlp``,
``heads``, ``kv``, ``vocab``, ``expert``, ``stage``, ``batch``, ``seq`` —
and this module owns the single table mapping those meanings onto mesh
axis names (``slice_``, ``pp``, ``dp``, ``sp``, ``tp``, ``ep``). Before
this existed every model family hand-wired ``P(...)`` trees (13 ``P(``
sites in gpt.py alone) and each ``parallel/`` module grew its own mesh
plumbing; now a spec is data (a tuple of logical names per array dim) and
policy lives in one rule table per family, T5X-style (SNIPPETS [2]/[3]).

Two entry points:

* :func:`resolve_specs` + :func:`rules_from_axes` — the low-level pair
  the model modules use so their historical ``*_param_specs(cfg,
  tp_axis)`` signatures survive as thin wrappers over logical trees.
* :class:`Partitioner` — mesh + family rules in one object. Training
  factories build one per mesh and pull param specs, optimizer-state
  specs, batch specs and axis names from it instead of consulting the
  mesh by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Mapping, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byteps_tpu.parallel.mesh import MeshAxes, factor_devices, make_mesh

#: A rule target: where one logical axis lands on the mesh. ``None``
#: replicates; a tuple shards over several mesh axes (outermost first).
AxisTarget = Union[None, str, Tuple[Optional[str], ...]]

#: The logical vocabulary. Model logical trees may only use these names
#: (or ``None`` for an always-replicated dim).
LOGICAL_AXES = ("batch", "seq", "embed", "mlp", "heads", "kv", "vocab",
                "expert", "stage")

_BASE_RULES: Dict[str, AxisTarget] = {
    "batch": ("slice_", "dp"),   # data parallel: DCN outermost, then ICI dp
    "seq": "sp",                 # sequence/context parallel (ring attention)
    "embed": None,               # residual stream stays replicated
    "mlp": "tp",                 # Megatron col/row: ffn hidden over tp
    "heads": "tp",               # attention heads over tp
    "kv": "tp",                  # kv heads over tp (= heads unless GQA)
    "vocab": None,               # embedding / readout replicated
    "expert": "ep",              # MoE expert dim
    "stage": "pp",               # pipeline stage dim (stacked blocks)
}

#: Per-model-family rule tables. All families currently share the
#: Megatron-ish base; they are separate dicts so a family can diverge
#: (e.g. moe_gpt folds ep into the batch axis — tokens ride the expert
#: axis as extra data parallelism outside the MoE blocks).
FAMILY_RULES: Dict[str, Dict[str, AxisTarget]] = {
    "gpt": dict(_BASE_RULES),
    "bert": dict(_BASE_RULES),
    "t5": dict(_BASE_RULES),
    "vit": dict(_BASE_RULES),
    "resnet": dict(_BASE_RULES),
    "moe_gpt": {**_BASE_RULES, "batch": ("slice_", "dp", "ep")},
}

#: Which logical dims a data batch carries, per family.
FAMILY_BATCH_DIMS: Dict[str, Tuple[str, ...]] = {
    "gpt": ("batch", "seq"),
    "bert": ("batch", "seq"),
    "t5": ("batch", "seq"),
    "moe_gpt": ("batch", "seq"),
    "vit": ("batch",),
    "resnet": ("batch",),
}


def _is_logical_leaf(node: Any) -> bool:
    return isinstance(node, tuple) and all(
        n is None or isinstance(n, str) for n in node)


def _filter_target(target: AxisTarget,
                   axis_names: Optional[Sequence[str]]) -> AxisTarget:
    """Drop ``None`` entries and (when ``axis_names`` given) mesh axes
    that don't exist; collapse to a bare name / ``None`` when possible."""
    if target is None:
        return None
    if isinstance(target, str):
        target = (target,)
    present = tuple(a for a in target
                    if a is not None
                    and (axis_names is None or a in axis_names))
    if not present:
        return None
    return present if len(present) > 1 else present[0]


def resolve_spec(logical: Tuple[Optional[str], ...],
                 rules: Mapping[str, AxisTarget],
                 axis_names: Optional[Sequence[str]] = None) -> P:
    """One logical leaf → a PartitionSpec.

    ``axis_names`` (usually ``mesh.axis_names``) filters rule targets to
    axes that actually exist; pass ``None`` to trust the rules as given
    (the model-module wrapper path, where the caller already passed
    ``tp_axis=None`` for a tp-less mesh). An all-replicated leaf
    canonicalizes to ``P()``.
    """
    entries = []
    for name in logical:
        if name is None:
            entries.append(None)
            continue
        if name not in LOGICAL_AXES:
            raise ValueError(f"unknown logical axis {name!r}; "
                             f"expected one of {LOGICAL_AXES}")
        entries.append(_filter_target(rules.get(name), axis_names))
    if all(e is None for e in entries):
        return P()
    return P(*entries)


def resolve_specs(logical_tree: Any, rules: Mapping[str, AxisTarget],
                  axis_names: Optional[Sequence[str]] = None) -> Any:
    """Map :func:`resolve_spec` over a pytree whose leaves are logical
    tuples (one entry per array dim)."""
    return jax.tree.map(
        lambda leaf: resolve_spec(leaf, rules, axis_names),
        logical_tree, is_leaf=_is_logical_leaf)


def stacked_logical_specs(logical_tree: Any) -> Any:
    """Prepend the ``stage`` logical axis to every leaf — the logical
    analog of :func:`byteps_tpu.parallel.pipeline.stacked_specs` for a
    pipeline slab stacked on a leading layer axis."""
    return jax.tree.map(lambda t: ("stage",) + t, logical_tree,
                        is_leaf=_is_logical_leaf)


def rules_from_axes(tp_axis: Optional[str] = None,
                    sp_axis: Optional[str] = None,
                    dp_axis: Optional[str] = None,
                    ep_axis: Optional[str] = None,
                    pp_axis: Optional[str] = None,
                    slice_axis: Optional[str] = None
                    ) -> Dict[str, AxisTarget]:
    """Rule table from explicit axis names — the compatibility bridge for
    the historical ``*_param_specs(cfg, tp_axis)`` signatures, where the
    caller resolved axis presence before calling."""
    return {
        "batch": (slice_axis, dp_axis),
        "seq": sp_axis,
        "embed": None,
        "mlp": tp_axis,
        "heads": tp_axis,
        "kv": tp_axis,
        "vocab": None,
        "expert": ep_axis,
        "stage": pp_axis,
    }


def _logical_specs_for(cfg: Any, params: Any = None) -> Any:
    """Dispatch a model config to its family's logical spec tree."""
    name = type(cfg).__name__
    if name == "GPTConfig":
        from byteps_tpu.models.gpt import gpt_logical_specs
        return gpt_logical_specs(cfg)
    if name == "MoEGPTConfig":
        from byteps_tpu.models.moe_gpt import moe_gpt_logical_specs
        return moe_gpt_logical_specs(cfg)
    if name == "T5Config":
        from byteps_tpu.models.t5 import t5_logical_specs
        return t5_logical_specs(cfg)
    if name == "BertConfig":
        from byteps_tpu.models.bert import bert_logical_specs
        return bert_logical_specs(cfg)
    if name == "ViTConfig":
        from byteps_tpu.models.vit import vit_logical_specs
        return vit_logical_specs(cfg)
    if name == "ResNetConfig":
        from byteps_tpu.models.resnet import resnet_logical_specs
        if params is None:
            raise ValueError("resnet logical specs need the params tree")
        return resnet_logical_specs(cfg, params)
    raise TypeError(f"no logical-spec table for config type {name}")


_FAMILY_BY_CONFIG = {
    "GPTConfig": "gpt", "MoEGPTConfig": "moe_gpt", "T5Config": "t5",
    "BertConfig": "bert", "ViTConfig": "vit", "ResNetConfig": "resnet",
}


@dataclasses.dataclass
class Partitioner:
    """Mesh + logical-axis rules in one object.

    Everything a training/serving factory needs from the topology flows
    through here: mesh axis names (``.dp``/``.tp``/...), param specs
    (:meth:`param_specs`), optimizer-state specs (:meth:`opt_state_specs`)
    and batch specs/shardings (:meth:`batch_spec`, :meth:`batch_sharding`).
    """

    mesh: Mesh
    family: str = "gpt"
    overrides: Optional[Mapping[str, AxisTarget]] = None

    def __post_init__(self):
        base = FAMILY_RULES.get(self.family)
        if base is None:
            raise ValueError(f"unknown model family {self.family!r}; "
                             f"have {sorted(FAMILY_RULES)}")
        self.rules: Dict[str, AxisTarget] = dict(base)
        if self.overrides:
            self.rules.update(self.overrides)

    # -- construction --------------------------------------------------
    @classmethod
    def create(cls, axes: Optional[MeshAxes] = None, family: str = "gpt",
               devices: Optional[Sequence] = None,
               num_slices: int = 1, **factor_kw) -> "Partitioner":
        """Build mesh and partitioner together. With ``axes=None`` the
        device count is factored heuristically (:func:`factor_devices`)."""
        if devices is None:
            devices = jax.devices()
        if axes is None:
            axes = factor_devices(len(devices), n_slices=num_slices,
                                  **factor_kw)
        return cls(make_mesh(axes, devices=devices), family=family)

    @classmethod
    def for_config(cls, cfg: Any, mesh: Mesh,
                   overrides: Optional[Mapping[str, AxisTarget]] = None
                   ) -> "Partitioner":
        family = _FAMILY_BY_CONFIG.get(type(cfg).__name__)
        if family is None:
            raise TypeError(f"no family for config type {type(cfg).__name__}")
        return cls(mesh, family=family, overrides=overrides)

    # -- mesh axis accessors -------------------------------------------
    def _axis(self, name: str) -> Optional[str]:
        return name if name in self.mesh.axis_names else None

    @property
    def dp(self) -> Optional[str]:
        return self._axis("dp")

    @property
    def tp(self) -> Optional[str]:
        return self._axis("tp")

    @property
    def sp(self) -> Optional[str]:
        return self._axis("sp")

    @property
    def pp(self) -> Optional[str]:
        return self._axis("pp")

    @property
    def ep(self) -> Optional[str]:
        return self._axis("ep")

    @property
    def slice_(self) -> Optional[str]:
        return self._axis("slice_")

    def axis_size(self, name: str) -> int:
        return self.mesh.shape[name] if name in self.mesh.axis_names else 1

    def mesh_axes(self, logical: str) -> AxisTarget:
        """Mesh axis (or axes) one logical axis lands on, filtered to
        axes present in this mesh. ``None`` → replicated."""
        if logical not in LOGICAL_AXES:
            raise ValueError(f"unknown logical axis {logical!r}")
        return _filter_target(self.rules.get(logical),
                              self.mesh.axis_names)

    def batch_axes(self) -> AxisTarget:
        """Mesh axes the batch dim is split over — what loss functions
        pmean over and the gradient reduction runs over."""
        return self.mesh_axes("batch")

    # -- specs ----------------------------------------------------------
    def spec(self, *logical: Optional[str]) -> P:
        return resolve_spec(tuple(logical), self.rules,
                            self.mesh.axis_names)

    def resolve(self, logical_tree: Any) -> Any:
        return resolve_specs(logical_tree, self.rules,
                             self.mesh.axis_names)

    def param_specs(self, cfg: Any, params: Any = None) -> Any:
        """PartitionSpec tree for a model config's params (resnet also
        needs the params tree — its shape depends on stage widths)."""
        return self.resolve(_logical_specs_for(cfg, params))

    def opt_state_specs(self, opt_state: Any, params: Any,
                        param_specs: Any) -> Any:
        from byteps_tpu.parallel.sharding import opt_state_specs
        return opt_state_specs(opt_state, params, param_specs)

    def batch_spec(self, dims: Optional[Tuple[str, ...]] = None) -> P:
        if dims is None:
            dims = FAMILY_BATCH_DIMS[self.family]
        return self.spec(*dims)

    def batch_sharding(self, dims: Optional[Tuple[str, ...]] = None
                       ) -> NamedSharding:
        return NamedSharding(self.mesh, self.batch_spec(dims))

    def param_sharding(self, cfg: Any, params: Any = None) -> Any:
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s),
                            self.param_specs(cfg, params))
