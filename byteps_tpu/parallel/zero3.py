"""ZeRO-3 FSDP for the GPT family: params live as flat f32 segments
sharded over the slice_/dp axis, all-gathered just-in-time per layer.

The memory story (DeepSpeed ZeRO stage 3, transposed to shard_map):

- **Persistent state** — the master weights AND the optimizer moments —
  is a handful of flat f32 buffers, each sharded 1/n over the shard
  axis. Per-chip param+opt memory drops ~n×.
- **Transient state** — the unsharded weights a layer needs to compute —
  exists only inside that layer's application: ``all_gather(tiled)``
  materializes one block's params, the block runs, and (under
  ``remat=True``) the gathered tree is dropped and re-gathered in the
  backward pass, so at most one block's full params are live at a time.
- **Gradients arrive pre-sharded.** The transpose of a tiled
  ``all_gather`` over the shard axis is ``psum_scatter``: AD itself
  reduce-scatters the gradient, every device receiving exactly the
  summed slice matching its param segment. No explicit gradient
  collective over the shard axis exists in this file — it falls out of
  differentiating the gather.
- **The update is elementwise on segments.** ``base_tx`` (adam, sgd,
  ...) applies to the flat f32 segs directly; params are never gathered
  for the update. This requires an elementwise transform — the same
  contract as ZeRO-1's segment update (see DistributedOptimizer).

Axis choice: the shard axis is ``slice_`` when the mesh has one (the
ISSUE's multi-slice FSDP: params sharded ACROSS slices, the DCN tier
carrying the gather/scatter), else ``dp``. Any remaining data axes
(``dp`` under a slice_ shard) replicate the segs and contribute an
explicit grad psum. Pure FSDP only: tp/sp/pp/ep meshes are rejected —
those compose on the non-ZeRO-3 paths. Compression is likewise
rejected: the gather/scatter here moves PARAMS, whose integrity the
next forward depends on; compressed gradient exchange composes on the
hybrid hierarchical path (``zero_3=False`` with a slice_ mesh) instead.

Padding: each group's flat concat is zero-padded to ``n*seg``. Pad
elements never reach the loss (the gather truncates before unflatten),
so their grads are identically zero and adam on them is a no-op
(m=v=0 → update 0) — the pad region stays zero forever.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byteps_tpu.jax.optimizer import _flatten_concat, _unconcat_unflatten
from byteps_tpu.parallel.partitioner import Partitioner
from byteps_tpu.parallel.remat import maybe_remat
from byteps_tpu.parallel.sharding import opt_state_specs

if False:  # pragma: no cover - typing only; models imports at call time
    from byteps_tpu.models.gpt import GPTConfig  # noqa: F401
# (models.gpt imports byteps_tpu.parallel submodules at module load, so
# this package-level module must import models.* lazily inside the
# functions below — a top-level import is circular.)


def _seg_of(total: int, n: int) -> int:
    return -(-total // n)


def _group_meta(params: Dict[str, Any], n_shard: int):
    """Per-group (templates, sizes, total, padded) for the two group
    kinds: ``rest`` (every non-block leaf: embeddings, final norm,
    untied head) and one group per transformer block. Templates are
    ShapeDtypeStructs — `_unconcat_unflatten` only reads shape/dtype."""

    def meta(tree):
        leaves = jax.tree.leaves(tree)
        sizes = [int(l.size) for l in leaves]
        total = sum(sizes)
        templates = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), tree)
        return templates, sizes, total, n_shard * _seg_of(total, n_shard)

    rest = {k: v for k, v in params.items() if k != "blocks"}
    return meta(rest), meta(params["blocks"][0])


def _to_segs(params: Dict[str, Any], n_shard: int) -> Dict[str, Any]:
    """Full param tree → {"rest": (padded,), "blocks": [(padded,), ...]}
    flat f32 global arrays, each zero-padded to a multiple of n_shard."""

    def flat_pad(tree, padded):
        flat, _ = _flatten_concat(tree)
        return jnp.pad(flat, (0, padded - flat.shape[0]))

    (_, _, _, rest_pad), (_, _, _, blk_pad) = _group_meta(params, n_shard)
    return {
        "rest": flat_pad({k: v for k, v in params.items()
                          if k != "blocks"}, rest_pad),
        "blocks": [flat_pad(b, blk_pad) for b in params["blocks"]],
    }


def zero3_gather_params(segs: Dict[str, Any], cfg: GPTConfig,
                        ) -> Dict[str, Any]:
    """Materialize the standard :func:`gpt_init` tree from the segment
    dict (host-side: checkpointing, export, eval on other meshes)."""
    from byteps_tpu.models.gpt import gpt_init

    shapes = jax.eval_shape(lambda: gpt_init(jax.random.PRNGKey(0), cfg))
    (r_tpl, r_sizes, r_total, _), (b_tpl, b_sizes, b_total, _) = \
        _group_meta(shapes, 1)
    out = _unconcat_unflatten(
        jnp.asarray(segs["rest"])[:r_total], r_tpl, r_sizes)
    out["blocks"] = [
        _unconcat_unflatten(jnp.asarray(s)[:b_total], b_tpl, b_sizes)
        for s in segs["blocks"]
    ]
    return out


def make_gpt_zero3_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,  # noqa: ARG001 - API symmetry
    remat: bool = False,
    seq_layout: str = "contiguous",
    init_params: Optional[Dict[str, Any]] = None,
    chunked_ce=True,
):
    """Returns ``(step, segs, opt_state, batch_sharding)`` —
    the ``zero_3=True`` backend of
    :func:`byteps_tpu.models.train.make_gpt_train_step`.

    ``step(segs, opt_state, tokens, targets) -> (loss, segs, opt_state)``;
    ``segs`` is the flat segment dict (``zero3_gather_params`` rebuilds
    the gpt tree). Matches the replicated trajectory to f32 roundoff:
    the only reassociation is the psum_scatter's cross-shard grad sum.
    """
    from byteps_tpu.models.gpt import (
        _embed, _readout_nll, resolve_norm, resolve_rope,
        transformer_block)

    part = Partitioner.for_config(cfg, mesh)
    dp, slc = part.dp, part.slice_
    banned = [n for n in (part.tp, part.sp, part.pp, part.ep)
              if n is not None]
    if banned:
        raise ValueError(
            f"zero_3 is pure FSDP — mesh axes {banned} are not supported "
            "(tp/sp/pp/ep compose on the zero_3=False paths)")
    if compression_params is not None:
        raise ValueError(
            "compression_params does not compose with zero_3 (the DCN "
            "collectives here move params, not grads) — use the hybrid "
            "compressed-gradient path (zero_3=False on a slice_ mesh)")
    zaxis = slc if slc is not None else dp
    if zaxis is None:
        raise ValueError("zero_3 needs a slice_ or dp mesh axis to shard "
                         "params over")
    n_shard = mesh.shape[zaxis]
    data_axes = tuple(a for a in (slc, dp) if a is not None)
    other_axes = tuple(a for a in data_axes if a != zaxis)
    n_workers = 1
    for a in data_axes:
        n_workers *= mesh.shape[a]

    from byteps_tpu.models.train import _resolve_init_params

    params = _resolve_init_params(init_params, cfg, part.param_specs(cfg))
    (r_tpl, r_sizes, r_total, _), (b_tpl, b_sizes, b_total, _) = \
        _group_meta(params, n_shard)
    seg_spec = P(zaxis)
    segs = jax.device_put(
        _to_segs(params, n_shard),
        NamedSharding(mesh, seg_spec))
    del params  # the segs are the master copy now
    seg_specs = jax.tree.map(lambda _: seg_spec, segs)
    opt_state = base_tx.init(segs)
    ospecs = opt_state_specs(opt_state, segs, seg_specs)
    opt_state = jax.device_put(
        opt_state, jax.tree.map(lambda s: NamedSharding(mesh, s), ospecs,
                                is_leaf=lambda x: isinstance(x, P)))
    batch_spec = part.batch_spec()

    rope_base = resolve_rope(cfg)
    norm_fn, norm_eps = resolve_norm(cfg)

    def gather(seg, templates, sizes, total):
        flat = jax.lax.all_gather(seg, zaxis, tiled=True)
        return _unconcat_unflatten(flat[:total], templates, sizes)

    def loss_from_segs(segs, tokens, targets):
        rest = gather(segs["rest"], r_tpl, r_sizes, r_total)
        x = _embed(rest, tokens, cfg, None, seq_layout)

        def apply_block(x, seg):
            # the just-in-time gather lives INSIDE the (remat'd) block:
            # backward re-gathers instead of keeping n_layers trees live
            p = gather(seg, b_tpl, b_sizes, b_total)
            return transformer_block(
                x, p, cfg.head_dim, None, None, causal=True,
                seq_layout=seq_layout, rope_base=rope_base,
                norm_fn=norm_fn, norm_eps=norm_eps, use_bias=cfg.use_bias)

        apply_block = maybe_remat(apply_block, remat)
        for seg in segs["blocks"]:
            x = apply_block(x, seg)
        nll = _readout_nll(rest, x, targets, norm_fn, norm_eps,
                           tp_axis=None, chunked=chunked_ce)
        return nll.mean()

    def per_device_step(segs, opt_state, tokens, targets):
        # grad of the LOCAL mean loss; the shard-axis sum arrives free
        # as the all_gather transpose (psum_scatter over zaxis), the
        # remaining data axes need the explicit psum, and /n_workers
        # turns the global sum into the global mean
        loss, grads = jax.value_and_grad(loss_from_segs)(
            segs, tokens, targets)
        if other_axes:
            grads = jax.tree.map(
                lambda g: jax.lax.psum(g, other_axes), grads)
        grads = jax.tree.map(lambda g: g / n_workers, grads)
        updates, opt_state = base_tx.update(grads, opt_state, segs)
        segs = optax.apply_updates(segs, updates)
        loss = jax.lax.pmean(loss, data_axes)
        return loss, segs, opt_state

    sharded = jax.shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(seg_specs, ospecs, batch_spec, batch_spec),
        out_specs=(P(), seg_specs, ospecs),
        check_vma=False,
    )
    step = jax.jit(sharded, donate_argnums=(0, 1))
    return step, segs, opt_state, NamedSharding(mesh, batch_spec)
