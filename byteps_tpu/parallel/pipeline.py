"""Pipeline parallelism (pp axis): GPipe-style microbatch pipelining.

The reference is data-parallel only (SURVEY §2.7); pipeline parallelism is
one of the "beyond reference" axes the TPU rebuild adds for large-model
training. The TPU-idiomatic formulation is a *collective pipeline* inside
``shard_map`` (the scaling-book recipe): each pp stage owns a contiguous
stack of layers (a stacked pytree sharded ``P('pp', ...)`` on its leading
axis), activations shift stage-to-stage with ``jax.lax.ppermute`` over ICI,
and a ``lax.scan`` over schedule ticks runs every stage in lockstep —
stage s computes microbatch t−s at tick t, so all stages are busy once the
pipeline fills. The whole schedule is one traced XLA program, and because
``ppermute``/``scan``/``where`` are differentiable, ``jax.grad`` through
:func:`pipeline_apply` yields the reverse (backward) pipeline automatically
— no hand-written 1F1B schedule.

Cost model: with M microbatches and S stages, bubble fraction is
(S−1)/(M+S−1); pick M ≥ 4·S to keep it under ~20%.
"""

from __future__ import annotations

from typing import Any, Callable, List

import jax
import jax.numpy as jnp

from byteps_tpu.parallel.remat import maybe_remat


def stack_blocks(blocks: List[Any]):
    """Stack a list of identically-shaped block pytrees into one pytree
    with a leading layer axis — shard it ``P('pp', ...)`` so each stage
    holds its own contiguous layer slab."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)


def stacked_specs(block_spec, pp_axis: str):
    """PartitionSpec tree for :func:`stack_blocks` output: the leading
    layer axis shards over pp, per-layer dims keep ``block_spec``."""
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda s: P(pp_axis, *s),
        block_spec,
        is_leaf=lambda x: isinstance(x, P),
    )


def _widen_to(axes):
    """Return f(z) casting z's VMA type up to exactly ``axes`` (adding any
    missing ones as varying). ONLY call under ``check_vma=True``: without
    VMA types every axis looks missing and pcast's transpose (a psum over
    a varying operand) breaks differentiation — pipeline_apply guards the
    call site on vma_mode for exactly this reason."""

    def widen(z):
        have = set(getattr(jax.typeof(z), "vma", ()) or ())
        need = tuple(sorted(set(axes) - have))
        return jax.lax.pcast(z, need, to="varying") if need else z

    return widen


def pipeline_apply(
    x_mb: jnp.ndarray,
    stacked: Any,
    block_fn: Callable[[jnp.ndarray, Any], jnp.ndarray],
    pp_axis: str,
    remat: bool = False,
    vma_axes: tuple = (),
    has_aux: bool = False,
) -> jnp.ndarray:
    """Run microbatches through the pp-staged layer pipeline.

    Call inside ``shard_map``. ``x_mb`` is the microbatched stage-0 input
    ``(M, mb, ...)`` (identical on every stage — only stage 0 injects it);
    ``stacked`` is THIS stage's ``(layers_per_stage, ...)`` parameter slab;
    ``block_fn(x, layer_params) -> x`` applies one layer and must preserve
    shape — or, with ``has_aux``, returns ``(x, scalar_aux)`` (an MoE
    block's load-balancing loss) and the call returns ``(outs,
    aux_total)`` where ``aux_total`` sums THIS stage's layers' aux over
    every real microbatch (warmup/drain ticks masked out). Returns
    ``(M, mb, ...)`` pipeline outputs, valid on the LAST pp stage (zeros
    elsewhere — mask with ``lax.axis_index(pp_axis)``).

    Schedule: M + S − 1 ticks; at each tick every stage applies its slab
    (a ``lax.scan`` over its layers) and ships the result to the next
    stage via ring ``ppermute`` (the wraparound edge feeds stage 0, which
    ignores it in favor of the next injected microbatch).

    Under ``check_vma=True`` pass ``vma_axes`` = the mesh axes the carried
    activations may vary over (e.g. every mesh axis name): the scan carry
    must be a type fixed point, so both the zero init and each tick's
    block output are widened to ``vma_axes ∪ {pp}`` — a block whose
    row-parallel psum makes outputs tp-INvariant would otherwise narrow
    the carry type mid-scan. Widening is semantically free (varying is
    the weaker claim); collapse it downstream with a pmean if needed.
    """
    nstages = jax.lax.axis_size(pp_axis)
    stage = jax.lax.axis_index(pp_axis)
    M = x_mb.shape[0]
    mb_shape = x_mb.shape[1:]
    perm = [(i, (i + 1) % nstages) for i in range(nstages)]
    # widen only under check_vma=True (where axis_index is typed varying):
    # pcast's transpose is a psum whose operand must be varying, so a
    # widen under check_vma=False would break differentiation
    vma_mode = bool(getattr(jax.typeof(stage), "vma", ()) or ())
    widen = (
        _widen_to(tuple(set(vma_axes) | {pp_axis})) if vma_mode
        else (lambda z: z)
    )

    fn = maybe_remat(block_fn, remat)

    def local_slab(x):
        def body(carry, layer):
            h, aux = carry
            out = fn(h, layer)
            if has_aux:
                h, a = out
                return (h, aux + a), None
            return (out, aux), None

        (h, aux), _ = jax.lax.scan(
            body, (x, widen(jnp.zeros((), jnp.float32))), stacked
        )
        return h, aux

    def tick(carry, t):
        recv, outs, aux_acc = carry
        inject = x_mb[jnp.clip(t, 0, M - 1)]
        xin = jnp.where(stage == 0, inject, recv)
        y, aux = local_slab(xin)
        y = widen(y)
        # stage s processes microbatch t - s at tick t; aux from warmup /
        # drain ticks is garbage-data noise and must not count
        mb_valid = ((t - stage) >= 0) & ((t - stage) < M)
        aux_acc = aux_acc + jnp.where(mb_valid, aux, 0.0)
        out_t = t - (nstages - 1)
        valid = (out_t >= 0) & (out_t < M) & (stage == nstages - 1)
        start = (jnp.clip(out_t, 0, M - 1),) + (0,) * len(mb_shape)
        updated = jax.lax.dynamic_update_slice(
            outs, y[None].astype(outs.dtype), start
        )
        outs = jnp.where(valid, updated, outs)
        recv = jax.lax.ppermute(y, pp_axis, perm)
        return (recv, outs, aux_acc), None

    init = (
        jnp.zeros(mb_shape, x_mb.dtype),
        jnp.zeros((M,) + mb_shape, x_mb.dtype),
        jnp.zeros((), jnp.float32),
    )
    # under check_vma=True the tick outputs are (at least) pp-varying
    # (axis_index / ppermute), so the zero init must be cast to match the
    # carry type; a no-op under check_vma=False
    init = jax.tree.map(widen, init)
    (_, outs, aux_total), _ = jax.lax.scan(
        tick, init, jnp.arange(M + nstages - 1)
    )
    if has_aux:
        # THIS stage's layers' aux, summed over its layers and all M
        # microbatches — psum over pp (and normalize) in the caller
        return outs, aux_total
    return outs


def last_stage_value(value: jnp.ndarray, pp_axis: str) -> jnp.ndarray:
    """Replicate a last-stage scalar/array to every pp stage (psum of the
    masked value — other stages contribute zero)."""
    nstages = jax.lax.axis_size(pp_axis)
    stage = jax.lax.axis_index(pp_axis)
    masked = jnp.where(stage == nstages - 1, value,
                       jnp.zeros_like(value))
    return jax.lax.psum(masked, pp_axis)
