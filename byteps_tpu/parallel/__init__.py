"""byteps_tpu.parallel — multi-dimensional parallelism over the device mesh.

The reference implements data parallelism only (SURVEY §2.7); the TPU
rebuild makes DP one axis of a general ``jax.sharding.Mesh`` and adds the
axes long-context / large-model training needs: tensor parallelism (tp,
Megatron-style column/row-parallel matmuls with psum over ICI), sequence /
context parallelism (sp, ring attention via ``ppermute``), and room for
pipeline (pp) / expert (ep) axes in the mesh factory.

Everything here is shard_map-first: functions take axis *names* and are
called inside ``jax.shard_map`` over a mesh built by :func:`make_mesh`.
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.parallel.mesh import MeshAxes, make_mesh, factor_devices
from byteps_tpu.parallel.partitioner import (FAMILY_RULES, LOGICAL_AXES,
                                             Partitioner, resolve_spec,
                                             resolve_specs, rules_from_axes,
                                             stacked_logical_specs)
from byteps_tpu.parallel.zero3 import (make_gpt_zero3_train_step,
                                       zero3_gather_params)
from byteps_tpu.parallel.moe import (moe_ffn, moe_init, moe_specs,
                                     top1_dispatch, topk_dispatch)
from byteps_tpu.parallel.pipeline import (
    last_stage_value,
    pipeline_apply,
    stack_blocks,
    stacked_specs,
)
from byteps_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention,
    zigzag_inverse,
    zigzag_local_positions,
    zigzag_permutation,
    zigzag_ring_attention,
)
from byteps_tpu.parallel.tp import (
    col_parallel_matmul,
    row_parallel_matmul,
    maybe_psum,
)

__all__ = [
    "MeshAxes",
    "make_mesh",
    "factor_devices",
    "Partitioner",
    "LOGICAL_AXES",
    "FAMILY_RULES",
    "resolve_spec",
    "resolve_specs",
    "rules_from_axes",
    "stacked_logical_specs",
    "make_gpt_zero3_train_step",
    "zero3_gather_params",
    "moe_ffn",
    "moe_init",
    "moe_specs",
    "top1_dispatch",
    "topk_dispatch",
    "pipeline_apply",
    "stack_blocks",
    "stacked_specs",
    "last_stage_value",
    "ring_attention",
    "plain_attention",
    "zigzag_ring_attention", "zigzag_permutation", "zigzag_inverse",
    "zigzag_local_positions",
    "col_parallel_matmul",
    "row_parallel_matmul",
    "maybe_psum",
]
