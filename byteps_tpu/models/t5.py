"""T5-style encoder–decoder with teacher-forced seq2seq loss.

The reference ships no models (SURVEY §1); the zoo's text families so far
are decoder-only (GPT) and encoder-only (BERT). T5 completes the
transformer triptych with the one structural piece neither has:
**cross-attention** — decoder queries over encoder memory. Built from the
same shared parts as the rest of the zoo:

* encoder blocks ARE :func:`byteps_tpu.models.gpt.transformer_block`
  (``causal=False``), so tp col/row sharding and per-block remat carry
  over unchanged;
* decoder blocks add a pre-LN cross-attention sublayer between the
  causal self-attention and the MLP; its q/k/v/o projections use the
  same Megatron col/row-parallel helpers, and the attention core runs
  the flash kernel where supported (``plain_attention`` dispatches);
* embeddings/readout are tied (``wte``), learned absolute positions per
  side, mirroring the GPT family's conventions.

Sequence parallelism (round 4): both sides shard over sp — the encoder
runs the non-causal ring, the decoder the causal ring, and
cross-attention a RECTANGULAR non-causal ring (stationary decoder-query
blocks, rotating encoder-memory k/v blocks — the ring helpers take the
k block's own length for offsets). Positions are sp-aware on both sides.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from byteps_tpu.models.gpt import (
    _attention,
    _layernorm,
    _mlp,
    _nll,
    _positions as _gpt_positions,
    _readout,
    block_init,
    block_specs,
    transformer_block,
)
from byteps_tpu.parallel.remat import maybe_remat
from byteps_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention,
)
from byteps_tpu.parallel.tp import col_parallel_matmul, row_parallel_matmul


@dataclasses.dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    max_src: int = 512
    max_tgt: int = 512
    d_model: int = 768
    n_heads: int = 12
    n_enc_layers: int = 12
    n_dec_layers: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls) -> "T5Config":
        return cls(vocab_size=256, max_src=64, max_tgt=64, d_model=64,
                   n_heads=4, n_enc_layers=2, n_dec_layers=2, d_ff=128)

    @classmethod
    def base(cls) -> "T5Config":
        return cls(dtype=jnp.bfloat16)


def _cross_init(rng, d: int, hd: int, n_layers: int) -> Dict[str, Any]:
    """Cross-attention sublayer params (decoder q over encoder k/v)."""
    std = 0.02
    ks = jax.random.split(rng, 4)

    def dense(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * std

    return {
        "lnx_g": jnp.ones((d,), jnp.float32),
        "lnx_b": jnp.zeros((d,), jnp.float32),
        "xwq": dense(ks[0], (d, hd)), "xbq": jnp.zeros((hd,), jnp.float32),
        "xwk": dense(ks[1], (d, hd)), "xbk": jnp.zeros((hd,), jnp.float32),
        "xwv": dense(ks[2], (d, hd)), "xbv": jnp.zeros((hd,), jnp.float32),
        "xwo": dense(ks[3], (hd, d)) / (2 * n_layers) ** 0.5,
        "xbo": jnp.zeros((d,), jnp.float32),
    }


def _cross_logical_specs() -> Dict[str, Any]:
    return {
        "lnx_g": ("embed",), "lnx_b": ("embed",),
        "xwq": ("embed", "heads"), "xbq": ("heads",),
        "xwk": ("embed", "kv"), "xbk": ("kv",),
        "xwv": ("embed", "kv"), "xbv": ("kv",),
        "xwo": ("heads", "embed"), "xbo": ("embed",),
    }


def _cross_specs(tp_axis) -> Dict[str, Any]:
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(_cross_logical_specs(),
                         rules_from_axes(tp_axis=tp_axis))


def cross_attention(x, mem, p, head_dim: int, tp_axis, sp_axis=None):
    """Decoder queries over encoder memory; bidirectional (no mask).

    With ``sp_axis`` both sides are sequence-sharded: ``x`` is this
    device's target block and ``mem`` its ENCODER-memory block — the
    ring rotates the memory k/v blocks under the stationary queries
    (rectangular, non-causal ring)."""
    B, Sq = x.shape[:2]
    Sk = mem.shape[1]
    q = col_parallel_matmul(x, p["xwq"].astype(x.dtype), p["xbq"].astype(x.dtype))
    k = col_parallel_matmul(mem, p["xwk"].astype(mem.dtype), p["xbk"].astype(mem.dtype))
    v = col_parallel_matmul(mem, p["xwv"].astype(mem.dtype), p["xbv"].astype(mem.dtype))
    h_loc = q.shape[-1] // head_dim
    q = q.reshape(B, Sq, h_loc, head_dim)
    k = k.reshape(B, Sk, h_loc, head_dim)
    v = v.reshape(B, Sk, h_loc, head_dim)
    o = ring_attention(q, k, v, sp_axis, causal=False)
    o = o.reshape(B, Sq, h_loc * head_dim)
    return row_parallel_matmul(o, p["xwo"].astype(x.dtype), tp_axis,
                               p["xbo"].astype(x.dtype))


def decoder_block(x, mem, p, head_dim: int, tp_axis=None, sp_axis=None):
    """Causal self-attn → cross-attn over ``mem`` → MLP, all pre-LN.

    ``p`` is a GPT ``block_init`` dict (self-attn + MLP) merged with
    :func:`_cross_init`'s cross-attention fields.
    """
    # self-attention + MLP halves reuse the shared block's pieces:
    # transformer_block is attn-then-mlp; here cross-attn goes between,
    # so apply the pieces explicitly with the same param names
    x = x + _attention(_layernorm(x, p["ln1_g"], p["ln1_b"]), p, head_dim,
                       tp_axis, sp_axis, causal=True)
    x = x + cross_attention(_layernorm(x, p["lnx_g"], p["lnx_b"]), mem, p,
                            head_dim, tp_axis, sp_axis)
    return x + _mlp(_layernorm(x, p["ln2_g"], p["ln2_b"]), p, tp_axis)


def t5_init(rng: jnp.ndarray, cfg: T5Config) -> Dict[str, Any]:
    d = cfg.d_model
    hd = cfg.n_heads * cfg.head_dim
    n_total = cfg.n_enc_layers + cfg.n_dec_layers
    keys = jax.random.split(rng, 3 + cfg.n_enc_layers + 2 * cfg.n_dec_layers)
    std = 0.02

    def dense(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * std

    dec_blocks = []
    for li in range(cfg.n_dec_layers):
        p = block_init(keys[3 + cfg.n_enc_layers + 2 * li], d, cfg.d_ff,
                       hd, n_total)
        p.update(_cross_init(keys[4 + cfg.n_enc_layers + 2 * li], d, hd,
                             n_total))
        dec_blocks.append(p)
    return {
        "wte": dense(keys[0], (cfg.vocab_size, d)),
        "wpe_src": dense(keys[1], (cfg.max_src, d)),
        "wpe_tgt": dense(keys[2], (cfg.max_tgt, d)),
        "enc_blocks": [
            block_init(keys[3 + li], d, cfg.d_ff, hd, n_total)
            for li in range(cfg.n_enc_layers)
        ],
        "dec_blocks": dec_blocks,
        "enc_ln_g": jnp.ones((d,), jnp.float32),
        "enc_ln_b": jnp.zeros((d,), jnp.float32),
        "lnf_g": jnp.ones((d,), jnp.float32),
        "lnf_b": jnp.zeros((d,), jnp.float32),
    }


def t5_logical_specs(cfg: T5Config) -> Dict[str, Any]:
    from byteps_tpu.models.gpt import block_logical_specs
    dec = []
    for _ in range(cfg.n_dec_layers):
        s = block_logical_specs()
        s.update(_cross_logical_specs())
        dec.append(s)
    return {
        "wte": ("vocab", "embed"), "wpe_src": (None, "embed"),
        "wpe_tgt": (None, "embed"),
        "enc_blocks": [block_logical_specs()
                       for _ in range(cfg.n_enc_layers)],
        "dec_blocks": dec,
        "enc_ln_g": ("embed",), "enc_ln_b": ("embed",),
        "lnf_g": ("embed",), "lnf_b": ("embed",),
    }


def t5_param_specs(cfg: T5Config, tp_axis: Optional[str]) -> Dict[str, Any]:
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(t5_logical_specs(cfg),
                         rules_from_axes(tp_axis=tp_axis))


def _sp_positions(S_loc: int, sp_axis: Optional[str]) -> jnp.ndarray:
    """This device's global positions for its contiguous sequence block
    (the GPT helper, fixed to the contiguous layout — T5 has no zigzag)."""
    return _gpt_positions(S_loc, sp_axis, "contiguous")


def t5_encode(params, src: jnp.ndarray, cfg: T5Config,
              tp_axis: Optional[str] = None,
              sp_axis: Optional[str] = None,
              remat: bool = False) -> jnp.ndarray:
    """(B, S_src) token ids → (B, S_src, d) encoder memory.

    With ``sp_axis``, ``src`` is this device's contiguous sequence block
    and self-attention runs the non-causal ring."""
    S = src.shape[1]
    pos = _sp_positions(S, sp_axis)
    x = (params["wte"][src] + params["wpe_src"][pos]).astype(cfg.dtype)

    def apply_block(x, p):
        return transformer_block(x, p, cfg.head_dim, tp_axis, sp_axis,
                                 causal=False)

    apply_block = maybe_remat(apply_block, remat)
    for p in params["enc_blocks"]:
        x = apply_block(x, p)
    return _layernorm(x, params["enc_ln_g"], params["enc_ln_b"])


def t5_decode(params, mem: jnp.ndarray, tgt_in: jnp.ndarray, cfg: T5Config,
              tp_axis: Optional[str] = None,
              sp_axis: Optional[str] = None,
              remat: bool = False,
              readout: bool = True) -> jnp.ndarray:
    """Teacher-forced decode: (B, S_tgt) shifted ids → f32 logits.

    With ``sp_axis``, the target side is sequence-sharded too: causal
    ring self-attention + rectangular cross-attention ring over the
    sp-sharded encoder memory. ``readout=False`` stops before the final
    norm + tied readout and returns the decoder hidden states —
    :func:`t5_loss`'s fused readout+CE path consumes those directly."""
    S = tgt_in.shape[1]
    pos = _sp_positions(S, sp_axis)
    x = (params["wte"][tgt_in]
         + params["wpe_tgt"][pos]).astype(cfg.dtype)

    def apply_block(x, p):
        return decoder_block(x, mem, p, cfg.head_dim, tp_axis, sp_axis)

    apply_block = maybe_remat(apply_block, remat)
    for p in params["dec_blocks"]:
        x = apply_block(x, p)
    return _readout(params, x) if readout else x


def t5_forward(params, src: jnp.ndarray, tgt_in: jnp.ndarray, cfg: T5Config,
               tp_axis: Optional[str] = None,
               sp_axis: Optional[str] = None,
               remat: bool = False) -> jnp.ndarray:
    mem = t5_encode(params, src, cfg, tp_axis=tp_axis, sp_axis=sp_axis,
                    remat=remat)
    return t5_decode(params, mem, tgt_in, cfg, tp_axis=tp_axis,
                     sp_axis=sp_axis, remat=remat)


def t5_loss(params, src, tgt_in, tgt_out, cfg: T5Config,
            dp_axis: Optional[str] = None,
            tp_axis: Optional[str] = None,
            sp_axis: Optional[str] = None,
            remat: bool = False,
            chunked_ce=True) -> jnp.ndarray:
    """Mean next-token CE over the target side (teacher forcing).

    Replication contract mirrors gpt_loss: identical across tp; pmean
    over sp (each device's local target-chunk mean is one summand of the
    global mean — equal chunks, so mean-of-means is exact); dp-local
    unless ``dp_axis`` is given. ``chunked_ce`` is the tri-state fused
    readout+CE knob (see ``gpt_loss``): truthy fuses the tied readout +
    CE over the decoder hidden states so the f32 (B, S_tgt, V) logits
    never materialize (``ops/chunked_ce.py``; ``"vocab_parallel"`` opts
    into the tp vocab split); ``False`` is the dense golden path."""
    from byteps_tpu.models.gpt import _readout_nll

    mem = t5_encode(params, src, cfg, tp_axis=tp_axis, sp_axis=sp_axis,
                    remat=remat)
    x = t5_decode(params, mem, tgt_in, cfg, tp_axis=tp_axis,
                  sp_axis=sp_axis, remat=remat, readout=False)
    loss = _readout_nll(params, x, tgt_out, tp_axis=tp_axis,
                        chunked=chunked_ce).mean()
    axes = tuple(a for a in (dp_axis, sp_axis) if a is not None)
    if axes:
        loss = jax.lax.pmean(loss, axes)
    return loss


def synthetic_seq2seq_batch(rng: jnp.ndarray, cfg: T5Config, batch: int,
                            src_len: int, tgt_len: int):
    """(src, tgt_in, tgt_out): random ids, target shifted right with BOS=0."""
    k1, k2 = jax.random.split(rng)
    src = jax.random.randint(k1, (batch, src_len), 0, cfg.vocab_size)
    tgt = jax.random.randint(k2, (batch, tgt_len + 1), 0, cfg.vocab_size)
    tgt = tgt.at[:, 0].set(0)
    return src, tgt[:, :-1], tgt[:, 1:]


# ---- cached seq2seq generation ---------------------------------------------
class T5DecCache(NamedTuple):
    """Decoder self-attention KV cache (n_dec, B, max_tgt, H, D) plus the
    fill level. Cross-attention k/v are not cached here — they are a pure
    function of the encoder memory, precomputed ONCE per sample by
    :func:`t5_cross_kv` (the structural win of encoder-decoder decode:
    the source side is encoded and projected exactly once)."""
    k: jnp.ndarray
    v: jnp.ndarray
    length: jnp.ndarray


def t5_init_cache(cfg: T5Config, batch: int,
                  h_loc: Optional[int] = None) -> T5DecCache:
    h = h_loc if h_loc is not None else cfg.n_heads
    shape = (cfg.n_dec_layers, batch, cfg.max_tgt, h, cfg.head_dim)
    return T5DecCache(k=jnp.zeros(shape, cfg.dtype),
                      v=jnp.zeros(shape, cfg.dtype),
                      length=jnp.zeros((), jnp.int32))


def t5_cross_kv(params, mem: jnp.ndarray, cfg: T5Config):
    """Precompute each decoder layer's cross-attention k/v from encoder
    memory: (n_dec, B, S_src, h_loc, D) pair."""
    ks, vs = [], []
    B, Sk = mem.shape[:2]
    for p in params["dec_blocks"]:
        k = col_parallel_matmul(mem, p["xwk"].astype(mem.dtype),
                                p["xbk"].astype(mem.dtype))
        v = col_parallel_matmul(mem, p["xwv"].astype(mem.dtype),
                                p["xbv"].astype(mem.dtype))
        h_loc = k.shape[-1] // cfg.head_dim
        ks.append(k.reshape(B, Sk, h_loc, cfg.head_dim))
        vs.append(v.reshape(B, Sk, h_loc, cfg.head_dim))
    return jnp.stack(ks), jnp.stack(vs)


def t5_decode_cached(params, tgt_tokens: jnp.ndarray, cache: T5DecCache,
                     cross_k: jnp.ndarray, cross_v: jnp.ndarray,
                     cfg: T5Config, tp_axis: Optional[str] = None):
    """Run T new target tokens through the decoder, appending to the cache.

    tgt_tokens: (B, T) continuing at position ``cache.length``; T =
    prompt length is the prefill, T = 1 one decode step — pinned to
    :func:`t5_decode` numerics either way. Returns (logits f32, cache).
    """
    from byteps_tpu.models.generate import _attn_cached_half

    B, T = tgt_tokens.shape
    pos0 = cache.length
    pos = pos0 + jnp.arange(T)
    x = (params["wte"][tgt_tokens]
         + jnp.take(params["wpe_tgt"], pos, axis=0)).astype(cfg.dtype)
    head_dim = cfg.head_dim
    new_k, new_v = [], []
    for li, p in enumerate(params["dec_blocks"]):
        # causal self-attention over the cache — the one shared
        # cache-append path (models/generate.py)
        x, ck, cv = _attn_cached_half(
            x, p, cache.k[li], cache.v[li], pos0, head_dim, tp_axis)
        h_loc = ck.shape[-2]    # T5 has no GQA: query heads == kv heads
        # cross-attention over the precomputed encoder k/v
        h = _layernorm(x, p["lnx_g"], p["lnx_b"])
        q = col_parallel_matmul(h, p["xwq"].astype(x.dtype),
                                p["xbq"].astype(x.dtype))
        q = q.reshape(B, T, h_loc, head_dim)
        o = plain_attention(q, cross_k[li].astype(q.dtype),
                            cross_v[li].astype(q.dtype), causal=False)
        x = x + row_parallel_matmul(o.reshape(B, T, h_loc * head_dim),
                                    p["xwo"].astype(x.dtype), tp_axis,
                                    p["xbo"].astype(x.dtype))
        x = x + _mlp(_layernorm(x, p["ln2_g"], p["ln2_b"]), p, tp_axis)
        new_k.append(ck)
        new_v.append(cv)
    logits = _readout(params, x)
    return logits, T5DecCache(k=jnp.stack(new_k), v=jnp.stack(new_v),
                              length=pos0 + T)


def make_t5_generate_fn(cfg: T5Config, max_new: int,
                        tp_axis: Optional[str] = None,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None):
    """Build a jitted seq2seq sampler: ``gen(params, src, rng, temperature)``.

    Encodes the source once, precomputes per-layer cross k/v once, then
    scans ``max_new`` single-token cached decoder steps from BOS (id 0).
    Greedy at ``temperature == 0``; ``top_k``/``top_p`` truncate the
    sampling distribution exactly as in the GPT sampler (shared
    ``make_truncate``). One XLA program end to end; returns (B, max_new)
    generated ids.
    """
    from byteps_tpu.models.generate import make_pick, make_truncate

    if 1 + max_new > cfg.max_tgt:
        # static shapes: past max_tgt the cache write offset would clamp
        # (overwriting the last slot) and wpe_tgt positions clip. The
        # bound depends only on factory args, so fail HERE, not at the
        # first traced call (the GPT sampler's guard needs the runtime
        # prompt length; this one doesn't).
        raise ValueError(f"BOS + max_new ({1 + max_new}) exceeds "
                         f"cfg.max_tgt ({cfg.max_tgt})")
    _pick = make_pick(make_truncate(top_k, top_p, cfg.vocab_size))

    def gen(params, src, rng, temperature=0.0):
        B = src.shape[0]
        mem = t5_encode(params, src, cfg, tp_axis=tp_axis)
        cross_k, cross_v = t5_cross_kv(params, mem, cfg)
        h_loc = cross_k.shape[-2]
        cache = t5_init_cache(cfg, B, h_loc=h_loc)
        bos = jnp.zeros((B, 1), jnp.int32)

        def step(carry, key):
            tok, cache = carry
            logits, cache = t5_decode_cached(
                params, tok, cache, cross_k, cross_v, cfg, tp_axis=tp_axis)
            nxt = _pick(logits[:, -1], key, temperature)[:, None]
            return (nxt, cache), nxt[:, 0]

        keys = jax.random.split(rng, max_new)
        (_, _), toks = jax.lax.scan(step, (bos, cache), keys)
        return toks.T  # (B, max_new)

    return jax.jit(gen, static_argnames=())
