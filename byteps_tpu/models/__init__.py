"""byteps_tpu.models — model zoo for benchmarks and examples.

The reference ships no models of its own (SURVEY §1: "models come from the
host framework") — its examples train torchvision/keras models. A
standalone TPU framework needs its own: these functional JAX models are the
benchmark/bench.py workloads (BASELINE configs: ResNet-50, BERT, GPT-2) and
the flagship for the driver's compile checks.
"""

from byteps_tpu.common.jax_compat import ensure as _ensure_jax_compat

_ensure_jax_compat()

from byteps_tpu.models.gpt import (GPTConfig, gpt_init, gpt_forward,
                                   gpt_hidden, gpt_loss, gpt_pp_loss)
from byteps_tpu.models.gpt import gpt_param_specs
from byteps_tpu.models.generate import (
    KVCache, gpt_apply_cached, init_cache, make_generate_fn,
)
from byteps_tpu.models.bert import (
    BertConfig, bert_init, bert_forward, bert_hidden, bert_mlm_loss,
    bert_param_specs,
)
from byteps_tpu.models.moe_gpt import (
    MoEGPTConfig, moe_gpt_init, moe_gpt_loss, moe_gpt_param_specs,
    moe_gpt_pp_loss,
)
from byteps_tpu.models.t5 import (
    T5Config, t5_init, t5_forward, t5_encode, t5_decode, t5_loss,
    t5_param_specs, synthetic_seq2seq_batch,
    T5DecCache, t5_init_cache, t5_cross_kv, t5_decode_cached,
    make_t5_generate_fn,
)
from byteps_tpu.models.vit import (
    ViTConfig, vit_init, vit_forward, vit_loss, vit_param_specs,
    synthetic_vit_batch,
)
from byteps_tpu.models.resnet import (
    ResNetConfig, resnet_init, resnet_forward, resnet_loss,
    resnet_param_specs,
)

__all__ = [
    "GPTConfig", "gpt_init", "gpt_forward", "gpt_hidden", "gpt_loss",
    "gpt_pp_loss", "gpt_param_specs",
    "KVCache", "gpt_apply_cached", "init_cache", "make_generate_fn",
    "BertConfig", "bert_init", "bert_forward", "bert_hidden",
    "bert_mlm_loss", "bert_param_specs",
    "MoEGPTConfig", "moe_gpt_init", "moe_gpt_loss", "moe_gpt_param_specs",
    "moe_gpt_pp_loss",
    "ResNetConfig", "resnet_init", "resnet_forward", "resnet_loss",
    "resnet_param_specs",
    "T5Config", "t5_init", "t5_forward", "t5_encode", "t5_decode",
    "t5_loss", "t5_param_specs", "synthetic_seq2seq_batch",
    "T5DecCache", "t5_init_cache", "t5_cross_kv", "t5_decode_cached",
    "make_t5_generate_fn",
    "ViTConfig", "vit_init", "vit_forward", "vit_loss",
    "vit_param_specs", "synthetic_vit_batch",
]
