"""Greedy speculative decoding — exact by construction.

Beyond-reference inference acceleration (the reference has no decode
path at all): a cheap draft model proposes ``spec_len`` tokens per
round; the target model verifies ALL of them in ONE cached forward
(sequence-parallel on the MXU instead of token-serial), keeps the
longest agreeing prefix, and emits its own correction at the first
mismatch. Greedy output is therefore token-for-token IDENTICAL to
plain greedy decoding of the target — the draft affects only speed
(accepted tokens per target forward), never content. Tests pin this
exactness with an adversarial draft.

TPU-first mechanics, all static shapes inside one jitted program:

* One ``lax.while_loop`` round = ``spec_len`` scanned draft steps +
  one target forward over ``spec_len`` fed tokens.
* Rollback is a fill-level rewind: both KV caches append every fed
  token, then ``length`` is reset to the committed prefix — entries
  past the fill level are masked out by construction and overwritten
  by the next round's writes (``generate.py`` cache contract), so no
  scatter/gather cleanup exists.
* Batched: rows accept independently, the round advances by the
  BATCH-MIN accepted count (rows that accepted more simply re-derive
  those tokens next round — correctness is unaffected, the cost is
  the standard batched-speculation tradeoff).

Two draft strategies:

* ``make_speculative_generate_fn`` — a draft MODEL (any GPT-family
  config sharing the target's vocabulary, typically distilled/
  shallower). Wall-clock win ≈ f(draft_cost/target_cost, accept rate);
  with draft == target it measures pure verify overhead (~1×), which
  is why the bench labels that configuration an overhead probe, not a
  ceiling.
* ``make_lookup_generate_fn`` — prompt-lookup drafting (the
  "assisted generation" n-gram trick): propose the K tokens that
  followed the most recent occurrence of the current bigram in the
  already-generated context. The draft costs a few vectorized
  compares — no model at all — so ANY nonzero accept rate is pure
  win; repetitive continuations (code, structured text, greedy
  attractors) accept in long runs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from byteps_tpu.models.generate import gpt_apply_cached, init_cache
from byteps_tpu.models.gpt import GPTConfig


def _verify_commit(d, logits, out, n_emitted, K):
    """The exactness-critical accept/commit arithmetic shared by both
    samplers: compare proposals against the target's greedy choices,
    commit the batch-min agreeing prefix (+ the correction token at the
    first mismatch), and report how many cache entries are committed.

    Returns ``(out, n_emitted, next_tok, committed)`` where
    ``committed`` is the count of newly-valid cache entries past the
    round's starting fill level (``[next_tok, d_1..d_{min(m, K-1)}]``).
    """
    B = d.shape[0]
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # (B, K)
    acc = (d == preds).astype(jnp.int32)
    m = jnp.min(jnp.cumprod(acc, axis=1).sum(axis=1))       # batch-min
    corr_idx = jnp.minimum(m, K - 1)
    correction = preds[jnp.arange(B), corr_idx]
    full = m == K
    # emit d_1..d_m, plus the correction when a mismatch happened; the
    # stray write at slot m when m == K lands exactly at the next
    # round's offset and is overwritten there
    block = jnp.where(jnp.arange(K + 1)[None, :] == m,
                      correction[:, None],
                      jnp.pad(d, ((0, 0), (0, 1))))
    out = jax.lax.dynamic_update_slice(out, block, (0, n_emitted))
    n_emitted = n_emitted + jnp.where(full, K, m + 1)
    next_tok = jnp.where(full, d[:, K - 1], correction)
    return out, n_emitted, next_tok, 1 + jnp.minimum(m, K - 1)


def make_speculative_generate_fn(cfg: GPTConfig, draft_cfg: GPTConfig,
                                 max_new: int, spec_len: int = 4,
                                 tp_axis: Optional[str] = None):
    """Build a jitted greedy speculative sampler.

    ``gen(params, draft_params, prompt) -> (tokens (B, T0+max_new),
    rounds)`` — ``rounds`` is the number of verify forwards the run
    took (== target forwards after prefill; plain greedy decoding would
    take ``max_new``). Output tokens are exactly plain greedy's.
    """
    if spec_len < 1:
        raise ValueError(f"spec_len must be >= 1; got {spec_len}")
    if draft_cfg.vocab_size != cfg.vocab_size:
        raise ValueError(
            f"draft vocab {draft_cfg.vocab_size} != target vocab "
            f"{cfg.vocab_size} — speculation compares token ids")
    K = spec_len

    @jax.jit
    def gen(params, draft_params, prompt):
        B, T0 = prompt.shape
        if T0 + max_new + K > cfg.max_seq:
            raise ValueError(
                f"prompt ({T0}) + max_new ({max_new}) + spec_len ({K}) "
                f"exceeds cfg.max_seq ({cfg.max_seq})")
        if T0 + max_new + K > draft_cfg.max_seq:
            raise ValueError(
                f"draft max_seq ({draft_cfg.max_seq}) too small for "
                f"prompt ({T0}) + max_new ({max_new}) + spec_len ({K})")

        kv_t = params["blocks"][0]["wk"].shape[-1] // cfg.head_dim
        kv_d = draft_params["blocks"][0]["wk"].shape[-1] // draft_cfg.head_dim
        cache_t = init_cache(cfg, B, h_loc=kv_t)
        cache_d = init_cache(draft_cfg, B, h_loc=kv_d)

        logits_t, cache_t = gpt_apply_cached(params, prompt, cache_t, cfg,
                                             tp_axis)
        _, cache_d = gpt_apply_cached(draft_params, prompt, cache_d,
                                      draft_cfg, tp_axis)
        # first committed token: target's greedy choice after the prompt
        # (emitted, not yet in either cache)
        next_tok = jnp.argmax(logits_t[:, -1], axis=-1).astype(jnp.int32)

        out = jnp.zeros((B, max_new + K + 1), jnp.int32)
        out = out.at[:, 0].set(next_tok)

        draft_step = functools.partial(gpt_apply_cached, cfg=draft_cfg,
                                       tp_axis=tp_axis)

        def round_body(state):
            out, n_emitted, next_tok, cache_t, cache_d, rounds = state
            len0 = cache_t.length

            # -- draft proposes K tokens (K cached single steps) -------
            def dstep(carry, _):
                tok, cd = carry
                lg, cd = draft_step(draft_params, tok[:, None], cd)
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
                return (nxt, cd), nxt

            (_, cache_d), d = jax.lax.scan(
                dstep, (next_tok, cache_d), None, length=K)
            d = jnp.moveaxis(d, 0, 1)                     # (B, K)

            # -- target verifies in ONE forward of K fed tokens --------
            feed = jnp.concatenate([next_tok[:, None], d[:, :K - 1]],
                                   axis=1)                # (B, K)
            logits, cache_t = gpt_apply_cached(params, feed, cache_t, cfg,
                                               tp_axis)
            out, n_emitted, next_tok, committed = _verify_commit(
                d, logits, out, n_emitted, K)
            # fill-level rewind on BOTH caches (they appended the same
            # K fed positions)
            cache_t = cache_t._replace(length=len0 + committed)
            cache_d = cache_d._replace(length=len0 + committed)
            return out, n_emitted, next_tok, cache_t, cache_d, rounds + 1

        def cond(state):
            return state[1] < max_new

        out, n_emitted, *_rest = jax.lax.while_loop(
            cond, round_body,
            (out, jnp.int32(1), next_tok, cache_t, cache_d, jnp.int32(0)))
        rounds = _rest[-1]
        return jnp.concatenate([prompt.astype(jnp.int32),
                                out[:, :max_new]], axis=1), rounds

    return gen


def make_lookup_generate_fn(cfg: GPTConfig, max_new: int,
                            spec_len: int = 4,
                            tp_axis: Optional[str] = None):
    """Prompt-lookup speculative greedy sampler (model-free draft).

    ``gen(params, prompt) -> (tokens (B, T0+max_new), rounds)``. Each
    round proposes the ``spec_len`` tokens that followed the most
    recent earlier occurrence of the current (prev, last) bigram in
    the committed context (per batch row), then verifies them with one
    target forward exactly like the model-draft sampler. Output is
    token-for-token plain greedy at any accept rate; ``rounds`` counts
    the verify forwards (plain decoding would take ``max_new``).
    """
    if spec_len < 1:
        raise ValueError(f"spec_len must be >= 1; got {spec_len}")
    K = spec_len

    @jax.jit
    def gen(params, prompt):
        B, T0 = prompt.shape
        if T0 < 2:
            raise ValueError("prompt must hold at least the seed bigram "
                             f"(2 tokens); got {T0}")
        if T0 + max_new + K > cfg.max_seq:
            raise ValueError(
                f"prompt ({T0}) + max_new ({max_new}) + spec_len ({K}) "
                f"exceeds cfg.max_seq ({cfg.max_seq})")
        kv_t = params["blocks"][0]["wk"].shape[-1] // cfg.head_dim
        cache_t = init_cache(cfg, B, h_loc=kv_t)
        logits_t, cache_t = gpt_apply_cached(params, prompt, cache_t, cfg,
                                             tp_axis)
        next_tok = jnp.argmax(logits_t[:, -1], axis=-1).astype(jnp.int32)

        W = T0 + max_new + K + 1
        out = jnp.zeros((B, max_new + K + 1), jnp.int32)
        out = out.at[:, 0].set(next_tok)

        def propose(out, n_emitted, next_tok):
            """Latest-bigram continuation from the committed context."""
            ctx = jnp.concatenate([prompt.astype(jnp.int32), out], axis=1)
            pos_last = T0 + n_emitted - 1          # next_tok's position
            prev = ctx[jnp.arange(B), pos_last - 1]
            pos = jnp.arange(W - 1)
            match = ((ctx[:, :-1] == prev[:, None])
                     & (ctx[:, 1:] == next_tok[:, None])
                     & (pos[None, :] <= pos_last - 2))
            # latest match; rows with none propose clamped-gather junk
            # (a junk proposal just means accept 0 for that row)
            p_star = jnp.argmax(
                jnp.where(match, pos[None, :], -1), axis=1)
            idx = jnp.clip(p_star[:, None] + 2 + jnp.arange(K)[None, :],
                           0, W - 1)
            return jnp.take_along_axis(ctx, idx, axis=1)   # (B, K)

        def round_body(state):
            out, n_emitted, next_tok, cache_t, rounds = state
            len0 = cache_t.length
            d = propose(out, n_emitted, next_tok)
            feed = jnp.concatenate([next_tok[:, None], d[:, :K - 1]],
                                   axis=1)
            logits, cache_t = gpt_apply_cached(params, feed, cache_t, cfg,
                                               tp_axis)
            out, n_emitted, next_tok, committed = _verify_commit(
                d, logits, out, n_emitted, K)
            cache_t = cache_t._replace(length=len0 + committed)
            return out, n_emitted, next_tok, cache_t, rounds + 1

        out, n_emitted, _nt, _c, rounds = jax.lax.while_loop(
            lambda s: s[1] < max_new, round_body,
            (out, jnp.int32(1), next_tok, cache_t, jnp.int32(0)))
        return jnp.concatenate([prompt.astype(jnp.int32),
                                out[:, :max_new]], axis=1), rounds

    return gen
