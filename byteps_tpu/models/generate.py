"""Autoregressive generation with a KV cache for the GPT family.

The reference is a training system (its inference story is "export to the
host framework"); a standalone framework needs the decode path too. The
TPU-idiomatic form: a static-shape KV cache ``(n_layers, B, max_seq, H,
D)`` updated in place with ``dynamic_update_slice`` inside a
``lax.scan`` over positions — one traced XLA program for the whole
generation, no per-token retrace, MXU-friendly (the decode matmuls are
(B·H, 1, D) × (D, S) batched GEMVs that XLA tiles together).

Weights are exactly the training params (`gpt.py`) — layernorms, Megatron
col/row-parallel projections (tp composes: q/k/v/cache shard over heads,
``row_parallel_matmul`` psums the output), weight-tied fp32 readout.
Causality is positional masking against the cache fill level, so prefill
and decode share one cached-attention implementation whose numerics are
pinned to ``gpt_forward`` in ``tests/test_generate.py``.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from byteps_tpu.models.gpt import (
    GPTConfig,
    _bias,
    _layernorm,
    _mlp,
    _readout,
    resolve_norm,
    resolve_rope,
    rope_rotate,
)
from byteps_tpu.parallel.tp import col_parallel_matmul, row_parallel_matmul



class KVCache(NamedTuple):
    """Static-shape per-layer key/value cache.

    k/v: (n_layers, B, max_seq, h_loc, head_dim); ``length`` is the fill
    level (tokens already written). Under tp, h_loc is this shard's head
    count — the cache is a per-device value inside shard_map.

    With ``init_cache(..., quant=True)`` k/v are int8 and ``k_scale`` /
    ``v_scale`` (n_layers, B, max_seq, h_loc) hold per-(position, head)
    fp32 dequantization scales — cache HBM drops to ~(1 + 4/head_dim)
    bytes/element, about half of bf16, the lever that doubles the decode
    batch or context a chip can hold. Dense caches leave the scale
    fields None (the pytree stays scan-carry compatible either way).
    """
    k: jnp.ndarray
    v: jnp.ndarray
    length: jnp.ndarray        # () int32
    k_scale: Optional[jnp.ndarray] = None
    v_scale: Optional[jnp.ndarray] = None


def init_cache(cfg: GPTConfig, batch: int, h_loc: Optional[int] = None,
               max_seq: Optional[int] = None,
               quant: bool = False) -> KVCache:
    h = h_loc if h_loc is not None else cfg.n_heads
    S = max_seq if max_seq is not None else cfg.max_seq
    shape = (cfg.n_layers, batch, S, h, cfg.head_dim)
    if quant:
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            length=jnp.zeros((), jnp.int32),
            k_scale=jnp.zeros(shape[:-1], jnp.float32),
            v_scale=jnp.zeros(shape[:-1], jnp.float32),
        )
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.zeros((), jnp.int32),
    )


class _QuantSlot(NamedTuple):
    """One layer's quantized cache side: int8 values + fp32 scales.
    A distinct type (not a bare tuple) so the polymorphic dispatch in
    _cache_write/_cache_read can never mistake another tuple-shaped
    value — KVCache itself is a NamedTuple — for a quantized slot."""
    q: jnp.ndarray
    scale: jnp.ndarray


def _quantize_block(x):
    """(B, T, h, D) → (int8 values, fp32 per-(B,T,h) scales).

    Symmetric absmax scaling over the head_dim axis: exact for inputs
    that already sit on their scale grid, ≤ scale/2 rounding error
    otherwise. A zero block gets scale eps (dequantizes to exact zeros).
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.round(x.astype(jnp.float32) / scale[..., None])
    return q.astype(jnp.int8), scale


def _cache_write(cache, new, pos0):
    """Append ``new`` (B, T, h, D) at position pos0. ``cache`` is either
    a dense (B, S, h, D) array or a :class:`_QuantSlot` — the quantized
    form flows through _block_step/_attn_cached_half polymorphically so
    the T5/MoE users of the same code path stay untouched."""
    if isinstance(cache, _QuantSlot):
        q, s = _quantize_block(new)
        return _QuantSlot(
            jax.lax.dynamic_update_slice(cache.q, q, (0, pos0, 0, 0)),
            jax.lax.dynamic_update_slice(cache.scale, s, (0, pos0, 0)),
        )
    return jax.lax.dynamic_update_slice(
        cache, new.astype(cache.dtype), (0, pos0, 0, 0))


def _cache_read(cache, dtype):
    """Materialize the attention-ready (B, S, h, D) view in ``dtype``;
    int8 entries dequantize through their scales. On the jnp decode
    path XLA fuses the multiply into the attention dot (reads stay
    int8); the Pallas prefill kernel takes concrete operands, so there
    the dequantized view is materialized once per prefill — the
    *persistent* cache footprint is what halves either way."""
    if isinstance(cache, _QuantSlot):
        return (cache.q.astype(jnp.float32)
                * cache.scale[..., None]).astype(dtype)
    return cache


def _cached_attention(q, k_cache, v_cache, q_pos0):
    """q: (B, T, H, D) new queries at positions q_pos0..q_pos0+T-1;
    k/v_cache: (B, S_max, H, D) with the new keys already written.
    Causal-masks against global positions, so entries past the fill level
    (zeros) are masked out by construction. Long prefills (tileable T)
    ride the flash kernel — same global-offset masking; single-token
    decode (T=1) stays on the fused-GEMV jnp path automatically."""
    from byteps_tpu.ops.flash_attention import attention_lse

    o, _ = attention_lse(q, k_cache, v_cache, q_pos0, 0, causal=True)
    return o


def _attn_cached_half(x, p, cache_k, cache_v, pos0, head_dim, tp_axis,
                      rope_base: float = 0.0, norm_fn=_layernorm,
                      norm_eps: float = 1e-5, use_bias: bool = True):
    """The attention residual branch over T new tokens with cache append.

    x: (B, T, d); cache_k/v: (B, S_max, h_loc, D) this layer's cache.
    Returns (x_out, new_cache_k, new_cache_v). With ``rope_base > 0``
    the new q/k rotate by their global positions before the cache write,
    so cached keys are stored post-rotation (the standard decode
    convention). Config-agnostic on purpose: the GPT/MoE block step AND
    the T5 decoder (models/t5.py t5_decode_cached) share this one
    cache-append path.
    """
    from byteps_tpu.models.lora import lora_delta

    B, T = x.shape[:2]
    h = norm_fn(x, p["ln1_g"], p.get("ln1_b"), norm_eps)
    q = col_parallel_matmul(h, p["wq"].astype(x.dtype), _bias(p, "bq", x, use_bias))
    k = col_parallel_matmul(h, p["wk"].astype(x.dtype), _bias(p, "bk", x, use_bias))
    v = col_parallel_matmul(h, p["wv"].astype(x.dtype), _bias(p, "bv", x, use_bias))
    if "lora" in p:
        # keep grafted (unmerged) trees decode-exact with gpt_forward —
        # without this the cached path silently ran the frozen base
        q = q + lora_delta(h, p, "wq")
        k = k + lora_delta(h, p, "wk")
        v = v + lora_delta(h, p, "wv")
    h_loc = q.shape[-1] // head_dim
    kv_loc = k.shape[-1] // head_dim    # GQA: the cache stores kv heads only
    q = q.reshape(B, T, h_loc, head_dim)
    k = k.reshape(B, T, kv_loc, head_dim)
    v = v.reshape(B, T, kv_loc, head_dim)
    if rope_base > 0.0:
        pos = pos0 + jnp.arange(T)
        q = rope_rotate(q, pos, rope_base)
        k = rope_rotate(k, pos, rope_base)
    cache_k = _cache_write(cache_k, k, pos0)
    cache_v = _cache_write(cache_v, v, pos0)
    # GQA is native on every path — prefill and decode read the narrow
    # cache directly, no repeat anywhere. The T=1 decode step takes the
    # flash-decode kernel when available: one explicit VMEM online-
    # softmax pass over the stored cache (int8 read directly, dequant
    # per block in VMEM with _cache_read's rounding), dead blocks
    # skipped past the fill level.
    from byteps_tpu.ops.flash_decode import (
        decode_supported, flash_decode, use_pallas)

    S_max = (cache_k.q if isinstance(cache_k, _QuantSlot)
             else cache_k).shape[1]
    if T == 1 and use_pallas() and decode_supported(S_max, head_dim):
        if isinstance(cache_k, _QuantSlot):
            o = flash_decode(q, cache_k.q, cache_v.q, pos0,
                             k_scale=cache_k.scale, v_scale=cache_v.scale)
        else:
            o = flash_decode(q, cache_k, cache_v, pos0)
    else:
        o = _cached_attention(q, _cache_read(cache_k, x.dtype),
                              _cache_read(cache_v, x.dtype), pos0)
    o = o.reshape(B, T, h_loc * head_dim)
    attn_out = row_parallel_matmul(o, p["wo"].astype(x.dtype), tp_axis,
                                   _bias(p, "bo", x, use_bias))
    if "lora" in p:
        attn_out = attn_out + lora_delta(o, p, "wo", tp_axis)
    return x + attn_out, cache_k, cache_v


def _block_step(x, p, cache_k, cache_v, pos0, cfg, tp_axis, ep_axis,
                norm_fn=_layernorm, norm_eps: float = 1e-5):
    """One transformer block (dense-MLP or MoE, by param structure) over
    T new tokens with cache append."""
    x, cache_k, cache_v = _attn_cached_half(
        x, p, cache_k, cache_v, pos0, cfg.head_dim, tp_axis,
        rope_base=(cfg.rope_base if cfg.pos_embedding == "rope" else 0.0),
        norm_fn=norm_fn, norm_eps=norm_eps, use_bias=cfg.use_bias)
    h = norm_fn(x, p["ln2_g"], p.get("ln2_b"), norm_eps)
    if "moe" in p:
        from byteps_tpu.parallel.moe import moe_ffn

        # inference uses no-drop capacity: the training capacity_factor
        # is a throughput/static-shape lever, and a dropped token at
        # decode time silently corrupts the sample
        m, _aux = moe_ffn(
            h, p["moe"], ep_axis=ep_axis, router_topk=cfg.router_topk,
            tp_axis=tp_axis, no_drop=True)
        x = x + m
    else:
        x = x + _mlp(h, p, tp_axis, use_bias=cfg.use_bias)
    return x, cache_k, cache_v


def gpt_apply_cached(params, tokens: jnp.ndarray, cache: KVCache,
                     cfg: GPTConfig, tp_axis: Optional[str] = None,
                     ep_axis: Optional[str] = None,
                     readout: bool = True
                     ) -> Tuple[Optional[jnp.ndarray], KVCache]:
    """Run T new tokens through the model, appending to the cache.

    tokens: (B, T) continuing at position ``cache.length``. Returns
    (logits (B, T, vocab) f32, updated cache). T=prompt length is the
    prefill; T=1 is one decode step — same code, pinned to
    ``gpt_forward`` numerics either way. Serves both the dense and the
    MoE GPT families (block type detected from the params; ``ep_axis``
    shards the experts inside shard_map).

    ``readout=False`` skips the vocab projection and returns
    ``(None, cache)`` — the serve tier's intermediate prefill chunks
    only need the cache side, and at real vocab sizes the readout is
    the single largest weight stream in the step.
    """
    resolve_rope(cfg)   # validate the position scheme decode-side too
    norm_fn, norm_eps = resolve_norm(cfg)
    B, T = tokens.shape
    pos0 = cache.length
    if cfg.pos_embedding == "rope":
        x = params["wte"][tokens].astype(cfg.dtype)
    else:
        pos = pos0 + jnp.arange(T)
        x = (params["wte"][tokens]
             + jnp.take(params["wpe"], pos, axis=0)).astype(cfg.dtype)

    quant = cache.k_scale is not None
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for li, p in enumerate(params["blocks"]):
        ck = (_QuantSlot(cache.k[li], cache.k_scale[li]) if quant
              else cache.k[li])
        cv = (_QuantSlot(cache.v[li], cache.v_scale[li]) if quant
              else cache.v[li])
        x, ck, cv = _block_step(x, p, ck, cv, pos0, cfg, tp_axis, ep_axis,
                                norm_fn=norm_fn, norm_eps=norm_eps)
        if quant:
            new_k.append(ck.q)
            new_ks.append(ck.scale)
            new_v.append(cv.q)
            new_vs.append(cv.scale)
        else:
            new_k.append(ck)
            new_v.append(cv)
    logits = _readout(params, x, norm_fn, norm_eps) if readout else None
    return logits, KVCache(
        k=jnp.stack(new_k), v=jnp.stack(new_v), length=pos0 + T,
        k_scale=jnp.stack(new_ks) if quant else None,
        v_scale=jnp.stack(new_vs) if quant else None,
    )


def make_truncate(top_k: Optional[int], top_p: Optional[float],
                  vocab_size: int):
    """Build the per-step logits filter shared by every sampler (GPT/MoE
    and T5): mask logits outside the top-k set / the top-p nucleus (both
    computed on the raw distribution; with both set, a token must pass
    both filters). top_k-only takes a partial lax.top_k; any top_p pays
    one descending sort that also serves the top_k threshold. Ties at
    the k-th (or nucleus-edge) logit are ALL kept — standard >=-threshold
    behavior, so sampling is not strictly limited to k tokens when the
    boundary value repeats."""
    if top_k is not None and not 1 <= top_k <= vocab_size:
        raise ValueError(f"top_k must be in [1, vocab]; got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1]; got {top_p}")

    def _truncate(logits_t):
        if top_k is None and top_p is None:
            return logits_t
        if top_p is None:
            # top_k only: a partial top-k beats the full vocab sort
            vals = jax.lax.top_k(logits_t, top_k)[0]
            return jnp.where(logits_t >= vals[:, -1:], logits_t, -jnp.inf)
        thresh = jnp.full_like(logits_t[:, :1], -jnp.inf)
        sorted_desc = jnp.sort(logits_t, axis=-1)[:, ::-1]
        if top_k is not None:
            thresh = jnp.maximum(thresh, sorted_desc[:, top_k - 1:top_k])
        if top_p is not None:
            probs = jax.nn.softmax(sorted_desc, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep every token whose PRECEDING cumulative mass < top_p
            # (the nucleus always includes the argmax)
            keep = jnp.concatenate(
                [jnp.zeros_like(cum[:, :1]), cum[:, :-1]], axis=-1) < top_p
            thresh = jnp.maximum(thresh, jnp.min(
                jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                keepdims=True))
        return jnp.where(logits_t >= thresh, logits_t, -jnp.inf)

    return _truncate


def make_pick(truncate):
    """Per-step token selection shared by every sampler: exact argmax at
    ``temperature == 0``, otherwise categorical over the truncated
    logits at ``temperature`` (floored at 1e-6 so the jitted branchless
    select never divides by zero)."""

    def pick(logits_t, key, temperature):
        greedy = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
        temp = jnp.maximum(temperature, 1e-6)
        sampled = jax.random.categorical(key, truncate(logits_t) / temp,
                                         axis=-1)
        return jnp.where(temperature > 0.0, sampled.astype(jnp.int32),
                         greedy)

    return pick


def make_generate_fn(cfg: GPTConfig, max_new: int,
                     tp_axis: Optional[str] = None,
                     ep_axis: Optional[str] = None,
                     top_k: Optional[int] = None,
                     top_p: Optional[float] = None,
                     quant_cache: bool = False):
    """Build a jitted sampler: ``gen(params, prompt, rng, temperature)``.

    prompt: (B, T0) int32; returns (B, T0 + max_new) tokens. Greedy when
    ``temperature == 0`` (exact argmax — the equivalence-vs-gpt_forward
    test drives this), categorical sampling otherwise, optionally
    truncated to the ``top_k`` highest-probability tokens and/or the
    ``top_p`` nucleus (smallest set with cumulative probability ≥ top_p,
    computed at temperature 1 then resampled at ``temperature``). One XLA
    program: cached prefill + ``lax.scan`` over max_new decode steps.

    ``quant_cache=True`` stores k/v as int8 with per-(position, head)
    scales (see :class:`KVCache`) — ~half the cache HBM of bf16 at a
    small, bounded numerics cost (symmetric absmax, ≤ scale/2 per
    element).
    """
    _pick = make_pick(make_truncate(top_k, top_p, cfg.vocab_size))

    @functools.partial(jax.jit, static_argnames=())
    def gen(params, prompt, rng, temperature=0.0):
        B, T0 = prompt.shape
        if T0 + max_new > cfg.max_seq:
            # static shapes: past max_seq the cache write offset would
            # clamp (overwriting the last slot) and wpe positions clip —
            # fail at trace time instead of generating garbage
            raise ValueError(
                f"prompt ({T0}) + max_new ({max_new}) exceeds "
                f"cfg.max_seq ({cfg.max_seq})")
        # under tp (inside shard_map) the projections are head-sharded —
        # size the cache from this device's wk shard (GQA: kv heads only,
        # the cache-memory lever)
        kv_loc = params["blocks"][0]["wk"].shape[-1] // cfg.head_dim
        cache = init_cache(cfg, B, h_loc=kv_loc, quant=quant_cache)
        logits, cache = gpt_apply_cached(params, prompt, cache, cfg, tp_axis,
                                         ep_axis)
        last = logits[:, -1]

        def step(carry, key):
            cache, last_logits = carry
            tok = _pick(last_logits, key, temperature)        # (B,)
            logits, cache = gpt_apply_cached(
                params, tok[:, None], cache, cfg, tp_axis, ep_axis)
            return (cache, logits[:, 0]), tok

        keys = jax.random.split(rng, max_new)
        (_, _), toks = jax.lax.scan(step, (cache, last), keys)
        return jnp.concatenate([prompt, toks.T], axis=1)

    return gen
