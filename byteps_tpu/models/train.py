"""Sharded training-step factories for the model zoo.

Builds full jitted train steps over a (dp, tp, sp) mesh: per-device
loss+grad via ``shard_map`` (ring attention over sp, Megatron collectives
over tp inside the models), and BytePS aggregation over dp through
``DistributedOptimizer`` (reference hot path, SURVEY §3.2 — here fused into
one XLA program so chunk collectives overlap backward compute).

VMA notes (apply to every factory): per-device AD is exact under
``check_vma=True`` — replicated params' cotangents get their sp/tp psums
auto-inserted, and marking params dp-varying (``pcast``) keeps grads
per-replica LOCAL so dp aggregation stays in DistributedOptimizer. The
compressed collective (comm/ici.py) and the ZeRO-1 all_gather defeat the
VMA replication analysis, so those modes run ``check_vma=False`` with the
VMA-equivalent gradient assembly done explicitly: pp/ep stage-partial
grads psum over the axes their specs don't shard (``_manual_axis_sums``),
and tp/sp — whose in-forward collectives leave no-VMA AD computing
``d(sum over replicated loss copies)/dw`` via psum self-transpose — get
the same psums plus a uniform division by the tp*sp axis product
(``_novma_collective_fix``; pinned against the VMA path in
tests/test_compressed_parallel.py). Every parallel composition therefore
works compressed: dp x {tp, sp, pp, ep} and their products.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byteps_tpu.jax.optimizer import DistributedOptimizer, dp_state_specs
from byteps_tpu.models.bert import BertConfig, bert_init, bert_mlm_loss
from byteps_tpu.models.gpt import (
    GPTConfig,
    gpt_init,
    gpt_loss,
    gpt_pp_loss,
)
from byteps_tpu.models.resnet import ResNetConfig, resnet_init, resnet_loss
from byteps_tpu.models.t5 import T5Config, t5_init, t5_loss
from byteps_tpu.models.vit import ViTConfig, vit_init, vit_loss
from byteps_tpu.parallel.partitioner import Partitioner, stacked_logical_specs
from byteps_tpu.parallel.sharding import opt_state_specs


def _axis(mesh: Mesh, name: str) -> Optional[str]:
    return name if name in mesh.axis_names else None


def _check_seq_layout(seq_layout, sp=None):
    if seq_layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown seq_layout {seq_layout!r} — expected "
                         "'contiguous' or 'zigzag'")
    if seq_layout == "zigzag" and sp is None:
        # the zigzag contract is "feed zigzag_permutation-permuted tokens";
        # without an sp axis the ring degenerates to contiguous attention
        # and that pre-permuted input would train on silently scrambled data
        raise ValueError(
            "seq_layout='zigzag' requires a mesh with an sp axis — the "
            "layout only exists to balance the causal ring over sp; on "
            "this mesh the permuted inputs would just be scrambled tokens")


def _resolve_init_params(init_params, cfg, pspecs):
    """Fresh :func:`gpt_init` weights, or the caller's ``init_params``
    (e.g. imported via ``models.import_hf``) validated — tree structure
    AND leaf shapes — against what the config would initialize, so a
    config/weights mismatch fails here instead of as a shape error deep
    inside the jitted step."""
    if init_params is None:
        return gpt_init(jax.random.PRNGKey(0), cfg)
    want = jax.tree_util.tree_structure(pspecs)
    got = jax.tree_util.tree_structure(init_params)
    if want != got:
        raise ValueError(
            "init_params tree structure does not match the config's "
            f"parameter tree:\n  config expects {want}\n  got {got}")
    expect = jax.eval_shape(
        lambda: gpt_init(jax.random.PRNGKey(0), cfg))
    bad = []

    def _cmp(path, e, g):
        if tuple(e.shape) != tuple(jnp.shape(g)):
            bad.append(f"  {jax.tree_util.keystr(path)}: config expects "
                       f"{tuple(e.shape)}, got {tuple(jnp.shape(g))}")

    jax.tree_util.tree_map_with_path(_cmp, expect, init_params)
    if bad:
        raise ValueError(
            "init_params leaf shapes do not match the config:\n"
            + "\n".join(bad))
    return init_params


def _novma_collective_fix(grads, pspecs, mesh, rep_axes, extra_sum_axes=()):
    """Correct check_vma=False gradients for in-forward collective axes.

    In no-VMA mode ``jax.lax.psum`` is its own transpose, so the adjoint
    computes ``d(sum over all replicated loss copies)/dw`` — every
    device's raw grad carries the cotangents of EVERY replica's loss copy
    (verified: after the per-leaf psums, every leaf is exactly
    ``prod(rep_axes sizes)`` times the VMA path's gradient, uniformly).
    The fix: psum each leaf over the axes its spec doesn't shard (what
    VMA would auto-insert; ``extra_sum_axes`` adds pp/ep whose
    stage-partial sums are needed too), then divide ALL leaves by the
    ``rep_axes`` product. ``rep_axes`` must be exactly the axes the loss
    is REPLICATED over before grad (tp/sp here — pp's loss is
    stage-masked and ep's is a per-device local mean, so they get sums
    but no division)."""
    rep_axes = tuple(a for a in rep_axes if a is not None)
    sum_axes = rep_axes + tuple(a for a in extra_sum_axes if a is not None)
    if not sum_axes:
        return grads
    grads = _manual_axis_sums(grads, pspecs, sum_axes)
    denom = 1
    for a in rep_axes:
        denom *= mesh.shape[a]
    if denom > 1:
        grads = jax.tree.map(lambda g: g / denom, grads)
    return grads


def _dist_state_setup(mesh, params, pspecs, dp, zero_1, slc=None):
    """The per-factory distributed-state bookkeeping: which mesh axes give
    each device its own worker state, the per-device grads numel, and the
    kwargs both _make_tx and _shard_params_state need."""
    if zero_1 and dp is None:
        raise ValueError(
            "zero_1=True requires a dp mesh axis — ZeRO-1 shards the "
            "optimizer state over dp and there is nothing to shard over "
            "on this mesh")
    if zero_1 and slc is not None:
        raise ValueError(
            "zero_1=True does not compose with a slice_ mesh axis — the "
            "ZeRO-1 segment flow owns the dp reduce-scatter; use "
            "zero_3=True for multi-slice FSDP instead")
    state_axes = _state_axes(mesh, pspecs, dp)
    pd_numel = _per_device_numel(params, pspecs, mesh)
    tx_kw = dict(
        per_device_numel=pd_numel,
        state_leading=tuple(mesh.shape[a] for a in state_axes),
        zero=zero_1,
    )
    return state_axes, tx_kw, (pd_numel if zero_1 else None)


def _state_axes(mesh, pspecs, dp) -> tuple:
    """Mesh axes (besides dp) that shard the params — each combination of
    their indices is a distinct "worker" whose EF/momentum residual must be
    its own buffer (pp stages grad different layer slabs, ep groups
    different expert slabs). Ordered by mesh axis order."""
    used = set()
    for spec in jax.tree.leaves(pspecs, is_leaf=lambda x: isinstance(x, P)):
        used |= _spec_axes(spec)
    return tuple(a for a in mesh.axis_names if a in used and a != dp)


def _per_device_numel(params, pspecs, mesh) -> int:
    """Element count of one device's gradient pytree: each leaf's numel
    divided by the sizes of the mesh axes its spec shards it over."""

    def leaf_numel(leaf, spec):
        n = int(np.prod(leaf.shape)) if leaf.ndim else 1
        for a in _spec_axes(spec):
            n //= mesh.shape[a]
        return n

    counts = jax.tree.map(leaf_numel, params, pspecs,
                          is_leaf=lambda x: x is None)
    return sum(jax.tree.leaves(counts))


def _accumulating_value_and_grad(loss_fn, accum_steps, weight_fn=None):
    """Gradient accumulation: ``accum_steps`` sequential microbatches per
    step, activations for only one microbatch live at a time (lax.scan).

    Reference analog: ``backward_passes_per_step`` in the torch adapter
    (byteps/torch DistributedOptimizer) — there, N backward passes skip
    the push_pull on all but the Nth; here the N grad computations fuse
    into one jitted scan and the aggregation sees their weighted mean.

    ``weight_fn(*microbatch) -> scalar`` gives each microbatch's weight in
    that mean. Losses that normalize per-call by a data-dependent count
    (BERT's masked mean) need it: mean-of-means mis-weights microbatches
    with unequal counts, while count-weighted averaging reproduces the
    full-batch mean exactly. Default (None) = equal weights, exact for
    fixed-size means (GPT's every-token loss).
    """
    vag = jax.value_and_grad(loss_fn)
    if accum_steps <= 1:
        return vag

    def accum(params, *batch):
        B = batch[0].shape[0]
        if B % accum_steps != 0:
            raise ValueError(
                f"per-device batch {B} not divisible by "
                f"accum_steps={accum_steps}")
        mbs = tuple(
            b.reshape((accum_steps, B // accum_steps) + b.shape[1:])
            for b in batch
        )

        # the scan carry must be a type fixed point under check_vma=True,
        # but per-leaf grad vma can differ from the params' (auto-psums
        # narrow replicated leaves, conservative inference widens others)
        # and differ per microbatch path — widen everything to the union
        # of the params' varying axes (semantically free; resym collapses
        # the excess after the scan)
        pvma = set()
        for leaf in jax.tree.leaves(params):
            pvma |= set(getattr(jax.typeof(leaf), "vma", ()) or ())

        def widen(x):
            need = tuple(sorted(
                pvma - set(getattr(jax.typeof(x), "vma", ()) or ())))
            return jax.lax.pcast(x, need, to="varying") if need else x

        def body(carry, mb):
            loss_sum, grad_sum, w_sum = carry
            loss, grads = vag(params, *mb)
            w = (weight_fn(*mb).astype(jnp.float32) if weight_fn is not None
                 else jnp.float32(1.0))
            return (loss_sum + widen(loss * w),
                    jax.tree.map(lambda a, g: a + widen(g * w),
                                 grad_sum, grads),
                    w_sum + widen(w)), None

        zeros = jax.tree.map(lambda l: widen(jnp.zeros_like(l)), params)
        zf = widen(jnp.zeros((), jnp.float32))
        (loss_sum, grad_sum, w_sum), _ = jax.lax.scan(
            body, (zf, zeros, zf), mbs
        )
        w_safe = jnp.where(w_sum > 0.0, w_sum, 1.0)
        return (loss_sum / w_safe,
                jax.tree.map(lambda g: g / w_safe, grad_sum))

    return accum


def _manual_axis_sums(grads, pspecs, axes):
    """No-vma grad assembly: psum each leaf over the listed mesh axes it is
    NOT sharded on (its stage-partial contributions), leaving sharded
    leaves (whose spec names the axis) stage-local. Under check_vma=True
    these psums are what VMA auto-inserts; the compressed paths run
    check_vma=False and do them explicitly."""

    def fix(g, spec):
        need = tuple(a for a in axes if a not in _spec_axes(spec))
        return jax.lax.psum(g, need) if need else g

    return jax.tree.map(fix, grads, pspecs, is_leaf=lambda x: x is None)


def _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
             per_device_numel=None, state_leading=(), zero=False,
             dcn=None):
    """Wrap base_tx with data-parallel aggregation (or pass through on a
    mesh with no data axes).

    ``dcn`` names the slice_ axis of a hybrid ICI×DCN mesh: aggregation
    then runs hierarchically (raw intra-slice reduce-scatter over ``dp``,
    compressed inter-slice exchange over ``dcn``, intra-slice all_gather
    — DistributedOptimizer's ``dcn_axis`` path). On a slice-only mesh
    (no dp axis) the DCN axis becomes THE worker axis and the legacy
    single-axis path compresses straight over the inter-slice wire.

    Separated from the params/state sharding so the auto-tuner can rebuild
    the transformation at a new partition size without re-initializing
    optimizer state (partition size affects chunking only, never state
    shapes)."""
    if dp is None and dcn is None:
        return base_tx
    if dp is None:
        dp, dcn = dcn, None
    kw = {}
    if dcn is not None:
        kw = dict(dcn_axis=dcn, num_dcn=mesh.shape[dcn])
    return DistributedOptimizer(
        base_tx, compression_params=compression_params, axis=dp,
        num_devices=mesh.shape[dp], partition_bytes=partition_bytes,
        per_device_numel=per_device_numel, state_leading=state_leading,
        zero=zero, **kw,
    )


def _shard_params_state(mesh, tx, params, pspecs, dp, state_axes=(),
                        zero_numel=None, slc=None):
    """device_put params, init + shard the optimizer state.

    ``zero_numel`` (ZeRO-1 mode, = per-device grads numel) switches the
    inner-state sharding rule: the inner transform's state lives on flat
    vectors shaped ``state_leading + (n_dp * ceil(numel/n_dp),)``, sharded
    ``P(*state_axes, dp)`` so each worker holds only its segment's
    moments. ``slc`` (hybrid mesh) shards the hierarchical optimizer's
    segment buffers over the combined ``(slice_, dp)`` axes."""
    params = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    opt_state = tx.init(params)
    ospecs = opt_state_specs(opt_state, params, pspecs)
    agg_dp, agg_dcn = (dp, slc) if dp is not None else (slc, None)
    if agg_dp is not None:
        # EF / momentum flats are per-worker state: one buffer per (pp/ep
        # stage combination, dp worker)
        buf_specs = dp_state_specs(axis=agg_dp, leading_axes=state_axes,
                                   dcn_axis=agg_dcn)
        buf = buf_specs.ef
        ospecs = ospecs._replace(
            ef=buf if opt_state.ef is not None else None,
            momentum=buf if opt_state.momentum is not None else None,
        )
        if zero_numel is not None:
            n = mesh.shape[agg_dp]
            proto_shape = tuple(mesh.shape[a] for a in state_axes) + (
                n * (-(-zero_numel // n)),
            )
            ospecs = ospecs._replace(inner=jax.tree.map(
                lambda l: buf if getattr(l, "shape", None) == proto_shape
                else P(),
                opt_state.inner,
            ))
    opt_state = jax.device_put(
        opt_state, jax.tree.map(lambda s: NamedSharding(mesh, s), ospecs)
    )
    return params, opt_state, ospecs


def _finalize_step(build_jit, partition_bytes, dp, tunable=True):
    """Return the jitted step, auto-tuned when BYTEPS_AUTO_TUNE=1.

    The tuned wrapper re-invokes ``build_jit`` with new partition sizes as
    the search moves (ByteScheduler's online partition tuning, SURVEY §2.6,
    transposed to the fused path where a move costs one cached retrace).
    ``tunable=False`` (ZeRO-1 mode) skips the tuner: the zero path
    aggregates the whole flat gradient in one scatter, so partition size
    changes nothing and every 'move' would retrace an identical program."""
    from byteps_tpu.common.config import get_config

    cfg = get_config()
    if cfg.auto_tune and dp is not None and tunable:
        from byteps_tpu.jax.tuned_step import AutoTunedStep

        step = AutoTunedStep(build_jit, partition_bytes or cfg.partition_bytes)
    else:
        step = build_jit(partition_bytes)
    # decided BEFORE any wrapper rebinds `step` to a plain function: the
    # tuned step ticks the flight recorder inside its own __call__, and
    # an isinstance check after the trace wrapper below would miss it —
    # double-ticking every step (halving step_ms and the ring's reach)
    ticks_itself = cfg.auto_tune and dp is not None and tunable
    if cfg.trace_on:
        from byteps_tpu.jax.optimizer import _host_callbacks_supported

        if not _host_callbacks_supported():
            # the in-program debug-callback step marker cannot run on
            # this backend (axon tunnel) — advance the trace window from
            # the host per dispatched step instead, so BYTEPS_TRACE_ON /
            # BYTEPS_TRACE_XPROF work everywhere
            from byteps_tpu.common.tracing import get_tracer

            inner = step

            def step(*a, **k):  # noqa: F811 — deliberate rebind
                out = inner(*a, **k)
                get_tracer().host_step()
                return out

    # Always-on train-step telemetry (docs/observability.md): one
    # flight-recorder tick per DISPATCHED step — a host-side function
    # call, unlike the in-program debug-callback marker above, which
    # costs a host sync and stays gated on BYTEPS_TRACE_ON.
    # AutoTunedStep ticks inside its own __call__ (tests rely on the
    # factory returning the instance, so it must not be wrapped into a
    # plain function here) — `ticks_itself`, decided before the trace
    # wrapper could rebind `step`, keeps this tick from stacking on it.
    if not ticks_itself:
        from byteps_tpu.common.flight_recorder import get_flight_recorder

        traced = step

        def step(*a, **k):  # noqa: F811 — deliberate rebind
            out = traced(*a, **k)
            # relative tick: the recorder may already be ahead (eager
            # rounds, a previous model) — a private 1-based counter
            # would be dropped there (FlightRecorder.tick docstring)
            get_flight_recorder().tick()
            return out

    return step


def _collapse_vma(x):
    """pmean away conservative VMA widening on a replicated value — a
    numerical identity (the values already agree across the collapsed
    axes); returns x untouched when it carries no varying axes."""
    vma = tuple(sorted(getattr(jax.typeof(x), "vma", ()) or ()))
    return jax.lax.pmean(x, vma) if vma else x


def _spec_axes(spec) -> set:
    """Flatten a PartitionSpec's entries to the set of mesh axis names."""
    axes = set()
    for part in spec:
        if part is None:
            continue
        axes.update((part,) if isinstance(part, str) else part)
    return axes


def _make_resymmetrize(pspecs, dp, slc=None):
    """Collapse conservative VMA variance on grad leaves (numerical identity
    — AD's auto-psums already made replicated grads bit-identical across
    sp/tp; only the inferred *type* is too wide on some paths)."""
    keep = {a for a in (dp, slc) if a is not None}

    def resym(g, spec):
        allowed = _spec_axes(spec)
        vma = set(getattr(jax.typeof(g), "vma", ()) or ())
        excess = tuple(sorted(a for a in vma
                              if a not in allowed and a not in keep))
        return jax.lax.pmean(g, excess) if excess else g

    def apply(grads):
        return jax.tree.map(resym, grads, pspecs,
                            is_leaf=lambda x: x is None)

    return apply


def _build_pp_jit(mesh, pspecs, ospecs, batch_spec, loss_fn, tx, dp, pp,
                  ep=None, ep_size=1, mean_axes=(), use_vma=True,
                  rep_axes=(), slc=None):
    """The grad-assembly skeleton both pipeline factories share: per-device
    masked loss -> psum of each leaf's stage-partial grads over the axes it
    is NOT sharded on (pp always; ep and tp/sp too under check_vma=False,
    where no VMA auto-psum exists), optional uniform /ep, the
    ``rep_axes`` (tp/sp) replicated-loss division (see
    ``_novma_collective_fix``), resym, dp aggregation via ``tx``, and
    VMA-collapsed loss reporting. ``use_vma=False`` is the compressed /
    ZeRO mode (their collectives defeat VMA's replication analysis)."""
    resym = _make_resymmetrize(pspecs, dp, slc)

    def per_device_step(params, opt_state, tokens, targets):
        grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
        # loss_fn returns the last-stage-masked loss: grading through an
        # already-replicated psum double-counts (psum transpose)
        loss, grads = jax.value_and_grad(loss_fn)(
            grad_params, tokens, targets
        )
        loss = jax.lax.psum(loss, pp)  # replicate for reporting
        if use_vma:
            # VMA auto-inserts the ep/tp/sp psums for invariant leaves;
            # manual-summing them too would double-count
            grads = _manual_axis_sums(grads, pspecs, (pp,))
        else:
            grads = _novma_collective_fix(
                grads, pspecs, mesh, rep_axes, extra_sum_axes=(pp, ep))
        if ep_size > 1:
            grads = jax.tree.map(lambda g: g / ep_size, grads)
        grads = resym(grads)  # collapse conservative VMA widening (no-op
        # without VMA types, as is _collapse_vma below)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if mean_axes:
            loss = jax.lax.pmean(loss, mean_axes)
        loss = _collapse_vma(loss)
        return loss, params, opt_state

    sharded = jax.shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(pspecs, ospecs, batch_spec, batch_spec),
        out_specs=(P(), pspecs, ospecs),
        check_vma=use_vma,
    )
    return jax.jit(sharded, donate_argnums=(0, 1))


def _pcast_dp(params, dp, mesh, use_vma, slc=None):
    """Mark params varying over the data axes (dp and, on hybrid meshes,
    slice_) so AD yields per-replica LOCAL grads (aggregation must stay
    in DistributedOptimizer, the framework's hot path)."""
    axes = tuple(a for a in (slc, dp)
                 if a is not None and mesh.shape[a] > 1)
    if axes and use_vma:
        return jax.tree.map(lambda x: jax.lax.pcast(x, axes, to="varying"),
                            params)
    return params


def make_gpt_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    zero_3: bool = False,
    accum_steps: int = 1,
    seq_layout: str = "contiguous",
    init_params: Optional[Dict[str, Any]] = None,
    chunked_ce=True,
):
    """Returns ``(step, params, opt_state, batch_sharding)``.

    ``init_params`` (structure of :func:`gpt_init`) starts training from
    existing weights — e.g. a checkpoint imported with
    ``models.import_hf`` — instead of a fresh initialization.
    ``step(params, opt_state, tokens, targets) -> (loss, params, opt_state)``
    is jitted over ``mesh``; tokens/targets are global (B, S) arrays
    sharded (dp, sp) by ``batch_sharding``. ``remat=True`` rematerializes
    each transformer block in the backward pass (HBM for FLOPs — the
    long-context lever; numerics unchanged). ``zero_1=True`` shards the
    inner optimizer state over dp (ZeRO-1: psum_scatter'd grads, segment
    update, all_gathered updates — 1/n_dp the optimizer HBM; composes
    with compression_params, whose EF residuals stay per-worker;
    requires an ELEMENTWISE base_tx — see DistributedOptimizer's
    ZeRO note).
    ``accum_steps>1`` accumulates gradients over that many sequential
    microbatches before the (single) aggregation+update — the torch
    adapter's ``backward_passes_per_step``, fused into the jitted step.
    ``seq_layout="zigzag"`` runs the load-balanced causal ring over sp
    (feed tokens/targets pre-permuted with ``zigzag_permutation``;
    positions and attention follow the layout — projected ~2x sp
    utilization for causal attention at scale, from the load-balance
    arithmetic; unmeasured, needs real multi-chip sp hardware).
    ``chunked_ce=True`` (default) fuses readout+CE so the f32 (B, S, V)
    logits never materialize (``ops/chunked_ce.py``; the flagship MFU
    lever — docs/performance.md §attribution); ``"vocab_parallel"``
    additionally splits the readout's vocab over tp (ntp× less readout
    GEMM/live logits, at f32-roundoff drift from the dp-only trajectory
    — see gpt_loss); ``False`` is the dense escape hatch the fused path
    is pinned against. All three accepted by every logits-bearing
    factory in this module.

    ``zero_3=True`` delegates to the ZeRO-3 FSDP factory
    (:func:`byteps_tpu.parallel.zero3.make_gpt_zero3_train_step`): params
    live as flat segments sharded over the slice_/dp axis, all-gathered
    just-in-time per layer inside a remat'd block — per-chip param AND
    optimizer memory drop ~n_shard×. Its returned ``params`` is the
    segment dict, not the gpt tree (gather with ``zero3_gather_params``).
    """
    if zero_3:
        if zero_1:
            raise ValueError("zero_1 and zero_3 are mutually exclusive")
        from byteps_tpu.parallel.zero3 import make_gpt_zero3_train_step
        return make_gpt_zero3_train_step(
            cfg, mesh, base_tx,
            compression_params=compression_params,
            partition_bytes=partition_bytes, remat=remat,
            seq_layout=seq_layout, init_params=init_params,
            chunked_ce=chunked_ce)
    part = Partitioner.for_config(cfg, mesh)
    dp, tp, sp, slc = part.dp, part.tp, part.sp, part.slice_
    _check_seq_layout(seq_layout, sp)
    use_vma = compression_params is None and not zero_1
    pspecs = part.param_specs(cfg)
    params = _resolve_init_params(init_params, cfg, pspecs)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    resym = _make_resymmetrize(pspecs, dp, slc)

    # Grad loss is dp-LOCAL (dp_axis=None): each dp replica is one reference
    # worker computing the grad of its own local mean loss; averaging across
    # workers is DistributedOptimizer's job (push_pull average=True). A dp
    # pmean inside the loss would double-apply the 1/n_dp.
    loss_fn = functools.partial(
        gpt_loss, cfg=cfg, dp_axis=None, tp_axis=tp, sp_axis=sp,
        remat=remat, seq_layout=seq_layout, chunked_ce=chunked_ce,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)

        vag = _accumulating_value_and_grad(loss_fn, accum_steps)

        def per_device_step(params, opt_state, tokens, targets):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            loss, grads = vag(grad_params, tokens, targets)
            if use_vma:
                grads = resym(grads)
            else:
                grads = _novma_collective_fix(grads, pspecs, mesh, (tp, sp))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)  # global mean loss
            return _collapse_vma(loss), params, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs),
            check_vma=use_vma,
        )
        # donate params/opt_state: the step is an in-place update at the XLA
        # level (halves HBM traffic for the weight/optimizer buffers)
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_gpt_lora_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    rank: int = 8,
    alpha: float = 16.0,
    targets: Tuple[str, ...] = ("wq", "wv"),
    base_params: Optional[Dict[str, Any]] = None,
    init_adapters: Optional[Dict[str, Any]] = None,
    rng: Optional[jax.Array] = None,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    accum_steps: int = 1,
    seq_layout: str = "contiguous",
    chunked_ce=True,
):
    """LoRA fine-tuning step over a (dp[, tp][, sp]) mesh: the frozen
    base never moves and ONLY the adapter gradients ride the dp
    aggregation tier (compressed or not) — rank/d_model the gradient
    traffic of full fine-tuning per targeted projection.

    ``base_params`` (default: fresh init) is typically an imported
    checkpoint (``models.import_hf``); ``init_adapters`` resumes from
    saved adapters and ``rng`` seeds a fresh adapter init (multi-seed
    sweeps). Returns ``(step, adapters,
    opt_state, base, batch_sharding)`` with
    ``step(adapters, opt_state, base, tokens, targets) ->
    (loss, adapters, opt_state)`` — the base is an explicit input
    (replicated over dp/sp, tp-sharded like the dense factory), never
    donated, never updated. ``b`` adapters start at zero, so step 0
    computes exactly the frozen model's loss. Merge for inference or
    export with :func:`byteps_tpu.models.lora.merge_lora`
    (``scale = alpha / rank``).

    Under tp, column-parallel targets add NO collective (``a``
    replicated, ``b`` column-sharded); row-parallel targets psum a thin
    ``(B, S, rank)`` intermediate. ``compression_params`` composes the
    same way as the dense factory (no-VMA explicit psums over tp/sp on
    the adapter grads).
    """
    from byteps_tpu.models.lora import (
        graft_lora, lora_init, lora_param_specs)

    part = Partitioner.for_config(cfg, mesh)
    dp, tp, sp, slc = part.dp, part.tp, part.sp, part.slice_
    _check_seq_layout(seq_layout, sp)
    use_vma = compression_params is None
    scale = alpha / rank

    base_specs = part.param_specs(cfg)
    base = _resolve_init_params(base_params, cfg, base_specs)
    base = jax.device_put(
        base, jax.tree.map(lambda s: NamedSharding(mesh, s), base_specs,
                           is_leaf=lambda x: isinstance(x, P)))

    aspecs = lora_param_specs(cfg, tp, rank, targets)
    if init_adapters is not None:
        adapters = init_adapters
        want = jax.tree_util.tree_structure(aspecs)
        got = jax.tree_util.tree_structure(adapters)
        if want != got:
            raise ValueError(
                "init_adapters tree structure does not match "
                f"(rank/targets/n_layers?):\n  expects {want}\n  got {got}")
    else:
        adapters = lora_init(rng if rng is not None
                             else jax.random.PRNGKey(1), cfg, rank, targets)
    # EF/momentum compressor state must be sized/sharded for THIS mesh
    # (per-device grads are tp-local shards) — same bookkeeping as the
    # dense factory
    state_axes, tx_kw, _ = _dist_state_setup(mesh, adapters, aspecs, dp,
                                             False, slc=slc)
    adapters, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        adapters, aspecs, dp, state_axes=state_axes, slc=slc,
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    resym = _make_resymmetrize(aspecs, dp, slc)

    def loss_fn(adapters, base, tokens, targets_):
        grafted = graft_lora(base, adapters, scale)
        return gpt_loss(grafted, tokens, targets_, cfg, dp_axis=None,
                        tp_axis=tp, sp_axis=sp, remat=remat,
                        seq_layout=seq_layout, chunked_ce=chunked_ce)

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)

        def per_device_step(adapters, opt_state, base, tokens, targets_):
            # base rides the closure: the accumulator microbatches every
            # positional batch arg, and the frozen base is not a batch
            vag = _accumulating_value_and_grad(
                lambda a, tok, tgt: loss_fn(a, base, tok, tgt),
                accum_steps)
            grad_adapters = _pcast_dp(adapters, dp, mesh, use_vma, slc)
            loss, grads = vag(grad_adapters, tokens, targets_)
            if use_vma:
                grads = resym(grads)
            else:
                grads = _novma_collective_fix(grads, aspecs, mesh, (tp, sp))
            updates, opt_state = tx.update(grads, opt_state, adapters)
            adapters = optax.apply_updates(adapters, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)
            return _collapse_vma(loss), adapters, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(aspecs, ospecs, base_specs, batch_spec, batch_spec),
            out_specs=(P(), aspecs, ospecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc),
        adapters, opt_state, base, NamedSharding(mesh, batch_spec),
    )


def make_gpt_pp_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    n_micro: int = 4,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    seq_layout: str = "contiguous",
    init_params: Optional[Dict[str, Any]] = None,
    chunked_ce=True,
):
    """Pipeline-parallel GPT train step over a (pp, dp[, tp][, sp]) mesh.

    ``init_params`` takes UNSTACKED weights (the :func:`gpt_init` /
    ``import_hf`` structure) and stacks them into the pipeline slab here.

    Transformer blocks are stacked on a leading layer axis and sharded
    ``P('pp')`` — each stage owns n_layers/pp contiguous layers and its
    optimizer moments for them; microbatches flow stage-to-stage via
    ppermute (GPipe schedule, backward derived by AD). tp and sp axes
    compose inside the stages (Megatron col/row-parallel matmuls and ring
    attention per layer, their collectives typed by VMA — the step runs
    check_vma=True, so replicated params' cotangents get their psums
    auto-inserted exactly as in the dense factory). dp aggregation is
    DistributedOptimizer as everywhere else; grads of pp-replicated
    leaves (embeddings, final LN) are psum'd over pp first.

    ``compression_params`` enables compressed dp aggregation
    (check_vma=False mode, like the dense factory's): each stage
    compresses its own slab + replicated-leaf grads over dp, with
    per-(stage, worker) EF/momentum state; tp/sp compose via the
    explicit no-VMA gradient assembly (``_novma_collective_fix``).

    ``seq_layout="zigzag"`` runs the load-balanced causal ring over sp
    inside the stages — feed tokens/targets pre-permuted with
    ``zigzag_permutation`` exactly as for the dense factory.

    Returns ``(step, params, opt_state, batch_sharding)`` like
    :func:`make_gpt_train_step`; ``params["blocks"]`` is the stacked slab.
    """
    from byteps_tpu.models.gpt import block_logical_specs
    from byteps_tpu.parallel.pipeline import stack_blocks

    part = Partitioner.for_config(cfg, mesh)
    dp, pp = part.dp, part.pp
    tp, sp, slc = part.tp, part.sp, part.slice_
    if pp is None:
        raise ValueError("mesh has no pp axis — use make_gpt_train_step")
    _check_seq_layout(seq_layout, sp)
    use_vma = compression_params is None and not zero_1
    nstages = mesh.shape[pp]
    if cfg.n_layers % nstages != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={nstages}"
        )
    raw = _resolve_init_params(init_params, cfg, part.param_specs(cfg))
    # pp-replicated leaves follow the config's tree (wpe only under
    # learned positions, lnf_b only under layernorm, lm_head only
    # untied); the blocks become the stacked stage slab
    params = {k: v for k, v in raw.items() if k != "blocks"}
    params["blocks"] = stack_blocks(raw["blocks"])
    pspecs = {k: P() for k in params if k != "blocks"}
    pspecs["blocks"] = part.resolve(stacked_logical_specs(
        block_logical_specs(cfg.mlp, use_bias=cfg.use_bias, norm=cfg.norm)))
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    loss_fn = functools.partial(
        gpt_pp_loss, cfg=cfg, pp_axis=pp, n_micro=n_micro, tp_axis=tp,
        sp_axis=sp, remat=remat,
        vma_axes=tuple(mesh.axis_names) if use_vma else (),
        seq_layout=seq_layout, chunked_ce=chunked_ce,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)
        return _build_pp_jit(
            mesh, pspecs, ospecs, batch_spec, loss_fn, tx, dp, pp,
            mean_axes=tuple(a for a in (slc, dp) if a is not None),
            use_vma=use_vma, rep_axes=(tp, sp), slc=slc,
        )

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_gpt_moe_train_step(
    cfg,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    seq_layout: str = "contiguous",
    chunked_ce=True,
):
    """Expert-parallel MoE GPT train step over a (dp, ep[, tp][, sp]) mesh.

    The batch shards over dp AND ep (every device routes its own tokens to
    all experts via all_to_all); expert-stacked FFN weights shard P('ep')
    and, with a tp axis, Megatron col/row shard their ff dim (attention
    runs tp-parallel too). The step runs check_vma=True: VMA auto-inserts
    the collectives for replicated-param cotangents over ep/tp, and one
    uniform /ep turns the summed per-device grads into the mean the
    mean-of-local-means loss needs; dp averaging stays in
    DistributedOptimizer as everywhere else.

    ``compression_params`` enables compressed dp aggregation
    (check_vma=False mode): the ep psums of ep-invariant leaves run
    explicitly (tp/sp via ``_novma_collective_fix``), then each
    (ep group, dp worker) compresses its grads over dp with its own
    EF/momentum state.

    ``seq_layout="zigzag"`` runs the load-balanced causal ring over sp —
    feed tokens/targets pre-permuted with ``zigzag_permutation``, as for
    the dense factory. (The load-balancing aux term is a function of
    per-device router statistics, so its VALUE legitimately depends on
    how tokens shard; the nll is exact across layouts.)

    Returns ``(step, params, opt_state, batch_sharding)``.
    """
    from byteps_tpu.models.moe_gpt import moe_gpt_init, moe_gpt_loss

    part = Partitioner.for_config(cfg, mesh)
    dp, ep = part.dp, part.ep
    tp, sp, slc = part.tp, part.sp, part.slice_
    if part.pp is not None:
        raise ValueError(
            "mesh has a pp axis — use make_gpt_moe_pp_train_step for "
            "pipelined MoE"
        )
    _check_seq_layout(seq_layout, sp)
    use_vma = compression_params is None and not zero_1
    ep_size = mesh.shape[ep] if ep is not None else 1
    if ep is not None and cfg.n_experts % ep_size != 0:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by ep={ep_size}"
        )
    pspecs = part.param_specs(cfg)
    params = moe_gpt_init(jax.random.PRNGKey(0), cfg)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    resym = _make_resymmetrize(pspecs, dp, slc)
    loss_fn = functools.partial(moe_gpt_loss, cfg=cfg, ep_axis=ep,
                                tp_axis=tp, sp_axis=sp, remat=remat,
                                seq_layout=seq_layout,
                                chunked_ce=chunked_ce)

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)

        def per_device_step(params, opt_state, tokens, targets):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            loss, grads = jax.value_and_grad(loss_fn)(
                grad_params, tokens, targets
            )
            if not use_vma:
                grads = _novma_collective_fix(
                    grads, pspecs, mesh, (tp, sp), extra_sum_axes=(ep,))
            if ep is not None:
                # the global loss is the MEAN of per-device local means;
                # the ep-invariant leaves' grads must arrive SUMMED over
                # ep (VMA auto-psum under check_vma=True, explicit psums
                # in compressed mode via _novma_collective_fix) and the
                # expert slabs already summed their peers' contributions
                # through the all_to_all transpose — one uniform /ep
                # gives means
                grads = jax.tree.map(lambda g: g / ep_size, grads)
            grads = resym(grads)  # collapse conservative VMA widening
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            axes = tuple(a for a in (slc, dp, ep) if a is not None)
            if axes:
                loss = jax.lax.pmean(loss, axes)
            loss = _collapse_vma(loss)
            return loss, params, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_gpt_moe_pp_train_step(
    cfg,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    n_micro: int = 4,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    seq_layout: str = "contiguous",
    chunked_ce=True,
):
    """Pipelined MoE GPT over a (pp, dp[, ep][, tp][, sp]) mesh — the full
    composition: GPipe microbatch pipelining whose stages hold MoE blocks
    with all_to_all expert dispatch (ep), Megatron-sharded experts and
    attention (tp), and ring attention (sp), all typed by VMA in one
    jitted program. Routing happens per microbatch (capacity from the
    microbatch token count). Grad assembly combines the pp and ep rules:
    pp-replicated leaves psum over pp, then everything divides by ep
    (mean of per-device local means); dp aggregation stays in
    DistributedOptimizer.

    ``seq_layout="zigzag"`` follows the same pre-permuted-input contract
    as every other factory (see :func:`make_gpt_moe_train_step`'s note on
    the aux term).

    Returns ``(step, params, opt_state, batch_sharding)``;
    ``params["blocks"]`` is the stacked MoE-block slab.
    """
    from byteps_tpu.models.moe_gpt import (
        moe_block_logical_specs,
        moe_gpt_init,
        moe_gpt_pp_loss,
    )
    from byteps_tpu.parallel.pipeline import stack_blocks

    part = Partitioner.for_config(cfg, mesh)
    dp, pp = part.dp, part.pp
    ep, tp, sp, slc = part.ep, part.tp, part.sp, part.slice_
    if pp is None:
        raise ValueError("mesh has no pp axis — use make_gpt_moe_train_step")
    _check_seq_layout(seq_layout, sp)
    use_vma = compression_params is None and not zero_1
    nstages = mesh.shape[pp]
    ep_size = mesh.shape[ep] if ep is not None else 1
    if cfg.n_layers % nstages != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pp={nstages}"
        )
    if ep is not None and cfg.n_experts % ep_size != 0:
        raise ValueError(
            f"n_experts={cfg.n_experts} not divisible by ep={ep_size}"
        )
    raw = moe_gpt_init(jax.random.PRNGKey(0), cfg)
    params = {k: v for k, v in raw.items() if k != "blocks"}
    params["blocks"] = stack_blocks(raw["blocks"])
    pspecs = {k: P() for k in params if k != "blocks"}
    pspecs["blocks"] = part.resolve(stacked_logical_specs(
        moe_block_logical_specs(use_bias=cfg.use_bias, norm=cfg.norm,
                                mlp=cfg.mlp)))
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    loss_fn = functools.partial(
        moe_gpt_pp_loss, cfg=cfg, pp_axis=pp, n_micro=n_micro,
        ep_axis=ep, tp_axis=tp, sp_axis=sp, remat=remat,
        vma_axes=tuple(mesh.axis_names) if use_vma else (),
        seq_layout=seq_layout, chunked_ce=chunked_ce,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)
        return _build_pp_jit(
            mesh, pspecs, ospecs, batch_spec, loss_fn, tx, dp, pp,
            ep=ep, ep_size=ep_size if ep is not None else 1,
            mean_axes=tuple(a for a in (slc, dp, ep) if a is not None),
            use_vma=use_vma, rep_axes=(tp, sp), slc=slc,
        )

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_bert_train_step(
    cfg: BertConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    accum_steps: int = 1,
    chunked_ce=True,
):
    """``step(params, opt_state, tokens, targets, mask)`` — MLM pretraining
    step (BASELINE config 3 shape), same sharding story as GPT (zero_1 /
    accum_steps / chunked_ce semantics included)."""
    part = Partitioner.for_config(cfg, mesh)
    dp, tp, sp, slc = part.dp, part.tp, part.sp, part.slice_
    use_vma = compression_params is None and not zero_1
    pspecs = part.param_specs(cfg)
    params = bert_init(jax.random.PRNGKey(0), cfg)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    resym = _make_resymmetrize(pspecs, dp, slc)
    loss_fn = functools.partial(
        bert_mlm_loss, cfg=cfg, dp_axis=None, tp_axis=tp, sp_axis=sp,
        remat=remat, chunked_ce=chunked_ce,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)
        # masked-mean loss: weight each microbatch by its mask count so
        # the accumulated gradient equals the full-batch masked mean; the
        # count must be the sp-GLOBAL one (the loss normalizes by it after
        # its sp psum) or the weights would be sp-varying while the grads
        # are sp-replicated
        def _mask_count(tokens, targets, mask):
            w = mask.sum()
            return jax.lax.psum(w, sp) if sp is not None else w

        vag = _accumulating_value_and_grad(loss_fn, accum_steps,
                                           weight_fn=_mask_count)

        def per_device_step(params, opt_state, tokens, targets, mask):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            loss, grads = vag(grad_params, tokens, targets, mask)
            if use_vma:
                grads = resym(grads)
            else:
                grads = _novma_collective_fix(grads, pspecs, mesh, (tp, sp))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)
            return _collapse_vma(loss), params, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_t5_train_step(
    cfg: T5Config,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    accum_steps: int = 1,
    chunked_ce=True,
):
    """``step(params, opt_state, src, tgt_in, tgt_out) -> (loss, params,
    opt_state)`` — encoder-decoder seq2seq over a (dp, tp, sp) mesh;
    blocks and tp sharding shared with GPT/BERT, cross-attention added by
    the decoder blocks (models/t5.py). With an sp axis BOTH sides
    sequence-shard: non-causal encoder ring, causal decoder ring, and a
    rectangular cross-attention ring over the sp-sharded encoder memory
    (src and tgt lengths must each divide by the sp size)."""
    part = Partitioner.for_config(cfg, mesh)
    dp, tp, sp, slc = part.dp, part.tp, part.sp, part.slice_
    use_vma = compression_params is None and not zero_1
    pspecs = part.param_specs(cfg)
    params = t5_init(jax.random.PRNGKey(0), cfg)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    resym = _make_resymmetrize(pspecs, dp, slc)
    loss_fn = functools.partial(
        t5_loss, cfg=cfg, dp_axis=None, tp_axis=tp, sp_axis=sp, remat=remat,
        chunked_ce=chunked_ce,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)
        vag = _accumulating_value_and_grad(loss_fn, accum_steps)

        def per_device_step(params, opt_state, src, tgt_in, tgt_out):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            loss, grads = vag(grad_params, src, tgt_in, tgt_out)
            if use_vma:
                grads = resym(grads)
            else:
                grads = _novma_collective_fix(grads, pspecs, mesh, (tp, sp))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)
            return _collapse_vma(loss), params, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, batch_spec, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_vit_train_step(
    cfg: ViTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    remat: bool = False,
    zero_1: bool = False,
    accum_steps: int = 1,
):
    """``step(params, opt_state, images, labels) -> (loss, params,
    opt_state)`` — ViT classification over a (dp, tp) mesh; blocks and
    their tp sharding are shared with GPT/BERT, the batch axis with
    ResNet (sp intentionally unsupported — models/vit.py rationale)."""
    part = Partitioner.for_config(cfg, mesh)
    dp, tp, slc = part.dp, part.tp, part.slice_
    use_vma = compression_params is None and not zero_1
    pspecs = part.param_specs(cfg)
    params = vit_init(jax.random.PRNGKey(0), cfg)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    resym = _make_resymmetrize(pspecs, dp, slc)
    loss_fn = functools.partial(
        vit_loss, cfg=cfg, dp_axis=None, tp_axis=tp, remat=remat,
    )

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)
        vag = _accumulating_value_and_grad(loss_fn, accum_steps)

        def per_device_step(params, opt_state, images, labels):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            loss, grads = vag(grad_params, images, labels)
            if use_vma:
                grads = resym(grads)
            else:
                grads = _novma_collective_fix(grads, pspecs, mesh, (tp,))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)
            return _collapse_vma(loss), params, opt_state

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, NamedSharding(mesh, batch_spec),
    )


def make_resnet_train_step(
    cfg: ResNetConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
    zero_1: bool = False,
):
    """``step(params, opt_state, bn_state, images, labels) ->
    (loss, params, opt_state, bn_state)`` — dp-only conv family
    (BASELINE config 2 shape); BN stats are dp-synced (SyncBN) so the
    replicated bn_state stays identical everywhere.
    """
    part = Partitioner.for_config(cfg, mesh)
    dp, slc = part.dp, part.slice_
    use_vma = compression_params is None and not zero_1
    params, bn_state = resnet_init(jax.random.PRNGKey(0), cfg)
    pspecs = part.param_specs(cfg, params)
    state_axes, tx_kw, zero_numel = _dist_state_setup(
        mesh, params, pspecs, dp, zero_1, slc=slc)
    params, opt_state, ospecs = _shard_params_state(
        mesh,
        _make_tx(mesh, base_tx, compression_params, partition_bytes, dp,
                 dcn=slc, **tx_kw),
        params, pspecs, dp, state_axes=state_axes, zero_numel=zero_numel,
        slc=slc,
    )
    sspecs = jax.tree.map(lambda _: P(), bn_state)
    bn_state = jax.device_put(
        bn_state, jax.tree.map(lambda s: NamedSharding(mesh, s), sspecs)
    )
    batch_spec = part.batch_spec()
    mean_axes = tuple(a for a in (slc, dp) if a is not None)
    # SyncBN statistics sync over every data axis (slice_ and dp)
    bn_axes = mean_axes if mean_axes else None
    resym = _make_resymmetrize(pspecs, dp, slc)

    def loss_fn(params, bn_state, images, labels):
        return resnet_loss(params, bn_state, images, labels, cfg,
                           dp_axis=bn_axes, train=True)

    def build_jit(pb):
        tx = _make_tx(mesh, base_tx, compression_params, pb, dp, dcn=slc,
                      **tx_kw)

        def per_device_step(params, opt_state, bn_state, images, labels):
            grad_params = _pcast_dp(params, dp, mesh, use_vma, slc)
            (loss, new_bn), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                grad_params, bn_state, images, labels
            )
            if use_vma:
                grads = resym(grads)
                # SyncBN pmean makes stats unvarying, but conservative VMA
                # can widen the state type the same way it widens grads
                new_bn = jax.tree.map(_collapse_vma, new_bn)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if mean_axes:
                loss = jax.lax.pmean(loss, mean_axes)
            return loss, params, opt_state, new_bn

        sharded = jax.shard_map(
            per_device_step,
            mesh=mesh,
            in_specs=(pspecs, ospecs, sspecs, batch_spec, batch_spec),
            out_specs=(P(), pspecs, ospecs, sspecs),
            check_vma=use_vma,
        )
        return jax.jit(sharded, donate_argnums=(0, 1, 2))

    return (
        _finalize_step(build_jit, partition_bytes, dp or slc,
                       tunable=not zero_1),
        params, opt_state, bn_state, NamedSharding(mesh, batch_spec),
    )


def synthetic_batch(
    rng: jnp.ndarray, cfg: GPTConfig, batch: int, seq: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Random next-token LM batch (the reference benchmarks train on
    synthetic data too — example/pytorch/benchmark_byteps.py)."""
    toks = jax.random.randint(rng, (batch, seq + 1), 0, cfg.vocab_size)
    return toks[:, :-1], toks[:, 1:]


def synthetic_mlm_batch(rng: jnp.ndarray, cfg: BertConfig, batch: int,
                        seq: int, mask_rate: float = 0.15):
    """(corrupted tokens, targets, mask) for MLM pretraining."""
    k1, k2 = jax.random.split(rng)
    targets = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size)
    mask = jax.random.bernoulli(k2, mask_rate, (batch, seq))
    mask_id = cfg.vocab_size - 1  # last id doubles as [MASK] in synthetic data
    tokens = jnp.where(mask, mask_id, targets)
    return tokens, targets, mask.astype(jnp.int32)

def make_eval_step(cfg: GPTConfig, mesh: Mesh, seq_layout: str = "contiguous",
                   chunked_ce=True):
    """Jitted eval step: ``eval_step(params, tokens, targets) -> mean nll``
    over the (dp, sp)-sharded batch — exp() of the running mean is the
    perplexity. Shares gpt_loss (and therefore every config option:
    rope/GQA/SwiGLU, zigzag layout, chunked readout+CE) with the train
    factories; no optimizer, no grads, safe to call on training params at
    any step.
    """
    part = Partitioner.for_config(cfg, mesh)
    dp, tp, sp, slc = part.dp, part.tp, part.sp, part.slice_
    _check_seq_layout(seq_layout, sp)
    batch_spec = part.batch_spec()
    pspecs = part.param_specs(cfg)

    def per_device(params, tokens, targets):
        loss = gpt_loss(params, tokens, targets, cfg, dp_axis=dp,
                        tp_axis=tp, sp_axis=sp, seq_layout=seq_layout,
                        chunked_ce=chunked_ce)
        if slc is not None:
            loss = jax.lax.pmean(loss, slc)
        return _collapse_vma(loss)

    sharded = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(pspecs, batch_spec, batch_spec),
        out_specs=P(),
        check_vma=True,
    )
    return jax.jit(sharded), NamedSharding(mesh, batch_spec)


def evaluate_perplexity(eval_step, params, batches, batch_sharding) -> float:
    """Mean perplexity over an iterable of (tokens, targets) host batches."""
    total, n = 0.0, 0
    for tokens, targets in batches:
        tok = jax.device_put(tokens, batch_sharding)
        tgt = jax.device_put(targets, batch_sharding)
        total += float(eval_step(params, tok, tgt))
        n += 1
    if n == 0:
        raise ValueError("evaluate_perplexity: no batches")
    return float(np.exp(total / n))
