"""Sharded training-step factory for the model zoo.

Builds the full jitted train step over a (dp, tp, sp) mesh: per-device
loss+grad via ``shard_map`` (ring attention over sp, Megatron collectives
over tp inside the model), gradient psum over sp, and BytePS aggregation
over dp through ``DistributedOptimizer`` (reference hot path, SURVEY §3.2 —
here fused into one XLA program so chunk collectives overlap backward
compute).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from byteps_tpu.jax.optimizer import DistributedOptimizer
from byteps_tpu.models.gpt import GPTConfig, gpt_init, gpt_loss, gpt_param_specs
from byteps_tpu.parallel.sharding import opt_state_specs


def _axis(mesh: Mesh, name: str) -> Optional[str]:
    return name if name in mesh.axis_names else None


def make_gpt_train_step(
    cfg: GPTConfig,
    mesh: Mesh,
    base_tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    partition_bytes: Optional[int] = None,
):
    """Returns ``(step, params, opt_state, batch_sharding)``.

    ``step(params, opt_state, tokens, targets) -> (loss, params, opt_state)``
    is jitted over ``mesh``; tokens/targets are global arrays of shape
    (B, S) sharded (dp, sp) by ``batch_sharding``.
    """
    dp, tp, sp = _axis(mesh, "dp"), _axis(mesh, "tp"), _axis(mesh, "sp")
    pspecs = gpt_param_specs(cfg, tp)

    if dp is not None:
        tx = DistributedOptimizer(
            base_tx, compression_params=compression_params, axis=dp,
            num_devices=mesh.shape[dp], partition_bytes=partition_bytes,
        )
    else:
        tx = base_tx

    params = gpt_init(jax.random.PRNGKey(0), cfg)
    params = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs)
    )
    opt_state = tx.init(params)
    ospecs = opt_state_specs(opt_state, params, pspecs)
    if dp is not None:
        # EF / momentum flats are per-dp-worker state (see dp_state_specs)
        ospecs = ospecs._replace(
            ef=P(dp) if opt_state.ef is not None else None,
            momentum=P(dp) if opt_state.momentum is not None else None,
        )
    opt_state = jax.device_put(
        opt_state, jax.tree.map(lambda s: NamedSharding(mesh, s), ospecs)
    )
    batch_spec = P(dp, sp)
    batch_sharding = NamedSharding(mesh, batch_spec)

    # Grad loss is dp-LOCAL (dp_axis=None): each dp replica is one reference
    # worker computing the grad of its own local mean loss; averaging across
    # workers is DistributedOptimizer's job (push_pull average=True). A dp
    # pmean inside the loss would double-apply the 1/n_dp.
    loss_fn = functools.partial(
        gpt_loss, cfg=cfg, dp_axis=None, tp_axis=tp, sp_axis=sp
    )

    # VMA (check_vma=True) is what makes per-device AD exact here: replicated
    # params' cotangents get the needed psums over sp/tp auto-inserted, and
    # psum/pmean transpose correctly (under check_vma=False psum transposes
    # to psum, scaling grads by the axis size whenever the forward contains
    # collectives). The compressed collective's tree_map'd all_to_all defeats
    # the VMA analysis (see comm/ici.py), so the compressed path runs with
    # check_vma=False and is restricted to dp-only meshes, where the forward
    # has no collectives and per-device AD is trivially exact.
    use_vma = compression_params is None
    if not use_vma and (tp is not None or sp is not None):
        raise NotImplementedError(
            "compressed aggregation currently requires a dp-only mesh "
            "(tp/sp axes need the VMA path, which the compressed collective "
            "does not yet support)"
        )

    def _resymmetrize(g, spec):
        """Collapse conservative VMA variance on a grad leaf.

        AD's auto-inserted psums make replicated params' grads bit-identical
        across sp/tp (verified numerically), but the VMA *type* inference is
        conservative on some paths (e.g. the embedding cotangent through the
        residual stream). Where the inferred varying-set exceeds the leaf's
        spec, a pmean over the excess axes is a numerical identity that
        restores the invariant type. dp-variance is intended (per-worker
        grads) and left alone.
        """
        allowed = set()
        for part in spec:
            if part is None:
                continue
            allowed.update((part,) if isinstance(part, str) else part)
        vma = set(getattr(jax.typeof(g), "vma", ()) or ())
        excess = tuple(sorted(a for a in vma if a not in allowed and a != dp))
        return jax.lax.pmean(g, excess) if excess else g

    def per_device_step(params, opt_state, tokens, targets):
        if dp is not None and mesh.shape[dp] > 1 and use_vma:
            # mark params dp-varying so AD yields per-replica LOCAL grads
            # (instead of auto-psumming over dp) — dp aggregation must stay
            # in DistributedOptimizer, the framework's hot path.
            grad_params = jax.tree.map(
                lambda x: jax.lax.pcast(x, (dp,), to="varying"), params
            )
        else:
            grad_params = params
        loss, grads = jax.value_and_grad(loss_fn)(grad_params, tokens, targets)
        if use_vma:
            grads = jax.tree.map(
                _resymmetrize, grads, pspecs,
                is_leaf=lambda x: x is None,
            )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if dp is not None:
            loss = jax.lax.pmean(loss, dp)  # report the global mean loss
        return loss, params, opt_state

    sharded = jax.shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(pspecs, ospecs, batch_spec, batch_spec),
        out_specs=(P(), pspecs, ospecs),
        check_vma=use_vma,
    )
    # donate params/opt_state: the step is an in-place update at the XLA
    # level (halves HBM traffic for the weight/optimizer buffers)
    return (
        jax.jit(sharded, donate_argnums=(0, 1)),
        params, opt_state, batch_sharding,
    )


def synthetic_batch(
    rng: jnp.ndarray, cfg: GPTConfig, batch: int, seq: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Random next-token LM batch (the reference benchmarks train on
    synthetic data too — example/pytorch/benchmark_byteps.py)."""
    toks = jax.random.randint(rng, (batch, seq + 1), 0, cfg.vocab_size)
    return toks[:, :-1], toks[:, 1:]
