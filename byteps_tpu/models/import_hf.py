"""HuggingFace checkpoint bridge for the GPT model family.

The reference framework trains torch models in place — its users' weights
live in torch/HF checkpoints (reference analog: the torch adapter +
``broadcast_parameters`` recipe, SURVEY §2.4). This module is the
switching path: load an HF ``GPT2LMHeadModel`` or ``LlamaForCausalLM``
(or its bare ``state_dict``) into this framework's functional GPT param
tree, train/generate on TPU, and export back.

Conventions bridged (both directions):

* GPT-2 stores Conv1D weights ``(in, out)`` — our layout, no transpose;
  the fused ``c_attn`` ``(d, 3d)`` splits into wq/wk/wv. Weight-tied
  readout maps to ``tied_readout=True``.
* Llama stores ``nn.Linear`` weights ``(out, in)`` — transposed on the
  way in. RMSNorm maps to ``norm="rmsnorm"`` and bias-free projections
  to ``use_bias=False`` — the imported tree carries NO leaves the
  checkpoint doesn't have (no wpe, no norm/projection biases), so
  training — including under lossy gradient compression, which would
  perturb an "inert" zero leaf — touches only real parameters and the
  tree re-exports cleanly. Rotary embeddings map to
  ``pos_embedding="rope"`` (both
  sides use the half-split/rotate_half convention with
  ``inv_freq = base^(-2i/D)``; HF checkpoints are already stored in
  this convention), GQA to ``n_kv_heads`` (query head ``q`` reads kv
  head ``q // G`` on both sides), SwiGLU to ``mlp="swiglu"`` with
  ``w1=gate_proj``, ``w3=up_proj``, ``w2=down_proj``; ``lm_head`` maps
  to the untied readout leaf unless ``tie_word_embeddings``.

Numerical parity (logits, fp32) against the HF torch forward is pinned
in ``tests/test_import_hf.py`` for both families, plus export
round-trips through ``load_state_dict(strict=True)``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from byteps_tpu.models.gpt import GPTConfig


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _state_and_config(source, config):
    """Accept a live HF model (carries its config) or a bare state_dict
    (config required)."""
    if hasattr(source, "state_dict"):
        cfg = config if config is not None else source.config
        return source.state_dict(), cfg
    if config is None:
        raise ValueError("a bare state_dict needs the HF config object "
                         "(or a dict of its fields) passed as config=")
    return dict(source), config


def _cfgget(hf_cfg, name, default=None):
    if isinstance(hf_cfg, dict):
        return hf_cfg.get(name, default)
    return getattr(hf_cfg, name, default)


def from_hf_gpt2(source, config=None,
                 dtype: Any = jnp.float32
                 ) -> Tuple[GPTConfig, Dict[str, Any]]:
    """``GPT2LMHeadModel`` (or its state_dict + config) → (GPTConfig,
    params) for this framework's GPT family. ``dtype`` sets the
    activation dtype (params stay fp32, cast per-op as everywhere else).
    """
    sd, hf = _state_and_config(source, config)
    act = _cfgget(hf, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise NotImplementedError(
            f"activation_function={act!r} — the GPT family's gelu is the "
            "tanh approximation (HF 'gelu_new', the GPT-2 default); "
            "importing a different activation would silently change the "
            "model's numerics")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if _cfgget(hf, flag, False):
            raise NotImplementedError(
                f"{flag}=True is not implemented — importing it with "
                "standard attention scaling would silently change the "
                "model's numerics")
    d = _cfgget(hf, "n_embd")
    n_inner = _cfgget(hf, "n_inner") or 4 * d
    cfg = GPTConfig(
        vocab_size=_cfgget(hf, "vocab_size"),
        max_seq=_cfgget(hf, "n_positions"),
        d_model=d,
        n_heads=_cfgget(hf, "n_head"),
        n_layers=_cfgget(hf, "n_layer"),
        d_ff=n_inner,
        dtype=dtype,
        norm_eps=float(_cfgget(hf, "layer_norm_epsilon", 1e-5)),
    )

    def g(key):
        return _np(sd[key])

    blocks = []
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        w_attn = g(p + "attn.c_attn.weight")          # (d, 3d), Conv1D
        b_attn = g(p + "attn.c_attn.bias")
        wq, wk, wv = np.split(w_attn, 3, axis=1)
        bq, bk, bv = np.split(b_attn, 3)
        blocks.append({
            "ln1_g": g(p + "ln_1.weight"), "ln1_b": g(p + "ln_1.bias"),
            "wq": wq, "bq": bq, "wk": wk, "bk": bk, "wv": wv, "bv": bv,
            "wo": g(p + "attn.c_proj.weight"),
            "bo": g(p + "attn.c_proj.bias"),
            "ln2_g": g(p + "ln_2.weight"), "ln2_b": g(p + "ln_2.bias"),
            "w1": g(p + "mlp.c_fc.weight"), "b1": g(p + "mlp.c_fc.bias"),
            "w2": g(p + "mlp.c_proj.weight"),
            "b2": g(p + "mlp.c_proj.bias"),
        })
    params = {
        "wte": g("transformer.wte.weight"),
        "wpe": g("transformer.wpe.weight"),
        "lnf_g": g("transformer.ln_f.weight"),
        "lnf_b": g("transformer.ln_f.bias"),
        "blocks": blocks,
    }
    return cfg, _to_jnp(params)


def to_hf_gpt2(params: Dict[str, Any], cfg: GPTConfig) -> Dict[str, Any]:
    """Our GPT-2-shaped params → an HF ``GPT2LMHeadModel`` state_dict
    (numpy values; wrap with ``torch.from_numpy`` per leaf or let
    ``load_state_dict`` do it via ``torch.as_tensor``). Inverse of
    :func:`from_hf_gpt2` — round-trip pinned in tests."""
    if (cfg.norm != "layernorm" or not cfg.tied_readout
            or cfg.mlp != "gelu" or not cfg.use_bias
            or cfg.pos_embedding != "learned"):
        raise ValueError("to_hf_gpt2 exports the GPT-2 option set "
                         "(layernorm, tied readout, gelu MLP, biases, "
                         "learned positions); got "
                         f"norm={cfg.norm!r} tied={cfg.tied_readout} "
                         f"mlp={cfg.mlp!r} use_bias={cfg.use_bias} "
                         f"pos={cfg.pos_embedding!r}")
    out: Dict[str, Any] = {
        "transformer.wte.weight": np.asarray(params["wte"]),
        "transformer.wpe.weight": np.asarray(params["wpe"]),
        "transformer.ln_f.weight": np.asarray(params["lnf_g"]),
        "transformer.ln_f.bias": np.asarray(params["lnf_b"]),
        "lm_head.weight": np.asarray(params["wte"]),
    }
    for i, b in enumerate(params["blocks"]):
        p = f"transformer.h.{i}."
        out[p + "attn.c_attn.weight"] = np.concatenate(
            [np.asarray(b["wq"]), np.asarray(b["wk"]), np.asarray(b["wv"])],
            axis=1)
        out[p + "attn.c_attn.bias"] = np.concatenate(
            [np.asarray(b["bq"]), np.asarray(b["bk"]), np.asarray(b["bv"])])
        out[p + "attn.c_proj.weight"] = np.asarray(b["wo"])
        out[p + "attn.c_proj.bias"] = np.asarray(b["bo"])
        out[p + "ln_1.weight"] = np.asarray(b["ln1_g"])
        out[p + "ln_1.bias"] = np.asarray(b["ln1_b"])
        out[p + "ln_2.weight"] = np.asarray(b["ln2_g"])
        out[p + "ln_2.bias"] = np.asarray(b["ln2_b"])
        out[p + "mlp.c_fc.weight"] = np.asarray(b["w1"])
        out[p + "mlp.c_fc.bias"] = np.asarray(b["b1"])
        out[p + "mlp.c_proj.weight"] = np.asarray(b["w2"])
        out[p + "mlp.c_proj.bias"] = np.asarray(b["b2"])
    return out


def from_hf_llama(source, config=None,
                  dtype: Any = jnp.float32,
                  max_seq: Optional[int] = None
                  ) -> Tuple[GPTConfig, Dict[str, Any]]:
    """``LlamaForCausalLM`` (or state_dict + config) → (GPTConfig,
    params). Also fits Llama-architecture descendants whose state_dict
    uses the same key scheme AND whose head_dim is the standard
    ``hidden_size / num_attention_heads`` (optional attention biases
    are imported when present, zeros otherwise; an explicit decoupled
    ``head_dim`` à la Mistral-Nemo is rejected at import).

    ``max_seq`` (default: HF ``max_position_embeddings``) caps the
    context window for cache sizing — with rope there is no position
    table, so it is a pure bound, not a parameter shape."""
    sd, hf = _state_and_config(source, config)
    d = _cfgget(hf, "hidden_size")
    n_heads = _cfgget(hf, "num_attention_heads")
    tied = bool(_cfgget(hf, "tie_word_embeddings", False))
    scaling = _cfgget(hf, "rope_scaling")
    if scaling is not None:
        raise NotImplementedError(
            f"this checkpoint uses rope_scaling={scaling!r} (Llama-3.1-"
            "style frequency remapping); importing it with plain "
            "rope_theta would silently change the model's numerics — "
            "scaled-rope import is not implemented")
    explicit_hd = _cfgget(hf, "head_dim")
    if explicit_hd is not None and explicit_hd != d // n_heads:
        raise NotImplementedError(
            f"checkpoint declares head_dim={explicit_hd} decoupled from "
            f"hidden_size/num_attention_heads={d // n_heads} — the GPT "
            "family derives head_dim from d_model/n_heads")
    cfg = GPTConfig(
        vocab_size=_cfgget(hf, "vocab_size"),
        max_seq=(max_seq if max_seq is not None
                 else _cfgget(hf, "max_position_embeddings")),
        d_model=d,
        n_heads=n_heads,
        n_layers=_cfgget(hf, "num_hidden_layers"),
        d_ff=_cfgget(hf, "intermediate_size"),
        dtype=dtype,
        pos_embedding="rope",
        rope_base=float(_cfgget(hf, "rope_theta", 10000.0)),
        n_kv_heads=_cfgget(hf, "num_key_value_heads", n_heads),
        mlp="swiglu",
        norm="rmsnorm",
        norm_eps=float(_cfgget(hf, "rms_norm_eps", 1e-5)),
        tied_readout=tied,
        # bias-free (plain Llama): the tree carries NO bias leaves.
        # Qwen-style checkpoints with projection biases get
        # use_bias=True — decided from the state_dict itself (some HF
        # config classes carry the biases unconditionally, without an
        # attention_bias/mlp_bias field), applying absent slots as
        # zeros.
        use_bias=any(".bias" in k for k in sd
                     if ".layers.0.self_attn." in k or ".layers.0.mlp." in k),
    )
    if d % n_heads != 0:
        raise ValueError(f"hidden_size {d} not divisible by "
                         f"num_attention_heads {n_heads}")

    kv_hd = cfg.kv_heads * cfg.head_dim

    def lin(block, ours, key, out_dim):
        """nn.Linear weight (out, in) → (in, out); the bias leaf exists
        only under use_bias=True (absent HF bias slots become zeros)."""
        block["w" + ours] = _np(sd[key + ".weight"]).T
        if cfg.use_bias:
            block["b" + ours] = (
                _np(sd[key + ".bias"]) if key + ".bias" in sd
                else np.zeros((out_dim,), np.float32))

    blocks = []
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        b: Dict[str, Any] = {
            "ln1_g": _np(sd[p + "input_layernorm.weight"]),
            "ln2_g": _np(sd[p + "post_attention_layernorm.weight"]),
        }
        lin(b, "q", p + "self_attn.q_proj", d)
        lin(b, "k", p + "self_attn.k_proj", kv_hd)
        lin(b, "v", p + "self_attn.v_proj", kv_hd)
        lin(b, "o", p + "self_attn.o_proj", d)
        lin(b, "1", p + "mlp.gate_proj", cfg.d_ff)   # silu path
        lin(b, "3", p + "mlp.up_proj", cfg.d_ff)     # value path
        lin(b, "2", p + "mlp.down_proj", d)
        blocks.append(b)
    params = {
        # rope carries positions — no wpe leaf; rmsnorm — no bias leaves
        "wte": _np(sd["model.embed_tokens.weight"]),
        "lnf_g": _np(sd["model.norm.weight"]),
        "blocks": blocks,
    }
    if not tied:
        params["lm_head"] = _np(sd["lm_head.weight"]).T
    return cfg, _to_jnp(params)


def to_hf_llama(params: Dict[str, Any], cfg: GPTConfig) -> Dict[str, Any]:
    """Our llama-shaped params → an HF ``LlamaForCausalLM`` state_dict
    (numpy values, ``(out, in)`` Linear layout). Requires the bias-free
    llama option set — a ``use_bias=True`` tree (Qwen-style) has bias
    leaves plain ``LlamaForCausalLM`` offers no slots for."""
    if cfg.norm != "rmsnorm" or cfg.mlp != "swiglu" \
            or cfg.pos_embedding != "rope" or cfg.use_bias:
        raise ValueError("to_hf_llama exports the llama option set "
                         "(rmsnorm, swiglu, rope, bias-free); got "
                         f"norm={cfg.norm!r} mlp={cfg.mlp!r} "
                         f"pos={cfg.pos_embedding!r} "
                         f"use_bias={cfg.use_bias}")
    out: Dict[str, Any] = {
        "model.embed_tokens.weight": np.asarray(params["wte"]),
        "model.norm.weight": np.asarray(params["lnf_g"]),
    }
    if cfg.tied_readout:
        out["lm_head.weight"] = np.asarray(params["wte"])
    else:
        out["lm_head.weight"] = np.asarray(params["lm_head"]).T
    for i, b in enumerate(params["blocks"]):
        p = f"model.layers.{i}."
        for ours, theirs in (("wq", "self_attn.q_proj"),
                             ("wk", "self_attn.k_proj"),
                             ("wv", "self_attn.v_proj"),
                             ("wo", "self_attn.o_proj"),
                             ("w1", "mlp.gate_proj"),
                             ("w3", "mlp.up_proj"),
                             ("w2", "mlp.down_proj")):
            out[p + theirs + ".weight"] = np.asarray(b[ours]).T
        out[p + "input_layernorm.weight"] = np.asarray(b["ln1_g"])
        out[p + "post_attention_layernorm.weight"] = np.asarray(b["ln2_g"])
    return out


def _to_jnp(tree):
    import jax

    return jax.tree_util.tree_map(jnp.asarray, tree)
