"""BERT-style bidirectional encoder with masked-LM loss.

BASELINE config 3's workload ("BERT-base pretrain with onebit gradient
compression"). Shares transformer blocks with the GPT family
(models/gpt.py ``transformer_block`` with ``causal=False`` — the ring
attention path supports bidirectional masks) plus token-type embeddings and
an MLM head. Same parallelism surface: tp col/row-parallel projections,
sp ring attention, dp BytePS aggregation via the train-step factory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from byteps_tpu.parallel.remat import maybe_remat
from byteps_tpu.models.gpt import (
    _layernorm,
    block_init,
    block_specs,
    head_dot,
    transformer_block,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    max_seq: int = 512
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    type_vocab: int = 2
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=256, max_seq=64, d_model=64, n_heads=4,
                   n_layers=2, d_ff=128)

    @classmethod
    def base(cls) -> "BertConfig":
        return cls(dtype=jnp.bfloat16)


def bert_init(rng: jnp.ndarray, cfg: BertConfig) -> Dict[str, Any]:
    d = cfg.d_model
    std = 0.02
    keys = jax.random.split(rng, 4 + cfg.n_layers)

    def dense(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * std

    return {
        "wte": dense(keys[0], (cfg.vocab_size, d)),
        "wpe": dense(keys[1], (cfg.max_seq, d)),
        "wtype": dense(keys[2], (cfg.type_vocab, d)),
        "emb_ln_g": jnp.ones((d,), jnp.float32),
        "emb_ln_b": jnp.zeros((d,), jnp.float32),
        "blocks": [
            block_init(keys[4 + li], d, cfg.d_ff,
                       cfg.n_heads * cfg.head_dim, cfg.n_layers)
            for li in range(cfg.n_layers)
        ],
        # MLM head: dense + LN, readout tied to wte (reference BERT shape)
        "mlm_w": dense(keys[3], (d, d)),
        "mlm_b": jnp.zeros((d,), jnp.float32),
        "mlm_ln_g": jnp.ones((d,), jnp.float32),
        "mlm_ln_b": jnp.zeros((d,), jnp.float32),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
    }


def bert_logical_specs(cfg: BertConfig) -> Dict[str, Any]:
    from byteps_tpu.models.gpt import block_logical_specs
    return {
        "wte": ("vocab", "embed"), "wpe": (None, "embed"),
        "wtype": (None, "embed"),
        "emb_ln_g": ("embed",), "emb_ln_b": ("embed",),
        "blocks": [block_logical_specs() for _ in range(cfg.n_layers)],
        "mlm_w": ("embed", "embed"), "mlm_b": ("embed",),
        "mlm_ln_g": ("embed",), "mlm_ln_b": ("embed",),
        "mlm_bias": ("vocab",),
    }


def bert_param_specs(cfg: BertConfig, tp_axis: Optional[str]) -> Dict[str, Any]:
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(bert_logical_specs(cfg),
                         rules_from_axes(tp_axis=tp_axis))


def bert_hidden(params, tokens: jnp.ndarray, cfg: BertConfig,
                type_ids: Optional[jnp.ndarray] = None,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None,
                remat: bool = False) -> jnp.ndarray:
    """Embeddings → blocks → MLM dense+LN, STOPPING before the tied
    vocab readout: the shared trunk of :func:`bert_forward` (dense
    logits) and the fused readout+CE path in :func:`bert_mlm_loss`.
    Returns the pre-readout hidden in the activation dtype."""
    B, S_loc = tokens.shape
    off = jax.lax.axis_index(sp_axis) * S_loc if sp_axis is not None else 0
    pos = off + jnp.arange(S_loc)
    x = params["wte"][tokens] + params["wpe"][pos]
    if type_ids is not None:
        x = x + params["wtype"][type_ids]
    x = _layernorm(x.astype(cfg.dtype), params["emb_ln_g"],
                   params["emb_ln_b"])
    def apply_block(x, p):
        return transformer_block(x, p, cfg.head_dim, tp_axis, sp_axis,
                                 causal=False)

    apply_block = maybe_remat(apply_block, remat)
    for p in params["blocks"]:
        x = apply_block(x, p)
    # MLM head via head_dot: activation-dtype operands, f32 accumulation
    # — bit-identical at f32 (default/test configs), MXU-native at bf16
    h = jax.nn.gelu(head_dot(x, params["mlm_w"]) + params["mlm_b"])
    h = _layernorm(h, params["mlm_ln_g"], params["mlm_ln_b"])
    return h.astype(x.dtype)


def bert_forward(params, tokens: jnp.ndarray, cfg: BertConfig,
                 type_ids: Optional[jnp.ndarray] = None,
                 tp_axis: Optional[str] = None,
                 sp_axis: Optional[str] = None,
                 remat: bool = False) -> jnp.ndarray:
    """(B, S_local) tokens → f32 MLM logits (B, S_local, V)."""
    h = bert_hidden(params, tokens, cfg, type_ids=type_ids, tp_axis=tp_axis,
                    sp_axis=sp_axis, remat=remat)
    return head_dot(h, params["wte"].T) + params["mlm_bias"]


def bert_mlm_loss(params, tokens, targets, mask, cfg: BertConfig,
                  dp_axis: Optional[str] = None,
                  tp_axis: Optional[str] = None,
                  sp_axis: Optional[str] = None,
                  remat: bool = False,
                  chunked_ce=True) -> jnp.ndarray:
    """Masked-LM cross-entropy over ``mask`` positions only.

    ``tokens`` are the corrupted inputs, ``targets`` the originals, ``mask``
    a {0,1} (B, S) array of predicted positions. Same replication contract
    as gpt_loss (identical across tp; pmean over sp; dp-local unless
    dp_axis given). ``chunked_ce`` is the tri-state fused readout+CE
    knob (see ``gpt_loss``): truthy fuses the tied vocab readout +
    ``mlm_bias`` + CE so the f32 (B, S, V) logits never materialize
    (``ops/chunked_ce.py``; ``"vocab_parallel"`` opts into the tp vocab
    split); ``False`` is the dense golden path.
    """
    if chunked_ce:
        from byteps_tpu.ops.chunked_ce import chunked_ce_nll

        h = bert_hidden(params, tokens, cfg, tp_axis=tp_axis,
                        sp_axis=sp_axis, remat=remat)
        nll = chunked_ce_nll(
            h, params["wte"].T.astype(jnp.float32), targets,
            bias=params["mlm_bias"],
            tp_axis=tp_axis if chunked_ce == "vocab_parallel" else None)
    else:
        logits = bert_forward(params, tokens, cfg, tp_axis=tp_axis,
                              sp_axis=sp_axis, remat=remat)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    m = mask.astype(jnp.float32)
    axes = tuple(a for a in (dp_axis, sp_axis) if a is not None)
    num = (nll * m).sum()
    den = m.sum()
    if axes:
        num = jax.lax.psum(num, axes)
        den = jax.lax.psum(den, axes)
    return num / jnp.maximum(den, 1.0)