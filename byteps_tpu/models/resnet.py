"""ResNet family (v1.5 bottleneck / basic blocks) with cross-replica SyncBN.

BASELINE config 2's workload ("ResNet-50 ImageNet, byteps.jax
DistributedOptimizer, pure ICI all-reduce"). Functional NHWC convolutions
(MXU-friendly: XLA lowers conv_general_dilated onto the systolic array);
batch-norm statistics are synchronized across the dp axis with pmean (true
SyncBN — keeps replica running stats identical, unlike the reference's
torch DDP local-BN), and running stats live in a separate state pytree the
optimizer never touches. Parallelism: dp only (tp/sp have no natural conv
mapping here; the transformer families carry those axes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depths: Tuple[int, ...] = (3, 4, 6, 3)   # resnet50
    width: int = 64
    bottleneck: bool = True
    num_classes: int = 1000
    bn_momentum: float = 0.9
    dtype: Any = jnp.float32

    @classmethod
    def resnet50(cls) -> "ResNetConfig":
        return cls()

    @classmethod
    def resnet18(cls) -> "ResNetConfig":
        return cls(depths=(2, 2, 2, 2), bottleneck=False)

    @classmethod
    def tiny(cls) -> "ResNetConfig":
        """CIFAR-sized test config."""
        return cls(depths=(1, 1), width=16, bottleneck=False,
                   num_classes=10)


def _conv_init(rng, kh, kw, cin, cout):
    fan_in = kh * kw * cin
    return jax.random.normal(rng, (kh, kw, cin, cout), jnp.float32) * (
        (2.0 / fan_in) ** 0.5
    )


def _bn_params(c):
    return {"g": jnp.ones((c,), jnp.float32), "b": jnp.zeros((c,), jnp.float32)}


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _block_channels(cfg: ResNetConfig, stage: int) -> Tuple[int, int]:
    width = cfg.width * (2 ** stage)
    return width, width * 4 if cfg.bottleneck else width


def resnet_init(rng: jnp.ndarray, cfg: ResNetConfig):
    """Returns (params, bn_state) — running stats separated from params."""
    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    k = iter(jax.random.split(rng, 4096))
    params["stem"] = {"w": _conv_init(next(k), 7, 7, 3, cfg.width),
                      "bn": _bn_params(cfg.width)}
    state["stem"] = _bn_stats(cfg.width)
    cin = cfg.width
    params["stages"], state["stages"] = [], []
    for si, depth in enumerate(cfg.depths):
        mid, cout = _block_channels(cfg, si)
        blocks, bstates = [], []
        for bi in range(depth):
            stride = 2 if (bi == 0 and si > 0) else 1
            blk: Dict[str, Any] = {}
            bst: Dict[str, Any] = {}
            if cfg.bottleneck:
                blk["conv1"] = {"w": _conv_init(next(k), 1, 1, cin, mid),
                                "bn": _bn_params(mid)}
                blk["conv2"] = {"w": _conv_init(next(k), 3, 3, mid, mid),
                                "bn": _bn_params(mid)}
                blk["conv3"] = {"w": _conv_init(next(k), 1, 1, mid, cout),
                                "bn": _bn_params(cout)}
                bst = {"conv1": _bn_stats(mid), "conv2": _bn_stats(mid),
                       "conv3": _bn_stats(cout)}
            else:
                blk["conv1"] = {"w": _conv_init(next(k), 3, 3, cin, mid),
                                "bn": _bn_params(mid)}
                blk["conv2"] = {"w": _conv_init(next(k), 3, 3, mid, cout),
                                "bn": _bn_params(cout)}
                bst = {"conv1": _bn_stats(mid), "conv2": _bn_stats(cout)}
            if stride != 1 or cin != cout:
                blk["proj"] = {"w": _conv_init(next(k), 1, 1, cin, cout),
                               "bn": _bn_params(cout)}
                bst["proj"] = _bn_stats(cout)
            blocks.append(blk)
            bstates.append(bst)
            cin = cout
        params["stages"].append(blocks)
        state["stages"].append(bstates)
    params["fc"] = {
        "w": jax.random.normal(next(k), (cin, cfg.num_classes),
                               jnp.float32) * 0.01,
        "b": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return params, state


def resnet_logical_specs(cfg: ResNetConfig, params) -> Any:
    """All dims replicated (dp-only family): every leaf is an empty
    logical tuple, which resolves to ``P()``."""
    return jax.tree.map(lambda _: (), params)


def resnet_param_specs(cfg: ResNetConfig, params) -> Any:
    """All replicated (dp-only family)."""
    from byteps_tpu.parallel.partitioner import resolve_specs
    return resolve_specs(resnet_logical_specs(cfg, params), {})


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _sync_bn(x, bn, st, dp_axis, train, momentum):
    """BatchNorm with dp-synchronized batch statistics; returns (y, new_st)."""
    xf = x.astype(jnp.float32)
    if train:
        mean = xf.mean(axis=(0, 1, 2))
        sq = (xf ** 2).mean(axis=(0, 1, 2))
        if dp_axis is not None:
            mean = jax.lax.pmean(mean, dp_axis)
            sq = jax.lax.pmean(sq, dp_axis)
        var = sq - mean ** 2
        new_st = {
            "mean": momentum * st["mean"] + (1 - momentum) * mean,
            "var": momentum * st["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    y = (xf - mean) * jax.lax.rsqrt(var + 1e-5) * bn["g"] + bn["b"]
    return y.astype(x.dtype), new_st


def _conv_bn(x, p, st, dp_axis, train, momentum, stride=1, relu=True):
    y = _conv(x, p["w"], stride)
    y, new_st = _sync_bn(y, p["bn"], st, dp_axis, train, momentum)
    if relu:
        y = jax.nn.relu(y)
    return y, new_st


def resnet_forward(params, state, images: jnp.ndarray, cfg: ResNetConfig,
                   dp_axis: Optional[str] = None, train: bool = True):
    """NHWC images → (logits f32, new_bn_state)."""
    mom = cfg.bn_momentum
    x = images.astype(cfg.dtype)
    new_state: Dict[str, Any] = {"stages": []}
    x, new_state["stem"] = _conv_bn(x, params["stem"], state["stem"],
                                    dp_axis, train, mom, stride=2)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME",
    )
    for si, blocks in enumerate(params["stages"]):
        bstates: List[Any] = []
        for bi, blk in enumerate(blocks):
            st = state["stages"][si][bi]
            nst: Dict[str, Any] = {}
            stride = 2 if (bi == 0 and si > 0) else 1
            identity = x
            if cfg.bottleneck:
                y, nst["conv1"] = _conv_bn(x, blk["conv1"], st["conv1"],
                                           dp_axis, train, mom)
                y, nst["conv2"] = _conv_bn(y, blk["conv2"], st["conv2"],
                                           dp_axis, train, mom, stride=stride)
                y, nst["conv3"] = _conv_bn(y, blk["conv3"], st["conv3"],
                                           dp_axis, train, mom, relu=False)
            else:
                y, nst["conv1"] = _conv_bn(x, blk["conv1"], st["conv1"],
                                           dp_axis, train, mom, stride=stride)
                y, nst["conv2"] = _conv_bn(y, blk["conv2"], st["conv2"],
                                           dp_axis, train, mom, relu=False)
            if "proj" in blk:
                identity, nst["proj"] = _conv_bn(
                    x, blk["proj"], st["proj"], dp_axis, train, mom,
                    stride=stride, relu=False,
                )
            x = jax.nn.relu(y + identity)
            bstates.append(nst)
        new_state["stages"].append(bstates)
    x = x.mean(axis=(1, 2)).astype(jnp.float32)   # global average pool
    logits = x @ params["fc"]["w"] + params["fc"]["b"]
    return logits, new_state


def resnet_loss(params, state, images, labels, cfg: ResNetConfig,
                dp_axis: Optional[str] = None, train: bool = True):
    """(softmax CE, new_bn_state); dp-local mean (the factory's contract)."""
    logits, new_state = resnet_forward(params, state, images, cfg,
                                       dp_axis=dp_axis, train=train)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    return nll.mean(), new_state