"""Vision Transformer family — image classification on transformer blocks.

The reference ships no models (SURVEY §1: BytePS sits under a framework;
its image workloads are torchvision's — example/pytorch/benchmark_byteps.py
trains ResNet/VGG). This repo's model zoo covers those conv families with
:mod:`byteps_tpu.models.resnet`; ViT rounds it out with the transformer
image family, built TPU-first:

* **Patchify is one reshape + one matmul** — no gather, no conv im2col:
  ``(B, H, W, C) → (B, N, P·P·C) @ W_patch`` keeps the embedding on the
  MXU as a single large GEMM.
* **Mean-pool head instead of a [CLS] token** — pooling is a reduction
  XLA fuses with the final layernorm, and it keeps the patch sequence
  length a power-of-two-friendly ``(H/P)·(W/P)`` with no ragged +1 token
  (which would force 197-length sequences off the MXU's preferred tiles).
* Transformer blocks are shared verbatim with GPT/BERT
  (:func:`byteps_tpu.models.gpt.transformer_block`, ``causal=False``), so
  tensor parallelism (col/row-parallel projections) and per-block
  rematerialization compose exactly as they do for the text families.

Sequence parallelism is intentionally not plumbed: ViT sequences are
``(image/patch)²`` ≈ 196 tokens — three orders of magnitude below where
the sp ring pays for its ppermutes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from byteps_tpu.models.gpt import (
    _layernorm,
    block_init,
    block_specs,
    transformer_block,
)
from byteps_tpu.parallel.remat import maybe_remat


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    n_classes: int = 1000
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.image_size % self.patch_size != 0:
            raise ValueError(
                f"image_size {self.image_size} not divisible by "
                f"patch_size {self.patch_size}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @classmethod
    def tiny(cls) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, channels=3, d_model=64,
                   n_heads=4, n_layers=2, d_ff=128, n_classes=10)

    @classmethod
    def base(cls) -> "ViTConfig":
        """ViT-B/16 shape, bf16 activations for the MXU."""
        return cls(dtype=jnp.bfloat16)


def vit_init(rng: jnp.ndarray, cfg: ViTConfig) -> Dict[str, Any]:
    d = cfg.d_model
    std = 0.02
    keys = jax.random.split(rng, 3 + cfg.n_layers)
    patch_dim = cfg.patch_size * cfg.patch_size * cfg.channels

    def dense(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * std

    return {
        "w_patch": dense(keys[0], (patch_dim, d)),
        "b_patch": jnp.zeros((d,), jnp.float32),
        "wpe": dense(keys[1], (cfg.n_patches, d)),
        "blocks": [
            block_init(keys[3 + li], d, cfg.d_ff,
                       cfg.n_heads * cfg.head_dim, cfg.n_layers)
            for li in range(cfg.n_layers)
        ],
        "ln_f_g": jnp.ones((d,), jnp.float32),
        "ln_f_b": jnp.zeros((d,), jnp.float32),
        "w_head": dense(keys[2], (d, cfg.n_classes)),
        "b_head": jnp.zeros((cfg.n_classes,), jnp.float32),
    }


def vit_logical_specs(cfg: ViTConfig) -> Dict[str, Any]:
    from byteps_tpu.models.gpt import block_logical_specs
    return {
        "w_patch": (None, "embed"), "b_patch": ("embed",),
        "wpe": (None, "embed"),
        "blocks": [block_logical_specs() for _ in range(cfg.n_layers)],
        "ln_f_g": ("embed",), "ln_f_b": ("embed",),
        "w_head": ("embed", None), "b_head": (None,),
    }


def vit_param_specs(cfg: ViTConfig, tp_axis: Optional[str]) -> Dict[str, Any]:
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(vit_logical_specs(cfg),
                         rules_from_axes(tp_axis=tp_axis))


def patchify(images: jnp.ndarray, patch: int) -> jnp.ndarray:
    """(B, H, W, C) → (B, N, patch·patch·C) by pure reshape/transpose."""
    B, H, W, C = images.shape
    gh, gw = H // patch, W // patch
    x = images.reshape(B, gh, patch, gw, patch, C)
    x = x.transpose(0, 1, 3, 2, 4, 5)            # (B, gh, gw, p, p, C)
    return x.reshape(B, gh * gw, patch * patch * C)


def vit_forward(params, images: jnp.ndarray, cfg: ViTConfig,
                tp_axis: Optional[str] = None,
                remat: bool = False) -> jnp.ndarray:
    """(B, H, W, C) images → f32 class logits (B, n_classes)."""
    x = patchify(images.astype(cfg.dtype), cfg.patch_size)
    x = x @ params["w_patch"].astype(x.dtype) + params["b_patch"].astype(x.dtype)
    x = x + params["wpe"].astype(x.dtype)

    def apply_block(x, p):
        return transformer_block(x, p, cfg.head_dim, tp_axis, None,
                                 causal=False)

    apply_block = maybe_remat(apply_block, remat)
    for p in params["blocks"]:
        x = apply_block(x, p)
    x = _layernorm(x, params["ln_f_g"], params["ln_f_b"])
    pooled = x.astype(jnp.float32).mean(axis=1)          # (B, d)
    return pooled @ params["w_head"] + params["b_head"]


def vit_loss(params, images, labels, cfg: ViTConfig,
             dp_axis: Optional[str] = None,
             tp_axis: Optional[str] = None,
             remat: bool = False) -> jnp.ndarray:
    """Mean softmax cross-entropy; dp mean via pmean when sharded."""
    logits = vit_forward(params, images, cfg, tp_axis=tp_axis, remat=remat)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1).mean()
    if dp_axis is not None:
        nll = jax.lax.pmean(nll, dp_axis)
    return nll


def synthetic_vit_batch(rng: jnp.ndarray, cfg: ViTConfig, batch: int):
    """Random (images, labels) classification batch."""
    k1, k2 = jax.random.split(rng)
    images = jax.random.normal(
        k1, (batch, cfg.image_size, cfg.image_size, cfg.channels),
        jnp.float32)
    labels = jax.random.randint(k2, (batch,), 0, cfg.n_classes)
    return images, labels
