"""GPT-style decoder-only transformer — the flagship model.

Functional (params pytree + pure apply), written once for every parallelism
configuration: the same forward runs single-chip (all axes ``None``),
tensor-parallel (Megatron col/row-parallel projections over ``tp``), and
sequence-parallel (ring attention over ``sp``) inside one ``shard_map``.
BASELINE config 4's workload ("GPT-2 medium with topk sparsification") uses
this model at size; tests and the driver dry-run use tiny shapes.

MXU notes: all FLOPs are batched matmuls (einsum/`@`) with static shapes;
activations can run in bfloat16 (``GPTConfig.dtype``) while layernorm,
softmax and the loss accumulate in float32.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from byteps_tpu.parallel.remat import maybe_remat
from byteps_tpu.parallel.ring_attention import (
    ring_attention,
    zigzag_local_positions,
    zigzag_ring_attention,
)
from byteps_tpu.parallel.tp import col_parallel_matmul, row_parallel_matmul


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    max_seq: int = 1024
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.float32
    # "learned" = GPT-2 wpe table; "rope" = rotary position embeddings
    # applied to q/k per head (no wpe leaf — the param tree carries
    # exactly the leaves the config trains, so lossy gradient
    # compression can never perturb a structurally-dead parameter)
    pos_embedding: str = "learned"
    rope_base: float = 10000.0
    # grouped-query attention: k/v carry n_kv_heads heads (None = n_heads,
    # plain MHA); queries repeat each kv head n_heads/n_kv_heads times.
    # The KV cache stores only the kv heads — the decode memory lever.
    n_kv_heads: Any = None
    # "gelu" = GPT-2 2-matrix MLP; "swiglu" = gated 3-matrix llama-style
    # FFN (silu(x·w1) ∘ (x·w3)) · w2 — same d_ff hidden width
    mlp: str = "gelu"
    # "layernorm" = GPT-2 LN (mean-centered, affine); "rmsnorm" =
    # llama-style RMS norm (no centering, no bias — the ln*_b / lnf_b
    # leaves are absent from the param tree)
    norm: str = "layernorm"
    norm_eps: float = 1e-5
    # False = llama-style bias-free projections: no b* leaves in the
    # tree. Leaves the config doesn't train must NOT exist — inert
    # zeros would drift under lossy gradient compression (onebit maps
    # a zero gradient to ±scale) and break checkpoint re-export.
    use_bias: bool = True
    # True = GPT-2 weight-tied readout (h @ wte.T); False = separate
    # (d, vocab) "lm_head" leaf (llama-style untied readout)
    tied_readout: bool = True

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        if self.n_heads % kv != 0:
            raise ValueError(
                f"n_heads ({self.n_heads}) must be a multiple of "
                f"n_kv_heads ({kv})")
        return kv

    @classmethod
    def tiny(cls) -> "GPTConfig":
        """Dry-run / unit-test size; dims divisible by tp=2, sp=2, heads=4."""
        return cls(vocab_size=256, max_seq=64, d_model=64, n_heads=4,
                   n_layers=2, d_ff=128)

    @classmethod
    def gpt2_medium(cls) -> "GPTConfig":
        return cls(vocab_size=50304, max_seq=1024, d_model=1024,
                   n_heads=16, n_layers=24, d_ff=4096, dtype=jnp.bfloat16)

    @classmethod
    def llama(cls, **kw) -> "GPTConfig":
        """The llama-family option set (RoPE + GQA + SwiGLU + RMSNorm +
        untied readout); size fields via ``**kw``."""
        defaults = dict(pos_embedding="rope", mlp="swiglu", norm="rmsnorm",
                        tied_readout=False, use_bias=False)
        defaults.update(kw)
        return cls(**defaults)


def gpt_init(rng: jnp.ndarray, cfg: GPTConfig) -> Dict[str, Any]:
    """Initialize full (unsharded) parameters; shard via device_put after."""
    d, ff, hd = cfg.d_model, cfg.d_ff, cfg.n_heads * cfg.head_dim
    kv_hd = cfg.kv_heads * cfg.head_dim
    std = 0.02

    def dense(key, shape):
        return (jax.random.normal(key, shape, jnp.float32) * std)

    keys = jax.random.split(rng, 2 + cfg.n_layers)
    params: Dict[str, Any] = {
        "wte": dense(keys[0], (cfg.vocab_size, d)),
        "lnf_g": jnp.ones((d,), jnp.float32),
        "blocks": [
            block_init(keys[2 + li], d, ff, hd, cfg.n_layers, kv_hd=kv_hd,
                       mlp=cfg.mlp, use_bias=cfg.use_bias, norm=cfg.norm)
            for li in range(cfg.n_layers)
        ],
    }
    if cfg.pos_embedding == "learned":
        params["wpe"] = dense(keys[1], (cfg.max_seq, d))
    if cfg.norm == "layernorm":
        params["lnf_b"] = jnp.zeros((d,), jnp.float32)
    if not cfg.tied_readout:
        params["lm_head"] = dense(jax.random.fold_in(keys[0], 1),
                                  (d, cfg.vocab_size))
    return params


def gpt_logical_specs(cfg: GPTConfig) -> Dict[str, Any]:
    """Logical-axis tree matching :func:`gpt_init`'s structure: one tuple
    of logical names per array dim. The Partitioner's per-family rule
    table decides what (if anything) each name shards over."""
    return {
        "wte": ("vocab", "embed"), "lnf_g": ("embed",),
        **({"wpe": (None, "embed")} if cfg.pos_embedding == "learned"
           else {}),
        **({"lnf_b": ("embed",)} if cfg.norm == "layernorm" else {}),
        **({} if cfg.tied_readout else {"lm_head": ("embed", "vocab")}),
        "blocks": [block_logical_specs(cfg.mlp, use_bias=cfg.use_bias,
                                       norm=cfg.norm)
                   for _ in range(cfg.n_layers)],
    }


def gpt_param_specs(cfg: GPTConfig, tp_axis: Optional[str]) -> Dict[str, Any]:
    """PartitionSpec tree matching :func:`gpt_init`'s structure.

    Column-parallel weights (qkv, w1) split their output dim over tp; the
    matching row-parallel weights (wo, w2) split their input dim; biases of
    column-parallel layers are sharded, everything else replicated (dp/sp
    replication is implicit — those axes never appear in param specs).
    Thin wrapper: the structure lives in :func:`gpt_logical_specs`, the
    tp policy in the partitioner rules.
    """
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(gpt_logical_specs(cfg),
                         rules_from_axes(tp_axis=tp_axis))


def resolve_rope(cfg: GPTConfig) -> float:
    """Validate the position scheme and return the rope base to thread to
    the blocks (0.0 = learned/wpe — no rotation)."""
    if cfg.pos_embedding not in ("learned", "rope"):
        raise ValueError(f"unknown pos_embedding {cfg.pos_embedding!r} — "
                         "expected 'learned' or 'rope'")
    if cfg.pos_embedding == "rope":
        if not cfg.rope_base > 0.0:
            raise ValueError(f"rope_base must be > 0; got {cfg.rope_base}")
        return cfg.rope_base
    return 0.0


def _positions(S_loc: int, sp_axis, seq_layout: str) -> jnp.ndarray:
    """This device's global sequence positions (layout-aware) — feeds both
    the learned wpe gather and the RoPE rotations."""
    if seq_layout == "zigzag" and sp_axis is not None:
        return zigzag_local_positions(S_loc, sp_axis)
    off = (jax.lax.axis_index(sp_axis) * S_loc if sp_axis is not None
           else 0)
    return off + jnp.arange(S_loc)


def rope_rotate(x: jnp.ndarray, pos: jnp.ndarray,
                base: float = 10000.0) -> jnp.ndarray:
    """Rotary position embedding (half-split convention), (B, S, H, D)
    with global positions ``pos`` — either ``(S,)`` shared across the
    batch (training / single-request decode) or ``(B, S)`` per-row (the
    serve tier's packed decode, where one batch holds requests at
    heterogeneous positions). Pure elementwise rotation — composes with
    the flash kernel, ring/zigzag schedules (positions are
    layout-aware), and the KV cache (keys cached post-rotation)."""
    D = x.shape[-1]
    half = D // 2
    inv_freq = 1.0 / (base ** (jnp.arange(half, dtype=jnp.float32) / half))
    if jnp.ndim(pos) == 2:
        ang = pos.astype(jnp.float32)[..., None] * inv_freq  # (B, S, half)
        cos = jnp.cos(ang)[:, :, None, :]
        sin = jnp.sin(ang)[:, :, None, :]
    else:
        ang = pos.astype(jnp.float32)[:, None] * inv_freq[None, :]
        cos = jnp.cos(ang)[None, :, None, :]
        sin = jnp.sin(ang)[None, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def _layernorm(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray,
               eps: float = 1e-5) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * g + b).astype(x.dtype)


def _rmsnorm(x: jnp.ndarray, g: jnp.ndarray, b=None,
             eps: float = 1e-5) -> jnp.ndarray:
    """Llama-style RMS norm. ``b`` is accepted for signature parity with
    layernorm but must be absent (RMSNorm has no bias — rmsnorm trees
    carry no ln*_b leaves)."""
    assert b is None, "rmsnorm trees carry no norm-bias leaf"
    xf = x.astype(jnp.float32)
    ms = (xf * xf).mean(-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps) * g).astype(x.dtype)


_NORMS = {"layernorm": _layernorm, "rmsnorm": _rmsnorm}


def resolve_norm(cfg: GPTConfig):
    """Validate cfg.norm and return the (norm_fn, eps) pair to thread to
    the blocks/readout."""
    if cfg.norm not in _NORMS:
        raise ValueError(f"unknown norm {cfg.norm!r} — expected one of "
                         f"{sorted(_NORMS)}")
    if not cfg.norm_eps > 0.0:
        raise ValueError(f"norm_eps must be > 0; got {cfg.norm_eps}")
    return _NORMS[cfg.norm], cfg.norm_eps


def _bias(p, name, x, use_bias: bool):
    """The projection bias to apply — None under use_bias=False (the
    leaf stays in the tree, inert, zero-gradient)."""
    return p[name].astype(x.dtype) if use_bias else None


def _attention(x, p, head_dim: int, tp_axis, sp_axis, causal: bool = True,
               seq_layout: str = "contiguous", rope_base: float = 0.0,
               use_bias: bool = True):
    from byteps_tpu.models.lora import lora_delta

    B, S = x.shape[:2]
    q = col_parallel_matmul(x, p["wq"].astype(x.dtype), _bias(p, "bq", x, use_bias))
    k = col_parallel_matmul(x, p["wk"].astype(x.dtype), _bias(p, "bk", x, use_bias))
    v = col_parallel_matmul(x, p["wv"].astype(x.dtype), _bias(p, "bv", x, use_bias))
    if "lora" in p:
        q = q + lora_delta(x, p, "wq")
        k = k + lora_delta(x, p, "wk")
        v = v + lora_delta(x, p, "wv")
    h_loc = q.shape[-1] // head_dim     # query heads this tp shard owns
    kv_loc = k.shape[-1] // head_dim    # kv heads (GQA: fewer)
    if kv_loc == 0 or h_loc % kv_loc != 0:
        raise ValueError(
            f"per-shard head split is invalid: {h_loc} query heads vs "
            f"{kv_loc} kv heads — with GQA under tensor parallelism, "
            "n_kv_heads must be divisible by the tp axis size")
    q = q.reshape(B, S, h_loc, head_dim)
    k = k.reshape(B, S, kv_loc, head_dim)
    v = v.reshape(B, S, kv_loc, head_dim)
    if rope_base > 0.0:
        pos = _positions(S, sp_axis, seq_layout)
        q = rope_rotate(q, pos, rope_base)
        k = rope_rotate(k, pos, rope_base)
    # GQA: k/v stay NARROW (kv_loc heads) — the flash kernels associate
    # query heads to kv heads by grid-index arithmetic, the jnp lse path
    # by grouped einsum, and the rings rotate the narrow blocks (G× less
    # ICI wire); only the legacy jnp contiguous-ring repeats internally
    if seq_layout == "zigzag":
        o = zigzag_ring_attention(q, k, v, sp_axis, causal=causal)
    elif seq_layout == "contiguous":
        o = ring_attention(q, k, v, sp_axis, causal=causal)
    else:
        raise ValueError(f"unknown seq_layout {seq_layout!r} — expected "
                         "'contiguous' or 'zigzag'")
    o = o.reshape(B, S, h_loc * head_dim)
    out = row_parallel_matmul(o, p["wo"].astype(x.dtype), tp_axis,
                              _bias(p, "bo", x, use_bias))
    if "lora" in p:
        out = out + lora_delta(o, p, "wo", tp_axis)
    return out


def _mlp(x, p, tp_axis, use_bias: bool = True):
    from byteps_tpu.models.lora import lora_delta

    h = col_parallel_matmul(x, p["w1"].astype(x.dtype),
                            _bias(p, "b1", x, use_bias))
    if "lora" in p:
        h = h + lora_delta(x, p, "w1")
    if "w3" in p:
        # SwiGLU: silu-gated hidden (w1 value path ∘ w3 gate path); w1/w3
        # col-parallel over tp, w2 row-parallel as in the gelu MLP
        g = col_parallel_matmul(x, p["w3"].astype(x.dtype),
                                _bias(p, "b3", x, use_bias))
        if "lora" in p:
            g = g + lora_delta(x, p, "w3")
        h = jax.nn.silu(h) * g
    else:
        h = jax.nn.gelu(h)
    out = row_parallel_matmul(h, p["w2"].astype(x.dtype), tp_axis,
                              _bias(p, "b2", x, use_bias))
    if "lora" in p:
        out = out + lora_delta(h, p, "w2", tp_axis)
    return out


def transformer_block(x, p, head_dim: int, tp_axis=None, sp_axis=None,
                      causal: bool = True, seq_layout: str = "contiguous",
                      rope_base: float = 0.0, norm_fn=_layernorm,
                      norm_eps: float = 1e-5, use_bias: bool = True):
    """Pre-LN block shared by the GPT (causal) and BERT (bidirectional)
    families: attention + MLP, tp col/row-parallel, optional sp ring
    (contiguous or zigzag sequence layout), optional RoPE
    (``rope_base > 0``), layernorm or rmsnorm (``norm_fn``), optional
    llama-style bias-free projections (``use_bias=False``)."""
    x = x + _attention(norm_fn(x, p["ln1_g"], p.get("ln1_b"), norm_eps), p,
                       head_dim, tp_axis, sp_axis, causal=causal,
                       seq_layout=seq_layout, rope_base=rope_base,
                       use_bias=use_bias)
    return x + _mlp(norm_fn(x, p["ln2_g"], p.get("ln2_b"), norm_eps), p,
                    tp_axis, use_bias=use_bias)


def block_init(rng, d: int, ff: int, hd: int, n_layers: int,
               kv_hd: int = None, mlp: str = "gelu",
               use_bias: bool = True, norm: str = "layernorm"):
    """One transformer block's params (shape shared across families).
    ``kv_hd`` (default ``hd``) narrows the k/v projections for GQA;
    ``mlp="swiglu"`` adds the gate matrix ``w3``; ``use_bias=False``
    omits the projection biases and ``norm="rmsnorm"`` the norm biases
    — absent, not zero, so no optimizer/compression state exists for
    them (see GPTConfig.use_bias)."""
    if mlp not in ("gelu", "swiglu"):
        raise ValueError(f"unknown mlp {mlp!r} — expected 'gelu' or "
                         "'swiglu'")
    std = 0.02
    if kv_hd is None:
        kv_hd = hd
    bk = jax.random.split(rng, 7)

    def dense(key, shape):
        return jax.random.normal(key, shape, jnp.float32) * std

    p = {
        "ln1_g": jnp.ones((d,), jnp.float32),
        "wq": dense(bk[0], (d, hd)),
        "wk": dense(bk[1], (d, kv_hd)),
        "wv": dense(bk[2], (d, kv_hd)),
        "wo": dense(bk[3], (hd, d)) / (2 * n_layers) ** 0.5,
        "ln2_g": jnp.ones((d,), jnp.float32),
        "w1": dense(bk[4], (d, ff)),
        "w2": dense(bk[5], (ff, d)) / (2 * n_layers) ** 0.5,
        **({"w3": dense(bk[6], (d, ff))} if mlp == "swiglu" else {}),
    }
    if norm == "layernorm":
        p["ln1_b"] = jnp.zeros((d,), jnp.float32)
        p["ln2_b"] = jnp.zeros((d,), jnp.float32)
    if use_bias:
        p.update({
            "bq": jnp.zeros((hd,), jnp.float32),
            "bk": jnp.zeros((kv_hd,), jnp.float32),
            "bv": jnp.zeros((kv_hd,), jnp.float32),
            "bo": jnp.zeros((d,), jnp.float32),
            "b1": jnp.zeros((ff,), jnp.float32),
            "b2": jnp.zeros((d,), jnp.float32),
            **({"b3": jnp.zeros((ff,), jnp.float32)} if mlp == "swiglu"
               else {}),
        })
    return p


def block_logical_specs(mlp: str = "gelu", use_bias: bool = True,
                        norm: str = "layernorm") -> Dict[str, Any]:
    """Logical-axis dict for one transformer block: qkv/w1 are
    column-parallel (output dim = heads/kv/mlp), wo/w2 row-parallel
    (input dim likewise), biases follow their weight's output dim."""
    s = {
        "ln1_g": ("embed",),
        "wq": ("embed", "heads"), "wk": ("embed", "kv"),
        "wv": ("embed", "kv"),
        "wo": ("heads", "embed"), "ln2_g": ("embed",),
        "w1": ("embed", "mlp"), "w2": ("mlp", "embed"),
        **({"w3": ("embed", "mlp")} if mlp == "swiglu" else {}),
    }
    if norm == "layernorm":
        s["ln1_b"] = ("embed",)
        s["ln2_b"] = ("embed",)
    if use_bias:
        s.update({
            "bq": ("heads",), "bk": ("kv",), "bv": ("kv",),
            "bo": ("embed",),
            "b1": ("mlp",), "b2": ("embed",),
            **({"b3": ("mlp",)} if mlp == "swiglu" else {}),
        })
    return s


def block_specs(tp_axis, mlp: str = "gelu", use_bias: bool = True,
                norm: str = "layernorm"):
    """PartitionSpec dict for one transformer block (see gpt_param_specs)."""
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(block_logical_specs(mlp, use_bias, norm),
                         rules_from_axes(tp_axis=tp_axis))


def _embed(params, tokens: jnp.ndarray, cfg: GPTConfig,
           sp_axis, seq_layout: str = "contiguous") -> jnp.ndarray:
    """Token + position embeddings with the sequence-shard offset, shared
    by the dense and pipelined paths. Under the zigzag layout the local
    tokens are this device's (early, late) chunk pair and the positions
    follow (`zigzag_local_positions`)."""
    S_loc = tokens.shape[1]
    if cfg.pos_embedding == "rope":
        # positions enter through the per-layer q/k rotations instead
        return params["wte"][tokens].astype(cfg.dtype)
    pos = _positions(S_loc, sp_axis, seq_layout)
    return (params["wte"][tokens]
            + jnp.take(params["wpe"], pos, axis=0)).astype(cfg.dtype)


@jax.custom_vjp
def head_dot(h: jnp.ndarray, head: jnp.ndarray) -> jnp.ndarray:
    """Readout matmul in the ACTIVATION dtype with f32 accumulation.

    ``h (..., d) @ head (d, V) → f32 logits``. The head weight casts to
    ``h.dtype`` for the dot — the same per-op cast every block matmul
    does (``p["wq"].astype(x.dtype)``); the readout was the one op that
    upcast to f32 instead, and the round-5 xprof attribution measured
    those f32 MXU passes at ~3× the cost (flagship: 2.4 ms of a 14 ms
    step; gpt2m: 4.0 ms) for no numerics the f32 *accumulation* doesn't
    already provide. With f32 activations (every test/parity config)
    the casts are no-ops and this is bit-identical to the f32 matmul.

    The custom VJP keeps the backward dots in the activation dtype too
    (cotangent rounds to ``h.dtype``, matching what the block weight
    grads already do through their bf16 dot outputs) while the head
    gradient accumulates — and is returned — in f32, so the optimizer
    update on the fp32 master weight loses nothing.
    """
    from byteps_tpu.ops.flash_attention import _unify_vma

    hu, hd = _unify_vma(h, head.astype(h.dtype))
    return jax.lax.dot_general(
        hu, hd, (((h.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _head_dot_fwd(h, head):
    return head_dot(h, head), (h, head)


def _head_dot_bwd(res, g):
    # Cotangent vma must match the primals' (shard_map check_vma): the
    # activation grad keeps h's varying axes; the head grad psums over
    # every axis h varies on that head doesn't — exactly the
    # pvary-transpose adjoint plain AD inserts for a replicated weight
    # used in a varying context (cf. the _novma_collective_fix note in
    # jax/optimizer.py).
    from byteps_tpu.ops.flash_attention import _unify_vma

    h, head = res
    gc = g.astype(h.dtype)
    gcu, hd, hu = _unify_vma(gc, head.astype(h.dtype), h)
    dh = jax.lax.dot_general(
        gcu, hd, (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(h.dtype)
    lead = tuple(range(h.ndim - 1))
    dhead = jax.lax.dot_general(
        hu, gcu, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32).astype(head.dtype)
    try:
        extra = tuple(jax.typeof(h).vma - jax.typeof(head).vma)
    except (AttributeError, TypeError):
        extra = ()
    if extra:
        dhead = jax.lax.psum(dhead, extra)
    return dh, dhead


head_dot.defvjp(_head_dot_fwd, _head_dot_bwd)


def _readout(params, h: jnp.ndarray, norm_fn=_layernorm,
             norm_eps: float = 1e-5) -> jnp.ndarray:
    """Final norm → f32-accumulated readout in the activation dtype
    (weight-tied ``wte.T`` unless the tree carries an untied
    ``lm_head``), shared by the dense and pipelined paths so their
    numerics cannot diverge. f32 activations (the default config, every
    parity test, the HF bridge) keep the exact f32 matmul."""
    h = norm_fn(h, params["lnf_g"], params.get("lnf_b"), norm_eps)
    head = (params["lm_head"] if "lm_head" in params
            else params["wte"].T)
    return head_dot(h, head.astype(jnp.float32))


def _nll(logits: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]


def _readout_nll(params, h: jnp.ndarray, targets: jnp.ndarray,
                 norm_fn=_layernorm, norm_eps: float = 1e-5,
                 tp_axis: Optional[str] = None,
                 chunked=True) -> jnp.ndarray:
    """Final norm → per-token next-token NLL, shared by every
    logits-bearing family (GPT dense/pipelined, MoE, T5 decoder).

    ``chunked`` is the tri-state ``chunked_ce`` knob (see
    :func:`gpt_loss`): truthy routes through the fused readout+CE path
    (``ops/chunked_ce.py``) — the f32 (..., V) logits never materialize
    — with ``"vocab_parallel"`` additionally splitting the vocab over
    ``tp_axis`` (V/ntp per device, stats psum'd before the
    log-partition). ``False`` is the dense escape hatch — the
    ``head_dot`` + ``log_softmax`` chain, bit-identical to the chunked
    path on single-device f32 configs and the golden it is pinned
    against."""
    h = norm_fn(h, params["lnf_g"], params.get("lnf_b"), norm_eps)
    head = (params["lm_head"] if "lm_head" in params
            else params["wte"].T).astype(jnp.float32)
    if chunked:
        from byteps_tpu.ops.chunked_ce import chunked_ce_nll

        return chunked_ce_nll(
            h, head, targets,
            tp_axis=tp_axis if chunked == "vocab_parallel" else None)
    return _nll(head_dot(h, head), targets)


def gpt_hidden(params, tokens: jnp.ndarray, cfg: GPTConfig,
               tp_axis: Optional[str] = None,
               sp_axis: Optional[str] = None,
               remat: bool = False,
               seq_layout: str = "contiguous") -> jnp.ndarray:
    """Embeddings → transformer blocks, STOPPING before the final norm +
    readout: the shared trunk of :func:`gpt_forward` (dense logits) and
    :func:`gpt_loss`'s fused readout+CE path (which never materializes
    them)."""
    rope_base = resolve_rope(cfg)
    norm_fn, norm_eps = resolve_norm(cfg)
    x = _embed(params, tokens, cfg, sp_axis, seq_layout)

    def apply_block(x, p):
        return transformer_block(x, p, cfg.head_dim, tp_axis, sp_axis,
                                 causal=True, seq_layout=seq_layout,
                                 rope_base=rope_base, norm_fn=norm_fn,
                                 norm_eps=norm_eps, use_bias=cfg.use_bias)

    # rematerialize per block: activations recomputed in backward — HBM
    # for FLOPs, the long-context lever (see maybe_remat for the tp/sp
    # collective-recompute caveat)
    apply_block = maybe_remat(apply_block, remat)
    for p in params["blocks"]:
        x = apply_block(x, p)
    return x


def gpt_forward(params, tokens: jnp.ndarray, cfg: GPTConfig,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None,
                remat: bool = False,
                seq_layout: str = "contiguous") -> jnp.ndarray:
    """Per-device forward: tokens (B_local, S_local) → logits (f32).

    Single chip: all axes None, tokens are the whole batch/sequence.
    Inside shard_map: tokens are this device's (dp, sp) block and the
    weights its tp shard; output logits stay tp/dp/sp-local (replicated
    over tp by construction).
    """
    x = gpt_hidden(params, tokens, cfg, tp_axis, sp_axis, remat=remat,
                   seq_layout=seq_layout)
    # f32 logits for a stable softmax/loss
    return _readout(params, x, *resolve_norm(cfg))


def gpt_pp_loss(params, tokens, targets, cfg: GPTConfig,
                pp_axis: str, n_micro: int,
                tp_axis: Optional[str] = None,
                sp_axis: Optional[str] = None,
                remat: bool = False,
                vma_axes: tuple = (),
                seq_layout: str = "contiguous",
                chunked_ce=True) -> jnp.ndarray:
    """Pipeline-parallel next-token loss (inside shard_map over pp).
    ``chunked_ce``: the tri-state fused readout+CE knob — see
    :func:`gpt_loss`.

    ``params["blocks"]`` is THIS stage's stacked layer slab
    ((n_layers/pp, ...) — build with ``stack_blocks`` + ``stacked_specs``);
    embeddings / final LN are pp-replicated. The batch is split into
    ``n_micro`` microbatches and pipelined through the stages
    (:func:`byteps_tpu.parallel.pipeline.pipeline_apply`); the last stage
    computes the readout + loss; the returned value is the MASKED per-stage
    loss (nonzero only on the last stage). Differentiate THIS value —
    grading an already-psum'd replica double-counts through the psum
    transpose under ``check_vma=False`` — and replicate it afterwards for
    reporting (``last_stage_value``). Per-device ``jax.grad`` then yields
    stage-local slab grads plus stage-partial grads for the replicated
    leaves (psum those over pp).
    """
    from byteps_tpu.parallel.pipeline import pipeline_apply

    B, S_loc = tokens.shape
    if B % n_micro != 0:
        raise ValueError(f"local batch {B} not divisible by {n_micro} "
                         "microbatches")
    x = _embed(params, tokens, cfg, sp_axis, seq_layout)
    x_mb = x.reshape(n_micro, B // n_micro, S_loc, x.shape[-1])

    rope_base = resolve_rope(cfg)
    norm_fn, norm_eps = resolve_norm(cfg)

    def blk(h, p):
        return transformer_block(
            h, p, cfg.head_dim, tp_axis, sp_axis, causal=True,
            seq_layout=seq_layout, rope_base=rope_base, norm_fn=norm_fn,
            norm_eps=norm_eps, use_bias=cfg.use_bias)

    y_mb = pipeline_apply(x_mb, params["blocks"], blk, pp_axis,
                          remat=remat, vma_axes=vma_axes)
    y = y_mb.reshape(B, S_loc, -1)
    nll = _readout_nll(params, y, targets, norm_fn, norm_eps,
                       tp_axis=tp_axis, chunked=chunked_ce)
    loss = nll.mean()
    if sp_axis is not None:
        # mean over the sequence shards (inside the grad — VMA types the
        # sp pmean's transpose correctly, unlike the pp axis below)
        loss = jax.lax.pmean(loss, sp_axis)
    # only the last stage's outputs are real; other stages' readout math
    # above is masked dead weight (grads through it are zeroed here)
    stage = jax.lax.axis_index(pp_axis)
    nstages = jax.lax.axis_size(pp_axis)
    return jnp.where(stage == nstages - 1, loss, 0.0)


def gpt_loss(params, tokens, targets, cfg: GPTConfig,
             dp_axis: Optional[str] = None,
             tp_axis: Optional[str] = None,
             sp_axis: Optional[str] = None,
             remat: bool = False,
             seq_layout: str = "contiguous",
             chunked_ce=True) -> jnp.ndarray:
    """Mean next-token cross-entropy, identical (replicated) on every device.

    The replication is what makes per-device ``jax.grad`` correct under
    shard_map: tp-sharded weights then need NO gradient collective, while
    dp/sp-replicated weights need a psum over (dp, sp) — exactly the
    aggregation `DistributedOptimizer` / `sync_grads` provide.

    ``chunked_ce`` is tri-state: ``True`` (default) fuses readout+CE so
    the f32 (B, S, V) logits never materialize (``ops/chunked_ce.py``),
    with the vocab replicated over tp — per-device math identical to the
    single-device path, so every cross-mesh equivalence pin holds
    bit-tight. ``"vocab_parallel"`` additionally splits the readout's
    vocab over tp (V/ntp logit columns per device — ntp× less readout
    GEMM and live logits; the tp stat-combine reassociates the sum-exp,
    so dp×tp drifts from dp-only by f32 roundoff — opt in where the
    memory/FLOPs win outweighs cross-mesh bit-parity). ``False`` is the
    dense golden path.
    """
    x = gpt_hidden(params, tokens, cfg, tp_axis, sp_axis, remat=remat,
                   seq_layout=seq_layout)
    nll = _readout_nll(params, x, targets, *resolve_norm(cfg),
                       tp_axis=tp_axis, chunked=chunked_ce)
    loss = nll.mean()
    axes = tuple(a for a in (dp_axis, sp_axis) if a is not None)
    if axes:
        loss = jax.lax.pmean(loss, axes)
    return loss
