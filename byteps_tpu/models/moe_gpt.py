"""MoE GPT: dense attention + Switch-style MoE FFN, expert-parallel over ep.

The sparse-FFN sibling of the flagship dense GPT (models/gpt.py — shared
attention/layernorm/readout code, so the families cannot diverge). Each
block's MLP is replaced by :func:`byteps_tpu.parallel.moe.moe_ffn`: top-1
capacity routing, expert weights stacked on a leading expert axis and
sharded ``P('ep')``, token slots shipped to their expert's owner and back
with ``all_to_all`` over ICI. The Switch load-balancing auxiliary loss is
averaged over layers and added with ``aux_coef``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from byteps_tpu.models.gpt import (
    GPTConfig,
    _attention,
    _embed,
    resolve_norm,
    _readout_nll,
    block_init,
    block_specs,
    resolve_rope,
)
from byteps_tpu.parallel.moe import moe_ffn, moe_init, moe_specs
from byteps_tpu.parallel.remat import maybe_remat


@dataclasses.dataclass(frozen=True)
class MoEGPTConfig(GPTConfig):
    n_experts: int = 8
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    router_topk: int = 1  # 1 = Switch, 2 = GShard-style top-2

    @classmethod
    def tiny(cls) -> "MoEGPTConfig":
        return cls(vocab_size=256, max_seq=64, d_model=64, n_heads=4,
                   n_layers=2, d_ff=128, n_experts=4,
                   capacity_factor=4.0)


def moe_block_init(rng, cfg: MoEGPTConfig):
    """Attention half of a dense block + expert-stacked MoE FFN
    (``cfg.mlp="swiglu"`` = llama-style gated experts)."""
    b = block_init(rng, cfg.d_model, cfg.d_ff,
                   cfg.n_heads * cfg.head_dim, cfg.n_layers,
                   kv_hd=cfg.kv_heads * cfg.head_dim,
                   mlp=cfg.mlp, use_bias=cfg.use_bias, norm=cfg.norm)
    for k in ("w1", "b1", "w2", "b2", "w3", "b3"):
        b.pop(k, None)   # bias keys absent under use_bias=False
    b["moe"] = moe_init(jax.random.fold_in(rng, 99), cfg.d_model,
                        cfg.d_ff, cfg.n_experts, mlp=cfg.mlp)
    return b


def moe_gpt_init(rng, cfg: MoEGPTConfig) -> Dict[str, Any]:
    d = cfg.d_model
    keys = jax.random.split(rng, 2 + cfg.n_layers)
    return {
        "wte": jax.random.normal(keys[0], (cfg.vocab_size, d),
                                 jnp.float32) * 0.02,
        "lnf_g": jnp.ones((d,), jnp.float32),
        **({"wpe": jax.random.normal(keys[1], (cfg.max_seq, d),
                                     jnp.float32) * 0.02}
           if cfg.pos_embedding == "learned" else {}),
        **({"lnf_b": jnp.zeros((d,), jnp.float32)}
           if cfg.norm == "layernorm" else {}),
        "blocks": [moe_block_init(keys[2 + li], cfg)
                   for li in range(cfg.n_layers)],
    }


def moe_block_logical_specs(use_bias: bool = True, norm: str = "layernorm",
                            mlp: str = "gelu"):
    # derive from the dense family's logical tree exactly like
    # moe_block_init derives from block_init, so new attention params
    # cannot diverge
    from byteps_tpu.models.gpt import block_logical_specs
    from byteps_tpu.parallel.moe import moe_logical_specs
    s = block_logical_specs(mlp=mlp, use_bias=use_bias, norm=norm)
    for k in ("w1", "b1", "w2", "b2", "w3", "b3"):
        s.pop(k, None)
    s["moe"] = moe_logical_specs(mlp=mlp)
    return s


def moe_block_specs(ep_axis: Optional[str], tp_axis: Optional[str] = None,
                    use_bias: bool = True, norm: str = "layernorm",
                    mlp: str = "gelu"):
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(
        moe_block_logical_specs(use_bias=use_bias, norm=norm, mlp=mlp),
        rules_from_axes(tp_axis=tp_axis, ep_axis=ep_axis))


def moe_gpt_logical_specs(cfg: MoEGPTConfig) -> Dict[str, Any]:
    return {
        "wte": ("vocab", "embed"), "lnf_g": ("embed",),
        **({"wpe": (None, "embed")} if cfg.pos_embedding == "learned"
           else {}),
        **({"lnf_b": ("embed",)} if cfg.norm == "layernorm" else {}),
        "blocks": [moe_block_logical_specs(use_bias=cfg.use_bias,
                                           norm=cfg.norm, mlp=cfg.mlp)
                   for _ in range(cfg.n_layers)],
    }


def moe_gpt_param_specs(cfg: MoEGPTConfig, ep_axis: Optional[str],
                        tp_axis: Optional[str] = None) -> Dict[str, Any]:
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    return resolve_specs(moe_gpt_logical_specs(cfg),
                         rules_from_axes(tp_axis=tp_axis, ep_axis=ep_axis))


def moe_transformer_block(x, p, cfg: MoEGPTConfig,
                          ep_axis: Optional[str],
                          tp_axis: Optional[str] = None,
                          sp_axis: Optional[str] = None,
                          seq_layout: str = "contiguous"):
    """Pre-LN attention + MoE FFN; returns (x, aux_loss)."""
    norm_fn, norm_eps = resolve_norm(cfg)
    x = x + _attention(norm_fn(x, p["ln1_g"], p.get("ln1_b"), norm_eps), p,
                       cfg.head_dim, tp_axis, sp_axis, causal=True,
                       seq_layout=seq_layout, rope_base=resolve_rope(cfg),
                       use_bias=cfg.use_bias)
    m, aux = moe_ffn(norm_fn(x, p["ln2_g"], p.get("ln2_b"), norm_eps), p["moe"],
                     cfg.capacity_factor, ep_axis,
                     router_topk=cfg.router_topk, tp_axis=tp_axis)
    return x + m, aux


def moe_gpt_loss(params, tokens, targets, cfg: MoEGPTConfig,
                 ep_axis: Optional[str] = None,
                 tp_axis: Optional[str] = None,
                 sp_axis: Optional[str] = None,
                 remat: bool = False,
                 seq_layout: str = "contiguous",
                 chunked_ce=True) -> jnp.ndarray:
    """Per-device next-token loss + Switch aux loss (local mean over this
    device's tokens, pmean'd over sequence shards — dp/ep averaging is
    the train step's job)."""
    x = _embed(params, tokens, cfg, sp_axis, seq_layout)
    aux_total = jnp.zeros((), jnp.float32)

    def apply_block(x, p):
        return moe_transformer_block(x, p, cfg, ep_axis, tp_axis, sp_axis,
                                     seq_layout)

    apply_block = maybe_remat(apply_block, remat)
    for p in params["blocks"]:
        x, aux = apply_block(x, p)
        aux_total = aux_total + aux
    nll = _readout_nll(params, x, targets, *resolve_norm(cfg),
                       tp_axis=tp_axis, chunked=chunked_ce)
    loss = nll.mean() + cfg.aux_coef * aux_total / cfg.n_layers
    if sp_axis is not None:
        loss = jax.lax.pmean(loss, sp_axis)
    return loss


def moe_gpt_pp_loss(params, tokens, targets, cfg: MoEGPTConfig,
                    pp_axis: str, n_micro: int,
                    ep_axis: Optional[str] = None,
                    tp_axis: Optional[str] = None,
                    sp_axis: Optional[str] = None,
                    remat: bool = False,
                    vma_axes: tuple = (),
                    seq_layout: str = "contiguous",
                    chunked_ce=True) -> jnp.ndarray:
    """Pipelined MoE loss (inside shard_map over pp): ``params["blocks"]``
    is THIS stage's stacked MoE-block slab. Same conventions as
    ``gpt_pp_loss`` — the returned scalar is per-device (masked nll on the
    last stage + this stage's own aux term); never psum it over pp inside
    the grad."""
    from byteps_tpu.parallel.pipeline import pipeline_apply

    B, S_loc = tokens.shape
    if B % n_micro != 0:
        raise ValueError(f"local batch {B} not divisible by {n_micro} "
                         "microbatches")
    x = _embed(params, tokens, cfg, sp_axis, seq_layout)
    x_mb = x.reshape(n_micro, B // n_micro, S_loc, x.shape[-1])

    def blk(h, p):
        return moe_transformer_block(h, p, cfg, ep_axis, tp_axis, sp_axis,
                                     seq_layout)

    y_mb, aux_total = pipeline_apply(
        x_mb, params["blocks"], blk, pp_axis,
        remat=remat, vma_axes=vma_axes, has_aux=True,
    )
    y = y_mb.reshape(B, S_loc, -1)
    nll = _readout_nll(params, y, targets, *resolve_norm(cfg),
                       tp_axis=tp_axis, chunked=chunked_ce).mean()
    stage = jax.lax.axis_index(pp_axis)
    nstages = jax.lax.axis_size(pp_axis)
    masked_nll = jnp.where(stage == nstages - 1, nll, 0.0)
    # aux_total covers THIS stage's layers x all M microbatches; every
    # (layer, microbatch) is counted once across the stages, so the
    # per-device terms sum to the model-wide per-layer mean the dense
    # family uses
    aux_term = cfg.aux_coef * aux_total / (cfg.n_layers * n_micro)
    total = masked_nll + aux_term
    if sp_axis is not None:
        # pmean the WHOLE per-device scalar over sp — pmeaning only the
        # nll would leave the aux term's sp-summed cotangents unscaled,
        # multiplying the load-balancing gradient by sp_size
        total = jax.lax.pmean(total, sp_axis)
    return total
