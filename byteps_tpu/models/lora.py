"""LoRA adapters for the GPT family — fine-tune with only adapter
gradients on the aggregation tier.

The reference aggregates EVERY gradient byte on its PS tier each step;
for fine-tuning, low-rank adaptation shrinks the trainable surface (and
with it the DCN/ICI gradient traffic) by orders of magnitude while the
frozen base never moves. Pairs with the HF bridge
(``models/import_hf.py``): import a checkpoint, LoRA-finetune it under
compressed dp aggregation, merge and export.

Design (TPU-first, functional like everything in ``models/``):

* Adapters live in their own pytree — ``{"blocks": [{target: {"a", "b"}
  ...}]}`` — which is the ONLY tree the optimizer and the gradient
  aggregation ever see. The frozen base is an explicit input to the
  jitted step (no stale closure constants, resharding stays possible).
* The forward grafts each block's adapters into the block dict under a
  ``"lora"`` key (with the ``alpha/rank`` scale pre-multiplied into
  ``b`` at graft time — optimizer state stays on the unscaled leaves);
  ``_attention`` / ``_mlp`` add ``(x @ a) @ b`` beside the frozen
  matmul. Two thin matmuls — the ``(d, d)`` delta is never
  materialized in training.
* Tensor parallelism: for column-parallel targets (wq/wk/wv/w1/w3)
  ``a`` is replicated and ``b`` column-sharded, so the adapter path
  needs NO extra collective. For row-parallel targets (wo/w2) ``a`` is
  row-sharded and the tiny ``(B, S, r)`` intermediate is psum'd —
  r/d_model the bytes of the base path's existing psum.
* ``b`` initializes to zero (standard LoRA): step 0 reproduces the
  frozen model exactly, which the tests pin.
* ``merge_lora`` folds ``w + scale * a @ b`` once for inference/export
  — the merged tree is a plain GPT tree (decode kernels, HF export,
  checkpointing all apply unchanged).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from byteps_tpu.models.gpt import GPTConfig

# target -> (in_dim attr, out_dim attr, orientation)
_COL_TARGETS = ("wq", "wk", "wv", "w1", "w3")
_ROW_TARGETS = ("wo", "w2")
ALL_TARGETS = _COL_TARGETS + _ROW_TARGETS


def _target_dims(cfg: GPTConfig, name: str) -> Tuple[int, int]:
    d, ff = cfg.d_model, cfg.d_ff
    hd = cfg.n_heads * cfg.head_dim
    kv_hd = cfg.kv_heads * cfg.head_dim
    return {
        "wq": (d, hd), "wk": (d, kv_hd), "wv": (d, kv_hd),
        "wo": (hd, d), "w1": (d, ff), "w3": (d, ff), "w2": (ff, d),
    }[name]


def _check_targets(cfg: GPTConfig, targets: Sequence[str]) -> Tuple[str, ...]:
    targets = tuple(targets)
    if not targets:
        raise ValueError("LoRA needs at least one target projection")
    for t in targets:
        if t not in ALL_TARGETS:
            raise ValueError(f"unknown LoRA target {t!r} — expected a "
                             f"subset of {ALL_TARGETS}")
        if t == "w3" and cfg.mlp != "swiglu":
            raise ValueError("target 'w3' needs mlp='swiglu'")
    return targets


def lora_init(rng, cfg: GPTConfig, rank: int,
              targets: Sequence[str] = ("wq", "wv")) -> Dict[str, Any]:
    """Adapter pytree: per block, per target, ``a ~ N(0, 1/rank)`` and
    ``b = 0`` — the grafted model starts exactly at the frozen base."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1; got {rank}")
    targets = _check_targets(cfg, targets)
    keys = jax.random.split(rng, cfg.n_layers)

    def one_block(key):
        ks = jax.random.split(key, len(targets))
        blk = {}
        for t, k in zip(targets, ks):
            d_in, d_out = _target_dims(cfg, t)
            blk[t] = {
                "a": jax.random.normal(k, (d_in, rank), jnp.float32)
                / (rank ** 0.5),
                "b": jnp.zeros((rank, d_out), jnp.float32),
            }
        return blk

    return {"blocks": [one_block(k) for k in keys]}


def lora_param_specs(cfg: GPTConfig, tp_axis: Optional[str], rank: int,
                     targets: Sequence[str] = ("wq", "wv")
                     ) -> Dict[str, Any]:
    """PartitionSpecs mirroring :func:`lora_init`: column-parallel
    targets shard ``b``'s output dim over tp (no extra collective);
    row-parallel targets shard ``a``'s input dim (the (B,S,r)
    intermediate is psum'd in the forward)."""
    from byteps_tpu.parallel.partitioner import resolve_specs, rules_from_axes
    targets = _check_targets(cfg, targets)

    def logical(t):
        if t in _COL_TARGETS:
            return {"a": ("embed", None), "b": (None, "heads")}
        return {"a": ("heads", None), "b": (None, "embed")}

    tree = {"blocks": [{t: logical(t) for t in targets}
                       for _ in range(cfg.n_layers)]}
    return resolve_specs(tree, rules_from_axes(tp_axis=tp_axis))


def graft_lora(base_params: Dict[str, Any], adapters: Dict[str, Any],
               scale: float) -> Dict[str, Any]:
    """Frozen base + adapters → the tree the forward consumes: each
    block carries a ``"lora"`` sub-dict with the scale pre-multiplied
    into ``b`` (optimizer state stays on the unscaled adapter tree).
    Pure and cheap (scaling fuses into the step's XLA program)."""
    blocks = []
    for bp, ad in zip(base_params["blocks"], adapters["blocks"]):
        blk = dict(bp)
        blk["lora"] = {
            t: {"a": ab["a"], "b": ab["b"] * scale}
            for t, ab in ad.items()
        }
        blocks.append(blk)
    out = dict(base_params)
    out["blocks"] = blocks
    return out


@jax.custom_jvp
def _fence(xs):
    """``optimization_barrier`` with a differentiation rule (this jax
    has none built in): identity forward, tangents pass straight
    through. The barrier only pins compiler scheduling/fusion — there
    is nothing to differentiate."""
    return jax.lax.optimization_barrier(xs)


@_fence.defjvp
def _fence_jvp(primals, tangents):
    (xs,), (ts,) = primals, tangents
    return jax.lax.optimization_barrier(xs), ts


def lora_delta(x: jnp.ndarray, p: Dict[str, Any], name: str,
               tp_axis: Optional[str] = None) -> jnp.ndarray:
    """``scale * (x @ a) @ b`` for one target, or 0.0 when the block
    carries no adapter for it. For row-parallel targets inside a tp
    shard_map, the thin ``(..., r)`` intermediate is psum'd — the
    base matmul's own psum runs separately (both are linear, but the
    base helper adds its bias after ITS psum, so the two terms stay
    independent)."""
    lr = p.get("lora")
    if lr is None or name not in lr:
        return jnp.zeros((), x.dtype)
    a = lr[name]["a"].astype(x.dtype)
    b = lr[name]["b"].astype(x.dtype)
    # barrier-fence the thin dot pair: the rank-r dots are small enough
    # that XLA folds them into whatever fusion surrounds them, and the
    # chosen loop shape (hence accumulation order) varies with the
    # CONSUMER — the same delta can differ by 1 ulp between two
    # programs. The fences pin an isolated, context-independent island,
    # which is what lets the serve tier's segmented twin
    # (ops/segmented_lora.py) reproduce this delta BIT-exactly from its
    # packed step. Numerically the barrier is identity; AD passes
    # through.
    x, a, b = _fence((x, a, b))
    h = x @ a
    if name in _ROW_TARGETS and tp_axis is not None:
        h = jax.lax.psum(h, tp_axis)
    return _fence(h @ b)


def lora_rank(adapters: Dict[str, Any]) -> int:
    """The adapter tree's rank (every target shares one by
    construction of :func:`lora_init`)."""
    blk = adapters["blocks"][0]
    first = next(iter(blk.values()))
    return int(first["a"].shape[-1])


def lora_pool_slabs(adapters: Dict[str, Any], cfg: GPTConfig,
                    rank_bucket: int, scale: float,
                    targets: Sequence[str]) -> Dict[str, Any]:
    """Pool-loadable A/B slabs for ONE adapter — the serve tier's
    :class:`~byteps_tpu.serve.adapter_pool.AdapterPool` stacks these
    into its device-resident slot arrays.

    Per target: ``a (n_layers, d_in, rank_bucket)`` and ``b
    (n_layers, rank_bucket, d_out)`` float32, rank-padded with zeros
    (a zero A column times a zero B row contributes exactly 0.0 to the
    delta, so mixed-rank tenants share one compiled packed step without
    touching the math) and with ``scale`` pre-multiplied into ``b`` —
    the same ``b * scale`` arithmetic :func:`graft_lora` performs, so
    the pooled delta is bit-identical to the solo grafted one. The
    adapter must carry every requested target (a pooled row can't
    distinguish "no adapter" from "no target"; register base-model
    tenants with no adapter instead)."""
    targets = _check_targets(cfg, targets)
    r = lora_rank(adapters)
    if r > rank_bucket:
        raise ValueError(
            f"adapter rank {r} exceeds the pool's rank bucket "
            f"{rank_bucket}")
    out: Dict[str, Any] = {}
    for t in targets:
        d_in, d_out = _target_dims(cfg, t)
        a_l, b_l = [], []
        for blk in adapters["blocks"]:
            if t not in blk:
                raise ValueError(
                    f"adapter is missing pool target {t!r} — the pool's "
                    "targets must be a subset of every registered "
                    "adapter's")
            ab = blk[t]
            a = jnp.zeros((d_in, rank_bucket), jnp.float32)
            a = a.at[:, :r].set(ab["a"].astype(jnp.float32))
            b = jnp.zeros((rank_bucket, d_out), jnp.float32)
            # multiply in the adapter's own dtype first (graft_lora's
            # exact arithmetic), THEN upcast losslessly for storage
            b = b.at[:r, :].set((ab["b"] * scale).astype(jnp.float32))
            a_l.append(a)
            b_l.append(b)
        out[t] = {"a": jnp.stack(a_l), "b": jnp.stack(b_l)}
    return out


def merge_lora(base_params: Dict[str, Any], adapters: Dict[str, Any],
               scale: float) -> Dict[str, Any]:
    """Fold the adapters into plain GPT weights: ``w + scale * a @ b``
    per target. The result is a standard tree — decode, checkpointing,
    and HF export apply unchanged."""
    blocks = []
    for bp, ad in zip(base_params["blocks"], adapters["blocks"]):
        blk = dict(bp)
        for t, ab in ad.items():
            blk[t] = (blk[t].astype(jnp.float32)
                      + scale * ab["a"] @ ab["b"]).astype(bp[t].dtype)
        blocks.append(blk)
    out = dict(base_params)
    out["blocks"] = blocks
    return out
