"""byteps_tpu.server — the DCN-tier parameter server (summation service).

Reference analogs: ``byteps/server/server.{h,cc}`` (the service itself,
started by ``import byteps.server`` from the launcher) and the worker-side
``ps::KVWorker`` usage in ``byteps/common/core_loops.cc`` PUSH/PULL stages.

Topology: ``DMLC_NUM_SERVER`` summation servers listen on
``DMLC_PS_ROOT_PORT + 1 + server_id`` (all on ``DMLC_PS_ROOT_URI`` in the
localhost test topology; one per aggregator host in a real deployment).
Partition keys are assigned to servers by ``key % num_server`` — the
reference's key→server hash placement. There is no separate scheduler
process: ``jax.distributed`` (or the launcher) does rendezvous, which is the
TPU-native simplification of ps-lite's scheduler node (SURVEY §5.8).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from byteps_tpu.common.config import Config, get_config
from byteps_tpu.common.logging import get_logger
from byteps_tpu.server.native import NativeClient, load_lib, reduce_sum_f32

log = get_logger("server")

__all__ = [
    "start_server", "stop_server", "serve_forever", "server_addresses",
    "PSWorker", "reduce_sum_f32",
]


def server_addresses(cfg: Optional[Config] = None) -> List[Tuple[str, int]]:
    cfg = cfg or get_config()
    num = max(1, cfg.num_server)
    return [(cfg.ps_root_uri, cfg.ps_root_port + 1 + i) for i in range(num)]


def start_server(
    port: Optional[int] = None,
    num_workers: Optional[int] = None,
    engine_threads: Optional[int] = None,
    async_mode: Optional[bool] = None,
    server_id: int = 0,
) -> int:
    """Start the native summation service in this process (non-blocking)."""
    cfg = get_config()
    lib = load_lib()
    port = port if port is not None else cfg.ps_root_port + 1 + server_id
    rc = lib.bps_server_start(
        port,
        num_workers if num_workers is not None else cfg.num_worker,
        engine_threads if engine_threads is not None
        else cfg.server_engine_threads,
        1 if (async_mode if async_mode is not None else cfg.enable_async)
        else 0,
    )
    if rc != 0:
        raise RuntimeError(f"bps_server_start failed (rc={rc}, port={port})")
    log.info("summation server listening on :%d", port)
    return port


def stop_server() -> None:
    load_lib().bps_server_stop()


def serve_forever(server_id: Optional[int] = None) -> None:
    """Launcher entry for the server role: start and block until all workers
    shut down (reference: ``import byteps.server`` → ``StartPS`` blocks)."""
    import os

    sid = (
        server_id if server_id is not None
        else int(os.environ.get("DMLC_SERVER_ID", "0"))
    )
    start_server(server_id=sid)
    load_lib().bps_server_wait()
    log.info("summation server stopped")


class PSWorker:
    """Worker-side facade: key→server placement, per-key round tracking,
    connection-per-thread for pipelined push/pull.

    Each OS thread (one per scheduler pool slot) gets its own serial
    connection to each server, so a pull blocked on a slow round never
    stalls another partition's push — the deadlock-freedom argument of the
    reference's separate PUSH/PULL core loops.
    """

    def __init__(
        self,
        servers: Optional[Sequence[Tuple[str, int]]] = None,
        timeout_ms: int = 60000,
    ):
        self._servers = list(servers) if servers else server_addresses()
        self._timeout = timeout_ms
        self._tls = threading.local()
        self._versions: Dict[int, int] = {}
        self._vlock = threading.Lock()
        self._all_conns: List[NativeClient] = []
        self._conn_lock = threading.Lock()

    # -- connection management ----------------------------------------------
    def _conn(self, sidx: int) -> NativeClient:
        pool = getattr(self._tls, "conns", None)
        if pool is None:
            pool = {}
            self._tls.conns = pool
        c = pool.get(sidx)
        if c is None:
            host, port = self._servers[sidx]
            c = NativeClient(host, port, self._timeout)
            pool[sidx] = c
            with self._conn_lock:
                self._all_conns.append(c)
        return c

    def server_for(self, key: int) -> int:
        return key % len(self._servers)

    # -- data plane ---------------------------------------------------------
    def init_key(self, key: int, nbytes: int) -> None:
        self._conn(self.server_for(key)).init_key(key, nbytes)

    def push(self, key: int, data: np.ndarray) -> int:
        """Push this worker's fp32 partition; returns the round number the
        matching pull must wait for."""
        data = np.ascontiguousarray(data, dtype=np.float32)
        with self._vlock:
            version = self._versions.get(key, 0) + 1
            self._versions[key] = version
        self._conn(self.server_for(key)).push(key, data)
        return version

    def pull(self, key: int, nelems: int, version: int) -> np.ndarray:
        out = np.empty(nelems, np.float32)
        self._conn(self.server_for(key)).pull(key, out, version)
        return out

    def push_pull(self, key: int, data: np.ndarray) -> np.ndarray:
        v = self.push(key, data)
        return self.pull(key, data.size, v)

    def barrier(self) -> None:
        """Global worker barrier through server 0 (reference: ps-lite
        Postoffice::Barrier via the scheduler)."""
        self._conn(0).barrier()

    def shutdown(self) -> None:
        """Tell every server this worker is done (server exits once all
        workers said so), then drop connections."""
        done = set()
        with self._conn_lock:
            conns = list(self._all_conns)
            self._all_conns.clear()
        # one shutdown per server (not per connection): servers count
        # shutdowns against DMLC_NUM_WORKER
        for sidx in range(len(self._servers)):
            try:
                self._conn(sidx)  # ensure a conn exists on this thread
            except ConnectionError:
                continue
        pool = getattr(self._tls, "conns", {})
        for sidx, c in pool.items():
            if sidx not in done:
                try:
                    c.shutdown()
                    done.add(sidx)
                except Exception:  # noqa: BLE001
                    pass
        for c in conns:
            c.close()
        self._tls.conns = {}
