"""byteps_tpu.server — the DCN-tier parameter server (summation service).

Reference analogs: ``byteps/server/server.{h,cc}`` (the service itself,
started by ``import byteps.server`` from the launcher) and the worker-side
``ps::KVWorker`` usage in ``byteps/common/core_loops.cc`` PUSH/PULL stages.

Topology: ``DMLC_NUM_SERVER`` summation servers listen on
``DMLC_PS_ROOT_PORT + 1 + server_id`` (all on ``DMLC_PS_ROOT_URI`` in the
localhost test topology; one per aggregator host in a real deployment).
Partition keys are assigned to servers by ``key % num_server`` — the
reference's key→server hash placement. There is no separate scheduler
process: ``jax.distributed`` (or the launcher) does rendezvous, which is the
TPU-native simplification of ps-lite's scheduler node (SURVEY §5.8).

Pushes and pulls carry a wire-codec id (``compression/wire.py`` formats):
the server decompresses each push into an fp32 accumulator and re-compresses
round results for compressed pulls — the reference server's
decompress→sum→recompress engine (SURVEY §2.2/§3.3).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from byteps_tpu.common.config import Config, get_config
from byteps_tpu.common.logging import get_logger
from byteps_tpu.server.native import (
    WIRE_RAW,
    NativeClient,
    load_lib,
    reduce_sum_f32,
)
from byteps_tpu.server.pacer import DcnPacer, pacer_from_mbps

log = get_logger("server")

__all__ = [
    "start_server", "stop_server", "serve_forever", "server_addresses",
    "PSWorker", "reduce_sum_f32", "DcnPacer",
]


def server_addresses(cfg: Optional[Config] = None) -> List[Tuple[str, int]]:
    cfg = cfg or get_config()
    num = max(1, cfg.num_server)
    return [(cfg.ps_root_uri, cfg.ps_root_port + 1 + i) for i in range(num)]


# server_id of the summation service running in THIS process, if any —
# lets PSWorker route that server's keys through the in-process fast path
# (BYTEPS_ENABLE_IPC) instead of TCP loopback.
_INPROC_SERVER_ID: Optional[int] = None


def start_server(
    port: Optional[int] = None,
    num_workers: Optional[int] = None,
    engine_threads: Optional[int] = None,
    async_mode: Optional[bool] = None,
    server_id: int = 0,
    pull_timeout_ms: Optional[int] = None,
    enable_schedule: Optional[bool] = None,
) -> int:
    """Start the native summation service in this process (non-blocking)."""
    global _INPROC_SERVER_ID
    cfg = get_config()
    lib = load_lib()
    port = port if port is not None else cfg.ps_root_port + 1 + server_id
    rc = lib.bps_server_start(
        port,
        num_workers if num_workers is not None else cfg.num_worker,
        engine_threads if engine_threads is not None
        else cfg.server_engine_threads,
        1 if (async_mode if async_mode is not None else cfg.enable_async)
        else 0,
        pull_timeout_ms if pull_timeout_ms is not None
        else cfg.pull_timeout_ms,
        server_id,
        1 if (enable_schedule if enable_schedule is not None
              else cfg.server_enable_schedule) else 0,
    )
    if rc != 0:
        raise RuntimeError(f"bps_server_start failed (rc={rc}, port={port})")
    _INPROC_SERVER_ID = server_id
    if cfg.trace_on:
        lib.bps_server_trace_enable(1)
    log.info("summation server listening on :%d", port)
    return port


def stop_server() -> None:
    global _INPROC_SERVER_ID
    load_lib().bps_server_stop()
    _INPROC_SERVER_ID = None


def dump_server_trace(path: str) -> int:
    """Write the server's chrome trace JSON; returns event count."""
    return load_lib().bps_server_trace_dump(path.encode())


def serve_forever(server_id: Optional[int] = None) -> None:
    """Launcher entry for the server role: start and block until all workers
    shut down (reference: ``import byteps.server`` → ``StartPS`` blocks)."""
    import os

    cfg = get_config()
    sid = (
        server_id if server_id is not None
        else int(os.environ.get("DMLC_SERVER_ID", "0"))
    )
    global _INPROC_SERVER_ID
    start_server(server_id=sid)
    load_lib().bps_server_wait()
    # the native server stopped (worker-driven shutdown); make sure no
    # later PSWorker(use_ipc=True) in this process routes into its leaked
    # store (the native Local* entries also refuse once stopped)
    _INPROC_SERVER_ID = None
    if cfg.trace_on:
        os.makedirs(cfg.trace_dir, exist_ok=True)
        path = os.path.join(cfg.trace_dir, f"trace_server{sid}.json")
        n = dump_server_trace(path)
        log.info("dumped %d server trace events to %s", n, path)
    log.info("summation server stopped")


class PSWorker:
    """Worker-side facade: key→server placement, per-key round tracking,
    connection-per-thread for pipelined push/pull, wire-byte accounting.

    Each OS thread (one per scheduler pool slot) gets its own serial
    connection to each server, so a pull blocked on a slow round never
    stalls another partition's push — the deadlock-freedom argument of the
    reference's separate PUSH/PULL core loops.

    With ``BYTEPS_ENABLE_IPC`` and a summation server running in THIS
    process (joint role), pushes/pulls for locally-owned keys skip TCP and
    access the store directly (the reference's colocated shared-memory
    fast path, ps-lite ``BYTEPS_ENABLE_IPC``).

    With ``BYTEPS_DCN_THROTTLE_MBPS`` > 0 (or ``throttle_mbps=``), this
    worker's payload bytes are paced through an emulated full-duplex NIC
    of that speed (``server/pacer.py``) — the bandwidth-throttled bench
    and the compression fast-lane regime. The pacer is per-PSWorker, so
    several workers emulated in one process each get their own NIC.
    """

    def __init__(
        self,
        servers: Optional[Sequence[Tuple[str, int]]] = None,
        timeout_ms: int = 60000,
        recv_timeout_ms: int = 120000,
        worker_id: Optional[int] = None,
        use_ipc: Optional[bool] = None,
        throttle_mbps: Optional[float] = None,
    ):
        cfg = get_config()
        self._servers = list(servers) if servers else server_addresses()
        self._timeout = timeout_ms
        self._recv_timeout = recv_timeout_ms
        self._worker_id = (
            worker_id if worker_id is not None else cfg.worker_id
        )
        self._tls = threading.local()
        self._versions: Dict[int, int] = {}
        self._vlock = threading.Lock()
        self._all_conns: List[NativeClient] = []
        self._conn_lock = threading.Lock()
        self._closed = False
        # wire accounting (compression tests / docs assert against these)
        self.bytes_pushed = 0
        self.bytes_pulled = 0
        self._ipc = (
            use_ipc if use_ipc is not None else cfg.enable_ipc
        ) and _INPROC_SERVER_ID is not None
        self.pacer: Optional[DcnPacer] = pacer_from_mbps(
            throttle_mbps if throttle_mbps is not None
            else cfg.dcn_throttle_mbps
        )

    # -- connection management ----------------------------------------------
    def _conn(self, sidx: int) -> NativeClient:
        pool = getattr(self._tls, "conns", None)
        if pool is None:
            pool = {}
            self._tls.conns = pool
        c = pool.get(sidx)
        if c is not None and c.is_dead():
            # a timeout/desync killed the socket (native side closes it so
            # no stale frame can be misread); evict so this thread's next
            # op reconnects instead of failing rc=-2 forever
            self._evict(sidx, c)
            c = None
        if c is None:
            if self._closed:
                raise RuntimeError("PSWorker is shut down")
            host, port = self._servers[sidx]
            c = NativeClient(host, port, self._timeout, self._recv_timeout)
            pool[sidx] = c
            with self._conn_lock:
                self._all_conns.append(c)
        return c

    def _evict(self, sidx: int, c: NativeClient) -> None:
        pool = getattr(self._tls, "conns", {})
        if pool.get(sidx) is c:
            del pool[sidx]
        with self._conn_lock:
            try:
                self._all_conns.remove(c)
            except ValueError:
                pass
        c.close()

    def server_for(self, key: int) -> int:
        return key % len(self._servers)

    def _is_local(self, sidx: int) -> bool:
        return self._ipc and sidx == _INPROC_SERVER_ID

    # -- data plane ---------------------------------------------------------
    def init_key(self, key: int, nbytes: int) -> None:
        sidx = self.server_for(key)
        if self._is_local(sidx):
            rc = load_lib().bps_local_init(key, nbytes)
            if rc != 0:
                raise RuntimeError(f"local init failed (rc={rc})")
            return
        self._conn(sidx).init_key(key, nbytes)

    def push_bytes(self, key: int, buf: np.ndarray,
                   codec: int = WIRE_RAW) -> int:
        """Push codec-encoded bytes; returns the round number the matching
        pull must wait for."""
        with self._vlock:
            version = self._versions.get(key, 0) + 1
            self._versions[key] = version
        if self.pacer is not None:
            # book the payload's transmission time on the emulated NIC
            # BEFORE the wire op — upstream bandwidth leaves this worker
            # at the paced rate (applies to the IPC path too: colocated
            # deployments being modeled still cross a NIC pod-to-pod)
            self.pacer.throttle_send(int(np.asarray(buf).nbytes))
        sidx = self.server_for(key)
        if self._is_local(sidx):
            b = np.ascontiguousarray(buf)
            rc = load_lib().bps_local_push(
                self._worker_id, key, codec,
                b.ctypes.data, b.nbytes,
            )
            if rc != 0:
                raise RuntimeError(f"local push failed (rc={rc})")
        else:
            self._conn(sidx).push(key, buf, codec, self._worker_id)
        with self._vlock:
            self.bytes_pushed += int(np.asarray(buf).nbytes)
        return version

    def pull_bytes(self, key: int, capacity: int, version: int,
                   codec: int = WIRE_RAW) -> np.ndarray:
        """Pull the round result as codec-encoded bytes."""
        out = np.empty(capacity, np.uint8)
        sidx = self.server_for(key)
        if self._is_local(sidx):
            got = load_lib().bps_local_pull(
                key, codec, version, self._recv_timeout,
                out.ctypes.data, out.nbytes,
            )
            if got < 0:
                raise RuntimeError(f"local pull failed (rc={got})")
        else:
            got = self._conn(sidx).pull(key, out, version, codec)
        if self.pacer is not None:
            # book the response's transmission time (downstream direction)
            self.pacer.throttle_recv(int(got))
        with self._vlock:
            self.bytes_pulled += int(got)
        return out[:got]

    def push(self, key: int, data: np.ndarray) -> int:
        """Push this worker's fp32 partition (raw wire)."""
        data = np.ascontiguousarray(data, dtype=np.float32)
        return self.push_bytes(key, data.view(np.uint8).ravel(), WIRE_RAW)

    def pull(self, key: int, nelems: int, version: int) -> np.ndarray:
        buf = self.pull_bytes(key, nelems * 4, version, WIRE_RAW)
        # view, not copy: pull_bytes allocated the buffer for this call, so
        # the caller owns it — the copy was a full extra pass per partition
        return buf.view(np.float32)

    def push_pull(self, key: int, data: np.ndarray) -> np.ndarray:
        v = self.push(key, data)
        return self.pull(key, data.size, v)

    def barrier(self) -> None:
        """Global worker barrier through server 0 (reference: ps-lite
        Postoffice::Barrier via the scheduler)."""
        self._conn(0).barrier()

    def ping(self, sidx: int = 0) -> Tuple[int, int]:
        """(server CLOCK_REALTIME ns, rtt ns) for clock alignment of merged
        worker/server traces (SURVEY §5.1 dPRO clock-offset capability)."""
        return self._conn(sidx).ping()

    def clock_offset_ns(self, sidx: int = 0) -> int:
        """Estimated server_clock − local_clock in ns (RTT/2 method)."""
        import time

        server_ns, rtt = self.ping(sidx)
        return server_ns + rtt // 2 - time.time_ns()

    def shutdown(self) -> None:
        """Tell every server this worker is done (server exits once all
        workers said so), then drop connections."""
        if self._closed:
            return
        self._closed = True
        # one shutdown per server (not per connection): servers count
        # shutdowns against DMLC_NUM_WORKER. Use this thread's pool
        # (creating connections as needed), then close EVERY connection
        # ever created — snapshot taken after the shutdown round so none
        # created during it escape.
        pool = getattr(self._tls, "conns", {})
        for sidx in range(len(self._servers)):
            try:
                c = pool.get(sidx)
                if c is not None and c.is_dead():
                    c = None  # killed socket cannot carry the kShutdown —
                    # send it on a fresh connection or the server's
                    # shutdown count never completes and serve_forever hangs
                if c is None:
                    host, port = self._servers[sidx]
                    c = NativeClient(host, port, 2000, self._recv_timeout)
                    with self._conn_lock:
                        self._all_conns.append(c)
                c.shutdown()
            except Exception:  # noqa: BLE001 - server may already be gone
                pass
        with self._conn_lock:
            conns = list(self._all_conns)
            self._all_conns.clear()
        for c in conns:
            c.close()
        self._tls.conns = {}
