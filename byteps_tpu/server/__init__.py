"""byteps_tpu.server — the DCN-tier parameter server (summation service).

Reference analogs: ``byteps/server/server.{h,cc}`` (the service itself,
started by ``import byteps.server`` from the launcher) and the worker-side
``ps::KVWorker`` usage in ``byteps/common/core_loops.cc`` PUSH/PULL stages.

Topology: ``DMLC_NUM_SERVER`` summation servers listen on
``DMLC_PS_ROOT_PORT + 1 + server_id`` (all on ``DMLC_PS_ROOT_URI`` in the
localhost test topology; one per aggregator host in a real deployment).
Partition keys are assigned to servers by ``key % num_server`` — the
reference's key→server hash placement. There is no separate scheduler
process: ``jax.distributed`` (or the launcher) does rendezvous, which is the
TPU-native simplification of ps-lite's scheduler node (SURVEY §5.8).

Pushes and pulls carry a wire-codec id (``compression/wire.py`` formats):
the server decompresses each push into an fp32 accumulator and re-compresses
round results for compressed pulls — the reference server's
decompress→sum→recompress engine (SURVEY §2.2/§3.3).
"""

from __future__ import annotations

import itertools
import random
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from byteps_tpu.common.config import Config, get_config
from byteps_tpu.common.faults import (
    FaultPlan,
    InjectedConnectionError,
    InjectedTimeout,
    ServerDownError,
    WorkerKilledError,
    plan_from_env,
)
from byteps_tpu.common.flight_recorder import get_flight_recorder
from byteps_tpu.common.logging import get_logger
from byteps_tpu.common.metrics import get_registry
from byteps_tpu.common.tracing import get_tracer
from byteps_tpu.server.native import (
    WIRE_RAW,
    NativeClient,
    WireCorruption,
    WorkerEvictedError,
    load_lib,
    reduce_sum_f32,
)
from byteps_tpu.server.pacer import DcnPacer, pacer_from_mbps

log = get_logger("server")

__all__ = [
    "start_server", "start_server_any_port", "stop_server",
    "serve_forever", "server_addresses",
    "PSWorker", "reduce_sum_f32", "DcnPacer", "FailedOverError",
    "NoLiveServersError", "WireCorruption", "WorkerEvictedError",
    "WorkerKilledError", "wire_crc32",
]


# Per-key rows the C++ summation server's own chrome trace emits
# (declared in the light stage_orders module so trace_analysis can
# learn the display order without importing the data plane).
from byteps_tpu.common.stage_orders import SERVER_STAGE_ORDER  # noqa: F401,E402

# Sequential id per PSWorker instance: each emulated NIC gets its own
# per-NIC metric series (wire.nic<N>.*) beside the process aggregates.
_NIC_SEQ = itertools.count()

# Per-server epochs of (epoch -> live count) divisor history retained in
# PSWorker._epoch_live: under churn every membership change adds an entry
# forever, so entries older than the newest adopted epoch minus this
# window are pruned (a response for a round >window epochs stale falls
# back to the currently adopted live count — by then the round snapshot
# itself has long been overwritten).
_EPOCH_LIVE_WINDOW = 64


def wire_crc32(buf) -> int:
    """CRC32 as carried in the frame header: 0 means 'unchecked', so the
    one-in-2^32 payload whose true CRC is 0 maps to 1 (the C++ side's
    wire_crc applies the identical adjustment)."""
    c = zlib.crc32(buf) & 0xFFFFFFFF
    return c if c != 0 else 1


class FailedOverError(RuntimeError):
    """The key's server placement changed (failover) while this op was in
    flight; its round numbering is gone. Not retryable at the wire level —
    the *stage* retry re-runs the op, which re-derives version and target
    against the post-failover topology."""


class NoLiveServersError(ConnectionError):
    """Every summation server is marked dead. Excluded from the WIRE retry
    budget (re-sending cannot help), but deliberately stage-retryable: the
    re-run of the PUSH stage takes the degraded pure-ICI branch when
    BYTEPS_DEGRADED_OK, else fails the handle."""


def hand_off_owner(workers, owners, rank: int):
    """The owner-failover handoff critical section — ONE definition shared
    by the jax hybrid pipeline and DcnCore (the caller holds its own pod
    lock around this). Fences the dying controller's worker so no round
    can be minted past the snapshot, hands its round counters / store
    sizes to every survivor, then shrinks the live set — in that order:
    fence-before-export closes the mint race, export-before-fail keeps a
    racing stage retry from minting a round at/below the server's replay
    watermark (the PR3 atomicity argument). Returns the PRE-fail live set
    (callers diff it to find which partitions moved), or None if ``rank``
    is already dead or the last controller."""
    live = owners.live()
    if rank not in live or len(live) <= 1:
        return None
    workers[rank].fence()
    versions, nbytes = workers[rank].export_rounds()
    for r in sorted(live - {rank}):
        workers[r].adopt_rounds(versions, nbytes)
    owners.fail(rank)
    return live


def retire_nic(worker, rank: int) -> None:
    """Free an EXTRA pod-controller NIC (owner failover or pod shutdown):
    fold its robustness counters into the trace first — tagged per-NIC,
    since every controller shares the pod's worker id — then close it
    (health monitor thread, connections, pacer). NIC 0 never retires this
    way: it alone carries the pod's single kShutdown round, so it goes
    through ``PSWorker.shutdown``."""
    worker.export_counters(f"worker{worker._worker_id}.nic{rank}")
    get_registry().counter("nic.retired").inc()
    worker.close()


def _is_retryable_wire_error(e: BaseException) -> bool:
    """Errors the worker retry engine may safely re-attempt: lost
    responses (rc=-7), desynchronized/killed sockets (rc=-6/-2/-3, the
    next attempt reconnects), detected corruption (CRC), and injected
    equivalents. Server-side kErr rejections (size/init mismatches, pull
    deadline expiry) are semantic failures a resend cannot fix."""
    if isinstance(e, (NoLiveServersError, FailedOverError)):
        return False
    if isinstance(e, (TimeoutError, ConnectionError, WireCorruption)):
        return True
    if isinstance(e, RuntimeError):
        s = str(e)
        return ("rc=-2" in s or "rc=-3" in s or "key mismatch" in s
                or "NativeClient is closed" in s)
    return False


def server_addresses(cfg: Optional[Config] = None) -> List[Tuple[str, int]]:
    cfg = cfg or get_config()
    num = max(1, cfg.num_server)
    return [(cfg.ps_root_uri, cfg.ps_root_port + 1 + i) for i in range(num)]


# server_id of the summation service running in THIS process, if any —
# lets PSWorker route that server's keys through the in-process fast path
# (BYTEPS_ENABLE_IPC) instead of TCP loopback.
_INPROC_SERVER_ID: Optional[int] = None


def start_server(
    port: Optional[int] = None,
    num_workers: Optional[int] = None,
    engine_threads: Optional[int] = None,
    async_mode: Optional[bool] = None,
    server_id: int = 0,
    pull_timeout_ms: Optional[int] = None,
    enable_schedule: Optional[bool] = None,
    lease_ms: Optional[int] = None,
    staleness: Optional[int] = None,
) -> int:
    """Start the native summation service in this process (non-blocking).

    ``lease_ms`` (default ``BYTEPS_WORKER_LEASE_MS``) > 0 arms elastic
    worker membership: a worker silent past the lease is evicted, the
    membership epoch bumps, open rounds re-target the live worker set,
    and stuck barriers release (docs/robustness.md §elastic membership).

    ``staleness`` (default ``BYTEPS_STALENESS``) > 0 arms BOUNDED-
    STALENESS rounds: a pull for round v is served from the newest
    CLOSED round >= v-K, a pull past the bound force-closes straggler-
    held rounds over their contributors (quorum-scaled), and responses
    stamp the served round — so one slow worker no longer sets the
    global step time (docs/robustness.md §bounded staleness). K=0 is
    bit-identical to the synchronous tier; ``BYTEPS_ENABLE_ASYNC`` is
    the K=inf limit and wins when both are set.
    """
    global _INPROC_SERVER_ID
    cfg = get_config()
    lib = load_lib()
    port = port if port is not None else cfg.ps_root_port + 1 + server_id
    rc = lib.bps_server_start(
        port,
        num_workers if num_workers is not None else cfg.num_worker,
        engine_threads if engine_threads is not None
        else cfg.server_engine_threads,
        1 if (async_mode if async_mode is not None else cfg.enable_async)
        else 0,
        pull_timeout_ms if pull_timeout_ms is not None
        else cfg.pull_timeout_ms,
        server_id,
        1 if (enable_schedule if enable_schedule is not None
              else cfg.server_enable_schedule) else 0,
        lease_ms if lease_ms is not None else cfg.worker_lease_ms,
        staleness if staleness is not None else cfg.staleness,
    )
    if rc != 0:
        raise RuntimeError(f"bps_server_start failed (rc={rc}, port={port})")
    _INPROC_SERVER_ID = server_id
    if cfg.trace_on:
        lib.bps_server_trace_enable(1)
    log.info("summation server listening on :%d", port)
    return port


def stop_server() -> None:
    global _INPROC_SERVER_ID
    load_lib().bps_server_stop()
    _INPROC_SERVER_ID = None


def any_port(bind, port: int, attempts: int = 16, stride: int = 1):
    """Probe ``attempts`` ports ``stride`` apart until ``bind(p)``
    succeeds, sidestepping ephemeral-port squatters: when the OS
    ip_local_port_range overlaps the chosen port (this image's starts at
    16000), any client socket can be sitting on it and the bind fails —
    rc=-2 from the native server, EADDRINUSE from a Python socket.
    Returns whatever ``bind`` returned for the port that stuck; any
    OTHER bind error propagates (a squatter is routine, a bad address
    is a bug). This is the one home of the PR 4 workaround — the native
    server path and the socket NIC listen path both delegate here."""
    import errno

    last: Optional[Exception] = None
    for i in range(attempts):
        p = port + i * stride
        try:
            return bind(p)
        except RuntimeError as e:
            if "rc=-2" not in str(e):
                raise
            last = e
        except OSError as e:
            if e.errno not in (errno.EADDRINUSE, errno.EACCES):
                raise
            last = e
    raise RuntimeError(
        f"no squatter-free port in {attempts} probes from {port}") from last


def start_server_any_port(port: int, attempts: int = 16, stride: int = 1,
                          **kw) -> int:
    """``start_server`` through the :func:`any_port` squatter sidestep;
    returns the port actually bound."""
    return any_port(lambda p: start_server(port=p, **kw), port,
                    attempts=attempts, stride=stride)


def dump_server_trace(path: str) -> int:
    """Write the server's chrome trace JSON; returns event count."""
    return load_lib().bps_server_trace_dump(path.encode())


def serve_forever(server_id: Optional[int] = None) -> None:
    """Launcher entry for the server role: start and block until all workers
    shut down (reference: ``import byteps.server`` → ``StartPS`` blocks)."""
    import os

    cfg = get_config()
    sid = (
        server_id if server_id is not None
        else int(os.environ.get("DMLC_SERVER_ID", "0"))
    )
    global _INPROC_SERVER_ID
    start_server(server_id=sid)
    load_lib().bps_server_wait()
    # the native server stopped (worker-driven shutdown); make sure no
    # later PSWorker(use_ipc=True) in this process routes into its leaked
    # store (the native Local* entries also refuse once stopped)
    _INPROC_SERVER_ID = None
    if cfg.trace_on:
        os.makedirs(cfg.trace_dir, exist_ok=True)
        path = os.path.join(cfg.trace_dir, f"trace_server{sid}.json")
        n = dump_server_trace(path)
        log.info("dumped %d server trace events to %s", n, path)
    log.info("summation server stopped")


class PSWorker:
    """Worker-side facade: key→server placement, per-key round tracking,
    connection-per-thread for pipelined push/pull, wire-byte accounting.

    Each OS thread (one per scheduler pool slot) gets its own serial
    connection to each server, so a pull blocked on a slow round never
    stalls another partition's push — the deadlock-freedom argument of the
    reference's separate PUSH/PULL core loops.

    With ``BYTEPS_ENABLE_IPC`` and a summation server running in THIS
    process (joint role), pushes/pulls for locally-owned keys skip TCP and
    access the store directly (the reference's colocated shared-memory
    fast path, ps-lite ``BYTEPS_ENABLE_IPC``).

    With ``BYTEPS_DCN_THROTTLE_MBPS`` > 0 (or ``throttle_mbps=``), this
    worker's payload bytes are paced through an emulated full-duplex NIC
    of that speed (``server/pacer.py``) — the bandwidth-throttled bench
    and the compression fast-lane regime. The pacer is per-PSWorker, so
    several workers emulated in one process each get their own NIC.
    """

    def __init__(
        self,
        servers: Optional[Sequence[Tuple[str, int]]] = None,
        timeout_ms: int = 60000,
        recv_timeout_ms: int = 120000,
        worker_id: Optional[int] = None,
        use_ipc: Optional[bool] = None,
        throttle_mbps: Optional[float] = None,
        fault_plan: Optional[FaultPlan] = None,
        health_interval_ms: Optional[int] = None,
    ):
        """``health_interval_ms`` overrides BYTEPS_HEALTH_INTERVAL_MS for
        THIS worker (chaos tests arm a heartbeating survivor beside a
        monitor-less victim in one process; None = the config value)."""
        cfg = get_config()
        self._servers = list(servers) if servers else server_addresses()
        self._timeout = timeout_ms
        self._recv_timeout = recv_timeout_ms
        self._worker_id = (
            worker_id if worker_id is not None else cfg.worker_id
        )
        self._tls = threading.local()
        self._versions: Dict[int, int] = {}
        self._vlock = threading.Lock()
        self._fenced = False
        self._all_conns: List[NativeClient] = []
        self._conn_lock = threading.Lock()
        self._closed = False
        # wire accounting (compression tests / docs assert against these)
        self.bytes_pushed = 0
        self.bytes_pulled = 0
        self._ipc = (
            use_ipc if use_ipc is not None else cfg.enable_ipc
        ) and _INPROC_SERVER_ID is not None
        self.pacer: Optional[DcnPacer] = pacer_from_mbps(
            throttle_mbps if throttle_mbps is not None
            else cfg.dcn_throttle_mbps
        )
        # --- robustness state (docs/robustness.md) -------------------------
        self._plan = (fault_plan if fault_plan is not None
                      else plan_from_env(cfg, worker_id=self._worker_id))
        # CRC is forced on while CORRUPTION injection is armed:
        # corruption must be *detected* to be retryable instead of
        # silently summed. Every other kind needs no checksum — loss
        # kinds (timeout/kill/down) are caught by the rc/desync
        # classification and the version dedupe, latency ('slow') and
        # control ('join'/'hang') kinds touch no payload — so they do
        # not force the 2×-per-payload software CRC pass onto every
        # worker sharing the spec string (the churn/straggler legs
        # would otherwise measure CRC overhead, not elasticity).
        self._crc = bool(cfg.wire_crc) or (
            self._plan is not None
            and any(r.kind == "corrupt" for r in self._plan.rules))
        self._retry_limit = max(0, cfg.retry_limit)
        self._backoff_ms = max(1, cfg.retry_backoff_ms)
        # bounded staleness (BYTEPS_STALENESS): armed here so pull_bytes
        # can re-sync the mint counter off a serve-ahead response
        self._staleness = max(0, cfg.staleness)
        # seeded jitter: reproducible backoff schedules per worker
        self._retry_rng = random.Random(
            0xC0FFEE ^ (self._worker_id * 7919) ^ cfg.fault_seed)
        self._live: Set[int] = set(range(len(self._servers)))
        self._epoch = 0  # bumped per failover; in-flight ops self-abort
        self._key_nbytes: Dict[int, int] = {}  # for post-failover re-init
        # --- elastic worker membership (docs/robustness.md) ----------------
        # per-server membership epoch (low 16 bits, stamped on every
        # response) this worker has ADOPTED; a mismatch on any op
        # triggers a kMembers query + adoption
        self._epoch_seen: Dict[int, int] = {}
        # (server, epoch16) -> live worker count at that epoch: pull
        # responses carry the epoch their ROUND closed under, and the
        # averaging divisor must be THAT epoch's live count — a round
        # closed at full membership but delivered after an eviction must
        # still divide by the full count. Seeded with epoch 0 = the
        # configured membership.
        self._epoch_live: Dict[Tuple[int, int], int] = {
            (s, 0): max(1, cfg.num_worker)
            for s in range(len(self._servers))
        }
        # live worker (pod) count per the most recent adoption — what
        # averaging consumers divide by instead of the static
        # DMLC_NUM_WORKER once the membership shrinks/grows
        self._live_pods = max(1, cfg.num_worker)
        # injected self-death (worker:kill) / wedge window (worker:hang)
        self._self_killed = False
        self._wedged_until = 0.0
        # one-shot latch for the worker<N>:join fault rule: a join window
        # wider than one op must not re-run the admission handshake on
        # every subsequent wire attempt
        self._join_fired = False
        self.counters: Dict[str, int] = {
            "retries": 0, "timeouts": 0, "conn_errors": 0,
            "crc_errors": 0, "reinits": 0, "give_ups": 0,
            "failovers": 0, "ici_fallbacks": 0,
            "membership_events": 0, "rejoins": 0, "joins": 0,
        }
        self._counter_lock = threading.Lock()
        # --- always-on metrics registry (docs/observability.md) ------------
        # Every robustness count and wire byte ALSO lands in the
        # process-wide registry: the per-instance views above die with
        # the NIC (owner failover retires it), the registry totals do
        # not — which is what keeps per-run accounting complete.
        # Handles are resolved once here; _count mirrors lazily.
        self._nic_tag = f"nic{next(_NIC_SEQ)}"
        _reg = get_registry()
        self._m_counts: Dict[str, Tuple] = {}
        self._m_push_bytes = _reg.counter("wire.push_bytes")
        self._m_pull_bytes = _reg.counter("wire.pull_bytes")
        self._m_push_bytes_nic = _reg.counter(
            f"wire.{self._nic_tag}.push_bytes")
        self._m_pull_bytes_nic = _reg.counter(
            f"wire.{self._nic_tag}.pull_bytes")
        self._m_push_size = _reg.histogram("wire.push_size_bytes")
        # bounded-staleness observability (docs/observability.md):
        # requested − served per pull (how stale the aggregate this
        # worker consumed was), and how many rounds this worker's newest
        # minted push runs ahead of the round it last consumed. The
        # gauge is per-NIC (two NICs sharing one series would mask each
        # other last-writer-wins); the plain series is the most recent
        # pull in the process — the per-step flight-recorder view.
        self._m_staleness = _reg.histogram("server.staleness")
        self._m_rounds_ahead = _reg.gauge("psworker.rounds_ahead")
        self._m_rounds_ahead_nic = _reg.gauge(
            f"psworker.{self._nic_tag}.rounds_ahead")
        self._m_attempts = {
            op: (_reg.counter(f"wire.{op}_attempts"),
                 _reg.counter(f"wire.{self._nic_tag}.{op}_attempts"))
            for op in ("push", "pull", "init")
        }
        self._health: Optional[_HealthMonitor] = None
        hb_ms = (health_interval_ms if health_interval_ms is not None
                 else cfg.health_interval_ms)
        if hb_ms > 0 and len(self._servers) > 0:
            self._health = _HealthMonitor(
                self, interval_ms=hb_ms,
                miss_limit=max(1, cfg.health_miss_limit))
            self._health.start()

    # -- robustness helpers -------------------------------------------------
    def _count(self, name: str, n: int = 1) -> None:
        with self._counter_lock:
            self.counters[name] = self.counters.get(name, 0) + n
        m = self._m_counts.get(name)
        if m is None:
            _reg = get_registry()
            m = (_reg.counter(f"psworker.{name}"),
                 _reg.counter(f"psworker.{self._nic_tag}.{name}"))
            self._m_counts[name] = m
        m[0].inc(n)
        m[1].inc(n)

    def _trace_fault(self, event: str, **args) -> None:
        get_tracer().instant(event, "FAULT",
                             {"worker": self._worker_id, **args})

    def _kill_conn(self, sidx: int) -> None:
        """Drop this thread's connection to ``sidx`` (injected socket
        death); the next attempt reconnects through ``_conn``."""
        pool = getattr(self._tls, "conns", {})
        c = pool.get(sidx)
        if c is not None:
            self._evict(sidx, c)

    def _inject_pre(self, op: str, sidx: int):
        """Evaluate the fault plan for one wire attempt. 'kill'/'down'
        raise here (the request never leaves); 'timeout'/'corrupt' are
        returned for the caller to act on around the real op. Worker-scope
        rules simulate THIS process's death ('worker:kill' — sticky, every
        later op refuses) or wedge ('worker:hang' — ops block out the
        window, then report a lost response); both stop the lease
        heartbeat so the server's eviction fires as for a real crash."""
        if self._self_killed:
            raise WorkerKilledError(
                f"worker {self._worker_id} is dead (injected worker:kill); "
                f"{op} refused")
        rest = self._wedged_until - time.time()
        if rest > 0:
            time.sleep(rest)
            self._kill_conn(sidx)
            raise InjectedTimeout(
                f"injected: worker {self._worker_id} wedged through {op} "
                "(worker:hang window)")
        if self._plan is None:
            return None
        inj = self._plan.intercept(op, sidx)
        if inj is None:
            return None
        if inj.rule.scope == "worker":
            if inj.kind == "kill":
                self._self_killed = True
                self._trace_fault("worker_kill", op=op,
                                  step=self._plan.step)
                log.warning(
                    "worker %d killed by injection at plan step %d",
                    self._worker_id, self._plan.step)
                # a dead process's sockets die with it
                for s in list(getattr(self._tls, "conns", {})):
                    self._kill_conn(s)
                raise WorkerKilledError(
                    f"injected: worker {self._worker_id} killed during "
                    f"{op} (plan step {self._plan.step})")
            if inj.kind == "hang":
                self._wedged_until = (time.time()
                                      + inj.rule.latency_ms / 1e3)
                self._trace_fault("worker_hang", op=op,
                                  ms=inj.rule.latency_ms)
                time.sleep(inj.rule.latency_ms / 1e3)
                self._kill_conn(sidx)
                raise InjectedTimeout(
                    f"injected: worker {self._worker_id} wedged for "
                    f"{inj.rule.latency_ms} ms during {op}")
            if inj.kind == "join":
                # deterministic mid-stream admission (worker<N>:join@
                # step=A): run the kJoin handshake once, then let the
                # intercepted op proceed under the adopted membership —
                # the churn bench/tests schedule joins this way
                if not self._join_fired:
                    self._join_fired = True
                    self.join()
                return None
            # other kinds under worker scope fall through to the generic
            # handling below (e.g. worker:timeout = lose own responses)
        if inj.kind == "down":
            self._kill_conn(sidx)
            raise ServerDownError(
                f"injected: server {sidx} down during {op} "
                f"(plan step {self._plan.step})")
        if inj.kind == "kill":
            self._kill_conn(sidx)
            raise InjectedConnectionError(
                f"injected: connection to server {sidx} killed before {op}")
        return inj

    def is_wedged(self) -> bool:
        """True while a worker:hang window is open (the health monitor
        stops heartbeating so the server lease can expire, exactly as a
        really-wedged process would go silent)."""
        return self._self_killed or self._wedged_until > time.time()

    def has_live_servers(self) -> bool:
        return bool(self._live)

    def live_servers(self) -> Set[int]:
        return set(self._live)

    def fail_over(self, sidx: int, barrier: bool = True) -> bool:
        """Mark server ``sidx`` dead and remap its keys to the survivors.

        All workers must take the same view of the live set before any
        pushes the new placement (their health monitors each call this;
        the worker barrier through the lowest surviving server aligns
        them). Key remap is rendezvous-hashed over the live set; the dead
        server's keys get fresh round counters (their stores — and the
        rounds in flight against them — are gone; in-flight ops for
        remapped keys abort with :class:`FailedOverError` and the stage
        retry re-runs them against the new placement). Returns False if
        the server was already dead."""
        with self._vlock:
            if sidx not in self._live:
                return False
            old_live = set(self._live)
            self._live.discard(sidx)
            self._epoch += 1
            # reset round numbering for every key whose placement changed,
            # atomically with the live-set shrink: a push racing this (a
            # stage retry landing on the survivor) must either see the old
            # placement (and abort FailedOverError) or a reset counter —
            # never mint a CONTINUATION version on the new server, which
            # would make all later fresh rounds look like replays to the
            # dedupe watermark
            for key in list(self._versions):
                if (self._server_for_live(key, old_live)
                        != self._server_for_live(key, self._live)):
                    del self._versions[key]
        self._count("failovers")
        self._trace_fault("failover", server=sidx,
                          survivors=sorted(self._live))
        log.warning("server %d marked dead; %s", sidx,
                    f"keys fail over to {sorted(self._live)}"
                    if self._live else "NO live servers remain "
                    "(degraded mode)")
        if barrier and self._live:
            try:
                self.barrier()
            except Exception as e:  # noqa: BLE001 - best-effort alignment
                log.warning("failover barrier failed: %s", e)
        return True

    def _server_for_live(self, key: int, live: Set[int]) -> int:
        """Deterministic placement agreed across workers: the home slot
        (key % n) when alive, else rendezvous hash over the survivors
        (zlib.crc32 is stable across processes, unlike salted hash())."""
        home = key % len(self._servers)
        if home in live or not live:
            return home  # no survivors: degraded path decides upstream
        return max(live,
                   key=lambda s: zlib.crc32(f"{key}:{s}".encode()))

    def server_for(self, key: int) -> int:
        with self._vlock:
            live = set(self._live)
        return self._server_for_live(key, live)

    # -- elastic worker membership (epoch adoption + rejoin) ----------------
    def live_pods(self) -> int:
        """Live WORKER (pod) count per the most recently adopted
        membership epoch — what averaging consumers divide by instead of
        the static DMLC_NUM_WORKER once a peer is evicted or rejoins."""
        with self._vlock:
            return max(1, self._live_pods)

    def _note_epoch(self, sidx: int) -> None:
        """Per-op membership-change detection: every server response
        stamps the current epoch (header reserved field); on a mismatch
        with the adopted one, query the live set and adopt it. Costs one
        ctypes read per op — no extra round trip until a change."""
        try:
            if self._is_local(sidx):
                e = int(load_lib().bps_server_epoch()) & 0xFFFF
            else:
                conn = getattr(self._tls, "conns", {}).get(sidx)
                if conn is None:
                    return
                e = conn.epoch()
        except Exception:  # noqa: BLE001 - detection is best-effort; the
            return         # next op retries it
        with self._vlock:
            seen = self._epoch_seen.get(sidx, 0)
        # adopt only a NEWER epoch (mod-2^16 window): a connection idle
        # across the bump still reports the old stamp on its last parsed
        # response, and adopting backwards would flap the live count
        if e != seen and ((e - seen) & 0xFFFF) < 0x8000:
            self._adopt_membership(sidx)

    def _adopt_membership(self, sidx: int) -> None:
        """Adopt a new membership epoch from server ``sidx`` (kMembers
        query): refresh the live pod count (pull results under the new
        epoch are sums over the LIVE set, so averaging must rescale
        consistently), record the query's own (epoch, live) pair in the
        divisor history, count the event, and land a MembershipEvent on
        the chrome trace's FAULT track. Failure leaves the old epoch
        adopted — the next op re-detects and retries."""
        try:
            if self._is_local(sidx):
                import ctypes

                lib = load_lib()
                ep = ctypes.c_uint64(0)
                live = ctypes.c_uint32(0)
                bitmap = (ctypes.c_uint8 * 1024)()
                n = lib.bps_server_members(
                    ctypes.byref(ep), ctypes.byref(live), bitmap, 1024)
                if n < 0:
                    return
                q_epoch = int(ep.value)
                live_count = int(live.value)
                bits = bytes(bitmap[: min(n, 1024)])
            else:
                q_epoch, live_count, bits = self._conn(sidx).members()
        except Exception as e:  # noqa: BLE001 - adoption retried next op
            log.debug("membership query on server %d failed: %s", sidx, e)
            return
        # the (epoch, live) pair must come from the QUERY's atomic view:
        # the trigger stamp `epoch16` may be older than the membership
        # the query answered for (another change landed in between), and
        # caching the new count under the old epoch would poison that
        # epoch's averaging divisor permanently
        q_epoch16 = q_epoch & 0xFFFF
        # plain bool: bits is a numpy array and an np.bool_ leaking into
        # the trace args breaks the chrome-trace JSON dump
        evicted_self = bool(self._worker_id < len(bits)
                            and bits[self._worker_id] == 0)
        with self._vlock:
            self._record_epoch_live(sidx, q_epoch16, int(live_count))
            seen = self._epoch_seen.get(sidx, 0)
            if (q_epoch16 == seen
                    or ((q_epoch16 - seen) & 0xFFFF) >= 0x8000):
                return  # another pool thread already adopted this epoch
            self._epoch_seen[sidx] = q_epoch16
            self._live_pods = max(1, int(live_count))
        self._count("membership_events")
        self._trace_fault("membership", server=sidx, epoch=q_epoch16,
                          live_pods=int(live_count),
                          evicted_self=evicted_self)
        log.warning(
            "membership epoch %d adopted from server %d: %d live "
            "worker(s)%s", q_epoch16, sidx, live_count,
            " — THIS worker is evicted (rejoin on next push)"
            if evicted_self else "")

    def _record_epoch_live(self, sidx: int, epoch16: int,
                           live: int) -> None:
        """Record the (epoch -> live count) divisor pair for ``sidx`` and
        PRUNE entries older than the recorded epoch minus
        ``_EPOCH_LIVE_WINDOW`` (mod-2^16 window, same arithmetic as the
        adoption ordering): under churn every membership change adds an
        entry forever, and a long-lived worker would otherwise grow this
        dict without bound. Caller holds ``_vlock``."""
        self._epoch_live[(sidx, epoch16 & 0xFFFF)] = max(1, int(live))
        # keep only entries within ±window of the recorded epoch: a
        # bare backward-window test would strand entries a large epoch
        # jump pushed onto the "future" half of the mod-2^16 ring —
        # they would then never age out (the unbounded growth this
        # prune exists to stop)
        stale = [
            k for k in self._epoch_live
            if k[0] == sidx
            and ((epoch16 - k[1]) & 0xFFFF) >= _EPOCH_LIVE_WINDOW
            and ((k[1] - epoch16) & 0xFFFF) >= _EPOCH_LIVE_WINDOW
        ]
        for k in stale:
            del self._epoch_live[k]

    def _live_at(self, sidx: int, epoch16: int) -> int:
        """Live worker count at ``epoch16`` on server ``sidx`` — the
        divisor for a round that CLOSED under that epoch. Unknown epochs
        (the round's close was the first sign of a membership change)
        adopt the current membership and retry the lookup; the final
        fallback is the currently adopted live count."""
        with self._vlock:
            v = self._epoch_live.get((sidx, epoch16))
        if v is not None:
            return v
        self._note_epoch(sidx)
        with self._vlock:
            return self._epoch_live.get((sidx, epoch16),
                                        max(1, self._live_pods))

    def last_round_live(self) -> Optional[int]:
        """Live worker count of the round the calling thread's most
        recent :meth:`pull_bytes` returned — what averaging consumers
        divide by for THAT round (``None`` before any pull). Thread-local,
        like the connections themselves."""
        return getattr(self._tls, "round_live", None)

    def last_pull_round(self) -> Optional[int]:
        """The round the calling thread's most recent :meth:`pull_bytes`
        was actually SERVED from (the response's round stamp). Under
        bounded staleness (``BYTEPS_STALENESS``) it may trail the
        requested round by up to K — requested − served is the pull's
        effective staleness. ``None`` before any pull; thread-local."""
        return getattr(self._tls, "round_served", None)

    def sync_rounds(self, sidx: int) -> None:
        """Adopt server ``sidx``'s per-key (round, nbytes) watermarks —
        the restart/rejoin half of the ``export_rounds``/``adopt_rounds``
        handshake: the server's store (and its (worker, key, version)
        replay-dedupe watermark) outlives this worker, so a fresh round
        counter would mint versions the dedupe silently drops — a
        permanent per-key stall. Max-merge via :meth:`adopt_rounds`;
        sizes seed the lazy re-init of inherited keys."""
        trips = self._conn(sidx).rounds()
        self.adopt_rounds(
            {int(k): int(v) for k, v, _ in trips},
            {int(k): int(nb) for k, _, nb in trips},
        )

    def rejoin(self) -> None:
        """Re-register with every live server after an eviction or a
        process restart: heartbeat with the worker id (the server
        re-admits and bumps the epoch), then adopt round watermarks so
        the next mint continues the server's round sequence. Invoked
        automatically when a push is refused with 'worker evicted'; also
        the public entry for a restarted process resuming from a
        checkpoint against a still-running server tier."""
        with self._vlock:
            live = sorted(self._live)
        for sidx in live:
            try:
                self.ping(sidx)        # heartbeat: re-admit + epoch bump
                self.sync_rounds(sidx)
                self._note_epoch(sidx)
            except Exception as e:  # noqa: BLE001 - a dead server cannot
                # block the rejoin against the live ones; its own
                # failover path owns it
                log.warning("rejoin against server %d failed: %s: %s",
                            sidx, type(e).__name__, e)
        self._count("rejoins")
        self._trace_fault("rejoin", servers=live)

    def join(self) -> int:
        """First-class mid-stream ADMISSION (kJoin) — the scale-UP
        counterpart of :meth:`rejoin`: register this worker id with
        every live server. A FRESH id (beyond ``DMLC_NUM_WORKER``) grows
        the server's membership table and per-key round vectors before
        the admission is published, so the join lands at a round
        boundary: the epoch bumps (stamped in every response — peers
        adopt it on their next op and rescale their averaging divisor),
        rounds open at admission close over their contributors
        (quorum-scaled), and this worker adopts round watermarks
        (``kRounds``) so its first mint continues at the served-round
        frontier — under ``BYTEPS_STALENESS`` that frontier never trails
        the force-close watermark. A previously evicted id re-admits the
        same way. Returns the number of servers that admitted us; raises
        :class:`NoLiveServersError` when none did (a joiner with no
        quorum cannot contribute)."""
        with self._vlock:
            live = sorted(self._live)
        joined = []
        for sidx in live:
            try:
                if self._is_local(sidx):
                    rc = int(load_lib().bps_server_join(self._worker_id))
                    if rc < 0:
                        raise RuntimeError(
                            f"local join failed (rc={rc})")
                else:
                    self._conn(sidx).join(self._worker_id)
                self.sync_rounds(sidx)
                self._note_epoch(sidx)
                joined.append(sidx)
            except Exception as e:  # noqa: BLE001 - mirror rejoin(): a
                # dead server must not block admission by the live
                # quorum; its own failover/recovery path owns it, and
                # its later recovery re-admits us via the eviction →
                # inline-rejoin handshake
                log.warning("join against server %d failed: %s: %s",
                            sidx, type(e).__name__, e)
        if not joined:
            raise NoLiveServersError(
                f"worker {self._worker_id} could not join any summation "
                "server")
        self._count("joins")
        self._trace_fault("join", servers=joined)
        log.info("worker %d joined mid-stream via server(s) %s",
                 self._worker_id, joined)
        return len(joined)

    # -- connection management ----------------------------------------------
    def _conn(self, sidx: int) -> NativeClient:
        pool = getattr(self._tls, "conns", None)
        if pool is None:
            pool = {}
            self._tls.conns = pool
        c = pool.get(sidx)
        if c is not None and c.is_dead():
            # a timeout/desync killed the socket (native side closes it so
            # no stale frame can be misread); evict so this thread's next
            # op reconnects instead of failing rc=-2 forever
            self._evict(sidx, c)
            c = None
        if c is None:
            if self._closed:
                raise RuntimeError("PSWorker is shut down")
            host, port = self._servers[sidx]
            c = NativeClient(host, port, self._timeout, self._recv_timeout)
            pool[sidx] = c
            with self._conn_lock:
                self._all_conns.append(c)
        return c

    def _evict(self, sidx: int, c: NativeClient) -> None:
        pool = getattr(self._tls, "conns", {})
        if pool.get(sidx) is c:
            del pool[sidx]
        with self._conn_lock:
            try:
                self._all_conns.remove(c)
            except ValueError:
                pass
        c.close()

    def _is_local(self, sidx: int) -> bool:
        return self._ipc and sidx == _INPROC_SERVER_ID

    # -- retry engine -------------------------------------------------------
    def _retry_loop(self, op: str, key: int, attempt_fn):
        """Drive ``attempt_fn(sidx) -> result`` under the per-op retry
        budget. Placement is re-resolved every attempt so a failover
        mid-retry lands on the survivor; an op whose key MOVED since the
        first attempt aborts with :class:`FailedOverError` (its round
        numbering died with the old server — the *stage* retry re-runs
        the whole op against the new placement, with a fresh version).

        Backoff: ``BYTEPS_RETRY_BACKOFF_MS`` × 2^attempt, capped at 2 s,
        with seeded jitter in [0.5, 1.0] — the standard exponential
        backoff + jitter that keeps a retry storm from re-synchronizing
        every worker onto the recovering server."""
        sidx0 = self.server_for(key)
        attempt = 0
        while True:
            with self._vlock:
                live = set(self._live)
                epoch = self._epoch
            if not live:
                raise NoLiveServersError(
                    f"{op} key {key}: every summation server is dead")
            sidx = self._server_for_live(key, live)
            if sidx != sidx0:
                raise FailedOverError(
                    f"{op} key {key}: placement moved {sidx0}->{sidx} "
                    f"(failover epoch {epoch}); round abandoned")
            m_att = self._m_attempts.get(op)
            if m_att is not None:
                m_att[0].inc()
                m_att[1].inc()
            try:
                result = attempt_fn(sidx)
                self._note_epoch(sidx)
                return result
            except BaseException as e:  # noqa: BLE001 - classified below
                self._note_epoch(sidx)
                if isinstance(e, WorkerEvictedError):
                    # the server refuses this worker until it rejoins:
                    # heartbeat re-admit + round-watermark adoption here,
                    # then escalate stage-retryably — the op's pinned
                    # round predates the adopted watermarks, so the stage
                    # re-run must mint afresh (push stages clear the pin
                    # on this error class)
                    log.warning(
                        "%s key %d refused: worker %d evicted; rejoining",
                        op, key, self._worker_id)
                    self.rejoin()
                    raise
                if (isinstance(e, RuntimeError) and "before init" in str(e)
                        and key in self._key_nbytes
                        and attempt < self._retry_limit):
                    # post-failover target has never seen this key:
                    # re-init from the recorded size and go again (init
                    # is idempotent server-side)
                    attempt += 1
                    self._count("reinits")
                    self._trace_fault("reinit", key=key, server=sidx)
                    self._conn(sidx).init_key(key, self._key_nbytes[key])
                    continue
                if not _is_retryable_wire_error(e):
                    raise
                if attempt >= self._retry_limit:
                    self._count("give_ups")
                    self._trace_fault("retry_exhausted", key=key, op=op,
                                      error=type(e).__name__)
                    raise
                attempt += 1
                if isinstance(e, TimeoutError):
                    self._count("timeouts")
                elif isinstance(e, WireCorruption):
                    self._count("crc_errors")
                else:
                    self._count("conn_errors")
                self._count("retries")
                self._trace_fault("retry", key=key, op=op, attempt=attempt,
                                  error=type(e).__name__)
                log.debug("%s key %d attempt %d failed (%s: %s); retrying",
                          op, key, attempt, type(e).__name__, e)
                backoff = min(self._backoff_ms * (2 ** (attempt - 1)), 2000)
                time.sleep(backoff * self._retry_rng.uniform(0.5, 1.0)
                           / 1e3)

    # -- owner-failover handoff (sharded-wire hierarchical mode) ------------
    def fence(self) -> None:
        """Refuse every future round mint on this worker. Set when its
        owner is declared dead, BEFORE ``export_rounds`` snapshots the
        counters: a push thread that resolved this owner pre-failover
        could otherwise mint a round AFTER the snapshot — invisible to
        the survivors' adopted counters, so the next round's re-mint of
        the same number would be dropped by the server's replay dedupe
        (silent stale gradient). The FailedOverError is stage-retryable:
        the re-run resolves ownership afresh and lands on a survivor."""
        with self._vlock:
            self._fenced = True

    def export_rounds(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        """Snapshot (per-key round counters, per-key store sizes) — what a
        surviving controller adopts when this worker's owner dies."""
        with self._vlock:
            return dict(self._versions), dict(self._key_nbytes)

    def adopt_rounds(self, versions: Dict[int, int],
                     nbytes: Dict[int, int]) -> None:
        """Seed round counters/store sizes from a dead owner's worker.

        Owner failover differs from PR3's SERVER failover: the summation
        server — and its per-(worker, key) replay watermark — survives an
        owner death, so the surviving controller must CONTINUE the pod's
        round numbering (all of a pod's controllers push under the pod's
        worker_id). A fresh counter would mint versions at/below the
        server's watermark and every later round would be dropped as a
        replay. Adopting the max also keeps a round the dead owner had
        pushed-but-not-pulled replayable: the stage retry re-sends the
        pinned version through this worker and the dedupe recognizes it.
        """
        with self._vlock:
            for k, v in versions.items():
                if v > self._versions.get(k, 0):
                    self._versions[k] = v
            for k, nb in nbytes.items():
                self._key_nbytes.setdefault(k, nb)

    # -- data plane ---------------------------------------------------------
    def init_key(self, key: int, nbytes: int) -> None:
        with self._vlock:
            self._key_nbytes[key] = int(nbytes)
        sidx = self.server_for(key)
        if self._is_local(sidx):
            rc = load_lib().bps_local_init(key, nbytes)
            if rc != 0:
                raise RuntimeError(f"local init failed (rc={rc})")
            return

        def attempt(s):
            # 'init'/server-scoped rules only (down windows, init-ack
            # loss) — push/pull loss rules target the data plane proper
            inj = self._inject_pre("init", s)
            if inj is not None and inj.kind == "corrupt":
                inj = None  # nothing summable to corrupt in an init
            self._conn(s).init_key(key, nbytes)
            if inj is not None and inj.kind == "timeout":
                # the init WAS applied (and is idempotent); lose the ack
                # so the caller's retry/stage-retry path re-inits
                self._kill_conn(s)
                raise InjectedTimeout(
                    f"injected: init ack for key {key} lost (server {s})")

        self._retry_loop("init", key, attempt)

    def mint_version(self, key: int, pinned: Optional[int] = None) -> int:
        """Reserve the round number a push will carry, BEFORE the wire
        attempt — the push stages pin it on their task so a stage retry
        re-sends the SAME round even when the first attempt died before
        ``push_bytes`` could return it. That pin is what keeps the
        server's per-key round sequence gapless across an owner failover:
        the counter increments at mint time, so a push that never reached
        the server still consumed its round number, and a survivor that
        adopted this worker's counters would otherwise mint one PAST the
        round the server is still waiting for — a permanent stall (the
        server can't complete round v without v's push, and the pull for
        v+1 waits on v). Re-sending the pinned round is safe in both
        failure modes: never-applied → the server sums it as round v;
        applied-but-ack-lost → the (worker, key, version) dedupe drops
        it. A pin that exceeds the current counter (it predates a server
        failover's counter reset) is discarded and a fresh round minted,
        exactly like ``push_bytes``'s own rule."""
        with self._vlock:
            if self._fenced:
                raise FailedOverError(
                    f"owner worker fenced (failed over); re-resolve the "
                    f"owner for key {key}")
            cur = self._versions.get(key, 0)
            if pinned is None or pinned > cur:
                pinned = cur + 1
                self._versions[key] = pinned
            return pinned

    def push_bytes(self, key: int, buf: np.ndarray,
                   codec: int = WIRE_RAW,
                   version: Optional[int] = None) -> int:
        """Push codec-encoded bytes; returns the round number the matching
        pull must wait for. Retryable wire failures re-send the SAME
        (worker, key, version) — the server dedupes a replay whose
        original landed (the version-safe replay contract), so a lost
        *response* cannot double-sum the round.

        ``version`` pins the round across HIGHER-level retries (the
        scheduler's stage retry passes the version its first try minted):
        a push whose wire budget was exhausted AFTER the server applied it
        must re-send the same version, not mint a fresh one that the
        dedupe cannot recognize. A pinned version from before a failover
        (the per-key counter was reset, so it exceeds the counter) is
        discarded and a fresh round minted against the new placement."""
        with self._vlock:
            cur = self._versions.get(key, 0)
            if version is None or version > cur:
                version = cur + 1
                self._versions[key] = version
        b = np.ascontiguousarray(buf)
        crc = wire_crc32(b) if self._crc and not self._is_local(
            self.server_for(key)) else 0

        def attempt(sidx):
            if self.pacer is not None:
                # book the payload's transmission time on the emulated NIC
                # BEFORE the wire op (every re-send pays wire time again,
                # as it would on a real NIC); applies to the IPC path too:
                # colocated deployments being modeled still cross a NIC
                self.pacer.throttle_send(int(b.nbytes))
            if self._is_local(sidx):
                rc = load_lib().bps_local_push2(
                    self._worker_id, key, codec, version,
                    b.ctypes.data, b.nbytes,
                )
                if rc == -11:
                    raise WorkerEvictedError(
                        f"local push of key {key} rejected: worker "
                        f"{self._worker_id} evicted; rejoin required")
                if rc != 0:
                    raise RuntimeError(f"local push failed (rc={rc})")
                return
            inj = self._inject_pre("push", sidx)
            send = b
            if inj is not None and inj.kind == "corrupt":
                # CRC was computed on the pristine payload: the flipped
                # byte is detected server-side and NEVER summed
                send = b.copy()
                FaultPlan.corrupt(send.view(np.uint8).reshape(-1),
                                  inj.corrupt_at)
            self._conn(sidx).push(key, send, codec, self._worker_id,
                                  version, crc)
            if inj is not None and inj.kind == "timeout":
                # the push WAS applied; lose the ack (models a lost
                # response) — the retry's re-send exercises the dedupe
                self._kill_conn(sidx)
                raise InjectedTimeout(
                    f"injected: push ack for key {key} lost "
                    f"(server {sidx})")

        self._retry_loop("push", key, attempt)
        with self._vlock:
            self.bytes_pushed += int(b.nbytes)
        self._m_push_bytes.inc(int(b.nbytes))
        self._m_push_bytes_nic.inc(int(b.nbytes))
        self._m_push_size.observe(int(b.nbytes))
        return version

    def pull_bytes(self, key: int, capacity: int, version: int,
                   codec: int = WIRE_RAW) -> np.ndarray:
        """Pull the round result as codec-encoded bytes. Pull retries are
        naturally idempotent (the round snapshot is immutable)."""

        def attempt(sidx):
            out = np.empty(capacity, np.uint8)
            if self._is_local(sidx):
                import ctypes

                ep = ctypes.c_uint64(0)
                served = ctypes.c_uint64(0)
                got = load_lib().bps_local_pull3(
                    key, codec, version, self._recv_timeout,
                    out.ctypes.data, out.nbytes, ctypes.byref(ep),
                    ctypes.byref(served),
                )
                if got < 0:
                    raise RuntimeError(f"local pull failed (rc={got})")
                if self.pacer is not None:
                    self.pacer.throttle_recv(int(got))
                # same divisor contract as the TCP header stamp: the
                # epoch the returned ROUND closed under
                self._tls.round_live = self._live_at(
                    sidx, int(ep.value) & 0xFFFF)
                self._tls.round_served = int(served.value)
                return out, int(got)
            inj = self._inject_pre("pull", sidx)
            conn = self._conn(sidx)
            if self._crc:
                got, resp_crc = conn.pull(key, out, version, codec,
                                          want_crc=True,
                                          worker_id=self._worker_id)
            else:
                got, resp_crc = conn.pull(
                    key, out, version, codec,
                    worker_id=self._worker_id), 0
            if self.pacer is not None:
                # book the response's transmission time per ATTEMPT
                # (downstream direction): a lost/corrupted response still
                # crossed the emulated NIC, exactly like a re-sent push
                self.pacer.throttle_recv(int(got))
            if inj is not None:
                if inj.kind == "timeout":
                    self._kill_conn(sidx)
                    raise InjectedTimeout(
                        f"injected: pull response for key {key} lost "
                        f"(server {sidx})")
                if inj.kind == "corrupt" and got > 0:
                    FaultPlan.corrupt(out[:got], inj.corrupt_at)
            if resp_crc and wire_crc32(out[:got]) != resp_crc:
                raise WireCorruption(
                    f"pull response for key {key} failed CRC "
                    f"(server {sidx}); retrying")
            # the response header carries the epoch this ROUND closed
            # under — resolve the round's own live count (divisor
            # authority for averaging; the current epoch may be newer)
            self._tls.round_live = self._live_at(sidx,
                                                 conn.last_pull_epoch())
            self._tls.round_served = conn.last_pull_round()
            return out, int(got)

        out, got = self._retry_loop("pull", key, attempt)
        with self._vlock:
            self.bytes_pulled += got
        self._m_pull_bytes.inc(got)
        self._m_pull_bytes_nic.inc(got)
        # bounded-staleness telemetry: requested − served = how stale the
        # consumed aggregate was (0 on the strict-sync tier), and minted −
        # served = how far this worker's pipeline runs ahead of the round
        # it just consumed (≈ K when the window is full)
        served = getattr(self._tls, "round_served", None)
        if served is not None and version > 0:
            self._m_staleness.observe(max(0, int(version) - int(served)))
            with self._vlock:
                # Serve-AHEAD re-sync (staleness only): a straggler whose
                # rounds were force-closed past it gets served a NEWER
                # round than it asked for. Its mint counter must adopt
                # that round — its next push then targets the OPEN round
                # and rejoins the quorum, instead of minting ever-late
                # versions the server consumes silently forever (a
                # transient slowdown would otherwise exclude the worker
                # for the rest of the job). Max-merge, same contract as
                # adopt_rounds; in strict sync served == requested ≤ the
                # counter, so this is structurally a no-op there.
                if (self._staleness > 0
                        and int(served) > self._versions.get(key, 0)):
                    self._versions[key] = int(served)
                minted = self._versions.get(key, int(version))
            ahead = max(0, int(minted) - int(served))
            self._m_rounds_ahead.set(ahead)
            self._m_rounds_ahead_nic.set(ahead)
        return out[:got]

    def push(self, key: int, data: np.ndarray) -> int:
        """Push this worker's fp32 partition (raw wire)."""
        data = np.ascontiguousarray(data, dtype=np.float32)
        return self.push_bytes(key, data.view(np.uint8).ravel(), WIRE_RAW)

    def pull(self, key: int, nelems: int, version: int) -> np.ndarray:
        buf = self.pull_bytes(key, nelems * 4, version, WIRE_RAW)
        # view, not copy: pull_bytes allocated the buffer for this call, so
        # the caller owns it — the copy was a full extra pass per partition
        return buf.view(np.float32)

    def push_pull(self, key: int, data: np.ndarray) -> np.ndarray:
        v = self.push(key, data)
        return self.pull(key, data.size, v)

    def barrier(self) -> None:
        """Global worker barrier through the lowest LIVE server (server 0
        while healthy — reference: ps-lite Postoffice::Barrier via the
        scheduler; after a failover the survivors host it). Carries the
        worker id: a barrier wait can outlast a short membership lease,
        and the arrival itself refreshes it."""
        with self._vlock:
            sidx = min(self._live) if self._live else 0
        self._conn(sidx).barrier(self._worker_id)

    def ping(self, sidx: int = 0) -> Tuple[int, int]:
        """(server CLOCK_REALTIME ns, rtt ns) for clock alignment of merged
        worker/server traces (SURVEY §5.1 dPRO clock-offset capability).
        Also the health monitor's probe — injected down windows fail it —
        and, carrying the worker id, the membership lease HEARTBEAT (an
        evicted worker's ping re-admits it)."""
        self._inject_pre("ping", sidx)
        return self._conn(sidx).ping(self._worker_id)

    def clock_offset_ns(self, sidx: int = 0) -> int:
        """Estimated server_clock − local_clock in ns (RTT/2 method)."""
        import time

        server_ns, rtt = self.ping(sidx)
        return server_ns + rtt // 2 - time.time_ns()

    def close(self) -> None:
        """Drop every connection WITHOUT the kShutdown round. For the
        extra per-controller NICs of a sharded pod (DcnCore
        ``pod_controllers``): servers count shutdowns against
        DMLC_NUM_WORKER and all of a pod's controllers share the pod's
        worker id, so exactly one of them — worker 0's ``shutdown()`` —
        may say goodbye."""
        if self._closed:
            return
        self._closed = True
        if self._health is not None:
            self._health.stop(join=True)
        with self._conn_lock:
            conns = list(self._all_conns)
            self._all_conns.clear()
        for c in conns:
            c.close()
        self._tls.conns = {}

    def shutdown(self) -> None:
        """Tell every server this worker is done (server exits once all
        workers said so), then drop connections."""
        if self._closed:
            return
        self._closed = True
        if self._health is not None:
            # join (bounded by the monitor's short probe timeouts) BEFORE
            # tearing down: the monitor owns its probe connections, but a
            # fail_over it triggers mid-shutdown would race the teardown
            self._health.stop(join=True)
        self.export_counters()
        # one shutdown per server (not per connection): servers count
        # shutdowns against DMLC_NUM_WORKER. Use this thread's pool
        # (creating connections as needed), then close EVERY connection
        # ever created — snapshot taken after the shutdown round so none
        # created during it escape.
        pool = getattr(self._tls, "conns", {})
        for sidx in range(len(self._servers)):
            try:
                c = pool.get(sidx)
                if c is not None and c.is_dead():
                    c = None  # killed socket cannot carry the kShutdown —
                    # send it on a fresh connection or the server's
                    # shutdown count never completes and serve_forever hangs
                if c is None:
                    host, port = self._servers[sidx]
                    c = NativeClient(host, port, 2000, self._recv_timeout)
                    with self._conn_lock:
                        self._all_conns.append(c)
                # identified goodbye: the membership layer marks this
                # worker DEPARTED, so the server can exit even if a PEER
                # died without one (departed + evicted covers everyone)
                c.shutdown(self._worker_id)
            except Exception as e:  # noqa: BLE001 - server may already be
                # gone (it stops itself once every worker said shutdown,
                # and a chaos run may have killed it outright) — expected
                # enough not to warn, but never silent: the index says
                # WHICH server missed its shutdown count
                log.debug("shutdown of server %d failed: %s: %s",
                          sidx, type(e).__name__, e)
        with self._conn_lock:
            conns = list(self._all_conns)
            self._all_conns.clear()
        for c in conns:
            c.close()
        self._tls.conns = {}

    def get_counters(self) -> Dict[str, int]:
        """Robustness counters (+ per-kind injected counts when a fault
        plan is armed, + the health monitor's last-probe age and
        per-server miss counts so a stall report shows WHY failover did
        or did not fire) — what the chaos smoke and the bench assert on."""
        with self._counter_lock:
            out = dict(self.counters)
        out["live_pods"] = self.live_pods()
        if self._plan is not None:
            for k, v in self._plan.counters().items():
                out[f"injected_{k}"] = v
        if self._health is not None:
            out.update(self._health.debug_counters())
        return out

    def export_counters(self, tag: Optional[str] = None) -> None:
        """Fold the robustness counters into the chrome-trace metadata so
        a retry storm / failover is visible beside the dPRO timeline.
        Extra pod-controller NICs share the pod's worker id, so callers
        closing them pass a ``worker<id>.nic<rank>`` tag — the plain
        ``worker<id>`` key belongs to NIC 0's ``shutdown()``."""
        counters = self.get_counters()
        if any(counters.values()):
            get_tracer().metadata.setdefault("robustness", {})[
                tag or f"worker{self._worker_id}"] = counters
            # the flight recorder keeps the final per-NIC snapshot too:
            # after retire_nic closes this worker, the snapshot (incl.
            # injected_* and health-probe state, which have no
            # per-increment registry mirror) outlives the instance
            get_flight_recorder().record_event(
                "counters_export",
                {"tag": tag or f"worker{self._worker_id}",
                 "nic": self._nic_tag, "counters": counters})


class _HealthMonitor:
    """Marks servers dead after K consecutive missed heartbeats.

    Built on the kPing probe, but on the monitor's OWN connections with
    SHORT connect/recv timeouts (scaled to the probe interval): they are
    never shared with — or torn down by — the data plane, so a probe
    mid-flight during ``PSWorker.shutdown`` cannot race a freed native
    client, and a really-hung server costs one bounded probe, not the
    data plane's long recv timeout. ``miss_limit`` consecutive failures
    trigger :meth:`PSWorker.fail_over`. The reference analog is ps-lite's
    scheduler heartbeat (SURVEY §5.3); every worker monitors
    independently and the failover barrier aligns their live-set views.
    Injected ``server<N>`` fault windows fail the probe through the
    worker's plan (``_inject_pre('ping', ...)``).
    """

    def __init__(self, worker: "PSWorker", interval_ms: int,
                 miss_limit: int):
        self._worker = worker
        self._interval = max(1, interval_ms) / 1e3
        # probe timeout: generous vs the interval, small vs the data
        # plane's recv timeout
        self._probe_ms = max(500, 4 * interval_ms)
        self._miss_limit = miss_limit
        self._misses: Dict[int, int] = {}
        # debuggability (stall reports): per-server CUMULATIVE miss count
        # and the monotonic time of the last finished probe attempt.
        # _dbg_lock guards these against debug_counters() readers — a
        # stall report must never crash on "dict changed during
        # iteration" while the monitor records its first miss.
        self._total_misses: Dict[int, int] = {}
        self._last_probe: Dict[int, float] = {}
        self._dbg_lock = threading.Lock()
        self._m_misses = get_registry().counter("health.misses")
        self._conns: Dict[int, NativeClient] = {}
        self._stop_ev = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bps-health", daemon=True)

    def debug_counters(self) -> Dict[str, int]:
        """Folded into PSWorker.get_counters(): per-server consecutive +
        cumulative miss counts and the age of the newest probe — a stall
        report then shows whether the monitor was even looking, and how
        close each server sat to the miss limit."""
        now = time.monotonic()
        out: Dict[str, int] = {}
        with self._dbg_lock:
            for sidx, n in sorted(self._misses.items()):
                out[f"health_consec_miss_s{sidx}"] = n
            for sidx, n in sorted(self._total_misses.items()):
                out[f"health_misses_s{sidx}"] = n
            if self._last_probe:
                age = now - max(self._last_probe.values())
                out["health_last_probe_age_ms"] = int(age * 1e3)
        return out

    def start(self) -> None:
        self._thread.start()

    def stop(self, join: bool = False) -> None:
        self._stop_ev.set()
        if join and self._thread.is_alive():
            # bounded: one probe + one bounded failover barrier, both on
            # probe timeouts (never the data plane's long recv timeout)
            self._thread.join(timeout=2 * self._probe_ms / 1e3 + 5.0)

    def _probe(self, sidx: int) -> None:
        self._worker._inject_pre("ping", sidx)
        c = self._conns.get(sidx)
        if c is None or c.is_dead():
            if c is not None:
                c.close()
            host, port = self._worker._servers[sidx]
            c = NativeClient(host, port, self._probe_ms, self._probe_ms)
            self._conns[sidx] = c
        # the probe doubles as this worker's membership lease HEARTBEAT
        # (and re-admits it after an eviction, e.g. a worker:hang window
        # that outlasted the lease)
        c.ping(self._worker._worker_id)

    def _run(self) -> None:
        try:
            while not self._stop_ev.wait(self._interval):
                if self._worker.is_wedged():
                    # a dead/wedged process heartbeats nothing: going
                    # silent here is exactly what lets the server lease
                    # evict this worker on schedule
                    continue
                for sidx in sorted(self._worker.live_servers()):
                    if self._stop_ev.is_set():
                        return
                    try:
                        self._probe(sidx)
                        with self._dbg_lock:
                            self._last_probe[sidx] = time.monotonic()
                            self._misses[sidx] = 0
                    except WorkerKilledError:
                        return  # injected process death: no more probes
                    except Exception as e:  # noqa: BLE001 - miss
                        self._m_misses.inc()
                        with self._dbg_lock:
                            self._last_probe[sidx] = time.monotonic()
                            n = self._misses.get(sidx, 0) + 1
                            self._misses[sidx] = n
                            self._total_misses[sidx] = (
                                self._total_misses.get(sidx, 0) + 1)
                        log.debug(
                            "heartbeat miss %d/%d for server %d (%s)",
                            n, self._miss_limit, sidx, e)
                        if n >= self._miss_limit:
                            self._fail_over(sidx)
        finally:
            for c in self._conns.values():
                c.close()

    def _fail_over(self, sidx: int) -> None:
        """Failover with a BOUNDED alignment barrier: the data-plane
        barrier waits on the worker's long recv timeout, which would hold
        this thread (and block a joining shutdown) for tens of seconds —
        use a dedicated probe-timeout connection instead, and accept that
        a laggard peer degrades the barrier to best-effort (fail_over's
        own barrier handling is best-effort already)."""
        if not self._worker.fail_over(sidx, barrier=False):
            return
        live = self._worker.live_servers()
        if not live:
            return
        try:
            host, port = self._worker._servers[min(live)]
            c = NativeClient(host, port, self._probe_ms, self._probe_ms)
            try:
                c.barrier()
            finally:
                c.close()
        except Exception as e:  # noqa: BLE001 - best-effort alignment
            log.warning("failover barrier (monitor) failed: %s", e)
