"""Application-level DCN bandwidth pacer (token bucket on the wire path).

The framework's compression story is about slow *cross-pod* networks
(SURVEY §6: up to ~2× on bandwidth-starved DCN links), but every benchmark
host exposes only loopback — where raw fp32 trivially beats every codec
because the "wire" runs at memcpy speed. ``BYTEPS_DCN_THROTTLE_MBPS``
arms this pacer inside :class:`~byteps_tpu.server.PSWorker` (and therefore
every consumer of the framed-TCP client path: ``DcnCore``, the jax hybrid
pipeline, ``bench.py --mode throttled``): payload bytes are charged
against per-direction token buckets before/after each wire operation, so
loopback behaves like a NIC of the configured speed — no root, no netem,
no tc, fully deterministic across hosts.

Model: one emulated full-duplex NIC per worker (one ``DcnPacer`` per
``PSWorker``), with independent send/recv buckets — pushes and pulls
overlap like they would on a real link, while all scheduler threads of
one worker share that worker's bandwidth (deficit accounting serializes
them exactly as a shared NIC would). Frame headers and control messages
(init/barrier/ack) are not charged; at the ≥64 KB partition sizes the
DCN tier moves, header bytes are noise.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

from byteps_tpu.common.metrics import get_registry

# sequential id per DcnPacer: one pacer per emulated NIC, and a shared
# debt gauge would be last-writer-wins across NICs — NIC 0's idle
# update must not mask NIC 2's 4 MB backlog
_PACER_SEQ = itertools.count()


class TokenBucket:
    """Deficit token bucket: ``throttle(n)`` sleeps long enough that the
    long-run byte rate never exceeds ``rate_bytes_per_s``.

    The balance may go arbitrarily negative (a 4 MB partition against a
    64 KB burst simply books its full transmission time), which is what
    makes one bucket correctly serialize concurrent senders: each caller
    books its bytes under the lock and sleeps out its own share of the
    accumulated deficit.
    """

    def __init__(self, rate_bytes_per_s: float,
                 burst_bytes: Optional[float] = None):
        if rate_bytes_per_s <= 0:
            raise ValueError(f"rate must be positive, got {rate_bytes_per_s}")
        self.rate = float(rate_bytes_per_s)
        # default burst: a FIXED 64 KB — small control messages ride it
        # (a real NIC does not pace a lone frame) while every payload
        # beyond one socket buffer pays wire time. Deliberately NOT
        # rate-scaled: a burst proportional to rate would let a heavily
        # compressed payload cross a fast emulated link entirely free,
        # skewing codec-vs-raw races at high rates.
        self.burst = float(
            burst_bytes if burst_bytes is not None else 64 << 10
        )
        self._avail = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def debt_bytes(self) -> float:
        """Current token DEBT: how many booked bytes have not yet 'fit'
        the rate (0 when the burst absorbs traffic). The always-on gauge
        of how far behind the emulated NIC is running."""
        with self._lock:
            return max(0.0, -self._avail)

    def throttle(self, nbytes: int) -> float:
        """Charge ``nbytes`` and sleep until they fit the rate; returns
        the seconds slept (0.0 when the burst absorbed the charge)."""
        if nbytes <= 0:
            return 0.0
        with self._lock:
            now = time.monotonic()
            self._avail = min(
                self.burst, self._avail + (now - self._last) * self.rate
            )
            self._last = now
            self._avail -= nbytes
            wait = -self._avail / self.rate if self._avail < 0 else 0.0
        if wait > 0:
            time.sleep(wait)
        return wait


class DcnPacer:
    """One emulated full-duplex NIC: independent send/recv buckets, each
    at ``mbps`` megabits/s (the way link speeds are quoted)."""

    def __init__(self, mbps: float, burst_bytes: Optional[float] = None):
        if mbps <= 0:
            raise ValueError(f"mbps must be positive, got {mbps}")
        self.mbps = float(mbps)
        rate = self.mbps * 1e6 / 8.0
        self.send = TokenBucket(rate, burst_bytes)
        self.recv = TokenBucket(rate, burst_bytes)
        # wire accounting for tests/bench: bytes charged + seconds slept
        self.sent_bytes = 0
        self.recv_bytes = 0
        self._acct_lock = threading.Lock()
        self.send_sleep_s = 0.0
        self.recv_sleep_s = 0.0
        # always-on registry mirror (docs/observability.md): sleep time
        # is the price the emulated link charged (process-wide counters
        # sum correctly across pacers); token debt is how far behind
        # THIS NIC is running, so the gauges are per-pacer series —
        # their max() is the high-water mark a stall report wants
        _reg = get_registry()
        tag = f"p{next(_PACER_SEQ)}"
        self._m_send_sleep = _reg.counter("pacer.send_sleep_us")
        self._m_recv_sleep = _reg.counter("pacer.recv_sleep_us")
        self._m_send_debt = _reg.gauge(f"pacer.{tag}.send_debt_bytes")
        self._m_recv_debt = _reg.gauge(f"pacer.{tag}.recv_debt_bytes")

    def throttle_send(self, nbytes: int) -> float:
        slept = self.send.throttle(nbytes)
        with self._acct_lock:
            self.sent_bytes += int(nbytes)
            self.send_sleep_s += slept
        if slept > 0:
            self._m_send_sleep.inc(int(slept * 1e6))
        self._m_send_debt.set(self.send.debt_bytes())
        return slept

    def throttle_recv(self, nbytes: int) -> float:
        slept = self.recv.throttle(nbytes)
        with self._acct_lock:
            self.recv_bytes += int(nbytes)
            self.recv_sleep_s += slept
        if slept > 0:
            self._m_recv_sleep.inc(int(slept * 1e6))
        self._m_recv_debt.set(self.recv.debt_bytes())
        return slept


def pacer_from_mbps(mbps: float) -> Optional[DcnPacer]:
    """``DcnPacer`` for a positive rate, None for 0/negative (pacing off)."""
    return DcnPacer(mbps) if mbps and mbps > 0 else None
