#include "server.h"

#include <atomic>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "reducer.h"
#include "threadpool.h"

namespace bps {
namespace {

struct PendingPull {
  int fd;
  uint64_t version;  // respond when store version >= this
};

// Double-buffered per-key state (reference: BytePSArray store + the
// "all workers arrived → answer queued pulls" logic in BytePSHandler).
// `accum` receives the in-progress round; on completion it is copied to
// `result` and zeroed. A worker cannot start round v+2 before every worker
// pulled round v+1 (its own pull gates it), so `result` is never
// overwritten while still being served.
struct KeyStore {
  std::mutex mu;
  std::vector<float> accum;
  std::vector<float> result;
  uint64_t version = 0;
  uint32_t arrived = 0;
  std::vector<PendingPull> pending;
};

class Server {
 public:
  int Start(uint16_t port, int num_workers, int engine_threads, bool async) {
    num_workers_ = num_workers;
    async_ = async;
    engine_ = std::make_unique<ThreadPool>(engine_threads);
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(port);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      ::close(listen_fd_);
      return -2;
    }
    if (::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      return -3;
    }
    running_ = true;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return 0;
  }

  void Wait() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return !running_.load(); });
  }

  void Stop() {
    // serialize concurrent stops (worker-initiated auto-stop can race an
    // explicit StopServer); the loser blocks until teardown completes so
    // the caller may safely delete the server afterwards
    std::lock_guard<std::mutex> stop_lk(stop_mu_);
    bool was = running_.exchange(false);
    if (!was) return;
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (listen_fd_ >= 0) ::close(listen_fd_);
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int fd : conns_) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable() &&
        accept_thread_.get_id() != std::this_thread::get_id()) {
      accept_thread_.join();
    }
    for (auto& t : conn_threads_) {
      if (t.joinable() && t.get_id() != std::this_thread::get_id()) t.join();
    }
    conn_threads_.clear();
    if (engine_) engine_->Stop();
    {
      // close only after every conn thread exited — closing earlier would
      // let the kernel reuse the fd number (e.g. for a Python socket in
      // this process) while a stale shutdown() could still target it
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (int fd : conns_) ::close(fd);
      conns_.clear();
      send_mu_.clear();
    }
    done_cv_.notify_all();
  }

 private:
  void AcceptLoop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      set_nodelay(fd);
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        conns_.push_back(fd);
        send_mu_[fd] = std::make_unique<std::mutex>();
        conn_threads_.emplace_back([this, fd] { ConnLoop(fd); });
      }
    }
  }

  void SendFrame(int fd, Cmd cmd, uint64_t key, uint64_t version,
                 const void* payload, uint32_t len) {
    std::mutex* mu = nullptr;
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      auto it = send_mu_.find(fd);
      if (it == send_mu_.end()) return;
      mu = it->second.get();
    }
    std::lock_guard<std::mutex> lk(*mu);
    send_frame(fd, cmd, key, version, payload, len);
  }

  KeyStore* GetOrCreate(uint64_t key, size_t nfloats) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto& slot = store_[key];
    if (!slot) {
      slot = std::make_unique<KeyStore>();
      slot->accum.assign(nfloats, 0.f);
      slot->result.assign(nfloats, 0.f);
    }
    return slot.get();
  }

  KeyStore* Get(uint64_t key) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto it = store_.find(key);
    return it == store_.end() ? nullptr : it->second.get();
  }

  void HandlePush(int fd, uint64_t key, std::shared_ptr<std::vector<char>> buf) {
    engine_->Submit([this, fd, key, buf] {
      KeyStore* ks = Get(key);
      if (ks == nullptr) {
        SendFrame(fd, kErr, key, 0, "push before init", 16);
        return;
      }
      const auto n = static_cast<int64_t>(buf->size() / sizeof(float));
      const float* src = reinterpret_cast<const float*>(buf->data());
      std::vector<std::pair<int, uint64_t>> ready;  // (fd, version) to answer
      uint64_t v = 0;
      {
        std::lock_guard<std::mutex> lk(ks->mu);
        if (async_) {
          // async mode: accumulate into the served buffer immediately, no
          // per-round barrier (reference BYTEPS_ENABLE_ASYNC)
          reduce_sum_f32(ks->result.data(), src, n);
          ks->version++;
        } else {
          reduce_sum_f32(ks->accum.data(), src, n);
          if (++ks->arrived == static_cast<uint32_t>(num_workers_)) {
            std::memcpy(ks->result.data(), ks->accum.data(),
                        ks->accum.size() * sizeof(float));
            std::memset(ks->accum.data(), 0,
                        ks->accum.size() * sizeof(float));
            ks->arrived = 0;
            ks->version++;
          }
        }
        v = ks->version;
        auto it = ks->pending.begin();
        while (it != ks->pending.end()) {
          if (v >= it->version || async_) {
            ready.emplace_back(it->fd, v);
            it = ks->pending.erase(it);
          } else {
            ++it;
          }
        }
        for (auto& [rfd, rv] : ready) {
          SendFrame(rfd, kResp, key, rv, ks->result.data(),
                    static_cast<uint32_t>(ks->result.size() * sizeof(float)));
        }
      }
      SendFrame(fd, kAck, key, v, nullptr, 0);
    });
  }

  void HandlePull(int fd, uint64_t key, uint64_t version) {
    KeyStore* ks = Get(key);
    if (ks == nullptr) {
      SendFrame(fd, kErr, key, 0, "pull before init", 16);
      return;
    }
    std::lock_guard<std::mutex> lk(ks->mu);
    if (ks->version >= version || (async_ && ks->version > 0)) {
      SendFrame(fd, kResp, key, ks->version, ks->result.data(),
                static_cast<uint32_t>(ks->result.size() * sizeof(float)));
    } else {
      ks->pending.push_back({fd, version});
    }
  }

  void HandleBarrier(int fd) {
    std::vector<int> release;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      barrier_fds_.push_back(fd);
      if (static_cast<int>(barrier_fds_.size()) == num_workers_) {
        release.swap(barrier_fds_);
      }
    }
    for (int rfd : release) SendFrame(rfd, kAck, 0, 0, nullptr, 0);
  }

  void ConnLoop(int fd) {
    FrameHeader h;
    while (running_ && recv_all(fd, &h, sizeof(h))) {
      if (h.magic != kMagic) break;
      auto payload = std::make_shared<std::vector<char>>();
      if (h.len > 0) {
        payload->resize(h.len);
        if (!recv_all(fd, payload->data(), h.len)) break;
      }
      switch (h.cmd) {
        case kInit:
          GetOrCreate(h.key, h.version / sizeof(float));
          SendFrame(fd, kAck, h.key, 0, nullptr, 0);
          break;
        case kPush:
          HandlePush(fd, h.key, std::move(payload));
          break;
        case kPull:
          HandlePull(fd, h.key, h.version);
          break;
        case kBarrier:
          HandleBarrier(fd);
          break;
        case kShutdown: {
          SendFrame(fd, kAck, 0, 0, nullptr, 0);
          int count = ++shutdown_count_;
          if (count >= num_workers_) {
            std::thread([this] { Stop(); }).detach();
          }
          return;
        }
        default:
          SendFrame(fd, kErr, h.key, 0, "bad cmd", 7);
          break;
      }
    }
  }

  int listen_fd_ = -1;
  int num_workers_ = 1;
  bool async_ = false;
  std::atomic<bool> running_{false};
  std::atomic<int> shutdown_count_{0};
  std::unique_ptr<ThreadPool> engine_;
  std::thread accept_thread_;
  std::vector<std::thread> conn_threads_;
  std::vector<int> conns_;
  std::mutex conn_mu_;
  std::unordered_map<int, std::unique_ptr<std::mutex>> send_mu_;
  std::mutex store_mu_;
  std::unordered_map<uint64_t, std::unique_ptr<KeyStore>> store_;
  std::mutex barrier_mu_;
  std::vector<int> barrier_fds_;
  std::mutex stop_mu_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
};

Server* g_server = nullptr;
std::mutex g_server_mu;

}  // namespace

int StartServer(uint16_t port, int num_workers, int engine_threads,
                bool async) {
  std::lock_guard<std::mutex> lk(g_server_mu);
  if (g_server != nullptr) return -10;  // already running
  auto* s = new Server();
  int rc = s->Start(port, num_workers, engine_threads, async);
  if (rc != 0) {
    delete s;
    return rc;
  }
  g_server = s;
  return 0;
}

void WaitServer() {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_server_mu);
    s = g_server;
  }
  if (s != nullptr) s->Wait();
}

void StopServer() {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_server_mu);
    s = g_server;
    g_server = nullptr;
  }
  if (s != nullptr) {
    s->Stop();
    delete s;
  }
}

}  // namespace bps
