#include "server.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "codec.h"
#include "common.h"
#include "threadpool.h"

namespace bps {
namespace {

int64_t realtime_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

int64_t steady_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// vector<char> whose resize() default-initializes instead of zeroing:
// payload buffers are filled by recv_all immediately after sizing, and the
// avoided memset is a full extra memory pass per 4 MB push.
template <class T>
struct uninit_alloc : std::allocator<T> {
  template <class U>
  struct rebind {
    using other = uninit_alloc<U>;
  };
  template <class U>
  void construct(U* p) noexcept {
    ::new (static_cast<void*>(p)) U;
  }
  template <class U, class... Args>
  void construct(U* p, Args&&... args) {
    ::new (static_cast<void*>(p)) U(std::forward<Args>(args)...);
  }
};
using RawBuf = std::vector<char, uninit_alloc<char>>;
// Accumulator/snapshot buffers skip value-initialization too: a closing
// round MOVES accum into the snapshot and must re-allocate; zero-filling
// 4 MB per round per key costs real memory bandwidth on the engine's
// critical path, and the first push of a round overwrites (raw memcpy) or
// explicitly zero+sums (other codecs) anyway.
using FloatBuf = std::vector<float, uninit_alloc<float>>;

// Ordered executor over the shared engine pool, one per (key, worker).
// A worker's pushes for one key are applied in RECEIVE order: two
// pipelined pushes (rounds v and v+1) submitted to an unordered pool could
// otherwise swap, crediting v+1's payload to round v and corrupting both
// sums. Keyed by (key, worker) — NOT by connection — so the ordering
// survives a client reconnect (a timed-out socket is killed client-side
// and the next push arrives on a fresh connection, but must still land
// after the old connection's queued push). Different keys and different
// workers fan out across the pool in parallel.
struct Strand {
  std::mutex mu;
  std::deque<std::function<void()>> q;
  bool running = false;
};

// Per-connection state. shared_ptr-owned by the conn thread, pending
// pulls, barrier waiters, and in-flight responses, so a response racing a
// disconnect can never touch a freed mutex or a recycled fd number: the
// `closed` flag (guarded by send_mu) gates every write, and the fd is only
// closed under that same lock.
struct Conn {
  uint64_t id = 0;
  int fd = -1;
  std::mutex send_mu;  // serializes frame writes; also guards `closed`
  bool closed = false;
};
using ConnPtr = std::shared_ptr<Conn>;

struct PendingPull {
  ConnPtr conn;
  uint64_t version;  // respond when store version >= this (under bounded
                     // staleness: the requested round minus K — the
                     // oldest round this pull may legally be served from)
  uint8_t codec;     // response encoding the worker asked for
  bool want_crc;     // checksummed response requested
  int64_t enq_ms;    // steady clock, for the timeout sweep
  uint64_t force_min = 0;  // bounded staleness: the round this pull may
                           // FORCE-close up to (0 = may not force) — a
                           // later push apply re-checks it so a parked
                           // pull can make progress off the straggler
};

struct DeferredPush {
  uint16_t worker;
  uint8_t codec;
  uint64_t version;
  std::shared_ptr<RawBuf> buf;
};

// Per-key state (reference: BytePSArray store + the "all workers arrived →
// answer queued pulls" logic in BytePSHandler). `accum` receives the
// in-progress round; on completion it is MOVED into an immutable
// shared_ptr snapshot (`result`) and a fresh UNINITIALIZED accumulator
// allocated (the next round's first push overwrites or zero-fills it —
// see ApplyPushLocked), so responses serialize from the snapshot OUTSIDE
// the key mutex — large sends never stall other consumers of the key.
struct KeyStore {
  std::mutex mu;
  std::condition_variable cv;  // local (in-process) pulls wait here
  // Membership epoch at the moment `result`'s round CLOSED: pull
  // responses are stamped with THIS (not the send-time epoch), so a
  // survivor averaging a round that closed under the old membership
  // divides by the old live count even when the response is delivered
  // after a later eviction bumped the epoch.
  uint64_t result_epoch = 0;
  // Dense element count, immutable after creation. Validation MUST read
  // this, not accum.size(): a closing round MOVES accum out and
  // reallocates it under mu, so an unlocked accum.size() can observe 0
  // and spuriously reject a concurrent pipelined push.
  size_t n_elems = 0;
  FloatBuf accum;
  std::shared_ptr<const FloatBuf> result;
  uint64_t version = 0;
  uint32_t arrived = 0;
  std::vector<uint8_t> pushed;         // per-worker arrival bitmap (sync)
  // Highest push version already summed per worker (0 = none). A re-sent
  // push from the worker retry engine carries the same (worker, key,
  // version) as the original; when the original DID land (the lost frame
  // was the ack/response, not the request), the replay must be dropped
  // here instead of double-summing the round.
  std::vector<uint64_t> applied_version;
  std::vector<DeferredPush> deferred;  // next-round pushes that came early
  CodecHint hint;         // evolves with every push (current open round)
  CodecHint result_hint;  // frozen copy of `hint` when `result`'s round
                          // closed — responses for that round encode with
                          // THIS, so a next-round push changing topk k or
                          // dithering params cannot retro-change the wire
                          // format of a round already being served
  std::vector<PendingPull> pending;
  // one re-encode per (version, codec): every worker pulls the same round
  uint64_t cache_version = 0;
  uint8_t cache_codec = 0xFF;
  std::shared_ptr<const std::vector<char>> cache_blob;
  // per-worker push-ordering strands (see Strand)
  std::mutex strands_mu;
  std::unordered_map<uint16_t, std::shared_ptr<Strand>> strands;
};

// Server-side chrome-trace stages (SURVEY §5.1 — the fork's server-side
// timestamp capability). Timestamps are absolute CLOCK_REALTIME so worker
// traces (which record their wall-clock origin) can be aligned.
enum TraceStage : uint8_t {
  kTrPushRecv = 0,
  kTrSum = 1,
  kTrPullResp = 2,
  kTrRound = 3,
  kTrMember = 4,  // key = worker id, len = live count,
                  // codec = 0 evict / 1 rejoin / 2 mid-stream join
};
const char* kTraceStageName[] = {"PUSH_RECV", "SUM", "PULL_RESP", "ROUND",
                                 "MEMBER"};

struct TraceEv {
  int64_t ts_us;
  int32_t dur_us;
  uint64_t key;
  uint32_t len;
  uint8_t stage;
  uint8_t codec;
};

constexpr size_t kMaxTraceEvents = 1u << 21;

// Ceiling on worker ids a kJoin may grow the membership table to —
// matches the worker-side Members() bitmap buffer (1024 bytes); a
// malformed frame must not drive an unbounded per-key vector resize.
constexpr uint16_t kMaxWorkers = 1024;

class Server {
 public:
  int Start(uint16_t port, int num_workers, int engine_threads, bool async,
            int pull_timeout_ms, int server_id, bool schedule,
            int lease_ms, int staleness) {
    num_workers_.store(num_workers);
    async_ = async;
    pull_timeout_ms_ = pull_timeout_ms;
    server_id_ = server_id;
    schedule_ = schedule;
    lease_ms_ = lease_ms;
    // bounded staleness is a SYNC-mode ladder; async is its K=inf limit
    // and keeps its own free-running code path
    staleness_ = async ? 0 : std::max(0, staleness);
    // membership starts fully live even with the lease disabled, so every
    // live-set consumer (round completion, barriers, shutdown gate) reads
    // one uniform source of truth
    member_state_.assign(num_workers_, kLive);
    last_seen_ms_.assign(num_workers_, steady_ms());
    live_workers_.store(num_workers_);
    epoch_.store(0);
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      PublishMembersLocked();
    }
    engine_ = std::make_unique<ThreadPool>(engine_threads);
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(port);
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0) {
      ::close(listen_fd_);
      return -2;
    }
    if (::listen(listen_fd_, 128) != 0) {
      ::close(listen_fd_);
      return -3;
    }
    running_ = true;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    if (pull_timeout_ms_ > 0 || lease_ms_ > 0) {
      sweep_thread_ = std::thread([this] { SweepLoop(); });
    }
    return 0;
  }

  uint64_t Epoch() const { return epoch_.load(); }

  int MembersInfo(uint64_t* epoch, uint32_t* live_count, uint8_t* bitmap,
                  uint32_t cap) {
    auto m = Members();
    // the SNAPSHOT's epoch, never a fresh epoch_.load(): a concurrent
    // membership change must not label an old live count with a new
    // epoch (workers cache epoch->live as the averaging divisor)
    if (epoch != nullptr) *epoch = m->epoch;
    if (live_count != nullptr) *live_count = m->count;
    if (bitmap != nullptr && !m->live.empty()) {
      std::memcpy(bitmap, m->live.data(),
                  std::min<size_t>(cap, m->live.size()));
    }
    return static_cast<int>(m->live.size());
  }

  void Wait() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return !running_.load(); });
  }

  void Stop() {
    // serialize concurrent stops (worker-initiated auto-stop can race an
    // explicit StopServer); the loser blocks until teardown completes so
    // the caller may safely retire the server afterwards
    std::lock_guard<std::mutex> stop_lk(stop_mu_);
    bool was = running_.exchange(false);
    if (!was) return;
    if (listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (listen_fd_ >= 0) ::close(listen_fd_);
    {
      // SHUT_RDWR (without close) unblocks every conn thread's recv AND
      // any engine thread blocked in a send to a stopped reader. No
      // send_mu here — a sender stuck in send_all() holds send_mu, and
      // only this shutdown can unblock it (lock-free is safe: a conn
      // still in the map has not run its teardown, whose erase-then-close
      // sequence is ordered by conn_mu_, so the fd is still open).
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (auto& [id, c] : conns_) ::shutdown(c->fd, SHUT_RDWR);
    }
    if (accept_thread_.joinable() &&
        accept_thread_.get_id() != std::this_thread::get_id()) {
      accept_thread_.join();
    }
    if (sweep_thread_.joinable()) sweep_thread_.join();
    {
      // conn threads are detached (a long-running server must not accrete
      // one joinable std::thread per reconnect); wait on the live count
      std::unique_lock<std::mutex> lk(threads_mu_);
      threads_cv_.wait(lk, [this] { return live_conn_threads_ == 0; });
    }
    if (engine_) engine_->Stop();
    {
      // conn threads closed their own fds on exit; this sweeps any that
      // never reached their cleanup (shouldn't happen, but harmless)
      std::lock_guard<std::mutex> lk(conn_mu_);
      for (auto& [id, c] : conns_) CloseConn(c);
      conns_.clear();
    }
    // wake any in-process pulls so joint-role callers fail fast
    {
      std::lock_guard<std::mutex> lk(store_mu_);
      for (auto& [k, ks] : store_) ks->cv.notify_all();
    }
    done_cv_.notify_all();
  }

  void TraceEnable(bool on) { trace_on_ = on; }

  int TraceDump(const char* path) {
    std::vector<TraceEv> evs;
    {
      std::lock_guard<std::mutex> lk(trace_mu_);
      evs = trace_;
    }
    FILE* f = std::fopen(path, "w");
    if (f == nullptr) return -1;
    // pid 10000+server_id keeps server rows apart from worker ranks when
    // traces are merged
    std::fprintf(f, "{\"traceEvents\":[");
    for (size_t i = 0; i < evs.size(); ++i) {
      const auto& e = evs[i];
      std::fprintf(
          f,
          "%s{\"name\":\"key%llu\",\"cat\":\"byteps_server\",\"ph\":\"X\","
          "\"ts\":%lld,\"dur\":%d,\"pid\":%d,\"tid\":\"%s\","
          "\"args\":{\"key\":%llu,\"len\":%u,\"codec\":%u}}",
          i ? "," : "", static_cast<unsigned long long>(e.key),
          static_cast<long long>(e.ts_us), e.dur_us, 10000 + server_id_,
          kTraceStageName[e.stage],
          static_cast<unsigned long long>(e.key), e.len, e.codec);
    }
    std::fprintf(f,
                 "],\"displayTimeUnit\":\"ms\",\"metadata\":{"
                 "\"role\":\"server\",\"server_id\":%d,"
                 "\"clock\":\"CLOCK_REALTIME_us\"}}",
                 server_id_);
    std::fclose(f);
    return static_cast<int>(evs.size());
  }

  bool IsRunning() const { return running_.load(); }

  // ---- in-process (IPC) fast path ----------------------------------------
  // Every entry checks running_: after a worker-driven shutdown stopped
  // the server, a later joint-role PSWorker must fail loudly instead of
  // silently reading/writing the stopped server's leaked store.
  int LocalInit(uint64_t key, uint64_t nbytes) {
    if (!running_) return -10;
    if (nbytes == 0 || nbytes > kMaxFrameLen || nbytes % 4 != 0) return -1;
    KeyStore* ks = GetOrCreate(key, nbytes / 4);
    return ks->n_elems * 4 == nbytes ? 0 : -2;
  }

  int LocalPush(uint16_t worker, uint64_t key, uint8_t codec,
                uint64_t version, const char* buf, size_t len) {
    if (!running_) return -10;
    KeyStore* ks = Get(key);
    if (ks == nullptr) return -1;
    // bounds/liveness hold in ASYNC mode too: an out-of-range or evicted
    // worker id must not silently sum into the free-running aggregate
    // (it would also never refresh a lease slot, leaving kMembers lying)
    if (worker >= num_workers_) return -2;
    // IPC analog of the TCP path's "worker evicted" kErr
    if (!WorkerLive(worker)) return -11;
    if (!async_ && staleness_ <= 0 && lease_ms_ > 0 && version != 0) {
      // stale-round guard (see the kPush handler): a round the worker
      // was evicted out of closed without it — reject, don't sum
      std::lock_guard<std::mutex> lk(ks->mu);
      if (version <= ks->version && worker < ks->applied_version.size() &&
          version > ks->applied_version[worker]) {
        return -11;
      }
    }
    Touch(worker, /*admit=*/false);
    const int64_t n = static_cast<int64_t>(ks->n_elems);
    if (!validate_payload(codec, buf, len, n)) return -3;
    auto owned = std::make_shared<RawBuf>(buf, buf + len);
    ApplyPush(ks, key, worker, codec, version, std::move(owned));
    return 0;
  }

  int LocalPull(uint64_t key, uint8_t codec, uint64_t version,
                int timeout_ms, std::vector<char>* out,
                uint64_t* out_epoch, uint64_t* out_version) {
    if (!running_) return -10;
    KeyStore* ks = Get(key);
    if (ks == nullptr) return -1;
    std::shared_ptr<const FloatBuf> snap;
    CodecHint hint;
    uint64_t v = 0;
    uint64_t epoch = 0;
    // bounded staleness: same serve/force ladder as the TCP path
    const uint64_t serve_min = ServeMin(version);
    const uint64_t force_min = ForceMin(version);
    {
      std::unique_lock<std::mutex> lk(ks->mu);
      const auto deadline = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(timeout_ms);
      while (running_ &&
             !(async_ ? ks->version > 0 : ks->version >= serve_min)) {
        if (force_min > ks->version && ks->arrived > 0) {
          std::vector<ReadyResp> released;
          auto memb = Members();
          ForceAdvanceLocked(ks, *memb, force_min, &released);
          if (!released.empty()) {
            // TCP pulls satisfied by OUR force-close must not wait for
            // this local pull's own condition — hand them off now
            lk.unlock();
            DispatchReady(key, ks, released);
            lk.lock();
          }
          continue;
        }
        if (ks->cv.wait_until(lk, deadline) == std::cv_status::timeout) {
          return -4;
        }
      }
      if (!running_) return -5;
      v = ks->version;
      if (async_) {
        snap = std::make_shared<const FloatBuf>(ks->accum);
        hint = ks->hint;
        epoch = epoch_.load();
      } else {
        snap = ks->result;
        hint = ks->result_hint;
        epoch = ks->result_epoch;
      }
    }
    if (out_epoch != nullptr) *out_epoch = epoch;
    if (out_version != nullptr) *out_version = v;
    *out = *EncodeResponse(ks, snap, hint, v, codec);
    return 0;
  }

 private:
  void Trace(uint8_t stage, uint64_t key, uint32_t len, uint8_t codec,
             int64_t t0_ns) {
    if (!trace_on_.load(std::memory_order_relaxed)) return;
    TraceEv e;
    e.ts_us = t0_ns / 1000;
    e.dur_us = static_cast<int32_t>((realtime_ns() - t0_ns) / 1000);
    e.key = key;
    e.len = len;
    e.stage = stage;
    e.codec = codec;
    std::lock_guard<std::mutex> lk(trace_mu_);
    if (trace_.size() < kMaxTraceEvents) trace_.push_back(e);
  }

  void AcceptLoop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        // EINTR: a signal (the embedding process — jax/XLA, profilers —
        // delivers them to arbitrary threads) interrupted accept;
        // ECONNABORTED: the peer gave up while queued. Neither means the
        // listening socket is done — exiting here silently stops the
        // server accepting ANYTHING while clients still see the port as
        // bound (their connects then fail for their whole retry budget).
        // Only a real teardown (Stop() closes listen_fd_ → EBADF) or an
        // unrecoverable socket error ends the loop.
        if (errno == EINTR || errno == ECONNABORTED) continue;
        break;
      }
      set_nodelay(fd);
      set_bufsizes(fd);
      auto c = std::make_shared<Conn>();
      c->fd = fd;
      {
        std::lock_guard<std::mutex> lk(conn_mu_);
        c->id = next_conn_id_++;
        conns_[c->id] = c;
      }
      {
        std::lock_guard<std::mutex> lk(threads_mu_);
        ++live_conn_threads_;
      }
      // detached: per-connection teardown reclaims everything (Conn, fd,
      // live count); Stop() waits on the count, so no per-reconnect
      // std::thread object accretes for the server's lifetime
      std::thread([this, c] {
        ConnLoop(c);
        {
          std::lock_guard<std::mutex> lk(threads_mu_);
          --live_conn_threads_;
        }
        threads_cv_.notify_all();
      }).detach();
    }
  }

  // Mark closed and close the fd, exactly once, under send_mu so no frame
  // write can race the close (or hit a recycled fd number).
  static void CloseConn(const ConnPtr& c) {
    std::lock_guard<std::mutex> lk(c->send_mu);
    if (!c->closed) {
      c->closed = true;
      ::close(c->fd);
    }
  }

  // Engine submission honoring BYTEPS_SERVER_ENABLE_SCHEDULE: with
  // scheduling on, tasks carry the key as priority (lower key =
  // earlier-declared tensor = higher priority — the worker scheduler's own
  // (priority, key) order) so a contended engine sums and answers
  // high-priority partitions first.
  void SubmitEngine(uint64_t key, std::function<void()> fn) {
    if (schedule_) {
      engine_->SubmitPriority(key, std::move(fn));
    } else {
      engine_->Submit(std::move(fn));
    }
  }

  // Enqueue `fn` on the key's per-worker strand: tasks run on the engine
  // pool but strictly in post order for that (key, worker).
  void PostOrdered(KeyStore* ks, uint64_t key, uint16_t worker,
                   std::function<void()> fn) {
    std::shared_ptr<Strand> st;
    {
      std::lock_guard<std::mutex> lk(ks->strands_mu);
      auto& slot = ks->strands[worker];
      if (!slot) slot = std::make_shared<Strand>();
      st = slot;
    }
    bool start = false;
    {
      std::lock_guard<std::mutex> lk(st->mu);
      st->q.push_back(std::move(fn));
      if (!st->running) {
        st->running = true;
        start = true;
      }
    }
    if (start) {
      if (schedule_) {
        SubmitEngine(key, [this, st, key] { RunStrandOne(st, key); });
      } else {
        engine_->Submit([st] {
          for (;;) {
            std::function<void()> task;
            {
              std::lock_guard<std::mutex> lk(st->mu);
              if (st->q.empty()) {
                st->running = false;
                return;
              }
              task = std::move(st->q.front());
              st->q.pop_front();
            }
            task();
          }
        });
      }
    }
  }

  // Scheduled strand pump: ONE task per engine submission, continuation
  // re-enqueued through the priority lane — a low-priority key receiving a
  // steady push stream must yield to higher-priority work between tasks
  // instead of monopolizing an engine thread with a drain loop.
  void RunStrandOne(const std::shared_ptr<Strand>& st, uint64_t key) {
    std::function<void()> task;
    {
      std::lock_guard<std::mutex> lk(st->mu);
      if (st->q.empty()) {
        st->running = false;
        return;
      }
      task = std::move(st->q.front());
      st->q.pop_front();
    }
    task();
    bool more;
    {
      std::lock_guard<std::mutex> lk(st->mu);
      more = !st->q.empty();
      if (!more) st->running = false;
    }
    if (more) {
      SubmitEngine(key, [this, st, key] { RunStrandOne(st, key); });
    }
  }

  // ---- elastic worker membership (leases + epochs) ------------------------
  // Reference failure story: ps-lite's scheduler heartbeat. The csrc
  // server completes a key's sum only when every expected worker arrived
  // and releases a barrier only at the full worker count, so ONE dead or
  // wedged worker deadlocks every key, every barrier, and every surviving
  // worker's wait() forever. With `lease_ms_` > 0 each worker holds a
  // lease refreshed by its pushes/pulls/heartbeats; expiry EVICTS it —
  // the membership epoch bumps (carried in every response header so
  // workers learn on their next op), open rounds re-target the live set,
  // and stuck barriers release over the survivors.
  enum MemberState : uint8_t { kEvicted = 0, kLive = 1, kDeparted = 2 };

  struct Membership {
    std::vector<uint8_t> live;  // 1 = live, indexed by worker id
    uint32_t count = 0;
    uint64_t epoch = 0;  // epoch this snapshot was published under —
                         // round closes stamp THIS, keeping the quorum
                         // scale and the epoch label consistent even
                         // when an eviction publishes mid-close
  };

  // Lock-free snapshot for the data plane: every push consults the
  // membership (round-completion targeting), and taking the global
  // members_mu_ + allocating a fresh vector under each per-key mutex
  // would serialize pushes to DIFFERENT keys on one lock. Membership
  // changes are rare; publishers rebuild the immutable snapshot under
  // members_mu_, readers atomic-load the shared_ptr.
  std::shared_ptr<const Membership> Members() {
    return std::atomic_load(&members_snap_);
  }

  // call with members_mu_ held
  void PublishMembersLocked() {
    auto snap = std::make_shared<Membership>();
    snap->live.resize(member_state_.size());
    for (size_t i = 0; i < member_state_.size(); ++i) {
      snap->live[i] = member_state_[i] == kLive ? 1 : 0;
    }
    const int live = live_workers_.load();
    snap->count = static_cast<uint32_t>(live > 0 ? live : 0);
    snap->epoch = epoch_.load();
    std::atomic_store(&members_snap_,
                      std::shared_ptr<const Membership>(std::move(snap)));
  }

  bool WorkerLive(uint16_t worker) {
    if (lease_ms_ <= 0) return true;
    // size read under the lock: a concurrent kJoin GROWS member_state_
    // (vector reallocation), so an unlocked size() probe is a race
    std::lock_guard<std::mutex> lk(members_mu_);
    if (worker >= member_state_.size()) return true;
    return member_state_[worker] == kLive;
  }

  // Refresh `worker`'s lease. With `admit`, an evicted/departed worker is
  // RE-ADMITTED (the kPing-heartbeat rejoin path): the epoch bumps and
  // the worker is expected in rounds again. Pushes/pulls deliberately do
  // NOT admit — an evicted worker must first adopt the current epoch and
  // round watermarks (kMembers/kRounds) or its stale rounds would leak
  // into post-eviction sums.
  bool Touch(uint16_t worker, bool admit) {
    if (lease_ms_ <= 0) return false;
    bool rejoined = false;
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      if (worker >= member_state_.size()) return false;
      last_seen_ms_[worker] = steady_ms();
      if (member_state_[worker] != kLive && admit) {
        member_state_[worker] = kLive;
        live_workers_.fetch_add(1);
        epoch_.fetch_add(1);
        PublishMembersLocked();
        rejoined = true;
      }
    }
    if (rejoined) {
      Trace(kTrMember, worker,
            static_cast<uint32_t>(live_workers_.load()), 1, realtime_ns());
    }
    return rejoined;
  }

  // Sweep-thread eviction: every live worker silent past the lease is
  // marked dead, then open rounds / barriers / the exit gate reconcile.
  void EvictExpired() {
    std::vector<uint16_t> dead;
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      const int64_t now = steady_ms();
      for (size_t w = 0; w < member_state_.size(); ++w) {
        if (member_state_[w] == kLive &&
            now - last_seen_ms_[w] > lease_ms_) {
          member_state_[w] = kEvicted;
          live_workers_.fetch_sub(1);
          epoch_.fetch_add(1);
          dead.push_back(static_cast<uint16_t>(w));
        }
      }
      if (!dead.empty()) PublishMembersLocked();
    }
    if (dead.empty()) return;
    for (uint16_t w : dead) {
      Trace(kTrMember, w,
            static_cast<uint32_t>(live_workers_.load()), 0, realtime_ns());
    }
    ReconcileAfterMembershipShrink(dead);
  }

  // A worker's clean goodbye under elastic membership: mark it DEPARTED
  // (it is no longer expected in rounds/barriers but is not an eviction)
  // and reconcile. Returns true when every worker is now accounted for
  // (departed or evicted) so the caller may stop the server.
  bool Depart(uint16_t worker) {
    if (lease_ms_ <= 0) return false;
    bool shrank = false;
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      if (worker >= member_state_.size()) return false;
      if (member_state_[worker] == kLive) {
        live_workers_.fetch_sub(1);
        epoch_.fetch_add(1);
        shrank = true;
      }
      member_state_[worker] = kDeparted;
      if (shrank) PublishMembersLocked();
    }
    if (shrank) ReconcileAfterMembershipShrink({worker});
    return AllAccountedFor();
  }

  bool AllAccountedFor() {
    std::lock_guard<std::mutex> lk(members_mu_);
    int departed = 0;
    for (auto s : member_state_) departed += s == kDeparted ? 1 : 0;
    // all-evicted with zero goodbyes is treated as a transient outage
    // (workers may rejoin), not a completed job. Anonymous (legacy)
    // kShutdowns can't mark a DEPARTED slot but still count as
    // goodbyes, so a mixed fleet that all said goodbye anonymously
    // stops once the lease has evicted the silent slots.
    return live_workers_.load() <= 0 &&
           (departed > 0 || shutdown_count_.load() > 0);
  }

  // Grow every key store's per-worker vectors (arrival bitmap + replay
  // watermarks) to the current worker count. Called by Join BEFORE the
  // admission is published: the first round-completion check that sees
  // the joiner live must also see its (empty) arrival slot — otherwise a
  // RoundCompleteLocked bounded by the stale pushed.size() could close a
  // round "complete" without the joiner ever being expected in it.
  void GrowStoreSlots() {
    const size_t n = static_cast<size_t>(num_workers_.load());
    std::vector<KeyStore*> stores;
    {
      std::lock_guard<std::mutex> lk(store_mu_);
      stores.reserve(store_.size());
      for (auto& [k, ks] : store_) stores.push_back(ks.get());
    }
    for (KeyStore* ks : stores) {
      std::lock_guard<std::mutex> lk(ks->mu);
      if (ks->pushed.size() < n) {
        ks->pushed.resize(n, 0);
        ks->applied_version.resize(n, 0);
      }
    }
  }

 public:
  // Mid-stream worker ADMISSION (kJoin; scale-up elasticity). A fresh id
  // beyond the configured count GROWS the membership table and — before
  // the admission is published — every key store's per-worker vectors,
  // so the join lands at a round boundary: rounds open at admission
  // close over whoever contributed (the eviction-side quorum scaling
  // generalized upward), and every later round targets the grown live
  // set. A previously evicted/departed id re-admits exactly like the
  // kPing rejoin path (epoch bump). The joiner is expected to adopt
  // round watermarks via kRounds before its first push — under bounded
  // staleness that watermark IS the served-round frontier, which never
  // trails the force-close watermark. Returns the post-admission epoch;
  // -1 = id out of range; -2 = fixed membership (lease disabled) and the
  // id is not a configured worker.
  int64_t Join(uint16_t worker) {
    if (worker >= kMaxWorkers) return -1;
    if (lease_ms_ <= 0) {
      // fixed membership has no admission machinery: a configured id is
      // already a member (idempotent ack), a fresh one cannot be grown
      return worker < static_cast<uint16_t>(num_workers_.load())
                 ? static_cast<int64_t>(epoch_.load())
                 : -2;
    }
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      if (worker >= member_state_.size()) {
        // new slots between the old count and the joiner default to
        // kEvicted: absent-but-admissible, and already accounted for by
        // the exit gate (evicted counts as accounted)
        member_state_.resize(worker + 1, kEvicted);
        last_seen_ms_.resize(worker + 1, steady_ms());
        // published BEFORE the store sweep below so any KeyStore created
        // concurrently (kInit racing the join) sizes its vectors for the
        // grown membership from the start
        num_workers_.store(static_cast<int>(member_state_.size()));
      }
    }
    GrowStoreSlots();
    bool admitted = false;
    {
      std::lock_guard<std::mutex> lk(members_mu_);
      last_seen_ms_[worker] = steady_ms();
      if (member_state_[worker] != kLive) {
        member_state_[worker] = kLive;
        live_workers_.fetch_add(1);
        epoch_.fetch_add(1);
        PublishMembersLocked();
        admitted = true;
      }
    }
    if (admitted) {
      Trace(kTrMember, worker,
            static_cast<uint32_t>(live_workers_.load()), 2, realtime_ns());
    }
    return static_cast<int64_t>(epoch_.load());
  }

 private:

  // Membership shrank: drop the dead workers' deferred (pipelined
  // next-round) pushes, close any round now complete over the live set —
  // answering its pending pulls — release barriers the dead can no
  // longer satisfy, and stop the server once every worker is departed or
  // evicted with at least one proper goodbye.
  void ReconcileAfterMembershipShrink(const std::vector<uint16_t>& dead) {
    std::vector<std::pair<uint64_t, KeyStore*>> stores;
    {
      std::lock_guard<std::mutex> lk(store_mu_);
      stores.reserve(store_.size());
      for (auto& [k, ks] : store_) stores.emplace_back(k, ks.get());
    }
    for (auto& [key, ks] : stores) {
      std::vector<ReadyResp> ready;
      {
        std::lock_guard<std::mutex> lk(ks->mu);
        auto it = ks->deferred.begin();
        while (it != ks->deferred.end()) {
          bool drop = false;
          for (uint16_t w : dead) drop = drop || it->worker == w;
          it = drop ? ks->deferred.erase(it) : it + 1;
        }
        if (!async_) {
          auto memb = Members();
          if (RoundCompleteLocked(ks, *memb)) {
            CloseRoundLocked(ks, *memb, &ready);
          }
          // a shrink can also unblock a parked bounded-staleness pull
          // (the dead worker was the missing contributor)
          ForcePendingLocked(ks, *memb, &ready);
        }
        ks->cv.notify_all();
      }
      DispatchReady(key, ks, ready);
    }
    ReleaseBarrierIfReady();
    if (AllAccountedFor()) {
      // detached: the sweep thread cannot join itself through Stop()
      std::thread([this] { Stop(); }).detach();
    }
  }

  // Barrier over the LIVE set: released as soon as the waiters cover
  // every live worker — on arrival (HandleBarrier) and again on every
  // membership shrink, so a dead worker cannot strand a barrier. Only
  // waiters that are anonymous (legacy frames) or still LIVE count
  // toward the target: a worker that barriered and then got evicted
  // must not stand in for a live peer that never arrived (its stale
  // arrival predates the membership the survivors are synchronizing).
  void ReleaseBarrierIfReady() {
    std::vector<ConnPtr> release;
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      int target = live_workers_.load();
      if (target <= 0) target = 1;
      auto memb = Members();
      int counted = 0;
      for (auto& p : barrier_conns_) {
        const uint16_t wid1 = p.second;
        const bool anon = wid1 == 0;
        const bool live =
            !anon && static_cast<size_t>(wid1 - 1) < memb->live.size() &&
            memb->live[wid1 - 1];
        counted += (anon || live) ? 1 : 0;
      }
      if (counted > 0 && counted >= target) {
        // release EVERY waiter (stale ones included — their acks land
        // on dead conns harmlessly, and leaving them queued would leak
        // them into the next barrier round)
        release.reserve(barrier_conns_.size());
        for (auto& p : barrier_conns_) release.push_back(p.first);
        barrier_conns_.clear();
      }
    }
    for (auto& rc : release) SendFrame(rc, kAck, 0, 0, nullptr, 0);
  }

  // Response frame with an explicit reserved stamp — pull responses
  // carry the epoch their ROUND closed under (a survivor must average a
  // pre-eviction round by the pre-eviction live count, even when the
  // response is delivered after the epoch bumped).
  void SendFrameStamped(const ConnPtr& c, Cmd cmd, uint64_t key,
                        uint64_t version, const void* payload, uint32_t len,
                        uint8_t flags, uint32_t crc, uint16_t reserved) {
    std::lock_guard<std::mutex> lk(c->send_mu);
    if (c->closed) return;  // peer went away; response is moot
    send_frame(c->fd, cmd, key, version, payload, len, flags, reserved,
               crc);
  }

  void SendFrame(const ConnPtr& c, Cmd cmd, uint64_t key, uint64_t version,
                 const void* payload, uint32_t len, uint8_t flags = 0,
                 uint32_t crc = 0) {
    // every response carries the CURRENT membership epoch (low 16 bits):
    // workers learn of evictions/rejoins on their next op, no extra
    // round trip
    SendFrameStamped(
        c, cmd, key, version, payload, len, flags, crc,
        static_cast<uint16_t>(epoch_.load(std::memory_order_relaxed)));
  }

  void SendErr(const ConnPtr& c, uint64_t key, const char* msg) {
    SendFrame(c, kErr, key, 0, msg, static_cast<uint32_t>(std::strlen(msg)));
  }

  KeyStore* GetOrCreate(uint64_t key, size_t nfloats) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto& slot = store_[key];
    if (!slot) {
      slot = std::make_unique<KeyStore>();
      slot->n_elems = nfloats;
      slot->accum.assign(nfloats, 0.f);
      slot->result = std::make_shared<const FloatBuf>(nfloats, 0.f);
      slot->pushed.assign(num_workers_, 0);
      slot->applied_version.assign(num_workers_, 0);
    }
    return slot.get();
  }

  KeyStore* Get(uint64_t key) {
    std::lock_guard<std::mutex> lk(store_mu_);
    auto it = store_.find(key);
    return it == store_.end() ? nullptr : it->second.get();
  }

  // A pull whose round is ready, with the (version, snapshot, codec hint)
  // captured under ks->mu AT THE MOMENT the round closed — a later round
  // closing before the response is sent must not substitute its own sum
  // or its own encoding parameters.
  struct ReadyResp {
    ConnPtr conn;
    uint8_t codec;
    bool want_crc;
    uint64_t version;
    std::shared_ptr<const FloatBuf> snap;
    CodecHint hint;
    uint64_t epoch;  // membership epoch the round CLOSED under
  };

  // ---- bounded staleness (BYTEPS_STALENESS=K, sync mode) ------------------
  // A pull for round v may be served from any CLOSED round >= v-K; the
  // oldest legal serve is also the round the pull may FORCE-close up to
  // when the straggler holds it open past the bound. The first K rounds
  // (v <= K) never force: the job starts with one naturally-closed
  // round, so the ladder's base is a real quorum sum, not served zeros.
  uint64_t ServeMin(uint64_t version) const {
    if (async_ || staleness_ <= 0) return version;
    const uint64_t k = static_cast<uint64_t>(staleness_);
    return version > k ? version - k : 1;
  }

  uint64_t ForceMin(uint64_t version) const {
    if (async_ || staleness_ <= 0) return 0;
    const uint64_t k = static_cast<uint64_t>(staleness_);
    return version > k ? version - k : 0;
  }

  // Close open rounds up to `target` over whoever contributed (the
  // eviction-analog: each close quorum-scales the partial sum to the
  // live count, so the global average stays unbiased). Stops at an
  // EMPTY open round — a round nobody joined yet cannot close, and the
  // parked pull waits for the next push apply to re-trigger.
  void ForceAdvanceLocked(KeyStore* ks, const Membership& memb,
                          uint64_t target,
                          std::vector<ReadyResp>* ready) {
    while (ks->version < target && ks->arrived > 0) {
      CloseRoundLocked(ks, memb, ready);
    }
  }

  // Re-check every parked pull's force bound after a push apply: the
  // push that just landed may be the contribution that lets a blocked
  // fast worker's round ladder advance.
  void ForcePendingLocked(KeyStore* ks, const Membership& memb,
                          std::vector<ReadyResp>* ready) {
    if (async_ || staleness_ <= 0 || ks->pending.empty()) return;
    uint64_t target = 0;
    for (const auto& p : ks->pending) {
      target = std::max(target, p.force_min);
    }
    if (target > ks->version) ForceAdvanceLocked(ks, memb, target, ready);
  }

  // Round completion over the LIVE membership: closed when every live
  // worker contributed. Contributions from workers evicted mid-round may
  // already sit in accum — the close-time quorum scaling handles them.
  // Never closes an empty round: accum is uninitialized until the first
  // push of the round lands.
  bool RoundCompleteLocked(KeyStore* ks, const Membership& m) {
    if (m.count == 0 || ks->arrived == 0) return false;
    for (size_t w = 0; w < m.live.size() && w < ks->pushed.size(); ++w) {
      if (m.live[w] && !ks->pushed[w]) return false;
    }
    return true;
  }

  // Close the open round: snapshot by MOVE, fresh accumulator, answer the
  // pulls this round satisfies, then re-apply deferred next-round pushes.
  void CloseRoundLocked(KeyStore* ks, const Membership& memb,
                        std::vector<ReadyResp>* ready) {
    // Quorum scaling: a worker evicted mid-round may have contributed to
    // accum (contributors > live), and a bounded-staleness FORCE-close
    // fires before every live worker arrived (contributors < live) —
    // either way the pullers will average this sum over the LIVE count
    // (the membership their epoch adoption reports), so scale the sum by
    // live/contributors to keep the global *average* unbiased. A clean
    // round (contributors == live) takes no multiply at all — healthy
    // epochs (and the whole K=0 ladder) stay bit-exact.
    if (memb.count > 0 && ks->arrived > 0 && ks->arrived != memb.count) {
      const float s = static_cast<float>(memb.count) /
                      static_cast<float>(ks->arrived);
      for (auto& v : ks->accum) v *= s;
    }
    // the codec hint is frozen with the result so deferred next-round
    // pushes below cannot change how THIS round's responses are encoded
    auto snap = std::make_shared<FloatBuf>(std::move(ks->accum));
    // moved-from accum is empty; resize on the no-init allocator
    // allocates WITHOUT the 4 MB zero-fill (the next round's first
    // push overwrites or zero+sums — ApplyPushLocked's start-of-round
    // branch)
    ks->accum.resize(snap->size());
    ks->result = std::move(snap);
    ks->result_hint = ks->hint;
    ks->result_epoch = memb.epoch;
    ks->version++;
    ks->arrived = 0;
    std::fill(ks->pushed.begin(), ks->pushed.end(), 0);
    ks->cache_codec = 0xFF;
    ks->cv.notify_all();
    // hand this round's snapshot to the pulls it satisfies BEFORE
    // applying deferred pushes (which may immediately close the next
    // round and overwrite ks->result)
    auto it = ks->pending.begin();
    while (it != ks->pending.end()) {
      if (ks->version >= it->version) {
        ready->push_back({it->conn, it->codec, it->want_crc, ks->version,
                          ks->result, ks->result_hint, ks->result_epoch});
        it = ks->pending.erase(it);
      } else {
        ++it;
      }
    }
    auto deferred = std::move(ks->deferred);
    ks->deferred.clear();
    for (auto& d : deferred) {
      ApplyPushLocked(ks, memb, d.worker, d.codec, d.version,
                      std::move(d.buf), ready);
    }
  }

  // Decode+sum one arrived push under ks->mu. A worker that pushes round
  // v+1 before round v closed (pipelined pushes are legal — the ack no
  // longer waits for the sum) is deferred and re-applied at round close.
  // Pulls satisfied by a closing round are appended to `ready` with that
  // round's snapshot. `version` != 0 arms replay dedupe: a (worker,
  // version) at or below the already-applied watermark — or already
  // sitting in the deferred queue — is a retry-engine re-send whose
  // original landed, and is dropped instead of double-summed. `memb` is
  // the live membership the round targets (snapshotted under ks->mu, so
  // an eviction either lands before this push — visible here — or its
  // reconcile sweep sees this contribution; a completable round can
  // never be missed between the two).
  void ApplyPushLocked(KeyStore* ks, const Membership& memb,
                       uint16_t worker, uint8_t codec, uint64_t version,
                       std::shared_ptr<RawBuf> buf,
                       std::vector<ReadyResp>* ready) {
    const int64_t n = static_cast<int64_t>(ks->n_elems);
    if (version != 0 && worker < ks->applied_version.size() &&
        version <= ks->applied_version[worker]) {
      return;  // duplicate of an already-summed push
    }
    if (staleness_ > 0 && !async_ && version != 0 &&
        version <= ks->version) {
      // Bounded staleness: the round this push belongs to already closed
      // over its contributors (a fast worker's pull force-closed it) —
      // a straggler's late push is EXPECTED and consumed silently, never
      // an error. The applied watermark still advances so a retry
      // engine's replay of this same round dedupes as before, and the
      // straggler's next pull serves it the newest closed round to
      // catch up from.
      if (worker < ks->applied_version.size()) {
        ks->applied_version[worker] = version;
      }
      return;
    }
    if (lease_ms_ > 0 && !async_ && version != 0 &&
        version <= ks->version) {
      // Stale round, re-checked ATOMICALLY with the round state: the
      // kPush handler's pre-ack guard races the eviction sweep (the
      // round can close between the check and this apply), and a round
      // that closed without this worker must never have the worker's
      // payload credited to the NEXT round. Dropped silently (the ack
      // already went out); the worker learns via the epoch stamp / its
      // next push's kErr and rejoins.
      return;
    }
    if (!async_ && ks->pushed[worker]) {
      if (version != 0) {
        for (const auto& d : ks->deferred) {
          if (d.worker == worker && d.version == version) {
            return;  // duplicate of a push already queued for next round
          }
        }
      }
      ks->deferred.push_back({worker, codec, version, std::move(buf)});
      return;
    }
    if (version != 0 && worker < ks->applied_version.size()) {
      ks->applied_version[worker] = version;
    }
    if (!async_ && ks->arrived == 0) {
      // Start of a round: accum is UNINITIALIZED (the close path moves it
      // into the snapshot and reallocates without a zero-fill). A raw
      // push OVERWRITES it in one pass — memcpy instead of
      // zero + read-modify-write saves two full memory sweeps per round
      // on the engine's critical path; every other codec zero-fills
      // first, then sums as before.
      if (codec == kCodecRaw &&
          buf->size() == static_cast<size_t>(n) * sizeof(float)) {
        std::memcpy(ks->accum.data(), buf->data(), buf->size());
      } else {
        std::fill(ks->accum.begin(), ks->accum.end(), 0.f);
        decode_sum(codec, buf->data(), buf->size(), ks->accum.data(), n);
      }
    } else {
      decode_sum(codec, buf->data(), buf->size(), ks->accum.data(), n);
    }
    update_hint(codec, buf->data(), buf->size(), &ks->hint);
    if (async_) {
      ks->version++;
      ks->cv.notify_all();
      return;
    }
    ks->pushed[worker] = 1;
    ++ks->arrived;
    if (RoundCompleteLocked(ks, memb)) {
      CloseRoundLocked(ks, memb, ready);
    }
  }

  void DispatchReady(uint64_t key, KeyStore* ks,
                     std::vector<ReadyResp>& ready) {
    for (auto& p : ready) {
      // parallel fan-out: each response encodes+sends on its own engine slot
      SubmitEngine(key, [this, ks, key, p = std::move(p)] {
        RespondPull(p.conn, key, ks, p.codec, p.want_crc, p.version, p.snap,
                    p.hint, p.epoch);
      });
    }
  }

  void ApplyPush(KeyStore* ks, uint64_t key, uint16_t worker, uint8_t codec,
                 uint64_t version, std::shared_ptr<RawBuf> buf) {
    const int64_t t0 = realtime_ns();
    const uint32_t len = static_cast<uint32_t>(buf->size());
    std::vector<ReadyResp> ready;
    {
      std::lock_guard<std::mutex> lk(ks->mu);
      auto memb = Members();
      ApplyPushLocked(ks, *memb, worker, codec, version, std::move(buf),
                      &ready);
      // bounded staleness: this push may be the contribution a parked
      // fast-worker pull was waiting on — re-check the force bounds of
      // every pending pull, and wake in-process (LocalPull) waiters so
      // they re-evaluate their own bound
      ForcePendingLocked(ks, *memb, &ready);
      if (staleness_ > 0 && !async_) ks->cv.notify_all();
      if (async_) {
        auto it = ks->pending.begin();
        while (it != ks->pending.end()) {
          ready.push_back(
              {it->conn, it->codec, it->want_crc, ks->version,
               std::make_shared<const FloatBuf>(ks->accum),
               ks->hint, memb->epoch});
          it = ks->pending.erase(it);
        }
      }
    }
    Trace(kTrSum, key, len, codec, t0);
    DispatchReady(key, ks, ready);
  }

  // Encode the round result for one pull. Cached per (version, codec) so a
  // round's W pulls cost one re-compression, not W; cache hits share the
  // immutable blob (zero-copy into SendFrame). `hint` is the codec hint
  // snapshotted when `snap`'s round closed, NOT the live ks->hint.
  std::shared_ptr<const std::vector<char>> EncodeResponse(
      KeyStore* ks, const std::shared_ptr<const FloatBuf>& snap,
      const CodecHint& hint, uint64_t version, uint8_t codec) {
    {
      std::lock_guard<std::mutex> lk(ks->mu);
      if (!async_ && ks->cache_version == version &&
          ks->cache_codec == codec && ks->cache_blob) {
        return ks->cache_blob;
      }
    }
    // deterministic stochastic-rounding seed per round
    auto blob = std::make_shared<const std::vector<char>>(
        encode(codec, snap->data(), static_cast<int64_t>(snap->size()),
               hint, version * 0x9E3779B97F4A7C15ull + 12345));
    if (!async_) {
      std::lock_guard<std::mutex> lk(ks->mu);
      ks->cache_version = version;
      ks->cache_codec = codec;
      ks->cache_blob = blob;
    }
    return blob;
  }

  // `epoch` = membership epoch the round closed under; stamped into the
  // response header so the puller averages by the round's OWN live count
  // (not the possibly-newer current membership).
  void RespondPull(const ConnPtr& c, uint64_t key, KeyStore* ks,
                   uint8_t codec, bool want_crc, uint64_t version,
                   std::shared_ptr<const FloatBuf> snap,
                   const CodecHint& hint, uint64_t epoch) {
    const int64_t t0 = realtime_ns();
    const uint16_t stamp = static_cast<uint16_t>(epoch);
    if (codec == kCodecRaw) {
      // zero-copy from the immutable snapshot
      const uint32_t len =
          static_cast<uint32_t>(snap->size() * sizeof(float));
      const uint32_t crc = want_crc ? wire_crc(snap->data(), len) : 0;
      SendFrameStamped(c, kResp, key, version, snap->data(), len,
                       kCodecRaw, crc, stamp);
      Trace(kTrPullResp, key, len, kCodecRaw, t0);
      return;
    }
    auto blob = EncodeResponse(ks, snap, hint, version, codec);
    const uint32_t crc =
        want_crc ? wire_crc(blob->data(), blob->size()) : 0;
    SendFrameStamped(c, kResp, key, version, blob->data(),
                     static_cast<uint32_t>(blob->size()), codec, crc,
                     stamp);
    Trace(kTrPullResp, key, static_cast<uint32_t>(blob->size()), codec, t0);
  }

  void HandlePull(const ConnPtr& c, uint64_t key, uint64_t version,
                  uint8_t codec, bool want_crc) {
    KeyStore* ks = Get(key);
    if (ks == nullptr) {
      SendErr(c, key, "pull before init");
      return;
    }
    bool ready;
    uint64_t v = 0;
    uint64_t epoch = 0;
    std::shared_ptr<const FloatBuf> snap;
    CodecHint hint;
    // bounded staleness: serve the NEWEST closed round as long as it is
    // within K of the requested one; a pull past the bound force-closes
    // the straggler-held rounds up to version-K (quorum-scaled over
    // their contributors) instead of parking forever behind it
    const uint64_t serve_min = ServeMin(version);
    const uint64_t force_min = ForceMin(version);
    std::vector<ReadyResp> released;
    {
      std::lock_guard<std::mutex> lk(ks->mu);
      if (force_min > ks->version) {
        auto memb = Members();
        ForceAdvanceLocked(ks, *memb, force_min, &released);
      }
      ready = async_ ? ks->version > 0 : ks->version >= serve_min;
      if (!ready) {
        ks->pending.push_back(
            {c, serve_min, codec, want_crc, steady_ms(), force_min});
      } else {
        v = ks->version;
        if (async_) {
          snap = std::make_shared<const FloatBuf>(ks->accum);
          hint = ks->hint;
          epoch = epoch_.load();
        } else {
          snap = ks->result;
          hint = ks->result_hint;
          epoch = ks->result_epoch;
        }
      }
    }
    // pulls from OTHER workers satisfied by the force-close
    DispatchReady(key, ks, released);
    if (ready) {
      SubmitEngine(key, [this, c, key, ks, codec, want_crc, v, hint, epoch,
                         snap = std::move(snap)] {
        RespondPull(c, key, ks, codec, want_crc, v, snap, hint, epoch);
      });
    }
  }

  void HandleBarrier(const ConnPtr& c, uint16_t reserved) {
    if (reserved > 0) Touch(static_cast<uint16_t>(reserved - 1), false);
    {
      std::lock_guard<std::mutex> lk(barrier_mu_);
      barrier_conns_.emplace_back(c, reserved);
    }
    ReleaseBarrierIfReady();
  }

  // Expire pulls stuck past the deadline (a dead worker otherwise leaves
  // its peers blocked forever — reference failure story: ps-lite
  // heartbeat) and, with the lease armed, evict workers whose lease
  // expired. The tick shortens with the lease so eviction latency stays
  // a small multiple of BYTEPS_WORKER_LEASE_MS.
  void SweepLoop() {
    const int tick_ms =
        lease_ms_ > 0 ? std::max(20, std::min(200, lease_ms_ / 4)) : 200;
    while (running_) {
      std::this_thread::sleep_for(std::chrono::milliseconds(tick_ms));
      if (!running_) break;
      if (lease_ms_ > 0) EvictExpired();
      if (pull_timeout_ms_ <= 0) continue;
      const int64_t now = steady_ms();
      std::vector<std::pair<uint64_t, KeyStore*>> stores;
      {
        std::lock_guard<std::mutex> lk(store_mu_);
        stores.reserve(store_.size());
        for (auto& [k, ks] : store_) stores.emplace_back(k, ks.get());
      }
      std::vector<std::pair<ConnPtr, uint64_t>> expired;  // (conn, key)
      for (auto& [key, ks] : stores) {
        std::lock_guard<std::mutex> lk(ks->mu);
        auto it = ks->pending.begin();
        while (it != ks->pending.end()) {
          if (now - it->enq_ms > pull_timeout_ms_) {
            expired.emplace_back(it->conn, key);
            it = ks->pending.erase(it);
          } else {
            ++it;
          }
        }
      }
      for (auto& [c, key] : expired) {
        SendErr(c, key, "pull timeout: a worker likely died");
      }
    }
  }

  void ConnLoop(const ConnPtr& c) {
    FrameHeader h;
    bool stop_server_after = false;
    while (running_ && recv_all(c->fd, &h, sizeof(h))) {
      if (h.magic != kMagic || h.len > kMaxFrameLen) break;
      const int64_t t_recv = realtime_ns();
      auto payload = std::make_shared<RawBuf>();
      if (h.len > 0) {
        payload->resize(h.len);
        if (!recv_all(c->fd, payload->data(), h.len)) break;
      }
      bool done = false;
      switch (h.cmd) {
        case kInit: {
          if (h.version == 0 || h.version > kMaxFrameLen ||
              h.version % 4 != 0) {
            SendErr(c, h.key, "bad init size");
            break;
          }
          KeyStore* ks = GetOrCreate(h.key, h.version / sizeof(float));
          if (ks->n_elems * sizeof(float) != h.version) {
            // mismatched partition config across pods — fail loudly
            // instead of letting a later push corrupt the store
            SendErr(c, h.key, "init size mismatch");
          } else {
            SendFrame(c, kAck, h.key, 0, nullptr, 0);
          }
          break;
        }
        case kPush: {
          KeyStore* ks = Get(h.key);
          if (ks == nullptr) {
            SendErr(c, h.key, "push before init");
            break;
          }
          // validated in ASYNC mode too: an out-of-range or evicted
          // worker must not silently sum into the free-running
          // aggregate (and its Touch below keeps kMembers truthful)
          if (h.reserved >= num_workers_) {
            SendErr(c, h.key, "worker id out of range");
            break;
          }
          if (!WorkerLive(h.reserved)) {
            // an evicted worker's stale round must not leak into the
            // post-eviction sums; it rejoins first (kPing heartbeat +
            // kRounds watermark adoption) and re-sends under the new
            // epoch (the worker-side WorkerEvictedError path)
            SendErr(c, h.key, "worker evicted: rejoin required");
            break;
          }
          if (!async_ && staleness_ <= 0 && lease_ms_ > 0 &&
              h.version != 0) {
            // Stale-round guard (strict-sync only — under bounded
            // staleness a late round is EXPECTED and consumed silently
            // by ApplyPushLocked, never a rejoin-forcing error): a
            // worker evicted MID-ROUND whose
            // heartbeat already re-admitted it (monitor rejoin after a
            // wedge) may still re-send the round it was evicted out of.
            // That round CLOSED without it — summing the payload now
            // would credit a stale gradient to the currently open
            // round. Detectably stale: version at/below the key's
            // closed-round watermark yet above the worker's applied
            // watermark (a true replay is at/below applied and is
            // dedupe-dropped as before). Reject like an eviction so the
            // worker rejoins, adopts watermarks, and re-mints.
            bool stale;
            {
              std::lock_guard<std::mutex> lk(ks->mu);
              stale = h.version <= ks->version &&
                      h.reserved < ks->applied_version.size() &&
                      h.version > ks->applied_version[h.reserved];
            }
            if (stale) {
              SendErr(c, h.key,
                      "worker evicted mid-round (stale round): rejoin "
                      "required");
              break;
            }
          }
          Touch(h.reserved, /*admit=*/false);
          if (!validate_payload(h.flags, payload->data(), h.len,
                                static_cast<int64_t>(ks->n_elems))) {
            SendErr(c, h.key, "payload does not match store size");
            break;
          }
          if (h.crc != 0 &&
              wire_crc(payload->data(), payload->size()) != h.crc) {
            // corrupted in transit — detected, NOT applied; the worker
            // retry engine treats this kErr as retryable and re-sends
            SendErr(c, h.key, "payload crc mismatch");
            break;
          }
          // ack on receipt — the pull's version gate provides the round
          // barrier, so the worker can pipeline its next push while the
          // engine sums this one. Applications are ordered per
          // (key, worker) strand: pipelined same-key pushes land in
          // receive order (even across a reconnect) while distinct keys
          // fan out across the pool.
          SendFrame(c, kAck, h.key, 0, nullptr, 0);
          Trace(kTrPushRecv, h.key, h.len, h.flags, t_recv);
          const uint16_t worker = h.reserved;
          const uint8_t codec = h.flags;
          PostOrdered(ks, h.key, worker,
                      [this, ks, key = h.key, worker, codec,
                       version = h.version,
                       buf = std::move(payload)]() mutable {
                        ApplyPush(ks, key, worker, codec, version,
                                  std::move(buf));
                      });
          break;
        }
        case kPull:
          if (h.reserved > 0) {
            Touch(static_cast<uint16_t>(h.reserved - 1), /*admit=*/false);
          }
          HandlePull(c, h.key, h.version, h.flags, h.crc != 0);
          break;
        case kBarrier:
          HandleBarrier(c, h.reserved);
          break;
        case kPing:
          // reserved = worker_id + 1 turns the clock probe into the
          // worker's lease heartbeat — and the REJOIN signal: an evicted
          // worker's heartbeat re-admits it (epoch bumps; the worker then
          // adopts round watermarks via kRounds before pushing again)
          if (h.reserved > 0 && h.reserved - 1 < num_workers_) {
            Touch(static_cast<uint16_t>(h.reserved - 1), /*admit=*/true);
          }
          SendFrame(c, kAck, h.key,
                    static_cast<uint64_t>(realtime_ns()), nullptr, 0);
          break;
        case kMembers: {
          auto m = Members();
          std::vector<char> pay(8 + m->live.size());
          const uint32_t live = m->count;
          const uint32_t nw = static_cast<uint32_t>(m->live.size());
          std::memcpy(pay.data(), &live, 4);
          std::memcpy(pay.data() + 4, &nw, 4);
          if (!m->live.empty()) {
            std::memcpy(pay.data() + 8, m->live.data(), m->live.size());
          }
          // version = the SNAPSHOT's epoch (see MembersInfo): the live
          // set and its epoch label must come from one atomic view
          SendFrame(c, kResp, h.key, m->epoch, pay.data(),
                    static_cast<uint32_t>(pay.size()));
          break;
        }
        case kRounds: {
          // per-key round watermarks for the rejoin handshake: a
          // restarted/evicted worker adopts these so its next mint
          // continues the server's round sequence (a fresh counter would
          // mint versions at/below the replay-dedupe watermark and every
          // later round would be dropped as a replay)
          std::vector<std::pair<uint64_t, KeyStore*>> stores;
          {
            std::lock_guard<std::mutex> lk(store_mu_);
            stores.reserve(store_.size());
            for (auto& [k, ks] : store_) stores.emplace_back(k, ks.get());
          }
          std::vector<char> pay;
          pay.reserve(stores.size() * 24);
          for (auto& [k, ks] : stores) {
            uint64_t trip[3];
            trip[0] = k;
            {
              std::lock_guard<std::mutex> lk(ks->mu);
              trip[1] = ks->version;
              trip[2] = static_cast<uint64_t>(ks->n_elems) * 4;
            }
            const char* p = reinterpret_cast<const char*>(trip);
            pay.insert(pay.end(), p, p + sizeof(trip));
          }
          SendFrame(c, kResp, h.key, epoch_.load(), pay.data(),
                    static_cast<uint32_t>(pay.size()));
          break;
        }
        case kJoin: {
          // first-class mid-stream admission (scale-up elasticity): the
          // tail of the PR 5 lease/epoch machinery — see Join()
          if (h.reserved == 0) {
            SendErr(c, h.key, "join needs a worker id");
            break;
          }
          const int64_t ep = Join(static_cast<uint16_t>(h.reserved - 1));
          if (ep == -1) {
            SendErr(c, h.key, "join: worker id out of range");
          } else if (ep == -2) {
            SendErr(c, h.key,
                    "join: fixed membership (lease disabled) cannot admit "
                    "a new worker id");
          } else {
            SendFrame(c, kAck, h.key, static_cast<uint64_t>(ep), nullptr,
                      0);
          }
          break;
        }
        case kShutdown: {
          SendFrame(c, kAck, 0, 0, nullptr, 0);
          int count = ++shutdown_count_;
          if (lease_ms_ <= 0) {
            // legacy gate: every configured worker said goodbye. Only
            // without the lease — a raw frame COUNT is wrong under
            // elastic membership, where one worker id can legitimately
            // say goodbye twice (depart → replacement rejoins → depart)
            // while a peer is still training.
            if (count >= num_workers_) stop_server_after = true;
          } else if (h.reserved > 0 && h.reserved - 1 < num_workers_) {
            // elastic gate: an identified goodbye marks the worker
            // DEPARTED; the server exits once every worker is departed
            // or evicted — a dead worker cannot hold up teardown, and a
            // live one cannot be stranded by double goodbyes
            if (Depart(static_cast<uint16_t>(h.reserved - 1))) {
              stop_server_after = true;
            }
          } else if (AllAccountedFor()) {
            // anonymous goodbye under the lease: counted (see
            // AllAccountedFor) but cannot name its slot — the lease
            // sweep evicts it and the exit gate re-checks there
            stop_server_after = true;
          }
          done = true;
          break;
        }
        default:
          SendErr(c, h.key, "bad cmd");
          break;
      }
      if (done) break;
    }
    // per-connection teardown: long-running servers with reconnecting
    // workers must not accrete dead Conn entries or leak fds until Stop
    {
      std::lock_guard<std::mutex> lk(conn_mu_);
      conns_.erase(c->id);
    }
    CloseConn(c);
    if (stop_server_after) {
      std::thread([this] { Stop(); }).detach();
    }
  }

  int listen_fd_ = -1;
  // atomic: read lock-free on every conn thread's bounds checks, GROWN
  // by a mid-stream kJoin admitting a fresh worker id
  std::atomic<int> num_workers_{1};
  bool async_ = false;
  bool schedule_ = false;
  int pull_timeout_ms_ = 0;
  int server_id_ = 0;
  int lease_ms_ = 0;
  int staleness_ = 0;  // bounded-staleness K (0 = strict sync rounds)
  // elastic membership (see the helper block above): per-worker lease +
  // state under members_mu_; live count and epoch are atomics so the
  // data plane (SendFrame's epoch stamp, barrier targets) reads them
  // without taking the membership lock
  std::mutex members_mu_;
  std::vector<uint8_t> member_state_;  // MemberState, indexed by worker id
  std::vector<int64_t> last_seen_ms_;  // steady clock, guarded by members_mu_
  std::atomic<int> live_workers_{1};
  std::atomic<uint64_t> epoch_{0};
  // immutable snapshot for lock-free data-plane reads (see Members())
  std::shared_ptr<const Membership> members_snap_ =
      std::make_shared<const Membership>();
  std::atomic<bool> running_{false};
  std::atomic<int> shutdown_count_{0};
  std::unique_ptr<ThreadPool> engine_;
  std::thread accept_thread_;
  std::thread sweep_thread_;
  std::mutex threads_mu_;
  std::condition_variable threads_cv_;
  int live_conn_threads_ = 0;  // guarded by threads_mu_
  std::mutex conn_mu_;
  uint64_t next_conn_id_ = 1;
  std::unordered_map<uint64_t, ConnPtr> conns_;
  std::mutex store_mu_;
  std::unordered_map<uint64_t, std::unique_ptr<KeyStore>> store_;
  std::mutex barrier_mu_;
  // (conn, worker_id + 1) — 0 = anonymous legacy frame; identity lets
  // the release target ignore waiters evicted while queued
  std::vector<std::pair<ConnPtr, uint16_t>> barrier_conns_;
  std::mutex stop_mu_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::atomic<bool> trace_on_{false};
  std::mutex trace_mu_;
  std::vector<TraceEv> trace_;
};

Server* g_server = nullptr;
// Stopped servers are RETIRED, never deleted: a thread can still hold the
// pointer it got from GetServer() (e.g. blocked in LocalPull's cv wait up
// to its timeout) when a restart reclaims the singleton slot — deleting
// would destroy mutexes/cvs under a waiter (UB). The leak is bounded by
// the number of in-process restarts, which is ~0 outside tests.
std::vector<Server*> g_retired;
std::mutex g_server_mu;

Server* GetServer() {
  std::lock_guard<std::mutex> lk(g_server_mu);
  return g_server;
}

}  // namespace

int StartServer(uint16_t port, int num_workers, int engine_threads,
                bool async, int pull_timeout_ms, int server_id,
                bool schedule, int lease_ms, int staleness) {
  std::lock_guard<std::mutex> lk(g_server_mu);
  if (g_server != nullptr) {
    if (g_server->IsRunning()) return -10;  // already running
    // worker-driven shutdown stopped it but left the pointer; retire it so
    // a fresh server can start in this process
    g_server->Stop();  // idempotent; joins any remaining teardown
    g_retired.push_back(g_server);
    g_server = nullptr;
  }
  auto* s = new Server();
  int rc = s->Start(port, num_workers, engine_threads, async,
                    pull_timeout_ms, server_id, schedule, lease_ms,
                    staleness);
  if (rc != 0) {
    delete s;  // never published: no other thread can hold it
    return rc;
  }
  g_server = s;
  return 0;
}

void WaitServer() {
  Server* s = GetServer();
  if (s != nullptr) s->Wait();
}

void StopServer() {
  Server* s;
  {
    std::lock_guard<std::mutex> lk(g_server_mu);
    s = g_server;
    g_server = nullptr;
  }
  if (s != nullptr) {
    s->Stop();
    std::lock_guard<std::mutex> lk(g_server_mu);
    g_retired.push_back(s);  // see g_retired: concurrent holders may remain
  }
}

void ServerTraceEnable(bool on) {
  Server* s = GetServer();
  if (s != nullptr) s->TraceEnable(on);
}

uint64_t ServerEpoch() {
  Server* s = GetServer();
  return s != nullptr ? s->Epoch() : 0;
}

int ServerMembers(uint64_t* epoch, uint32_t* live_count, uint8_t* bitmap,
                  uint32_t cap) {
  Server* s = GetServer();
  if (s == nullptr) return -10;
  return s->MembersInfo(epoch, live_count, bitmap, cap);
}

int64_t ServerJoin(uint16_t worker) {
  Server* s = GetServer();
  if (s == nullptr) return -10;
  return s->Join(worker);
}

int ServerTraceDump(const char* path) {
  Server* s = GetServer();
  if (s == nullptr) {
    // trace of the most recently retired server (dump-after-shutdown)
    std::lock_guard<std::mutex> lk(g_server_mu);
    if (g_retired.empty()) return -2;
    s = g_retired.back();
  }
  return s->TraceDump(path);
}

int LocalInit(uint64_t key, uint64_t nbytes) {
  Server* s = GetServer();
  return s != nullptr ? s->LocalInit(key, nbytes) : -10;
}

int LocalPush(uint16_t worker, uint64_t key, uint8_t codec,
              uint64_t version, const char* buf, size_t len) {
  Server* s = GetServer();
  return s != nullptr ? s->LocalPush(worker, key, codec, version, buf, len)
                      : -10;
}

int LocalPull(uint64_t key, uint8_t codec, uint64_t version, int timeout_ms,
              std::vector<char>* out, uint64_t* out_epoch,
              uint64_t* out_version) {
  Server* s = GetServer();
  return s != nullptr
             ? s->LocalPull(key, codec, version, timeout_ms, out, out_epoch,
                            out_version)
             : -10;
}

}  // namespace bps
