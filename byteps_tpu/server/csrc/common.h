// Wire protocol + socket helpers for the DCN parameter-server tier.
//
// Reference analog: 3rdparty/ps-lite message framing (ps::Message over the
// ZMQ/RDMA van) reduced to what the summation service needs: a fixed little-
// endian header + raw payload over TCP. One frame per request/response.
//
// Frame layout (32 bytes header):
//   u32 magic 'BPS1'  | u8 cmd | u8 flags | u16 reserved
//   u64 key           | u64 version       | u32 payload_len | u32 crc
//
// Field use per command:
//   kInit     version = dense store bytes (payload empty)
//   kPush     flags = codec, reserved = worker_id, version = round the
//             push belongs to (0 = unversioned legacy; nonzero versions
//             let the server drop replayed (worker, key, version) pushes
//             from the worker retry engine instead of double-summing),
//             crc = wire_crc of payload (0 = unchecked)
//   kPull     flags = desired response codec, version = min round,
//             reserved = worker_id + 1 (0 = anonymous; nonzero refreshes
//             the worker's membership lease), crc != 0 requests a
//             checksummed response
//   kResp     flags = codec, version = round, payload = encoded result,
//             crc = wire_crc of payload when the pull asked for it
//   kPing     reserved = worker_id + 1 (0 = anonymous clock probe;
//             nonzero is the worker's lease HEARTBEAT and re-admits an
//             evicted worker) -> kAck with version = server
//             CLOCK_REALTIME ns (clock align)
//   kMembers  -> kResp with version = membership epoch, payload =
//             u32 live_count | u32 num_workers | u8 live[num_workers]
//   kRounds   -> kResp, payload = (u64 key, u64 round, u64 nbytes)*
//             for every key store — the rejoin round-watermark handshake
//   kJoin     reserved = worker_id + 1: first-class mid-stream ADMISSION.
//             A fresh id (>= the configured worker count — the membership
//             table GROWS) or a previously evicted/departed one is
//             admitted at a round boundary: epoch bump, open rounds close
//             over their contributors (quorum-scaled), the joiner adopts
//             round watermarks via kRounds before pushing. -> kAck with
//             version = post-admission epoch, or kErr (id out of range /
//             fixed membership)
//
// Every server->worker frame carries the current membership EPOCH in the
// header's reserved field (low 16 bits): workers learn of membership
// changes on their next op and query kMembers for the full live set.
#pragma once

#include <array>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <string>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/uio.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace bps {

constexpr uint32_t kMagic = 0x31535042;  // "BPS1"

// Upper bound on any frame payload and on a kInit store allocation: a
// malformed header must not drive a multi-GiB resize (the reference caps
// implicitly via BYTEPS_PARTITION_BYTES; 256 MB is ~64x the default 4 MB
// partition).
constexpr uint32_t kMaxFrameLen = 256u * 1024 * 1024;

enum Cmd : uint8_t {
  kInit = 1,      // allocate store[key] (dense bytes in `version`)
  kPush = 2,      // payload = codec-encoded data to sum into store[key]
  kPull = 3,      // wait until store[key].version >= version, then kResp
  kResp = 4,      // payload = codec-encoded result
  kBarrier = 5,   // block until num_workers barriers arrive
  kShutdown = 6,  // connection is done
  kAck = 7,       // empty acknowledgement
  kErr = 8,       // payload = error string
  kPing = 9,      // clock-offset probe / worker lease heartbeat
  kMembers = 10,  // membership query: epoch + live worker bitmap
  kRounds = 11,   // per-key round watermarks (rejoin adoption)
  kJoin = 12,     // mid-stream worker admission (scale-up elasticity)
};

#pragma pack(push, 1)
struct FrameHeader {
  uint32_t magic = kMagic;
  uint8_t cmd = 0;
  uint8_t flags = 0;
  uint16_t reserved = 0;
  uint64_t key = 0;
  uint64_t version = 0;
  uint32_t len = 0;
  uint32_t crc = 0;  // payload CRC32 (0 = unchecked; was padding)
};
#pragma pack(pop)

static_assert(sizeof(FrameHeader) == 32, "frame header must be 32 bytes");

// CRC-32 (IEEE 802.3 polynomial, zlib-compatible: Python's zlib.crc32
// computes the identical value, which the worker-side verify relies on).
inline uint32_t crc32_of(const void* buf, size_t len) {
  static const std::array<uint32_t, 256> table = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      t[i] = c;
    }
    return t;
  }();
  uint32_t c = 0xFFFFFFFFu;
  const unsigned char* p = static_cast<const unsigned char*>(buf);
  for (size_t i = 0; i < len; ++i) {
    c = table[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

// CRC as carried on the wire: 0 means "unchecked", so the one-in-2^32
// payload whose true CRC is 0 is mapped to 1 by BOTH sides (sender and
// verifier apply the same adjustment before comparing).
inline uint32_t wire_crc(const void* buf, size_t len) {
  uint32_t c = crc32_of(buf, len);
  return c != 0 ? c : 1u;
}

// Full-buffer send/recv (TCP gives a byte stream; short reads are normal).
inline bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// Returns false on error/close; a receive timeout (SO_RCVTIMEO expiry)
// leaves errno == EAGAIN/EWOULDBLOCK for the caller to distinguish.
inline bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // peer closed
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Read and discard n payload bytes so the stream stays framed after an
// unexpected-length response (a desynchronized connection would misparse
// every later header).
inline bool drain_bytes(int fd, size_t n) {
  char sink[4096];
  while (n > 0) {
    size_t chunk = n < sizeof(sink) ? n : sizeof(sink);
    if (!recv_all(fd, sink, chunk)) return false;
    n -= chunk;
  }
  return true;
}

inline bool send_frame(int fd, Cmd cmd, uint64_t key, uint64_t version,
                       const void* payload, uint32_t len, uint8_t flags = 0,
                       uint16_t reserved = 0, uint32_t crc = 0) {
  FrameHeader h;
  h.cmd = cmd;
  h.flags = flags;
  h.reserved = reserved;
  h.key = key;
  h.version = version;
  h.len = len;
  h.crc = crc;
  // scatter-gather write: header + payload leave in one sendmsg (one
  // syscall and one coalesced TCP segment stream instead of two sends
  // per frame; MSG_NOSIGNAL keeps the no-SIGPIPE contract of send_all)
  iovec iov[2];
  iov[0].iov_base = &h;
  iov[0].iov_len = sizeof(h);
  iov[1].iov_base = const_cast<void*>(payload);
  iov[1].iov_len = len;
  msghdr msg{};
  msg.msg_iov = iov;
  msg.msg_iovlen = len > 0 ? 2 : 1;
  while (msg.msg_iovlen > 0) {
    ssize_t w = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    size_t n = static_cast<size_t>(w);
    while (msg.msg_iovlen > 0 && n >= msg.msg_iov[0].iov_len) {
      n -= msg.msg_iov[0].iov_len;
      ++msg.msg_iov;
      --msg.msg_iovlen;
    }
    if (msg.msg_iovlen > 0 && n > 0) {
      msg.msg_iov[0].iov_base =
          static_cast<char*>(msg.msg_iov[0].iov_base) + n;
      msg.msg_iov[0].iov_len -= n;
    }
  }
  return true;
}

inline void set_nodelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

// Large socket buffers: a 4 MB partition should stream without the default
// ~200 KB windows throttling loopback throughput.
inline void set_bufsizes(int fd, int bytes = 8 * 1024 * 1024) {
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes));
}

inline void set_recv_timeout(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

}  // namespace bps
