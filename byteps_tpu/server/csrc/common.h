// Wire protocol + socket helpers for the DCN parameter-server tier.
//
// Reference analog: 3rdparty/ps-lite message framing (ps::Message over the
// ZMQ/RDMA van) reduced to what the summation service needs: a fixed little-
// endian header + raw payload over TCP. One frame per request/response.
//
// Frame layout (32 bytes header):
//   u32 magic 'BPS1'  | u8 cmd | u8 flags | u16 reserved
//   u64 key           | u64 version       | u32 payload_len | u32 pad
#pragma once

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace bps {

constexpr uint32_t kMagic = 0x31535042;  // "BPS1"

enum Cmd : uint8_t {
  kInit = 1,      // allocate store[key] of payload_len bytes (payload empty)
  kPush = 2,      // payload = fp32 data to sum into store[key]
  kPull = 3,      // wait until store[key].version >= version, then kResp
  kResp = 4,      // payload = fp32 result
  kBarrier = 5,   // block until num_workers barriers arrive
  kShutdown = 6,  // connection is done
  kAck = 7,       // empty acknowledgement
  kErr = 8,       // payload = error string
};

#pragma pack(push, 1)
struct FrameHeader {
  uint32_t magic = kMagic;
  uint8_t cmd = 0;
  uint8_t flags = 0;
  uint16_t reserved = 0;
  uint64_t key = 0;
  uint64_t version = 0;
  uint32_t len = 0;
  uint32_t pad = 0;
};
#pragma pack(pop)

static_assert(sizeof(FrameHeader) == 32, "frame header must be 32 bytes");

// Full-buffer send/recv (TCP gives a byte stream; short reads are normal).
inline bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

inline bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    if (r == 0) return false;  // peer closed
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

inline bool send_frame(int fd, Cmd cmd, uint64_t key, uint64_t version,
                       const void* payload, uint32_t len) {
  FrameHeader h;
  h.cmd = cmd;
  h.key = key;
  h.version = version;
  h.len = len;
  if (!send_all(fd, &h, sizeof(h))) return false;
  if (len > 0 && !send_all(fd, payload, len)) return false;
  return true;
}

inline void set_nodelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace bps
