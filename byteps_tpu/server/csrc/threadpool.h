// Minimal fixed-size thread pool.
//
// Reference analog: byteps/common/thread_pool.h, used by the server engine
// (BYTEPS_SERVER_ENGINE_THREAD) to parallelize summation across keys while
// the van threads keep receiving.
#pragma once

#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace bps {

class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      threads_.emplace_back([this] { Loop(); });
    }
  }

  ~ThreadPool() { Stop(); }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) {
      if (t.joinable()) t.join();
    }
  }

 private:
  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !q_.empty(); });
        if (stop_ && q_.empty()) return;
        fn = std::move(q_.front());
        q_.pop();
      }
      fn();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> q_;
  std::vector<std::thread> threads_;
  bool stop_ = false;
};

}  // namespace bps
