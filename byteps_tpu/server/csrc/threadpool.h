// Minimal fixed-size thread pool with an optional priority lane.
//
// Reference analog: byteps/common/thread_pool.h, used by the server engine
// (BYTEPS_SERVER_ENGINE_THREAD) to parallelize summation across keys while
// the van threads keep receiving. SubmitPriority is the
// BYTEPS_SERVER_ENABLE_SCHEDULE lane: tasks carry a priority (key id —
// lower = earlier-declared tensor = higher priority, the worker
// scheduler's own order) and pool threads drain the priority lane
// lowest-first before FIFO work.
#pragma once

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace bps {

class ThreadPool {
 public:
  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      threads_.emplace_back([this] { Loop(); });
    }
  }

  ~ThreadPool() { Stop(); }

  void Submit(std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      q_.push(std::move(fn));
    }
    cv_.notify_one();
  }

  // Priority lane: lowest `prio` first; FIFO within equal prio (seq).
  void SubmitPriority(uint64_t prio, std::function<void()> fn) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      pq_.push_back(PTask{prio, seq_++, std::move(fn)});
      std::push_heap(pq_.begin(), pq_.end(), PTaskLater{});
    }
    cv_.notify_one();
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stop_) return;
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) {
      if (t.joinable()) t.join();
    }
  }

 private:
  struct PTask {
    uint64_t prio;
    uint64_t seq;
    std::function<void()> fn;
  };
  // "later" ordering for std::push_heap (max-heap of later-ness = min
  // task first at front)
  struct PTaskLater {
    bool operator()(const PTask& a, const PTask& b) const {
      return a.prio != b.prio ? a.prio > b.prio : a.seq > b.seq;
    }
  };

  void Loop() {
    for (;;) {
      std::function<void()> fn;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk,
                 [this] { return stop_ || !q_.empty() || !pq_.empty(); });
        if (stop_ && q_.empty() && pq_.empty()) return;
        if (!pq_.empty()) {
          std::pop_heap(pq_.begin(), pq_.end(), PTaskLater{});
          fn = std::move(pq_.back().fn);
          pq_.pop_back();
        } else {
          fn = std::move(q_.front());
          q_.pop();
        }
      }
      fn();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> q_;
  std::vector<PTask> pq_;
  uint64_t seq_ = 0;
  std::vector<std::thread> threads_;
  bool stop_ = false;
};

}  // namespace bps
