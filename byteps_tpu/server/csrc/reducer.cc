#include "reducer.h"

namespace bps {

void reduce_sum_f32_range(float* dst, const float* src, int64_t lo,
                          int64_t hi) {
  // restrict-qualified simple loop: auto-vectorizes to AVX2/AVX-512 at -O3
  float* __restrict__ d = dst + lo;
  const float* __restrict__ s = src + lo;
  const int64_t n = hi - lo;
  for (int64_t i = 0; i < n; ++i) d[i] += s[i];
}

void reduce_sum_f32(float* dst, const float* src, int64_t n) {
  reduce_sum_f32_range(dst, src, 0, n);
}

}  // namespace bps

extern "C" void bps_reduce_sum_f32(float* dst, const float* src, int64_t n) {
  bps::reduce_sum_f32(dst, src, n);
}
