// Vectorized summation kernels for the aggregation tier.
//
// Reference analog: byteps/common/cpu_reducer.{h,cc} (AVX+OpenMP sum used by
// servers and cross-PCIe-switch reduce). Here plain C++ loops compiled with
// -O3 -march=native -ffast-math: the compiler emits the AVX; threading comes
// from the server's engine pool (parallel across keys), with a split helper
// for very large single keys.
#pragma once

#include <cstdint>

namespace bps {

void reduce_sum_f32(float* dst, const float* src, int64_t n);
// dst += src for a slice [lo, hi) — lets callers parallelize one huge key.
void reduce_sum_f32_range(float* dst, const float* src, int64_t lo,
                          int64_t hi);

}  // namespace bps

extern "C" {
// exposed for Python-side golden tests of the kernel
void bps_reduce_sum_f32(float* dst, const float* src, int64_t n);
}
