#include "codec.h"

#include <algorithm>
#include <cmath>
#include <cstring>
#include <numeric>
#include <random>

namespace bps {

namespace {

inline int64_t onebit_words(int64_t n) { return (n + 31) / 32; }

// xorshift-based uniform in [0,1) — cheap, reproducible stochastic rounding
// for re-encoded dithering responses (seeded per key+version by the server).
struct Rng01 {
  uint64_t s;
  explicit Rng01(uint64_t seed) : s(seed * 0x9E3779B97F4A7C15ull + 1) {}
  float next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return static_cast<float>((s >> 11) & 0xFFFFFF) * (1.0f / 16777216.0f);
  }
};

}  // namespace

float half_to_float(uint16_t h) {
  const uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1F;
  uint32_t man = h & 0x3FF;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;  // ±0
    } else {
      // subnormal half -> normalized float
      exp = 127 - 15 + 1;
      while ((man & 0x400) == 0) {
        man <<= 1;
        exp--;
      }
      man &= 0x3FF;
      bits = sign | (exp << 23) | (man << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);  // inf/nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float f;
  std::memcpy(&f, &bits, 4);
  return f;
}

uint16_t float_to_half(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  const uint16_t sign = static_cast<uint16_t>((bits >> 16) & 0x8000u);
  int32_t exp = static_cast<int32_t>((bits >> 23) & 0xFF) - 127 + 15;
  uint32_t man = bits & 0x7FFFFF;
  if (exp >= 31) {
    // overflow -> inf (or nan preserved)
    const bool is_nan = ((bits >> 23) & 0xFF) == 0xFF && man != 0;
    return static_cast<uint16_t>(sign | 0x7C00 | (is_nan ? 0x200 : 0));
  }
  if (exp <= 0) {
    if (exp < -10) return sign;  // underflow to ±0
    // subnormal: shift mantissa (with implicit 1) right
    man |= 0x800000;
    const int shift = 14 - exp;
    uint32_t half_man = man >> shift;
    // round to nearest even
    const uint32_t rem = man & ((1u << shift) - 1);
    const uint32_t halfway = 1u << (shift - 1);
    if (rem > halfway || (rem == halfway && (half_man & 1))) half_man++;
    return static_cast<uint16_t>(sign | half_man);
  }
  uint32_t half_man = man >> 13;
  const uint32_t rem = man & 0x1FFF;
  if (rem > 0x1000 || (rem == 0x1000 && (half_man & 1))) {
    half_man++;
    if (half_man == 0x400) {  // mantissa rollover bumps exponent
      half_man = 0;
      exp++;
      if (exp >= 31) return static_cast<uint16_t>(sign | 0x7C00);
    }
  }
  return static_cast<uint16_t>(sign | (exp << 10) | half_man);
}

float fp8_to_float(uint8_t b) {
  const float sign = (b & 0x80u) ? -1.0f : 1.0f;
  const int exp = (b >> 3) & 0xF;
  const int man = b & 0x7;
  if (exp == 15 && man == 7) return std::nanf("");  // the only NaN pattern
  if (exp == 0) return sign * std::ldexp(static_cast<float>(man), -9);
  // (1 + man/8) * 2^(exp-7) == (8 + man) * 2^(exp-10)
  return sign * std::ldexp(static_cast<float>(8 + man), exp - 10);
}

uint8_t float_to_fp8(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  const uint8_t sign = static_cast<uint8_t>((bits >> 24) & 0x80u);
  const uint32_t exp_f = (bits >> 23) & 0xFFu;
  const uint32_t man_f = bits & 0x7FFFFFu;
  if (exp_f == 0xFF) return sign | 0x7F;  // inf/NaN -> NaN
  if ((bits & 0x7FFFFFFFu) == 0) return sign;  // ±0
  const int e = static_cast<int>(exp_f) - 127;
  // 24-bit significand with the implicit bit (fp32 subnormal inputs have
  // e == -127 and no implicit bit, but those are << the fp8 subnormal
  // cutoff and fall into the shift>31 underflow below regardless)
  const uint32_t sig = man_f | 0x800000u;
  int shift, out_exp;
  if (e < -6) {  // fp8-subnormal target: ulp = 2^-9
    shift = 20 + (-6 - e);
    out_exp = 0;
    if (shift > 31) return sign;  // underflow to ±0
  } else {
    shift = 20;
    out_exp = e + 7;
  }
  // round to nearest, ties to even
  uint32_t rounded = sig >> shift;
  const uint32_t rem = sig & ((1u << shift) - 1u);
  const uint32_t half = 1u << (shift - 1);
  if (rem > half || (rem == half && (rounded & 1u))) rounded++;
  if (out_exp == 0) {
    if (rounded >= 8) {  // rounded up into the normal range
      out_exp = 1;
      rounded -= 8;
    }
  } else {
    if (rounded >= 16) {  // mantissa carry: exponent bumps, mantissa 0
      out_exp++;
      rounded >>= 1;
    }
    rounded -= 8;  // strip the implicit bit
  }
  if (out_exp > 15 || (out_exp == 15 && rounded >= 7)) {
    // e4m3fn has no inf and S.1111.111 is NaN: anything rounding past
    // ±448 (i.e. |x| > 464 after RNE) becomes NaN, matching the
    // ml_dtypes cast bit-for-bit on ALL inputs. The scaled wire path
    // pre-clips to ±448 before this function, so production encodes
    // never take this branch.
    return sign | 0x7F;
  }
  return sign | static_cast<uint8_t>(out_exp << 3) |
         static_cast<uint8_t>(rounded);
}

bool validate_payload(uint8_t codec, const char* buf, size_t len, int64_t n) {
  switch (codec) {
    case kCodecRaw:
      return len == static_cast<size_t>(n) * 4;
    case kCodecFP16:
      return len == static_cast<size_t>(n) * 2;
    case kCodecFP8:
      return len == 4 + static_cast<size_t>(n);
    case kCodecOnebit:
      return len == 4 + static_cast<size_t>(onebit_words(n)) * 4;
    case kCodecTopk: {
      if (len < 4) return false;
      uint32_t k;
      std::memcpy(&k, buf, 4);
      if (k == 0 || static_cast<int64_t>(k) > n) return false;
      if (len != 4 + static_cast<size_t>(k) * 8) return false;
      const char* ip = buf + 4;
      for (uint32_t i = 0; i < k; ++i) {
        uint32_t idx;
        std::memcpy(&idx, ip + i * 4, 4);
        if (static_cast<int64_t>(idx) >= n) return false;
      }
      return true;
    }
    case kCodecDither: {
      if (len != 8 + static_cast<size_t>(n)) return false;
      const uint8_t s = static_cast<uint8_t>(buf[1]);
      return s >= 1 && s <= 127;
    }
    default:
      return false;
  }
}

void decode_sum(uint8_t codec, const char* buf, size_t len, float* dst,
                int64_t n) {
  switch (codec) {
    case kCodecRaw: {
      const float* src = reinterpret_cast<const float*>(buf);
      float* __restrict__ d = dst;
      for (int64_t i = 0; i < n; ++i) d[i] += src[i];
      break;
    }
    case kCodecFP16: {
      const uint16_t* src = reinterpret_cast<const uint16_t*>(buf);
      for (int64_t i = 0; i < n; ++i) dst[i] += half_to_float(src[i]);
      break;
    }
    case kCodecFP8: {
      float scale;
      std::memcpy(&scale, buf, 4);
      const uint8_t* src = reinterpret_cast<const uint8_t*>(buf + 4);
      for (int64_t i = 0; i < n; ++i) dst[i] += fp8_to_float(src[i]) * scale;
      break;
    }
    case kCodecOnebit: {
      float scale;
      std::memcpy(&scale, buf, 4);
      const uint32_t* words = reinterpret_cast<const uint32_t*>(buf + 4);
      for (int64_t i = 0; i < n; ++i) {
        const bool pos = (words[i >> 5] >> (i & 31)) & 1u;
        dst[i] += pos ? scale : -scale;
      }
      break;
    }
    case kCodecTopk: {
      uint32_t k;
      std::memcpy(&k, buf, 4);
      const uint32_t* idx = reinterpret_cast<const uint32_t*>(buf + 4);
      const float* val = reinterpret_cast<const float*>(buf + 4 + k * 4);
      for (uint32_t i = 0; i < k; ++i) dst[idx[i]] += val[i];
      break;
    }
    case kCodecDither: {
      const uint8_t flags = static_cast<uint8_t>(buf[0]);
      const int s = static_cast<uint8_t>(buf[1]);
      float norm;
      std::memcpy(&norm, buf + 4, 4);
      const int8_t* lv = reinterpret_cast<const int8_t*>(buf + 8);
      const bool natural = flags & kDitherNatural;
      for (int64_t i = 0; i < n; ++i) {
        const int l = lv[i];
        const int mag = l < 0 ? -l : l;
        if (mag == 0) continue;
        float p;
        if (natural) {
          p = std::exp2f(static_cast<float>(mag - 1 - (s - 1)));
        } else {
          p = static_cast<float>(mag) / static_cast<float>(s);
        }
        dst[i] += (l < 0 ? -p : p) * norm;
      }
      break;
    }
    default:
      (void)len;
      break;
  }
}

void update_hint(uint8_t codec, const char* buf, size_t len, CodecHint* hint) {
  (void)len;
  if (codec == kCodecTopk) {
    std::memcpy(&hint->topk_k, buf, 4);
  } else if (codec == kCodecDither) {
    hint->dither_flags = static_cast<uint8_t>(buf[0]);
    hint->dither_s = static_cast<uint8_t>(buf[1]);
  } else if (codec == kCodecOnebit) {
    float scale;
    std::memcpy(&scale, buf, 4);
    hint->onebit_scaled = scale != 1.0f;
  }
}

std::vector<char> encode(uint8_t codec, const float* src, int64_t n,
                         const CodecHint& hint, uint64_t seed) {
  switch (codec) {
    case kCodecFP16: {
      std::vector<char> out(static_cast<size_t>(n) * 2);
      uint16_t* dst = reinterpret_cast<uint16_t*>(out.data());
      for (int64_t i = 0; i < n; ++i) dst[i] = float_to_half(src[i]);
      return out;
    }
    case kCodecFP8: {
      float absmax = 0.f;
      for (int64_t i = 0; i < n; ++i)
        absmax = std::max(absmax, std::fabs(src[i]));
      const float scale = absmax > 0.f ? absmax / 448.0f : 1.0f;
      std::vector<char> out(4 + static_cast<size_t>(n));
      std::memcpy(out.data(), &scale, 4);
      uint8_t* dst = reinterpret_cast<uint8_t*>(out.data() + 4);
      for (int64_t i = 0; i < n; ++i) {
        const float q =
            std::min(448.0f, std::max(-448.0f, src[i] / scale));
        dst[i] = float_to_fp8(q);
      }
      return out;
    }
    case kCodecOnebit: {
      // scale = mean|x|, unless the pushes were unscaled (scale 1.0 ==
      // signSGD, learned via CodecHint) — then mirror ±1 semantics
      float scale = 1.f;
      if (hint.onebit_scaled) {
        double acc = 0.0;
        for (int64_t i = 0; i < n; ++i) acc += std::fabs(src[i]);
        scale = n > 0 ? static_cast<float>(acc / n) : 0.f;
      }
      std::vector<char> out(4 + static_cast<size_t>(onebit_words(n)) * 4, 0);
      std::memcpy(out.data(), &scale, 4);
      uint32_t* words = reinterpret_cast<uint32_t*>(out.data() + 4);
      for (int64_t i = 0; i < n; ++i) {
        if (!std::signbit(src[i])) words[i >> 5] |= 1u << (i & 31);
      }
      return out;
    }
    case kCodecTopk: {
      uint32_t k = hint.topk_k;
      if (k == 0 || static_cast<int64_t>(k) > n) {
        k = static_cast<uint32_t>(n);
      }
      std::vector<uint32_t> order(static_cast<size_t>(n));
      std::iota(order.begin(), order.end(), 0u);
      std::nth_element(
          order.begin(), order.begin() + k, order.end(),
          [src](uint32_t a, uint32_t b) {
            return std::fabs(src[a]) > std::fabs(src[b]);
          });
      std::vector<char> out(4 + static_cast<size_t>(k) * 8);
      std::memcpy(out.data(), &k, 4);
      uint32_t* idx = reinterpret_cast<uint32_t*>(out.data() + 4);
      float* val = reinterpret_cast<float*>(out.data() + 4 + k * 4);
      for (uint32_t i = 0; i < k; ++i) {
        idx[i] = order[i];
        val[i] = src[order[i]];
      }
      return out;
    }
    case kCodecDither: {
      const bool natural = hint.dither_flags & kDitherNatural;
      const bool maxnorm = hint.dither_flags & kDitherMaxNorm;
      const int s = hint.dither_s >= 1 ? hint.dither_s : 127;
      float norm = 0.f;
      if (maxnorm) {
        for (int64_t i = 0; i < n; ++i)
          norm = std::max(norm, std::fabs(src[i]));
      } else {
        double acc = 0.0;
        for (int64_t i = 0; i < n; ++i)
          acc += static_cast<double>(src[i]) * src[i];
        norm = static_cast<float>(std::sqrt(acc));
      }
      const float safe = norm > 0 ? norm : 1.f;
      Rng01 rng(seed);
      std::vector<char> out(8 + static_cast<size_t>(n), 0);
      out[0] = static_cast<char>(hint.dither_flags);
      out[1] = static_cast<char>(s);
      std::memcpy(out.data() + 4, &norm, 4);
      int8_t* lv = reinterpret_cast<int8_t*>(out.data() + 8);
      for (int64_t i = 0; i < n; ++i) {
        const float x = src[i];
        const float p = std::fabs(x) / safe;  // in [0, 1]
        const float u = rng.next();
        int level;
        if (!natural) {
          const float y = std::min(p, 1.f) * s;
          const float lo = std::floor(y);
          level = static_cast<int>(lo) + (u < (y - lo) ? 1 : 0);
        } else {
          // quantize p onto {0} ∪ {2^-j : j in [0, s-1]}, stochastic in the
          // mantissa; level index = log2(q) + (s-1) + 1, 0 => zero (matches
          // the worker-side DitheringCompressor natural partition)
          const float tiny = std::exp2f(static_cast<float>(-(s - 1)));
          if (p < tiny) {
            level = (u < p / tiny) ? 1 : 0;  // level 1 == tiny, else zero
          } else {
            const float pc = std::min(p, 1.f);
            const float e = std::floor(std::log2f(pc));
            const float base = std::exp2f(e);
            const float frac = pc / base - 1.f;
            const float q = base * (u < frac ? 2.f : 1.f);
            level = static_cast<int>(std::lround(std::log2f(q))) + (s - 1) + 1;
            if (level > s) level = s;
          }
        }
        if (level > 127) level = 127;
        lv[i] = static_cast<int8_t>(x < 0 ? -level : level);
      }
      return out;
    }
    case kCodecRaw:
    default: {
      std::vector<char> out(static_cast<size_t>(n) * 4);
      std::memcpy(out.data(), src, out.size());
      return out;
    }
  }
}

}  // namespace bps
