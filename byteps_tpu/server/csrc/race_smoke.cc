// Thread-race smoke test for the DCN summation service (build with
// `make tsan`, run under ThreadSanitizer). Exercises every concurrency
// surface in one process: parallel TCP clients pushing/pulling raw and
// codec-encoded keys against the engine pool with scheduling on, the
// in-process (IPC) fast path racing them, a mid-flight reconnect, and a
// concurrent Stop against live traffic.
//
// Reference analog: SURVEY §5.2 recommends TSAN CI for the native tier;
// the reference repo itself ships none. Exit code 0 = clean (TSAN aborts
// nonzero on a detected race).
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <chrono>
#include <thread>
#include <vector>

#include "client.h"
#include "codec.h"
#include "server.h"

namespace {

constexpr uint16_t kPort = 24123;
constexpr int kWorkers = 2;
constexpr int kKeysPerWorker = 4;
constexpr int kRounds = 20;
constexpr int64_t kElems = 4096;

void worker_body(int wid, std::atomic<int>* failures) {
  bps::Client c;
  if (c.Connect("127.0.0.1", kPort, 5000, 20000) != 0) {
    failures->fetch_add(1);
    return;
  }
  std::vector<float> data(kElems, 1.0f + wid);
  std::vector<float> out(kElems);
  for (int k = 0; k < kKeysPerWorker; ++k) {
    uint64_t key = k;  // shared keys: both workers sum into each round
    if (c.InitKey(key, kElems * 4) != 0) failures->fetch_add(1);
  }
  for (int r = 1; r <= kRounds; ++r) {
    for (int k = 0; k < kKeysPerWorker; ++k) {
      if (c.Push(k, data.data(), kElems * 4, 0, wid) != 0) {
        failures->fetch_add(1);
        return;
      }
    }
    for (int k = 0; k < kKeysPerWorker; ++k) {
      uint64_t got = 0;
      if (c.Pull(k, out.data(), kElems * 4, r, 0, &got) != 0 ||
          got != kElems * 4) {
        failures->fetch_add(1);
        return;
      }
      const float want = (1.0f + 0) + (1.0f + 1);  // both workers' pushes
      if (out[0] != want || out[kElems - 1] != want) {
        std::fprintf(stderr, "round %d key sum %f != %f\n", r, out[0],
                     want);
        failures->fetch_add(1);
        return;
      }
    }
  }
  // NO counted Shutdown here: num_workers shutdowns would self-stop the
  // server mid-test; the destructor just closes the socket, exercising
  // the conn-reap path instead
}

void stop_phase_body() {
  // best-effort traffic whose whole purpose is to be live while
  // StopServer runs — every error is expected once teardown begins
  bps::Client c;
  if (c.Connect("127.0.0.1", kPort, 2000, 2000) != 0) return;
  std::vector<float> data(kElems, 1.0f);
  for (int i = 0; i < 500; ++i) {
    if (c.Push(2000 + (i % 3), data.data(), kElems * 4, 0,
               i % kWorkers) != 0) {
      return;
    }
  }
}

void local_body(std::atomic<int>* failures) {
  // in-process fast path on its own key, racing the TCP traffic
  const uint64_t key = 1000;
  if (bps::LocalInit(key, kElems * 4) != 0) {
    failures->fetch_add(1);
    return;
  }
  std::vector<float> data(kElems, 3.0f);
  for (int r = 1; r <= kRounds; ++r) {
    for (int w = 0; w < kWorkers; ++w) {
      if (bps::LocalPush(w, key, 0, static_cast<uint64_t>(r),
                         reinterpret_cast<const char*>(data.data()),
                         kElems * 4) != 0) {
        failures->fetch_add(1);
        return;
      }
    }
    std::vector<char> blob;
    if (bps::LocalPull(key, 0, r, 20000, &blob) != 0 ||
        blob.size() != kElems * 4) {
      failures->fetch_add(1);
      return;
    }
  }
}

}  // namespace

int main() {
  if (bps::StartServer(kPort, kWorkers, /*engine_threads=*/2,
                       /*async=*/false, /*pull_timeout_ms=*/20000,
                       /*server_id=*/0, /*schedule=*/true,
                       /*lease_ms=*/5000, /*staleness=*/0) != 0) {
    std::fprintf(stderr, "server start failed\n");
    return 1;
  }
  std::atomic<int> failures{0};
  std::vector<std::thread> ts;
  for (int w = 0; w < kWorkers; ++w) {
    ts.emplace_back(worker_body, w, &failures);
  }
  ts.emplace_back(local_body, &failures);
  for (auto& t : ts) t.join();

  // reconnect after a full traffic cycle (client teardown vs conn reap)
  {
    bps::Client c;
    if (c.Connect("127.0.0.1", kPort, 5000, 20000) != 0) {
      failures.fetch_add(1);
    }
  }

  // lease eviction under live traffic: worker 1 goes silent, worker 0
  // heartbeats (kPing with worker id) while its pull blocks on a round
  // worker 1 will never push — the sweep thread's eviction must close the
  // round over the live set and answer the pull, with membership state
  // (lease refresh / epoch stamp / Members query) racing the data plane
  {
    bps::Client c;
    if (c.Connect("127.0.0.1", kPort, 5000, 30000) == 0) {
      const uint64_t key = 3000;
      std::vector<float> data(kElems, 5.0f);
      std::vector<float> out(kElems);
      if (c.InitKey(key, kElems * 4) != 0 ||
          c.Push(key, data.data(), kElems * 4, 0, /*worker=*/0,
                 /*version=*/1) != 0) {
        failures.fetch_add(1);
      } else {
        std::atomic<bool> hb_stop{false};
        std::thread hb([&hb_stop] {
          bps::Client h;
          if (h.Connect("127.0.0.1", kPort, 5000, 5000) != 0) return;
          while (!hb_stop.load()) {
            int64_t sns = 0, rtt = 0;
            h.Ping(&sns, &rtt, /*worker_id=*/0);
            uint64_t ep = 0;
            uint32_t live = 0, nw = 0;
            uint8_t bitmap[16] = {0};
            h.Members(&ep, &live, &nw, bitmap, sizeof(bitmap));
            std::this_thread::sleep_for(std::chrono::milliseconds(100));
          }
        });
        uint64_t got = 0;
        int rc = c.Pull(key, out.data(), kElems * 4, 1, 0, &got);
        hb_stop.store(true);
        hb.join();
        if (rc != 0 || got != kElems * 4 || out[0] != 5.0f) {
          std::fprintf(stderr,
                       "lease phase: pull rc=%d got=%llu out0=%f\n", rc,
                       static_cast<unsigned long long>(got), out[0]);
          failures.fetch_add(1);
        }
      }
    } else {
      failures.fetch_add(1);
    }
  }

  // mid-stream JOIN under live traffic (scale-up elasticity, mirror of
  // the lease-eviction phase): workers 0/1 stream rounds of key 4000
  // while a FRESH worker id 2 — beyond the configured count, so the
  // membership table and every key store's per-worker vectors GROW —
  // joins (kJoin), adopts the round watermark (kRounds), and contributes
  // every remaining round; a fourth thread hammers Join/Members
  // idempotently against the same growth. Values are not asserted (the
  // join boundary quorum-scales rounds by design); completion without a
  // hang/race is the property.
  {
    const uint64_t key = 4000;
    {
      bps::Client init;
      if (init.Connect("127.0.0.1", kPort, 5000, 30000) != 0 ||
          init.InitKey(key, kElems * 4) != 0) {
        std::fprintf(stderr, "join phase: init failed\n");
        failures.fetch_add(1);
      }
      // re-admit BOTH base workers BEFORE any concurrent traffic: the
      // lease phase above deliberately evicted worker 1, and a round
      // closed over the pre-readmit live set {0} would shift the round
      // numbering under worker 1's first push (a deterministic stale
      // reject, not the race under test — the JOIN races, these don't)
      int64_t sns = 0, rtt = 0;
      init.Ping(&sns, &rtt, 0);
      init.Ping(&sns, &rtt, 1);
    }
    auto pusher = [&failures, key](int wid) {
      bps::Client c;
      if (c.Connect("127.0.0.1", kPort, 5000, 60000) != 0) {
        std::fprintf(stderr, "join phase: pusher connect failed\n");
        failures.fetch_add(1);
        return;
      }
      std::vector<float> data(kElems, 1.0f + wid);
      std::vector<float> out(kElems);
      for (int r = 1; r <= kRounds; ++r) {
        if (c.Push(key, data.data(), kElems * 4, 0, wid,
                   static_cast<uint64_t>(r)) != 0) {
          std::fprintf(stderr, "join phase: pusher push failed\n");
          failures.fetch_add(1);
          return;
        }
        uint64_t got = 0;
        if (c.Pull(key, out.data(), kElems * 4, static_cast<uint64_t>(r),
                   0, &got, false, nullptr, wid) != 0 ||
            got != kElems * 4) {
          std::fprintf(stderr, "join phase: pusher pull failed\n");
          failures.fetch_add(1);
          return;
        }
      }
    };
    auto joiner = [&failures, key] {
      bps::Client c;
      if (c.Connect("127.0.0.1", kPort, 5000, 60000) != 0) {
        std::fprintf(stderr, "join phase: joiner connect failed\n");
        failures.fetch_add(1);
        return;
      }
      if (c.Join(2) != 0) {
        std::fprintf(stderr, "join phase: kJoin failed\n");
        failures.fetch_add(1);
        return;
      }
      std::vector<float> data(kElems, 9.0f);
      std::vector<float> out(kElems);
      uint64_t v = 0;
      for (;;) {
        // adopt (or re-adopt) the round watermark; a push refused as
        // stale — its round closed in the publish window before our
        // first contribution landed — re-syncs and continues, the
        // worker-side rejoin contract
        uint8_t buf[24 * 64];
        uint64_t got = 0;
        if (c.Rounds(buf, sizeof(buf), &got) != 0) {
          std::fprintf(stderr, "join phase: kRounds failed\n");
          failures.fetch_add(1);
          return;
        }
        v = 0;
        for (uint64_t off = 0; off + 24 <= got; off += 24) {
          uint64_t k = 0, round = 0;
          std::memcpy(&k, buf + off, 8);
          std::memcpy(&round, buf + off + 8, 8);
          if (k == key) v = round;
        }
        bool resync = false;
        for (uint64_t r = v + 1; r <= kRounds; ++r) {
          int rc = c.Push(key, data.data(), kElems * 4, 0, /*worker=*/2,
                          r);
          if (rc == 1) {  // kErr: stale round — re-adopt and go again
            resync = true;
            break;
          }
          if (rc != 0) {
            std::fprintf(stderr, "join phase: joiner push failed\n");
            failures.fetch_add(1);
            return;
          }
          uint64_t got2 = 0;
          if (c.Pull(key, out.data(), kElems * 4, r, 0, &got2, false,
                     nullptr, 2) != 0 ||
              got2 != kElems * 4) {
            std::fprintf(stderr, "join phase: joiner pull failed\n");
            failures.fetch_add(1);
            return;
          }
        }
        if (!resync) return;
      }
    };
    auto rejoiner = [&failures] {
      // idempotent re-admissions of the SAME id + membership queries
      // racing the growth (id 2, not a fresh one: a live-but-silent
      // extra member would strand every later round by design)
      bps::Client c;
      if (c.Connect("127.0.0.1", kPort, 5000, 10000) != 0) return;
      for (int i = 0; i < 50; ++i) {
        uint64_t ep = 0;
        if (c.Join(2, &ep) != 0) {
          std::fprintf(stderr, "join phase: re-join failed\n");
          failures.fetch_add(1);
          return;
        }
        uint32_t live = 0, nw = 0;
        uint8_t bitmap[32] = {0};
        c.Members(&ep, &live, &nw, bitmap, sizeof(bitmap));
      }
    };
    std::vector<std::thread> jt;
    jt.emplace_back(pusher, 0);
    jt.emplace_back(pusher, 1);
    jt.emplace_back(joiner);
    jt.emplace_back(rejoiner);
    for (auto& t : jt) t.join();
  }

  // concurrent Stop vs live traffic: the hardest teardown paths (listener
  // shutdown, conn fd shutdown under send, engine drain) race real pushes
  {
    bps::Client init;
    if (init.Connect("127.0.0.1", kPort, 5000, 20000) == 0) {
      for (int k = 0; k < 3; ++k) init.InitKey(2000 + k, kElems * 4);
    }
    std::vector<std::thread> st;
    for (int i = 0; i < 3; ++i) st.emplace_back(stop_phase_body);
    std::this_thread::sleep_for(std::chrono::milliseconds(30));
    bps::StopServer();
    for (auto& t : st) t.join();
  }
  if (failures.load() != 0) {
    std::fprintf(stderr, "race_smoke: %d failures\n", failures.load());
    return 1;
  }
  std::puts("race_smoke: OK");
  return 0;
}
