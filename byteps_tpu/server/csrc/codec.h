// Server-side wire codecs for the DCN summation service.
//
// Reference analog: the server half of byteps's compression feature —
// byteps/server/server.cc decompresses each pushed partition, sums in fp32,
// and re-compresses the round result before answering pulls (SURVEY §2.2 /
// §3.3). The codec id rides the frame header's `flags` byte; per-codec
// parameters the response must reuse (topk's k, dithering's mode/levels)
// are remembered per key from the last push (CodecHint).
//
// Wire formats (little-endian), dense store = n fp32 elements:
//   kCodecRaw    n*f32                      (positional sum; also the
//                                            values-only wire of seed-synced
//                                            randomk, store size = k)
//   kCodecFP16   n*f16 (IEEE binary16)
//   kCodecOnebit [f32 scale][ceil(n/32)*u32]  bit (i&31) of word i>>5 set
//                                            => x[i] >= 0; value = ±scale
//   kCodecTopk   [u32 k][k*u32 idx][k*f32 val]  scatter-add
//   kCodecDither [u8 flags][u8 s][u16 0][f32 norm][n*i8 levels]
//                flags bit0: natural (powers-of-two) levels, else linear
//                flags bit1: max-norm (else l2) — used when re-encoding
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace bps {

enum Codec : uint8_t {
  kCodecRaw = 0,
  kCodecFP16 = 1,
  kCodecOnebit = 2,
  kCodecTopk = 3,
  kCodecDither = 4,
  // [f32 scale][n bytes e4m3fn] — quarter of raw fp32 (see
  // compression/fp8.py; byte-exact twin of the ml_dtypes cast)
  kCodecFP8 = 5,
};

constexpr uint8_t kDitherNatural = 0x1;
constexpr uint8_t kDitherMaxNorm = 0x2;

// Per-key parameters remembered from the most recent push, reused when
// re-encoding the round result for a compressed pull response.
struct CodecHint {
  uint32_t topk_k = 0;
  uint8_t dither_flags = 0;
  uint8_t dither_s = 127;
  // scaling=False workers push scale == 1.0f exactly (signSGD); mirror
  // that choice when re-encoding so two-way pulls return ±1, not ±mean|x|.
  bool onebit_scaled = true;
};

// Validate payload size + internal header against a dense store of n floats.
bool validate_payload(uint8_t codec, const char* buf, size_t len, int64_t n);

// dst[0..n) += decode(payload). Caller validated first.
void decode_sum(uint8_t codec, const char* buf, size_t len, float* dst,
                int64_t n);

// Remember response-relevant parameters from a validated push payload.
void update_hint(uint8_t codec, const char* buf, size_t len, CodecHint* hint);

// Encode src[0..n) for a pull response. `seed` drives stochastic rounding
// (dithering); deterministic per (key, version) so tests can golden it.
std::vector<char> encode(uint8_t codec, const float* src, int64_t n,
                         const CodecHint& hint, uint64_t seed);

// Portable IEEE half conversions (software; auto-vectorizable loops).
float half_to_float(uint16_t h);
uint16_t float_to_half(float f);

// e4m3fn conversions (1-4-3, bias 7, max finite 448, no inf;
// round-to-nearest-even on encode — matches the ml_dtypes cast the
// Python wire codec uses, asserted over all 256 bytes in tests).
float fp8_to_float(uint8_t b);
uint8_t float_to_fp8(float f);

}  // namespace bps
