// DCN worker-side client — the reference's ps::KVWorker<char>::ZPush/ZPull
// (3rdparty/ps-lite include/ps/kv_app.h) reduced to the summation service's
// needs. One Client = one TCP connection with strictly serial
// request/response (parallelism = several Client instances, one per
// scheduler pool thread, mirroring ps-lite's per-thread customers).
#pragma once

#include <cstdint>
#include <mutex>
#include <string>

namespace bps {

class Client {
 public:
  ~Client();
  // Retries until the server accepts or timeout_ms elapses (workers may
  // start before servers; ps-lite's scheduler rendezvous absorbs this in
  // the reference).
  int Connect(const std::string& host, uint16_t port, int timeout_ms);
  int InitKey(uint64_t key, uint64_t nbytes);
  int Push(uint64_t key, const void* data, uint64_t nbytes);
  // Blocks until the server completed round `version` for this key.
  int Pull(uint64_t key, void* data, uint64_t nbytes, uint64_t version);
  int Barrier();
  int Shutdown();

 private:
  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace bps
