// DCN worker-side client — the reference's ps::KVWorker<char>::ZPush/ZPull
// (3rdparty/ps-lite include/ps/kv_app.h) reduced to the summation service's
// needs. One Client = one TCP connection with strictly serial
// request/response (parallelism = several Client instances, one per
// scheduler pool thread, mirroring ps-lite's per-thread customers).
//
// Return codes: 0 ok; >0 server kErr (message via last_error());
// -2 send failed / connection dead; -3 recv failed/closed; -4 bad magic;
// -5 response larger than the caller's buffer (stream drained, still
// framed); -6 response key does not match the request (desynchronized
// stream); -7 receive timeout (dead/stalled server).
//
// Any error that can leave bytes of a late/foreign frame in the stream
// (-3/-4/-6/-7) closes the connection: a timed-out response would
// otherwise be consumed by the NEXT request on this client and silently
// return another round's (or key's) data. Subsequent calls fail fast
// with -2; the owner reconnects or reports.
#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <string>

#include "common.h"

namespace bps {

class Client {
 public:
  ~Client();
  // Retries until the server accepts or timeout_ms elapses (workers may
  // start before servers; ps-lite's scheduler rendezvous absorbs this in
  // the reference). recv_timeout_ms > 0 arms SO_RCVTIMEO so a pull against
  // a dead server errors instead of blocking a scheduler thread forever.
  int Connect(const std::string& host, uint16_t port, int timeout_ms,
              int recv_timeout_ms);
  int InitKey(uint64_t key, uint64_t nbytes);
  // Push `nbytes` of codec-encoded payload as `worker_id`. `version` is
  // the round this push belongs to (0 = unversioned): the server drops a
  // replayed (worker, key, version) instead of double-summing, which is
  // what makes the worker retry engine's re-sent pushes safe. `crc` is
  // the payload checksum as computed by wire_crc (0 = unchecked); a
  // mismatch is rejected server-side with a retryable kErr.
  int Push(uint64_t key, const void* data, uint64_t nbytes, uint8_t codec,
           uint16_t worker_id, uint64_t version = 0, uint32_t crc = 0);
  // Blocks until the server completed round `version`; response encoded as
  // `codec` is written into data (capacity `nbytes`); *out_bytes = actual.
  // want_crc requests a checksummed response; *out_crc receives the
  // server-computed wire_crc of the payload (0 when not requested) for
  // the CALLER to verify — verification is deliberately not done here so
  // the fault-injection layer can corrupt the buffer in between.
  // `worker_id` >= 0 rides the request so the server refreshes that
  // worker's membership lease (a worker blocked in a long pull is alive).
  // *out_epoch receives the membership epoch the pulled ROUND closed
  // under (its header stamp) — the divisor authority for averaging.
  // *out_round receives the SERVED round (response header version):
  // under bounded staleness (BYTEPS_STALENESS) it may differ from the
  // requested round — requested − served is the effective staleness.
  int Pull(uint64_t key, void* data, uint64_t nbytes, uint64_t version,
           uint8_t codec, uint64_t* out_bytes, bool want_crc = false,
           uint32_t* out_crc = nullptr, int worker_id = -1,
           uint16_t* out_epoch = nullptr, uint64_t* out_round = nullptr);
  // `worker_id` >= 0 rides the barrier/shutdown frame so the server can
  // refresh the worker's lease (barrier) or mark it DEPARTED (shutdown);
  // -1 keeps the anonymous legacy frame.
  int Barrier(int worker_id = -1);
  int Shutdown(int worker_id = -1);
  // Clock-offset probe: *server_ns = server CLOCK_REALTIME at serve time,
  // *rtt_ns = local round-trip (offset ≈ server_ns + rtt/2 − local_now).
  // `worker_id` >= 0 makes the probe the worker's membership lease
  // HEARTBEAT (and the rejoin signal for an evicted worker).
  int Ping(int64_t* server_ns, int64_t* rtt_ns, int worker_id = -1);
  // Membership query: *epoch, *live_count, and up to `cap` bytes of the
  // per-worker live bitmap; *num_workers = configured worker count.
  int Members(uint64_t* epoch, uint32_t* live_count, uint32_t* num_workers,
              uint8_t* bitmap, uint32_t cap);
  // Per-key round watermarks (u64 key, u64 round, u64 nbytes triples)
  // into `out` (cap bytes); *got = actual bytes. The rejoin handshake.
  int Rounds(void* out, uint64_t cap, uint64_t* got);
  // Mid-stream worker ADMISSION (kJoin; scale-up elasticity): admit
  // `worker_id` — a fresh id (the server grows its membership table) or
  // a previously evicted/departed one — at a round boundary. *out_epoch
  // (optional) receives the post-admission membership epoch. The caller
  // must adopt round watermarks (Rounds) before pushing. Returns -8 for
  // an id outside [0, 0xFFFE] (it would truncate in the wire encoding
  // and admit a DIFFERENT worker).
  int Join(int worker_id, uint64_t* out_epoch = nullptr);
  // Membership epoch (low 16 bits) carried by the LAST response this
  // client parsed — workers poll it per op to detect membership changes
  // without an extra round trip.
  uint16_t epoch() const { return epoch_.load(std::memory_order_relaxed); }
  const char* last_error() const { return last_err_.c_str(); }
  // True once a desynchronizing error closed the socket; the owner should
  // drop this client and connect a fresh one.
  bool dead() const { return fd_ < 0; }

 private:
  int Roundtrip(Cmd cmd, uint64_t key, uint64_t version, const void* req,
                uint32_t req_len, void* in, uint64_t in_cap, uint64_t* got,
                uint8_t flags, uint16_t reserved, uint64_t* resp_version,
                uint32_t req_crc = 0, uint32_t* resp_crc = nullptr,
                uint16_t* resp_reserved = nullptr);
  // Close the socket after a stream-desynchronizing error; later calls
  // return -2 instead of misparsing stale frames.
  void Kill();

  int fd_ = -1;
  std::mutex mu_;
  std::string last_err_;
  std::atomic<uint16_t> epoch_{0};
};

}  // namespace bps
