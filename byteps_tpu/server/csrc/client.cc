#include "client.h"

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <netdb.h>

#include "common.h"

namespace bps {

namespace {
int ConnectOnce(const std::string& host, uint16_t port, const char** why) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  int grc = ::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                          &res);
  if (grc != 0) {
    *why = ::gai_strerror(grc);
    return -1;
  }
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    *why = ::strerror(errno);
  } else if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    *why = ::strerror(errno);
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  return fd;
}

int64_t steady_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

int Client::Connect(const std::string& host, uint16_t port, int timeout_ms,
                    int recv_timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  const char* why = "unknown";
  for (;;) {
    int fd = ConnectOnce(host, port, &why);
    if (fd >= 0) {
      set_nodelay(fd);
      set_bufsizes(fd);
      set_recv_timeout(fd, recv_timeout_ms);
      fd_ = fd;
      return 0;
    }
    if (std::chrono::steady_clock::now() >= deadline) {
      // surfaced via stderr because there is no client handle yet for
      // last_error(); "refused for the whole budget while the port looks
      // bound" has meant a dead accept loop before — name the errno so
      // the next person doesn't have to strace a flake
      std::fprintf(stderr, "bps client: connect %s:%u gave up after %d ms"
                   " (last error: %s)\n", host.c_str(), port, timeout_ms,
                   why);
      return -1;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void Client::Kill() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

// Serial request → response. Negative on transport error, positive on
// server kErr (message in last_err), 0 ok. `in`/`in_cap` receive a kResp
// payload; *got gets the actual size. kAck payloads are drained; a
// too-large kResp is drained too, keeping the stream framed (-5). Every
// server response echoes the request key, which is verified here — a
// mismatch means the stream carries a stale frame (e.g. a late response
// after a timeout) and the connection is closed rather than trusted.
int Client::Roundtrip(Cmd cmd, uint64_t key, uint64_t version,
                      const void* req, uint32_t req_len, void* in,
                      uint64_t in_cap, uint64_t* got, uint8_t flags,
                      uint16_t reserved, uint64_t* resp_version,
                      uint32_t req_crc, uint32_t* resp_crc,
                      uint16_t* resp_reserved) {
  if (fd_ < 0) return -2;
  if (!send_frame(fd_, cmd, key, version, req, req_len, flags, reserved,
                  req_crc)) {
    Kill();
    return -2;
  }
  FrameHeader h;
  if (!recv_all(fd_, &h, sizeof(h))) {
    int rc = (errno == EAGAIN || errno == EWOULDBLOCK) ? -7 : -3;
    Kill();
    return rc;
  }
  if (h.magic != kMagic) {
    Kill();
    return -4;
  }
  if (h.key != key) {
    // stale frame from a previous (timed-out) request, or a server bug —
    // either way the stream can no longer be trusted
    Kill();
    return -6;
  }
  // every server response stamps a membership epoch into reserved (pull
  // responses: the epoch their ROUND closed under; everything else: the
  // current epoch); remember it so the owner can detect evictions and
  // rejoins per op
  epoch_.store(h.reserved, std::memory_order_relaxed);
  if (resp_reserved != nullptr) *resp_reserved = h.reserved;
  if (h.cmd == kErr) {
    std::vector<char> msg(h.len);
    if (h.len > 0 && !recv_all(fd_, msg.data(), h.len)) {
      Kill();
      return -3;
    }
    last_err_.assign(msg.begin(), msg.end());
    return 1;
  }
  if (resp_version != nullptr) *resp_version = h.version;
  if (resp_crc != nullptr) *resp_crc = h.crc;
  if (h.cmd == kResp) {
    if (in == nullptr || h.len > in_cap) {
      if (!drain_bytes(fd_, h.len)) {
        Kill();
        return -3;
      }
      return -5;
    }
    if (h.len > 0 && !recv_all(fd_, in, h.len)) {
      int rc = (errno == EAGAIN || errno == EWOULDBLOCK) ? -7 : -3;
      Kill();
      return rc;
    }
    if (got != nullptr) *got = h.len;
    return 0;
  }
  // kAck
  if (h.len > 0 && !drain_bytes(fd_, h.len)) {
    Kill();
    return -3;
  }
  return 0;
}

int Client::InitKey(uint64_t key, uint64_t nbytes) {
  std::lock_guard<std::mutex> lk(mu_);
  // nbytes rides the version field (payload-free frame)
  return Roundtrip(kInit, key, nbytes, nullptr, 0, nullptr, 0, nullptr,
                   0, 0, nullptr);
}

int Client::Push(uint64_t key, const void* data, uint64_t nbytes,
                 uint8_t codec, uint16_t worker_id, uint64_t version,
                 uint32_t crc) {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(kPush, key, version, data,
                   static_cast<uint32_t>(nbytes), nullptr, 0, nullptr,
                   codec, worker_id, nullptr, crc);
}

int Client::Pull(uint64_t key, void* data, uint64_t nbytes, uint64_t version,
                 uint8_t codec, uint64_t* out_bytes, bool want_crc,
                 uint32_t* out_crc, int worker_id, uint16_t* out_epoch,
                 uint64_t* out_round) {
  std::lock_guard<std::mutex> lk(mu_);
  const uint16_t wid =
      worker_id >= 0 ? static_cast<uint16_t>(worker_id + 1) : 0;
  // request crc = 1 is the "checksum the response" marker (any nonzero
  // value works; the pull request itself has no payload to checksum);
  // out_round = the response header's version field, i.e. the round the
  // server actually SERVED (>= requested − BYTEPS_STALENESS)
  return Roundtrip(kPull, key, version, nullptr, 0, data, nbytes,
                   out_bytes, codec, wid, out_round, want_crc ? 1u : 0u,
                   out_crc, out_epoch);
}

int Client::Barrier(int worker_id) {
  std::lock_guard<std::mutex> lk(mu_);
  const uint16_t wid =
      worker_id >= 0 ? static_cast<uint16_t>(worker_id + 1) : 0;
  return Roundtrip(kBarrier, 0, 0, nullptr, 0, nullptr, 0, nullptr, 0,
                   wid, nullptr);
}

int Client::Shutdown(int worker_id) {
  std::lock_guard<std::mutex> lk(mu_);
  const uint16_t wid =
      worker_id >= 0 ? static_cast<uint16_t>(worker_id + 1) : 0;
  return Roundtrip(kShutdown, 0, 0, nullptr, 0, nullptr, 0, nullptr, 0,
                   wid, nullptr);
}

int Client::Ping(int64_t* server_ns, int64_t* rtt_ns, int worker_id) {
  std::lock_guard<std::mutex> lk(mu_);
  const int64_t t0 = steady_ns();
  uint64_t sv = 0;
  const uint16_t wid =
      worker_id >= 0 ? static_cast<uint16_t>(worker_id + 1) : 0;
  int rc = Roundtrip(kPing, 0, 0, nullptr, 0, nullptr, 0, nullptr, 0,
                     wid, &sv);
  if (rc == 0) {
    if (server_ns != nullptr) *server_ns = static_cast<int64_t>(sv);
    if (rtt_ns != nullptr) *rtt_ns = steady_ns() - t0;
  }
  return rc;
}

int Client::Members(uint64_t* epoch, uint32_t* live_count,
                    uint32_t* num_workers, uint8_t* bitmap, uint32_t cap) {
  std::lock_guard<std::mutex> lk(mu_);
  // payload: u32 live_count | u32 num_workers | u8 live[num_workers]
  std::vector<char> buf(8 + 65536);
  uint64_t got = 0;
  uint64_t ep = 0;
  int rc = Roundtrip(kMembers, 0, 0, nullptr, 0, buf.data(), buf.size(),
                     &got, 0, 0, &ep);
  if (rc != 0) return rc;
  if (got < 8) {
    Kill();
    return -4;
  }
  uint32_t live = 0;
  uint32_t nw = 0;
  std::memcpy(&live, buf.data(), 4);
  std::memcpy(&nw, buf.data() + 4, 4);
  if (got < 8 + nw) {
    Kill();
    return -4;
  }
  if (epoch != nullptr) *epoch = ep;
  if (live_count != nullptr) *live_count = live;
  if (num_workers != nullptr) *num_workers = nw;
  if (bitmap != nullptr && nw > 0) {
    std::memcpy(bitmap, buf.data() + 8, std::min(nw, cap));
  }
  return 0;
}

int Client::Rounds(void* out, uint64_t cap, uint64_t* got) {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(kRounds, 0, 0, nullptr, 0, out, cap, got, 0, 0,
                   nullptr);
}

int Client::Join(int worker_id, uint64_t* out_epoch) {
  // range-checked BEFORE the uint16 wire encoding: a truncated id would
  // silently admit a DIFFERENT worker (65536 -> wid 1 -> worker 0).
  // Mirrors the bps_server_join IPC check; -8 = invalid argument.
  if (worker_id < 0 || worker_id > 0xFFFE) return -8;
  std::lock_guard<std::mutex> lk(mu_);
  const uint16_t wid = static_cast<uint16_t>(worker_id + 1);
  uint64_t ep = 0;
  int rc = Roundtrip(kJoin, 0, 0, nullptr, 0, nullptr, 0, nullptr, 0,
                     wid, &ep);
  if (rc == 0 && out_epoch != nullptr) *out_epoch = ep;
  return rc;
}

}  // namespace bps
