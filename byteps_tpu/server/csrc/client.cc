#include "client.h"

#include <chrono>
#include <cstring>
#include <thread>
#include <vector>

#include <netdb.h>

#include "common.h"

namespace bps {

namespace {
int ConnectOnce(const std::string& host, uint16_t port) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0) {
    return -1;
  }
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    ::close(fd);
    fd = -1;
  }
  ::freeaddrinfo(res);
  return fd;
}
}  // namespace

Client::~Client() {
  if (fd_ >= 0) ::close(fd_);
}

int Client::Connect(const std::string& host, uint16_t port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    int fd = ConnectOnce(host, port);
    if (fd >= 0) {
      set_nodelay(fd);
      fd_ = fd;
      return 0;
    }
    if (std::chrono::steady_clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

// Serial request → response. Returns 0 ok, negative on transport error,
// positive on server kErr.
static int Roundtrip(int fd, Cmd cmd, uint64_t key, uint64_t version,
                     const void* out, uint32_t out_len, void* in,
                     uint64_t in_len) {
  if (!send_frame(fd, cmd, key, version, out, out_len)) return -2;
  FrameHeader h;
  if (!recv_all(fd, &h, sizeof(h))) return -3;
  if (h.magic != kMagic) return -4;
  if (h.cmd == kErr) {
    std::vector<char> msg(h.len);
    recv_all(fd, msg.data(), h.len);
    return 1;
  }
  if (h.cmd == kResp) {
    if (h.len != in_len || in == nullptr) return -5;
    if (!recv_all(fd, in, h.len)) return -6;
    return 0;
  }
  // kAck
  if (h.len > 0) {
    std::vector<char> skip(h.len);
    if (!recv_all(fd, skip.data(), h.len)) return -6;
  }
  return 0;
}

int Client::InitKey(uint64_t key, uint64_t nbytes) {
  std::lock_guard<std::mutex> lk(mu_);
  // nbytes rides the version field (payload-free frame)
  return Roundtrip(fd_, kInit, key, nbytes, nullptr, 0, nullptr, 0);
}

int Client::Push(uint64_t key, const void* data, uint64_t nbytes) {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(fd_, kPush, key, 0, data,
                   static_cast<uint32_t>(nbytes), nullptr, 0);
}

int Client::Pull(uint64_t key, void* data, uint64_t nbytes,
                 uint64_t version) {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(fd_, kPull, key, version, nullptr, 0, data, nbytes);
}

int Client::Barrier() {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(fd_, kBarrier, 0, 0, nullptr, 0, nullptr, 0);
}

int Client::Shutdown() {
  std::lock_guard<std::mutex> lk(mu_);
  return Roundtrip(fd_, kShutdown, 0, 0, nullptr, 0, nullptr, 0);
}

}  // namespace bps
