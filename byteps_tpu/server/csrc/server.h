// DCN summation service — the reference's byteps/server/server.{h,cc}
// (BytePSServer + BytePSHandler over ps::KVServer<char>) rebuilt on a plain
// TCP van: workers INIT/PUSH/PULL codec-encoded partitions by u64 key; the
// server decodes each push into an fp32 accumulator on an engine thread
// pool (decompress→sum, reference server.cc push handler), and answers
// pulls when all DMLC_NUM_WORKER workers contributed the round (sync) or
// immediately (BYTEPS_ENABLE_ASYNC), re-encoding the result with the
// requested codec (recompress-before-pull, SURVEY §2.2/§3.3).
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace bps {

// Returns 0 on success. num_workers: pushes per round per key; engine
// threads: decode/sum pool size; async: no per-round barrier.
// `pull_timeout_ms` > 0 expires pulls waiting past the deadline with kErr
// (dead-worker fail-fast; reference analog: ps-lite heartbeat/resender,
// SURVEY §5.3). `server_id` labels trace output. `schedule` enables
// priority-ordered engine work by key (BYTEPS_SERVER_ENABLE_SCHEDULE).
// `lease_ms` > 0 arms ELASTIC WORKER MEMBERSHIP (BYTEPS_WORKER_LEASE_MS):
// every worker holds a lease refreshed by its pushes/pulls and kPing
// heartbeats; a worker silent past the lease is EVICTED — the membership
// epoch bumps, open rounds re-target the live worker set (partial sums
// with contributions from the dead worker are scaled by live/contributors
// so the global *average* stays unbiased), stuck barriers release over
// the live set, and the server exits once every worker is departed or
// evicted (a dead worker can no longer stall its peers' pulls, barriers,
// or teardown). A later heartbeat from an evicted worker RE-ADMITS it
// (epoch bumps again); pushes from an evicted worker are rejected with a
// "worker evicted" kErr until it rejoins, so its stale rounds can never
// leak into a post-eviction sum. 0 = fixed membership (legacy).
// `staleness` > 0 arms BOUNDED-STALENESS rounds (BYTEPS_STALENESS=K, sync
// mode only — async is the K=inf limit): a pull for round v is served from
// the newest CLOSED round v' >= v-K instead of blocking on v itself, and a
// pull that would otherwise wait past the bound FORCE-closes open rounds
// (each over its contributors, quorum-scaled exactly like an
// eviction-shrunk round) up to v-K so one straggler can no longer set the
// global step time. A straggler's push for a round that already closed is
// consumed silently (watermark advanced, payload dropped) — backpressure
// and catch-up, never an error. K=0 is bit-identical to the synchronous
// tier. Responses stamp the SERVED round in the version field, so the
// worker knows its effective staleness.
int StartServer(uint16_t port, int num_workers, int engine_threads,
                bool async, int pull_timeout_ms, int server_id,
                bool schedule, int lease_ms, int staleness);
// Current membership epoch of the in-process server (0 if none running) —
// the IPC-path analog of the epoch carried in every TCP response header.
uint64_t ServerEpoch();
// Membership snapshot of the in-process server: *epoch, *live_count, and
// up to `cap` bytes of the per-worker live bitmap. Returns num_workers,
// or -10 when no server runs in this process.
int ServerMembers(uint64_t* epoch, uint32_t* live_count, uint8_t* bitmap,
                  uint32_t cap);
// Mid-stream worker ADMISSION (the IPC analog of kJoin; scale-up
// elasticity): admit `worker` — a fresh id beyond the configured count
// (the membership table and every key store's per-worker vectors GROW
// before the admission is published, so the join lands at a round
// boundary) or a previously evicted/departed one. Returns the
// post-admission epoch, -1 for an out-of-range id, -2 under fixed
// membership (lease disabled) for an unknown id, -10 with no server.
int64_t ServerJoin(uint16_t worker);
// Blocks until the server stops (all workers sent kShutdown, or StopServer).
void WaitServer();
void StopServer();

// Chrome-trace collection (reference: BYTEPS_TRACE_* server-side timestamps,
// the joapolarbear fork's defining capability). Events carry absolute
// CLOCK_REALTIME microseconds so they merge with worker traces.
void ServerTraceEnable(bool on);
// Writes chrome trace JSON; returns events dumped, negative on I/O error.
int ServerTraceDump(const char* path);

// In-process (colocated) fast path — BYTEPS_ENABLE_IPC: a worker living in
// the same process as the server (joint role) reads/writes the store
// directly instead of looping through TCP. Round completion still answers
// remote TCP pulls.
int LocalInit(uint64_t key, uint64_t nbytes);
// `version` != 0 arms the per-(worker, key) replay dedupe (a re-sent push
// with an already-applied version is dropped, not double-summed).
int LocalPush(uint16_t worker, uint64_t key, uint8_t codec,
              uint64_t version, const char* buf, size_t len);
// Blocks up to timeout_ms for round `version`; fills `out` with the
// response encoded as `codec`. *out_epoch (optional) receives the
// membership epoch the returned ROUND closed under — the averaging
// divisor authority, same contract as the TCP response header stamp.
// *out_version (optional) receives the SERVED round — under bounded
// staleness it may differ from the requested one (the TCP analog is the
// response header's version field).
int LocalPull(uint64_t key, uint8_t codec, uint64_t version, int timeout_ms,
              std::vector<char>* out, uint64_t* out_epoch = nullptr,
              uint64_t* out_version = nullptr);

}  // namespace bps
