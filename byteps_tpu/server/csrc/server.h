// DCN summation service — the reference's byteps/server/server.{h,cc}
// (BytePSServer + BytePSHandler over ps::KVServer<char>) rebuilt on a plain
// TCP van: workers INIT/PUSH/PULL fp32 partitions by u64 key; the server
// sums pushes in fp32 on an engine thread pool and answers pulls when all
// DMLC_NUM_WORKER workers contributed the round (sync) or immediately
// (BYTEPS_ENABLE_ASYNC).
#pragma once

#include <cstdint>

namespace bps {

// Returns 0 on success. num_workers: pushes per round per key; engine
// threads: summation pool size; async: no per-round barrier.
int StartServer(uint16_t port, int num_workers, int engine_threads,
                bool async);
// Blocks until the server stops (all workers sent kShutdown, or StopServer).
void WaitServer();
void StopServer();

}  // namespace bps
