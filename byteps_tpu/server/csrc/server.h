// DCN summation service — the reference's byteps/server/server.{h,cc}
// (BytePSServer + BytePSHandler over ps::KVServer<char>) rebuilt on a plain
// TCP van: workers INIT/PUSH/PULL codec-encoded partitions by u64 key; the
// server decodes each push into an fp32 accumulator on an engine thread
// pool (decompress→sum, reference server.cc push handler), and answers
// pulls when all DMLC_NUM_WORKER workers contributed the round (sync) or
// immediately (BYTEPS_ENABLE_ASYNC), re-encoding the result with the
// requested codec (recompress-before-pull, SURVEY §2.2/§3.3).
#pragma once

#include <cstddef>
#include <cstdint>
#include <vector>

namespace bps {

// Returns 0 on success. num_workers: pushes per round per key; engine
// threads: decode/sum pool size; async: no per-round barrier.
// `pull_timeout_ms` > 0 expires pulls waiting past the deadline with kErr
// (dead-worker fail-fast; reference analog: ps-lite heartbeat/resender,
// SURVEY §5.3). `server_id` labels trace output. `schedule` enables
// priority-ordered engine work by key (BYTEPS_SERVER_ENABLE_SCHEDULE).
int StartServer(uint16_t port, int num_workers, int engine_threads,
                bool async, int pull_timeout_ms, int server_id,
                bool schedule);
// Blocks until the server stops (all workers sent kShutdown, or StopServer).
void WaitServer();
void StopServer();

// Chrome-trace collection (reference: BYTEPS_TRACE_* server-side timestamps,
// the joapolarbear fork's defining capability). Events carry absolute
// CLOCK_REALTIME microseconds so they merge with worker traces.
void ServerTraceEnable(bool on);
// Writes chrome trace JSON; returns events dumped, negative on I/O error.
int ServerTraceDump(const char* path);

// In-process (colocated) fast path — BYTEPS_ENABLE_IPC: a worker living in
// the same process as the server (joint role) reads/writes the store
// directly instead of looping through TCP. Round completion still answers
// remote TCP pulls.
int LocalInit(uint64_t key, uint64_t nbytes);
// `version` != 0 arms the per-(worker, key) replay dedupe (a re-sent push
// with an already-applied version is dropped, not double-summed).
int LocalPush(uint16_t worker, uint64_t key, uint8_t codec,
              uint64_t version, const char* buf, size_t len);
// Blocks up to timeout_ms for round `version`; fills `out` with the
// response encoded as `codec`.
int LocalPull(uint64_t key, uint8_t codec, uint64_t version, int timeout_ms,
              std::vector<char>* out);

}  // namespace bps
