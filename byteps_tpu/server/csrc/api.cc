// C API surface loaded from Python via ctypes (no pybind11 in this image).
// Reference analog: the extern "C" block of byteps/common/operations.h plus
// byteps/server's StartPS entry.
#include <cstdint>
#include <cstring>
#include <vector>

#include "client.h"
#include "codec.h"
#include "server.h"

extern "C" {

int bps_server_start(uint16_t port, int num_workers, int engine_threads,
                     int async_mode, int pull_timeout_ms, int server_id,
                     int enable_schedule, int lease_ms, int staleness) {
  return bps::StartServer(port, num_workers, engine_threads, async_mode != 0,
                          pull_timeout_ms, server_id, enable_schedule != 0,
                          lease_ms, staleness);
}

// Elastic-membership observability: the in-process server's epoch and
// live worker set (the IPC analog of the epoch every TCP response
// carries).
uint64_t bps_server_epoch() { return bps::ServerEpoch(); }

int bps_server_members(uint64_t* epoch, uint32_t* live_count,
                       uint8_t* bitmap, uint32_t cap) {
  return bps::ServerMembers(epoch, live_count, bitmap, cap);
}

// Mid-stream worker admission against the in-process server (the IPC
// analog of kJoin; scale-up elasticity). Returns the post-admission
// epoch, or negative (-1 out of range, -2 fixed membership, -10 no
// server in this process).
int64_t bps_server_join(int worker) {
  if (worker < 0 || worker > 0xFFFF) return -1;
  return bps::ServerJoin(static_cast<uint16_t>(worker));
}

void bps_server_wait() { bps::WaitServer(); }

void bps_server_stop() { bps::StopServer(); }

void bps_server_trace_enable(int on) { bps::ServerTraceEnable(on != 0); }

// e4m3 conversions exposed for the Python<->C++ bit-exactness tests
// (tests/test_dcn.py asserts parity with the ml_dtypes cast over all
// 256 byte values and random grids).
float bps_fp8_to_float(uint8_t b) { return bps::fp8_to_float(b); }

uint8_t bps_float_to_fp8(float f) { return bps::float_to_fp8(f); }

int bps_server_trace_dump(const char* path) {
  return bps::ServerTraceDump(path);
}

// ---- what-if simulator calibration (byteps_tpu/sim/extract.py) ------------
// Price the server's REAL codec paths — push-side decode_sum and the
// two-way re-encode — without a running server: the numpy wire codecs
// are not rate-representative of these loops (bit unpack, scatter-add,
// top-k reselection), and a what-if over a codec the recorded run never
// exercised needs the C++ rates its PUSH/PULL spans would carry.
int64_t bps_codec_decode_sum(uint8_t codec, const char* buf, int64_t len,
                             float* dst, int64_t n) {
  if (!bps::validate_payload(codec, buf, static_cast<size_t>(len), n))
    return -1;
  bps::decode_sum(codec, buf, static_cast<size_t>(len), dst, n);
  return 0;
}

int64_t bps_codec_encode(uint8_t codec, const float* src, int64_t n,
                         uint32_t topk_k, uint64_t seed, char* out,
                         int64_t cap) {
  bps::CodecHint hint;
  hint.topk_k = topk_k;
  std::vector<char> buf = bps::encode(codec, src, n, hint, seed);
  if (static_cast<int64_t>(buf.size()) > cap)
    return -static_cast<int64_t>(buf.size());
  std::memcpy(out, buf.data(), buf.size());
  return static_cast<int64_t>(buf.size());
}

// ---- in-process (IPC) fast path -------------------------------------------
int bps_local_init(uint64_t key, uint64_t nbytes) {
  return bps::LocalInit(key, nbytes);
}

int bps_local_push(uint16_t worker, uint64_t key, uint8_t codec,
                   const void* buf, uint64_t nbytes) {
  return bps::LocalPush(worker, key, codec, 0,
                        static_cast<const char*>(buf), nbytes);
}

// Versioned variant: `version` != 0 arms the per-(worker, key) replay
// dedupe, making retry-engine re-sends idempotent.
int bps_local_push2(uint16_t worker, uint64_t key, uint8_t codec,
                    uint64_t version, const void* buf, uint64_t nbytes) {
  return bps::LocalPush(worker, key, codec, version,
                        static_cast<const char*>(buf), nbytes);
}

// Fills out (capacity cap); returns actual bytes >= 0, or negative error
// (-4 timeout, -5 buffer too small, -10 no server in this process).
int64_t bps_local_pull(uint64_t key, uint8_t codec, uint64_t version,
                       int timeout_ms, void* out, uint64_t cap) {
  std::vector<char> blob;
  int rc = bps::LocalPull(key, codec, version, timeout_ms, &blob);
  if (rc != 0) return rc;
  if (blob.size() > cap) return -5;
  std::memcpy(out, blob.data(), blob.size());
  return static_cast<int64_t>(blob.size());
}

// As bps_local_pull, additionally surfacing the membership epoch the
// returned ROUND closed under (the IPC analog of the TCP response
// header's stamp — the averaging divisor authority).
int64_t bps_local_pull2(uint64_t key, uint8_t codec, uint64_t version,
                        int timeout_ms, void* out, uint64_t cap,
                        uint64_t* out_epoch) {
  std::vector<char> blob;
  int rc = bps::LocalPull(key, codec, version, timeout_ms, &blob,
                          out_epoch);
  if (rc != 0) return rc;
  if (blob.size() > cap) return -5;
  std::memcpy(out, blob.data(), blob.size());
  return static_cast<int64_t>(blob.size());
}

// As bps_local_pull2, additionally surfacing the SERVED round (the TCP
// response header's version field): under bounded staleness
// (BYTEPS_STALENESS) it may differ from the requested round — requested
// minus served is the worker's effective staleness.
int64_t bps_local_pull3(uint64_t key, uint8_t codec, uint64_t version,
                        int timeout_ms, void* out, uint64_t cap,
                        uint64_t* out_epoch, uint64_t* out_round) {
  std::vector<char> blob;
  int rc = bps::LocalPull(key, codec, version, timeout_ms, &blob,
                          out_epoch, out_round);
  if (rc != 0) return rc;
  if (blob.size() > cap) return -5;
  std::memcpy(out, blob.data(), blob.size());
  return static_cast<int64_t>(blob.size());
}

// ---- TCP client -----------------------------------------------------------
void* bps_client_connect(const char* host, uint16_t port, int timeout_ms,
                         int recv_timeout_ms) {
  auto* c = new bps::Client();
  if (c->Connect(host, port, timeout_ms, recv_timeout_ms) != 0) {
    delete c;
    return nullptr;
  }
  return c;
}

int bps_client_init_key(void* client, uint64_t key, uint64_t nbytes) {
  return static_cast<bps::Client*>(client)->InitKey(key, nbytes);
}

int bps_client_push(void* client, uint64_t key, const void* data,
                    uint64_t nbytes, uint8_t codec, uint16_t worker_id) {
  return static_cast<bps::Client*>(client)->Push(key, data, nbytes, codec,
                                                 worker_id);
}

// Versioned + checksummed push: `version` != 0 arms the server-side
// (worker, key, version) replay dedupe; `crc` != 0 is verified server-side
// before the payload is summed (mismatch -> retryable kErr).
int bps_client_push2(void* client, uint64_t key, const void* data,
                     uint64_t nbytes, uint8_t codec, uint16_t worker_id,
                     uint64_t version, uint32_t crc) {
  return static_cast<bps::Client*>(client)->Push(key, data, nbytes, codec,
                                                 worker_id, version, crc);
}

int bps_client_pull(void* client, uint64_t key, void* data, uint64_t nbytes,
                    uint64_t version, uint8_t codec, uint64_t* out_bytes) {
  return static_cast<bps::Client*>(client)->Pull(key, data, nbytes, version,
                                                 codec, out_bytes);
}

// Checksummed pull: want_crc != 0 asks the server to checksum the
// response; *out_crc receives it (caller verifies — kept out of the C
// layer so the fault-injection harness can corrupt the buffer first).
// `worker_id` >= 0 refreshes the worker's membership lease server-side;
// *out_epoch receives the membership epoch the pulled ROUND closed
// under (low 16 bits — the divisor authority for averaging).
int bps_client_pull2(void* client, uint64_t key, void* data,
                     uint64_t nbytes, uint64_t version, uint8_t codec,
                     int want_crc, uint64_t* out_bytes, uint32_t* out_crc,
                     int worker_id, uint32_t* out_epoch) {
  uint16_t ep = 0;
  int rc = static_cast<bps::Client*>(client)->Pull(
      key, data, nbytes, version, codec, out_bytes, want_crc != 0, out_crc,
      worker_id, &ep);
  if (out_epoch != nullptr) *out_epoch = ep;
  return rc;
}

// As bps_client_pull2, additionally surfacing the SERVED round (response
// header version) — under bounded staleness (BYTEPS_STALENESS) the server
// answers from the newest closed round >= requested − K, and the worker
// reads its effective staleness off this stamp.
int bps_client_pull3(void* client, uint64_t key, void* data,
                     uint64_t nbytes, uint64_t version, uint8_t codec,
                     int want_crc, uint64_t* out_bytes, uint32_t* out_crc,
                     int worker_id, uint32_t* out_epoch,
                     uint64_t* out_round) {
  uint16_t ep = 0;
  int rc = static_cast<bps::Client*>(client)->Pull(
      key, data, nbytes, version, codec, out_bytes, want_crc != 0, out_crc,
      worker_id, &ep, out_round);
  if (out_epoch != nullptr) *out_epoch = ep;
  return rc;
}

// `worker_id` >= 0 identifies the worker to the server's membership
// layer (lease refresh on barrier, DEPARTED marking on shutdown, lease
// heartbeat + rejoin on ping); -1 keeps the anonymous legacy frame.
int bps_client_barrier(void* client, int worker_id) {
  return static_cast<bps::Client*>(client)->Barrier(worker_id);
}

int bps_client_shutdown(void* client, int worker_id) {
  return static_cast<bps::Client*>(client)->Shutdown(worker_id);
}

int bps_client_ping(void* client, int64_t* server_ns, int64_t* rtt_ns,
                    int worker_id) {
  return static_cast<bps::Client*>(client)->Ping(server_ns, rtt_ns,
                                                 worker_id);
}

// Membership epoch (low 16 bits) stamped on the last response this client
// parsed — polled per op by the worker to detect membership changes.
int bps_client_epoch(void* client) {
  return static_cast<int>(static_cast<bps::Client*>(client)->epoch());
}

int bps_client_members(void* client, uint64_t* epoch, uint32_t* live_count,
                       uint32_t* num_workers, uint8_t* bitmap,
                       uint32_t cap) {
  return static_cast<bps::Client*>(client)->Members(
      epoch, live_count, num_workers, bitmap, cap);
}

// Per-key (u64 key, u64 round, u64 nbytes) watermark triples into `out`;
// *got = bytes written. The rejoin round-adoption handshake.
int bps_client_rounds(void* client, void* out, uint64_t cap,
                      uint64_t* got) {
  return static_cast<bps::Client*>(client)->Rounds(out, cap, got);
}

// Mid-stream worker admission (kJoin): a fresh worker id (the server
// grows its membership table) or a previously evicted/departed one is
// admitted at a round boundary; *out_epoch receives the post-admission
// epoch. Adopt round watermarks (bps_client_rounds) before pushing.
int bps_client_join(void* client, int worker_id, uint64_t* out_epoch) {
  return static_cast<bps::Client*>(client)->Join(worker_id, out_epoch);
}

const char* bps_client_last_error(void* client) {
  return static_cast<bps::Client*>(client)->last_error();
}

int bps_client_is_dead(void* client) {
  return static_cast<bps::Client*>(client)->dead() ? 1 : 0;
}

void bps_client_free(void* client) {
  delete static_cast<bps::Client*>(client);
}

}  // extern "C"
