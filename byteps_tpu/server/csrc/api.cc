// C API surface loaded from Python via ctypes (no pybind11 in this image).
// Reference analog: the extern "C" block of byteps/common/operations.h plus
// byteps/server's StartPS entry.
#include <cstdint>

#include "client.h"
#include "server.h"

extern "C" {

int bps_server_start(uint16_t port, int num_workers, int engine_threads,
                     int async_mode) {
  return bps::StartServer(port, num_workers, engine_threads,
                          async_mode != 0);
}

void bps_server_wait() { bps::WaitServer(); }

void bps_server_stop() { bps::StopServer(); }

void* bps_client_connect(const char* host, uint16_t port, int timeout_ms) {
  auto* c = new bps::Client();
  if (c->Connect(host, port, timeout_ms) != 0) {
    delete c;
    return nullptr;
  }
  return c;
}

int bps_client_init_key(void* client, uint64_t key, uint64_t nbytes) {
  return static_cast<bps::Client*>(client)->InitKey(key, nbytes);
}

int bps_client_push(void* client, uint64_t key, const void* data,
                    uint64_t nbytes) {
  return static_cast<bps::Client*>(client)->Push(key, data, nbytes);
}

int bps_client_pull(void* client, uint64_t key, void* data, uint64_t nbytes,
                    uint64_t version) {
  return static_cast<bps::Client*>(client)->Pull(key, data, nbytes, version);
}

int bps_client_barrier(void* client) {
  return static_cast<bps::Client*>(client)->Barrier();
}

int bps_client_shutdown(void* client) {
  return static_cast<bps::Client*>(client)->Shutdown();
}

void bps_client_free(void* client) {
  delete static_cast<bps::Client*>(client);
}

}  // extern "C"
