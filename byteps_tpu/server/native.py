"""ctypes binding for the native DCN summation service.

Builds ``libbyteps_tpu_server.so`` on first use if missing (``make`` +
``g++`` are part of the supported toolchain; no pybind11 in this image, so
the boundary is a C API + ctypes, reference analog: the ctypes-free
``byteps/server/__init__.py`` loading the prebuilt native lib).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from byteps_tpu.common.logging import get_logger

log = get_logger("server.native")

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_SO = os.path.join(_CSRC, "libbyteps_tpu_server.so")

_lib = None
_lib_lock = threading.Lock()

# Wire codec ids — must match csrc/codec.h Codec enum.
WIRE_RAW = 0
WIRE_FP16 = 1
WIRE_ONEBIT = 2
WIRE_TOPK = 3
WIRE_DITHER = 4
WIRE_FP8 = 5


class WireCorruption(RuntimeError):
    """A CRC32-checked payload arrived corrupted (push rejected server-side
    or pull response failing the worker-side verify). Always retryable:
    the data was detected bad, never summed or consumed."""


class WorkerEvictedError(RuntimeError):
    """The server's membership layer evicted this worker's lease (it went
    silent past BYTEPS_WORKER_LEASE_MS) and rejected the op. NOT a wire
    retry candidate — re-sending the same round cannot help while the
    server refuses the worker. The PSWorker rejoins (heartbeat re-admit +
    kRounds watermark adoption) and raises this stage-retryably: the
    stage re-run drops its pinned round and mints a fresh one under the
    adopted epoch."""

    retryable = True  # stage-level, after the in-line rejoin


def _build() -> None:
    log.info("building native server library (one-time)…")
    subprocess.run(
        ["make", "-C", _CSRC, "-j4"], check=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # wheel built on another platform shipped a foreign .so —
            # rebuild from the packaged sources for THIS machine
            log.warning("packaged native library unloadable; rebuilding")
            os.remove(_SO)
            _build()
            lib = ctypes.CDLL(_SO)
        try:
            # staleness probe: a prebuilt .so predating the newest API
            # generation (bps_codec_encode — the what-if simulator's
            # codec-calibration surface; implies bps_client_join, the
            # membership API, and bps_client_pull3 too) would otherwise
            # be dlopen'd with a mismatched bps_server_start signature
            lib.bps_codec_encode
        except AttributeError:
            log.warning(
                "native library predates the codec-calibration API; "
                "rebuilding")
            os.remove(_SO)
            _build()
            lib = ctypes.CDLL(_SO)
            try:
                lib.bps_codec_encode
            except AttributeError:
                # dlopen matched the ALREADY-MAPPED stale object by path
                # (nothing dlcloses the first handle), so the rebuild
                # cannot take effect in this process — fail loudly
                # instead of crashing on the argtypes below
                raise RuntimeError(
                    "stale libbyteps_tpu_server.so was already mapped "
                    "into this process and cannot be replaced by a "
                    "rebuild; restart the process (the rebuilt library "
                    "now on disk will load cleanly)") from None
        lib.bps_server_start.argtypes = [
            ctypes.c_uint16, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.bps_server_start.restype = ctypes.c_int
        lib.bps_server_wait.argtypes = []
        lib.bps_server_stop.argtypes = []
        lib.bps_server_trace_enable.argtypes = [ctypes.c_int]
        lib.bps_fp8_to_float.argtypes = [ctypes.c_uint8]
        lib.bps_fp8_to_float.restype = ctypes.c_float
        lib.bps_float_to_fp8.argtypes = [ctypes.c_float]
        lib.bps_float_to_fp8.restype = ctypes.c_uint8
        lib.bps_server_trace_dump.argtypes = [ctypes.c_char_p]
        lib.bps_server_trace_dump.restype = ctypes.c_int
        # what-if simulator codec calibration (sim/extract.py): the
        # server's REAL decode_sum / re-encode loops, priced offline
        lib.bps_codec_decode_sum.argtypes = [
            ctypes.c_uint8, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.bps_codec_decode_sum.restype = ctypes.c_int64
        lib.bps_codec_encode.argtypes = [
            ctypes.c_uint8, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.bps_codec_encode.restype = ctypes.c_int64
        lib.bps_server_epoch.argtypes = []
        lib.bps_server_epoch.restype = ctypes.c_uint64
        lib.bps_server_members.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
        ]
        lib.bps_server_members.restype = ctypes.c_int
        lib.bps_server_join.argtypes = [ctypes.c_int]
        lib.bps_server_join.restype = ctypes.c_int64
        lib.bps_local_init.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.bps_local_init.restype = ctypes.c_int
        lib.bps_local_push.argtypes = [
            ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_push.restype = ctypes.c_int
        lib.bps_local_push2.argtypes = [
            ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_push2.restype = ctypes.c_int
        lib.bps_local_pull.argtypes = [
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_pull.restype = ctypes.c_int64
        lib.bps_local_pull2.argtypes = [
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_local_pull2.restype = ctypes.c_int64
        lib.bps_local_pull3.argtypes = [
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_local_pull3.restype = ctypes.c_int64
        lib.bps_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int, ctypes.c_int,
        ]
        lib.bps_client_connect.restype = ctypes.c_void_p
        lib.bps_client_init_key.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.bps_client_init_key.restype = ctypes.c_int
        lib.bps_client_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint16,
        ]
        lib.bps_client_push.restype = ctypes.c_int
        lib.bps_client_push2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint16,
            ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.bps_client_push2.restype = ctypes.c_int
        lib.bps_client_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_client_pull.restype = ctypes.c_int
        lib.bps_client_pull2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.bps_client_pull2.restype = ctypes.c_int
        lib.bps_client_pull3.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_client_pull3.restype = ctypes.c_int
        lib.bps_client_barrier.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.bps_client_barrier.restype = ctypes.c_int
        lib.bps_client_shutdown.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.bps_client_shutdown.restype = ctypes.c_int
        lib.bps_client_ping.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ]
        lib.bps_client_ping.restype = ctypes.c_int
        lib.bps_client_epoch.argtypes = [ctypes.c_void_p]
        lib.bps_client_epoch.restype = ctypes.c_int
        lib.bps_client_members.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
        ]
        lib.bps_client_members.restype = ctypes.c_int
        lib.bps_client_rounds.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_client_rounds.restype = ctypes.c_int
        lib.bps_client_join.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_client_join.restype = ctypes.c_int
        lib.bps_client_last_error.argtypes = [ctypes.c_void_p]
        lib.bps_client_last_error.restype = ctypes.c_char_p
        lib.bps_client_is_dead.argtypes = [ctypes.c_void_p]
        lib.bps_client_is_dead.restype = ctypes.c_int
        lib.bps_client_free.argtypes = [ctypes.c_void_p]
        lib.bps_reduce_sum_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        _lib = lib
        return lib


def reduce_sum_f32(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src via the native kernel (golden-testable)."""
    lib = load_lib()
    assert dst.dtype == np.float32 and src.dtype == np.float32
    assert dst.flags.c_contiguous and src.flags.c_contiguous
    lib.bps_reduce_sum_f32(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dst.size,
    )


class NativeClient:
    """One serial TCP connection to one summation server.

    Reference analog: a ps-lite customer. Thread-safety: the native side
    serializes per connection; use one NativeClient per scheduler pool
    thread for parallelism.
    """

    def __init__(self, host: str, port: int, timeout_ms: int = 30000,
                 recv_timeout_ms: int = 120000):
        self._lib = load_lib()
        # serializes teardown (close/shutdown): an eviction on one thread
        # can race PSWorker.shutdown() on another, and bps_client_free
        # must run at most once (double delete = heap corruption)
        self._teardown_lock = threading.Lock()
        # held across every native wire op so close() cannot free the
        # handle UNDER an in-flight call (use-after-free; observed as a
        # teardown segfault when a scheduler shutdown raced a blocked
        # pull). Uncontended in normal operation — the class contract is
        # one client per pool thread — so the only time it waits is
        # close() draining a straggler, bounded by the recv timeout.
        self._op_lock = threading.Lock()
        self._last_pull_epoch = 0
        self._last_pull_round = 0
        self._h: Optional[int] = self._lib.bps_client_connect(
            host.encode(), port, timeout_ms, recv_timeout_ms
        )
        if not self._h:
            raise ConnectionError(f"cannot reach bps server {host}:{port}")

    def init_key(self, key: int, nbytes: int) -> None:
        with self._op_lock:
            self._require_open()
            self._check(self._lib.bps_client_init_key(self._h, key, nbytes),
                        "init")

    def push(self, key: int, data, codec: int = WIRE_RAW,
             worker_id: int = 0, version: int = 0, crc: int = 0) -> None:
        """Push codec-encoded bytes (np array of any contiguous dtype).
        ``version`` != 0 arms the server's (worker, key, version) replay
        dedupe; ``crc`` != 0 (the wire convention of
        :func:`~byteps_tpu.server.wire_crc32`) is verified server-side
        before the payload is summed."""
        buf = np.ascontiguousarray(data)
        with self._op_lock:
            self._require_open()
            self._check(
                self._lib.bps_client_push2(
                    self._h, key, buf.ctypes.data, buf.nbytes, codec,
                    worker_id, version, crc,
                ),
                "push",
            )

    def pull(self, key: int, out: np.ndarray, version: int,
             codec: int = WIRE_RAW, want_crc: bool = False,
             worker_id: int = -1) -> int:
        """Pull into `out` (capacity buffer); returns actual bytes (or
        ``(bytes, crc)`` when ``want_crc`` — the caller verifies, so the
        fault-injection layer can corrupt the buffer in between).
        ``worker_id`` >= 0 refreshes that worker's membership lease
        server-side (a worker blocked in a long pull is still alive).
        The epoch the pulled ROUND closed under is retained on this
        client (:meth:`last_pull_epoch`) — the averaging divisor
        authority under elastic membership — and so is the SERVED round
        (:meth:`last_pull_round`): under bounded staleness
        (``BYTEPS_STALENESS``) the server answers from the newest closed
        round >= requested − K, and requested − served is this pull's
        effective staleness."""
        assert out.flags.c_contiguous
        with self._op_lock:
            self._require_open()
            got = ctypes.c_uint64(0)
            crc = ctypes.c_uint32(0)
            ep = ctypes.c_uint32(0)
            served = ctypes.c_uint64(0)
            self._check(
                self._lib.bps_client_pull3(
                    self._h, key, out.ctypes.data, out.nbytes, version,
                    codec, 1 if want_crc else 0, ctypes.byref(got),
                    ctypes.byref(crc), worker_id, ctypes.byref(ep),
                    ctypes.byref(served),
                ),
                "pull",
            )
            self._last_pull_epoch = int(ep.value)
            self._last_pull_round = int(served.value)
            if want_crc:
                return int(got.value), int(crc.value)
            return int(got.value)

    def last_pull_epoch(self) -> int:
        """Membership epoch (low 16 bits) the most recently pulled round
        CLOSED under — see :meth:`pull`."""
        return self._last_pull_epoch

    def last_pull_round(self) -> int:
        """The round the most recent :meth:`pull` was actually SERVED
        from (response header version) — under bounded staleness it may
        trail the requested round by up to ``BYTEPS_STALENESS``."""
        return self._last_pull_round

    def barrier(self, worker_id: int = -1) -> None:
        """``worker_id`` >= 0 also refreshes that worker's membership
        lease server-side (barrier waits can outlast a short lease)."""
        with self._op_lock:
            self._require_open()
            self._check(self._lib.bps_client_barrier(self._h, worker_id),
                        "barrier")

    def ping(self, worker_id: int = -1) -> Tuple[int, int]:
        """(server CLOCK_REALTIME ns, round-trip ns) — clock alignment.
        ``worker_id`` >= 0 makes the probe that worker's membership lease
        HEARTBEAT (and the rejoin signal when it was evicted)."""
        with self._op_lock:
            self._require_open()
            sns = ctypes.c_int64(0)
            rtt = ctypes.c_int64(0)
            self._check(
                self._lib.bps_client_ping(
                    self._h, ctypes.byref(sns), ctypes.byref(rtt),
                    worker_id,
                ),
                "ping",
            )
            return int(sns.value), int(rtt.value)

    def epoch(self) -> int:
        """Membership epoch (low 16 bits) stamped on the last response
        this connection parsed — cheap per-op change detection; query
        :meth:`members` for the full live set on a change."""
        with self._op_lock:
            if not self._h:
                return 0
            return int(self._lib.bps_client_epoch(self._h))

    def members(self) -> Tuple[int, int, "np.ndarray"]:
        """(epoch, live_count, live bitmap[num_workers]) from the server's
        membership layer."""
        with self._op_lock:
            self._require_open()
            ep = ctypes.c_uint64(0)
            live = ctypes.c_uint32(0)
            nw = ctypes.c_uint32(0)
            bitmap = (ctypes.c_uint8 * 1024)()
            self._check(
                self._lib.bps_client_members(
                    self._h, ctypes.byref(ep), ctypes.byref(live),
                    ctypes.byref(nw), bitmap, 1024,
                ),
                "members",
            )
            n = min(int(nw.value), 1024)
            return (int(ep.value), int(live.value),
                    np.frombuffer(bytes(bitmap[:n]), np.uint8).copy())

    def join(self, worker_id: int) -> int:
        """Mid-stream worker ADMISSION (kJoin; scale-up elasticity):
        admit ``worker_id`` — a fresh id (the server GROWS its
        membership table and per-key vectors) or a previously
        evicted/departed one — at a round boundary. Returns the
        post-admission membership epoch. The caller must adopt round
        watermarks (:meth:`rounds`) before its first push."""
        with self._op_lock:
            self._require_open()
            ep = ctypes.c_uint64(0)
            self._check(
                self._lib.bps_client_join(self._h, worker_id,
                                          ctypes.byref(ep)),
                "join",
            )
            return int(ep.value)

    def rounds(self) -> "np.ndarray":
        """Per-key round watermarks as an (n, 3) uint64 array of
        (key, round, nbytes) — the rejoin adoption handshake."""
        with self._op_lock:
            self._require_open()
            cap = 1 << 20  # 43k keys per fetch; far above real key counts
            out = np.empty(cap, np.uint8)
            got = ctypes.c_uint64(0)
            self._check(
                self._lib.bps_client_rounds(
                    self._h, out.ctypes.data, out.nbytes, ctypes.byref(got),
                ),
                "rounds",
            )
            n = int(got.value) // 24
            return out[: n * 24].view(np.uint64).reshape(n, 3).copy()

    def is_dead(self) -> bool:
        """True once a timeout/desync closed the underlying socket (or the
        client itself was closed); the owner should discard this client
        and connect a fresh one. Holds the op lock like every other
        native call — close() frees the handle under it, and a retiring
        NIC closes clients owned by other pool threads."""
        with self._op_lock:
            if not self._h:
                return True
            return bool(self._lib.bps_client_is_dead(self._h))

    def shutdown(self, worker_id: int = -1) -> None:
        """``worker_id`` >= 0 marks the worker DEPARTED in the server's
        membership layer (a clean goodbye, distinct from an eviction)."""
        with self._op_lock:
            with self._teardown_lock:
                if self._h:
                    self._lib.bps_client_shutdown(self._h, worker_id)

    def close(self) -> None:
        # op lock first: wait out any in-flight wire op (freeing under
        # one is a use-after-free); a later op finds _h None and raises
        with self._op_lock:
            with self._teardown_lock:
                h, self._h = self._h, None
        if h:
            self._lib.bps_client_free(h)

    def _require_open(self) -> None:
        if not self._h:
            raise RuntimeError("NativeClient is closed")

    def _check(self, rc: int, op: str) -> None:
        if rc > 0:  # server-side kErr with a message
            msg = self._lib.bps_client_last_error(self._h) or b""
            if b"crc mismatch" in msg:
                raise WireCorruption(
                    f"bps {op} rejected: {msg.decode()} (detected, "
                    "not applied; retryable)")
            if b"worker evicted" in msg:
                raise WorkerEvictedError(
                    f"bps {op} rejected: {msg.decode()}")
            raise RuntimeError(f"bps {op} rejected: {msg.decode()}")
        if rc == -11:
            raise WorkerEvictedError(
                f"bps {op} rejected: worker evicted (local/IPC path); "
                "rejoin required")
        if rc == -8:
            raise RuntimeError(
                f"bps {op} rejected: worker id out of range for the "
                "wire encoding (must be within [0, 65534])")
        if rc == -7:
            raise TimeoutError(
                f"bps {op} receive timeout (server dead or stalled); "
                "connection closed"
            )
        if rc == -6:
            raise RuntimeError(
                f"bps {op} response key mismatch (stale frame on a "
                "desynchronized stream); connection closed"
            )
        if rc != 0:
            raise RuntimeError(f"bps {op} failed (rc={rc})")

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
