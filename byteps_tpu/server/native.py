"""ctypes binding for the native DCN summation service.

Builds ``libbyteps_tpu_server.so`` on first use if missing (``make`` +
``g++`` are part of the supported toolchain; no pybind11 in this image, so
the boundary is a C API + ctypes, reference analog: the ctypes-free
``byteps/server/__init__.py`` loading the prebuilt native lib).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from byteps_tpu.common.logging import get_logger

log = get_logger("server.native")

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_SO = os.path.join(_CSRC, "libbyteps_tpu_server.so")

_lib = None
_lib_lock = threading.Lock()

# Wire codec ids — must match csrc/codec.h Codec enum.
WIRE_RAW = 0
WIRE_FP16 = 1
WIRE_ONEBIT = 2
WIRE_TOPK = 3
WIRE_DITHER = 4
WIRE_FP8 = 5


class WireCorruption(RuntimeError):
    """A CRC32-checked payload arrived corrupted (push rejected server-side
    or pull response failing the worker-side verify). Always retryable:
    the data was detected bad, never summed or consumed."""


def _build() -> None:
    log.info("building native server library (one-time)…")
    subprocess.run(
        ["make", "-C", _CSRC, "-j4"], check=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            _build()
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            # wheel built on another platform shipped a foreign .so —
            # rebuild from the packaged sources for THIS machine
            log.warning("packaged native library unloadable; rebuilding")
            os.remove(_SO)
            _build()
            lib = ctypes.CDLL(_SO)
        lib.bps_server_start.argtypes = [
            ctypes.c_uint16, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.bps_server_start.restype = ctypes.c_int
        lib.bps_server_wait.argtypes = []
        lib.bps_server_stop.argtypes = []
        lib.bps_server_trace_enable.argtypes = [ctypes.c_int]
        lib.bps_fp8_to_float.argtypes = [ctypes.c_uint8]
        lib.bps_fp8_to_float.restype = ctypes.c_float
        lib.bps_float_to_fp8.argtypes = [ctypes.c_float]
        lib.bps_float_to_fp8.restype = ctypes.c_uint8
        lib.bps_server_trace_dump.argtypes = [ctypes.c_char_p]
        lib.bps_server_trace_dump.restype = ctypes.c_int
        lib.bps_local_init.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.bps_local_init.restype = ctypes.c_int
        lib.bps_local_push.argtypes = [
            ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_push.restype = ctypes.c_int
        lib.bps_local_push2.argtypes = [
            ctypes.c_uint16, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_push2.restype = ctypes.c_int
        lib.bps_local_pull.argtypes = [
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.bps_local_pull.restype = ctypes.c_int64
        lib.bps_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int, ctypes.c_int,
        ]
        lib.bps_client_connect.restype = ctypes.c_void_p
        lib.bps_client_init_key.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.bps_client_init_key.restype = ctypes.c_int
        lib.bps_client_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint16,
        ]
        lib.bps_client_push.restype = ctypes.c_int
        lib.bps_client_push2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint16,
            ctypes.c_uint64, ctypes.c_uint32,
        ]
        lib.bps_client_push2.restype = ctypes.c_int
        lib.bps_client_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bps_client_pull.restype = ctypes.c_int
        lib.bps_client_pull2.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint8,
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32),
        ]
        lib.bps_client_pull2.restype = ctypes.c_int
        lib.bps_client_barrier.argtypes = [ctypes.c_void_p]
        lib.bps_client_barrier.restype = ctypes.c_int
        lib.bps_client_shutdown.argtypes = [ctypes.c_void_p]
        lib.bps_client_shutdown.restype = ctypes.c_int
        lib.bps_client_ping.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.bps_client_ping.restype = ctypes.c_int
        lib.bps_client_last_error.argtypes = [ctypes.c_void_p]
        lib.bps_client_last_error.restype = ctypes.c_char_p
        lib.bps_client_is_dead.argtypes = [ctypes.c_void_p]
        lib.bps_client_is_dead.restype = ctypes.c_int
        lib.bps_client_free.argtypes = [ctypes.c_void_p]
        lib.bps_reduce_sum_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        _lib = lib
        return lib


def reduce_sum_f32(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src via the native kernel (golden-testable)."""
    lib = load_lib()
    assert dst.dtype == np.float32 and src.dtype == np.float32
    assert dst.flags.c_contiguous and src.flags.c_contiguous
    lib.bps_reduce_sum_f32(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dst.size,
    )


class NativeClient:
    """One serial TCP connection to one summation server.

    Reference analog: a ps-lite customer. Thread-safety: the native side
    serializes per connection; use one NativeClient per scheduler pool
    thread for parallelism.
    """

    def __init__(self, host: str, port: int, timeout_ms: int = 30000,
                 recv_timeout_ms: int = 120000):
        self._lib = load_lib()
        # serializes teardown (close/shutdown): an eviction on one thread
        # can race PSWorker.shutdown() on another, and bps_client_free
        # must run at most once (double delete = heap corruption)
        self._teardown_lock = threading.Lock()
        # held across every native wire op so close() cannot free the
        # handle UNDER an in-flight call (use-after-free; observed as a
        # teardown segfault when a scheduler shutdown raced a blocked
        # pull). Uncontended in normal operation — the class contract is
        # one client per pool thread — so the only time it waits is
        # close() draining a straggler, bounded by the recv timeout.
        self._op_lock = threading.Lock()
        self._h: Optional[int] = self._lib.bps_client_connect(
            host.encode(), port, timeout_ms, recv_timeout_ms
        )
        if not self._h:
            raise ConnectionError(f"cannot reach bps server {host}:{port}")

    def init_key(self, key: int, nbytes: int) -> None:
        with self._op_lock:
            self._require_open()
            self._check(self._lib.bps_client_init_key(self._h, key, nbytes),
                        "init")

    def push(self, key: int, data, codec: int = WIRE_RAW,
             worker_id: int = 0, version: int = 0, crc: int = 0) -> None:
        """Push codec-encoded bytes (np array of any contiguous dtype).
        ``version`` != 0 arms the server's (worker, key, version) replay
        dedupe; ``crc`` != 0 (the wire convention of
        :func:`~byteps_tpu.server.wire_crc32`) is verified server-side
        before the payload is summed."""
        buf = np.ascontiguousarray(data)
        with self._op_lock:
            self._require_open()
            self._check(
                self._lib.bps_client_push2(
                    self._h, key, buf.ctypes.data, buf.nbytes, codec,
                    worker_id, version, crc,
                ),
                "push",
            )

    def pull(self, key: int, out: np.ndarray, version: int,
             codec: int = WIRE_RAW, want_crc: bool = False) -> int:
        """Pull into `out` (capacity buffer); returns actual bytes (or
        ``(bytes, crc)`` when ``want_crc`` — the caller verifies, so the
        fault-injection layer can corrupt the buffer in between)."""
        assert out.flags.c_contiguous
        with self._op_lock:
            self._require_open()
            got = ctypes.c_uint64(0)
            if want_crc:
                crc = ctypes.c_uint32(0)
                self._check(
                    self._lib.bps_client_pull2(
                        self._h, key, out.ctypes.data, out.nbytes, version,
                        codec, 1, ctypes.byref(got), ctypes.byref(crc),
                    ),
                    "pull",
                )
                return int(got.value), int(crc.value)
            self._check(
                self._lib.bps_client_pull(
                    self._h, key, out.ctypes.data, out.nbytes, version,
                    codec, ctypes.byref(got),
                ),
                "pull",
            )
            return int(got.value)

    def barrier(self) -> None:
        with self._op_lock:
            self._require_open()
            self._check(self._lib.bps_client_barrier(self._h), "barrier")

    def ping(self) -> Tuple[int, int]:
        """(server CLOCK_REALTIME ns, round-trip ns) — clock alignment."""
        with self._op_lock:
            self._require_open()
            sns = ctypes.c_int64(0)
            rtt = ctypes.c_int64(0)
            self._check(
                self._lib.bps_client_ping(
                    self._h, ctypes.byref(sns), ctypes.byref(rtt)
                ),
                "ping",
            )
            return int(sns.value), int(rtt.value)

    def is_dead(self) -> bool:
        """True once a timeout/desync closed the underlying socket (or the
        client itself was closed); the owner should discard this client
        and connect a fresh one. Holds the op lock like every other
        native call — close() frees the handle under it, and a retiring
        NIC closes clients owned by other pool threads."""
        with self._op_lock:
            if not self._h:
                return True
            return bool(self._lib.bps_client_is_dead(self._h))

    def shutdown(self) -> None:
        with self._op_lock:
            with self._teardown_lock:
                if self._h:
                    self._lib.bps_client_shutdown(self._h)

    def close(self) -> None:
        # op lock first: wait out any in-flight wire op (freeing under
        # one is a use-after-free); a later op finds _h None and raises
        with self._op_lock:
            with self._teardown_lock:
                h, self._h = self._h, None
        if h:
            self._lib.bps_client_free(h)

    def _require_open(self) -> None:
        if not self._h:
            raise RuntimeError("NativeClient is closed")

    def _check(self, rc: int, op: str) -> None:
        if rc > 0:  # server-side kErr with a message
            msg = self._lib.bps_client_last_error(self._h) or b""
            if b"crc mismatch" in msg:
                raise WireCorruption(
                    f"bps {op} rejected: {msg.decode()} (detected, "
                    "not applied; retryable)")
            raise RuntimeError(f"bps {op} rejected: {msg.decode()}")
        if rc == -7:
            raise TimeoutError(
                f"bps {op} receive timeout (server dead or stalled); "
                "connection closed"
            )
        if rc == -6:
            raise RuntimeError(
                f"bps {op} response key mismatch (stale frame on a "
                "desynchronized stream); connection closed"
            )
        if rc != 0:
            raise RuntimeError(f"bps {op} failed (rc={rc})")

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
