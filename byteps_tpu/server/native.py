"""ctypes binding for the native DCN summation service.

Builds ``libbyteps_tpu_server.so`` on first use if missing (``make`` +
``g++`` are part of the supported toolchain; no pybind11 in this image, so
the boundary is a C API + ctypes, reference analog: the ctypes-free
``byteps/server/__init__.py`` loading the prebuilt native lib).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from byteps_tpu.common.logging import get_logger

log = get_logger("server.native")

_CSRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "csrc")
_SO = os.path.join(_CSRC, "libbyteps_tpu_server.so")

_lib = None
_lib_lock = threading.Lock()


def _build() -> None:
    log.info("building native server library (one-time)…")
    subprocess.run(
        ["make", "-C", _CSRC, "-j4"], check=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def load_lib() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.bps_server_start.argtypes = [
            ctypes.c_uint16, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.bps_server_start.restype = ctypes.c_int
        lib.bps_server_wait.argtypes = []
        lib.bps_server_stop.argtypes = []
        lib.bps_client_connect.argtypes = [
            ctypes.c_char_p, ctypes.c_uint16, ctypes.c_int,
        ]
        lib.bps_client_connect.restype = ctypes.c_void_p
        lib.bps_client_init_key.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.bps_client_init_key.restype = ctypes.c_int
        lib.bps_client_push.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64,
        ]
        lib.bps_client_push.restype = ctypes.c_int
        lib.bps_client_pull.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
            ctypes.c_uint64, ctypes.c_uint64,
        ]
        lib.bps_client_pull.restype = ctypes.c_int
        lib.bps_client_barrier.argtypes = [ctypes.c_void_p]
        lib.bps_client_barrier.restype = ctypes.c_int
        lib.bps_client_shutdown.argtypes = [ctypes.c_void_p]
        lib.bps_client_shutdown.restype = ctypes.c_int
        lib.bps_client_free.argtypes = [ctypes.c_void_p]
        lib.bps_reduce_sum_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64,
        ]
        _lib = lib
        return lib


def reduce_sum_f32(dst: np.ndarray, src: np.ndarray) -> None:
    """dst += src via the native kernel (golden-testable)."""
    lib = load_lib()
    assert dst.dtype == np.float32 and src.dtype == np.float32
    assert dst.flags.c_contiguous and src.flags.c_contiguous
    lib.bps_reduce_sum_f32(
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        dst.size,
    )


class NativeClient:
    """One serial TCP connection to one summation server.

    Reference analog: a ps-lite customer. Thread-safety: the native side
    serializes per connection; use one NativeClient per scheduler pool
    thread for parallelism.
    """

    def __init__(self, host: str, port: int, timeout_ms: int = 30000):
        self._lib = load_lib()
        self._h: Optional[int] = self._lib.bps_client_connect(
            host.encode(), port, timeout_ms
        )
        if not self._h:
            raise ConnectionError(f"cannot reach bps server {host}:{port}")

    def init_key(self, key: int, nbytes: int) -> None:
        self._check(self._lib.bps_client_init_key(self._h, key, nbytes),
                    "init")

    def push(self, key: int, data: np.ndarray) -> None:
        assert data.dtype == np.float32 and data.flags.c_contiguous
        self._check(
            self._lib.bps_client_push(
                self._h, key, data.ctypes.data, data.nbytes
            ),
            "push",
        )

    def pull(self, key: int, out: np.ndarray, version: int) -> None:
        assert out.dtype == np.float32 and out.flags.c_contiguous
        self._check(
            self._lib.bps_client_pull(
                self._h, key, out.ctypes.data, out.nbytes, version
            ),
            "pull",
        )

    def barrier(self) -> None:
        self._check(self._lib.bps_client_barrier(self._h), "barrier")

    def shutdown(self) -> None:
        if self._h:
            self._lib.bps_client_shutdown(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.bps_client_free(self._h)
            self._h = None

    def _check(self, rc: int, op: str) -> None:
        if rc != 0:
            raise RuntimeError(f"bps {op} failed (rc={rc})")

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
