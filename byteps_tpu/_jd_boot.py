"""Worker bootstrap: bring up ``jax.distributed`` BEFORE user code runs.

Reference analog: ps-lite rendezvous happens inside ``byteps_init()``
before any CUDA work; with a global-mesh job (``BYTEPS_JAX_DISTRIBUTED=1``)
the JAX coordination service must likewise be joined before the user script
touches any JAX backend, so ``bpslaunch`` interposes this module around the
user command::

    python -m byteps_tpu._jd_boot train.py args...

User scripts need no changes: ``sys.argv`` is rewritten so the script sees
exactly the argv it was launched with.
"""

from __future__ import annotations

import runpy
import sys


def main() -> int:
    from byteps_tpu.comm.distributed import maybe_init_distributed

    maybe_init_distributed()
    if len(sys.argv) < 2:
        print("usage: python -m byteps_tpu._jd_boot script.py [args...]",
              file=sys.stderr)
        return 2
    sys.argv = sys.argv[1:]
    runpy.run_path(sys.argv[0], run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
