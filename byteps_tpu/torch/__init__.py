"""byteps_tpu.torch — the PyTorch framework adapter (CPU workers over the
DCN summation service).

Reference analog: ``byteps/torch/__init__.py`` + ``byteps/torch/ops.cc`` —
the same public surface (``init``, ``rank``/``size``, ``push_pull``,
``DistributedOptimizer`` with per-parameter gradient hooks,
``broadcast_parameters``, ``broadcast_optimizer_state``), with the native
NCCL/ps-lite pipeline replaced by this framework's credit-scheduled
partition pipeline over the native TCP summation servers
(byteps_tpu/server). The TPU compute path lives in ``byteps_tpu.jax``; this
adapter exists for capability parity with the reference's torch users
(BASELINE config 1: torch MNIST, 2 local CPU workers, unchanged script).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np
import torch

from byteps_tpu.common.config import get_config
from byteps_tpu.common.dcn_adapter import DcnCore, wire_codec_for
from byteps_tpu.common.logging import bps_check, get_logger
from byteps_tpu.common.scheduler import Handle

log = get_logger("torch")


class Compression:
    """Compression choices for the DCN wire (reference:
    byteps/torch/compression.py). ``fp16`` rides the real binary16 wire
    codec — every push and pull moves half the bytes; the server decodes,
    fp32-sums, and re-encodes (partitions under BYTEPS_MIN_COMPRESS_BYTES
    stay raw fp32)."""

    none = "none"
    fp16 = "fp16"


class _TorchState:
    def __init__(self) -> None:
        self.initialized = False
        self.cfg = None
        self.core: Optional[DcnCore] = None


_state = _TorchState()


def init() -> None:
    """Connect to the summation servers and rendezvous (reference:
    ``byteps_init`` — env-driven: DMLC_PS_ROOT_URI/PORT, DMLC_NUM_WORKER,
    DMLC_NUM_SERVER, DMLC_WORKER_ID)."""
    if _state.initialized:
        return
    cfg = get_config()
    _state.cfg = cfg
    _state.core = DcnCore()
    _state.initialized = True
    log.info("byteps_tpu.torch initialized: worker %d/%d",
             cfg.worker_id, cfg.num_worker)


def shutdown() -> None:
    if not _state.initialized:
        return
    _state.core.shutdown()
    _state.initialized = False


def _require_init() -> None:
    bps_check(_state.initialized, "call byteps_tpu.torch.init() first")


def rank() -> int:
    _require_init()
    return _state.cfg.worker_id


def size() -> int:
    _require_init()
    return _state.cfg.num_worker


def local_rank() -> int:
    _require_init()
    return _state.cfg.local_rank


def local_size() -> int:
    _require_init()
    return _state.cfg.local_size


# --- push_pull --------------------------------------------------------------
def push_pull_async(
    tensor: torch.Tensor,
    average: bool = True,
    name: Optional[str] = None,
    priority: Optional[int] = None,
    compression: str = Compression.none,
) -> Handle:
    """In-place async sum (mean) of ``tensor`` across workers.

    Reference: ``byteps_torch_push_pull_async`` (byteps/torch/ops.cc).
    ``synchronize(handle)`` writes the result back into ``tensor``.
    """
    _require_init()
    bps_check(name is not None,
              "byteps_tpu.torch.push_pull requires a tensor name (keys must "
              "agree across workers)")
    t = tensor.detach()
    flat = t.to(torch.float32).contiguous().view(-1).numpy()
    handle = _state.core.push_pull_async(
        flat, name, priority, codec=wire_codec_for(compression)
    )
    handle.tensor = tensor          # type: ignore[attr-defined]
    handle.average = average        # type: ignore[attr-defined]
    return handle


def synchronize(handle: Handle, timeout: Optional[float] = 120.0) -> torch.Tensor:
    """Wait and write the aggregated value back into the original tensor
    (reference: ``synchronize``/``wait_and_clear``)."""
    flat = DcnCore.assemble(handle, timeout)
    if handle.average:  # type: ignore[attr-defined]
        # Degraded partitions (no live summation servers mid-handle,
        # docs/robustness.md) resolved to the LOCAL contribution, whose
        # average over the available contributions is itself; only the
        # globally-aggregated slices divide by the LIVE worker count
        # (== size() at full membership; after a lease eviction the sums
        # cover — and the server's quorum scaling normalizes to — the
        # survivors). A handle can be MIXED when the last server died or
        # the membership changed between partitions: each slice divides
        # by the membership ITS round closed under (handle.part_live,
        # from the pull response's epoch stamp).
        d = _state.core.live_size() if _state.core is not None else size()
        flat = flat / d
        for off, ln, live in getattr(handle, "part_live", {}).values():
            if live != d:
                flat[off:off + ln] *= d / np.float32(live)
        for off, ln in getattr(handle, "degraded_parts", {}).values():
            flat[off:off + ln] *= d
    tensor: torch.Tensor = handle.tensor  # type: ignore[attr-defined]
    out = torch.from_numpy(flat).view(tensor.shape).to(tensor.dtype)
    with torch.no_grad():
        tensor.copy_(out)
    return tensor


def push_pull(
    tensor: torch.Tensor,
    average: bool = True,
    name: Optional[str] = None,
    priority: Optional[int] = None,
    compression: str = Compression.none,
) -> torch.Tensor:
    return synchronize(
        push_pull_async(tensor, average, name, priority, compression)
    )


# --- broadcast --------------------------------------------------------------
def broadcast_parameters(
    params: Iterable[Tuple[str, torch.Tensor]] | Dict[str, torch.Tensor],
    root_rank: int = 0,
) -> None:
    """Replicate root's values to all workers, in place. Implemented as
    zero-on-non-root + summed push_pull — the reference's own trick
    (byteps/torch/__init__.py broadcast_parameters)."""
    _require_init()
    items = params.items() if isinstance(params, dict) else params
    handles = []
    for pname, p in items:
        if p is None:
            continue
        if rank() != root_rank:
            with torch.no_grad():
                p.zero_()
        handles.append(push_pull_async(
            p, average=False, name=f"byteps_broadcast.{pname}"
        ))
    for h in handles:
        synchronize(h)


def broadcast_optimizer_state(optimizer: torch.optim.Optimizer,
                              root_rank: int = 0) -> None:
    """Broadcast optimizer state tensors + hyperparameters from root
    (reference: broadcast_optimizer_state)."""
    _require_init()
    tensors = {}
    for gi, group in enumerate(optimizer.param_groups):
        for k, v in group.items():
            if isinstance(v, (int, float)) and k != "params":
                t = torch.tensor(float(v), dtype=torch.float64)
                tensors[f"opt_group{gi}.{k}"] = (group, k, t)
    for pid, st in optimizer.state.items():
        for k, v in st.items():
            if torch.is_tensor(v):
                tensors[f"opt_state.{pid}.{k}"] = (st, k, v)
            elif isinstance(v, (int, float)):
                t = torch.tensor(float(v), dtype=torch.float64)
                tensors[f"opt_state.{pid}.{k}"] = (st, k, t)
    broadcast_parameters(
        {n: t for n, (_, _, t) in tensors.items()}, root_rank
    )
    for n, (container, k, t) in tensors.items():
        if torch.is_tensor(container.get(k)):
            continue  # broadcast wrote in place
        orig = container[k]
        container[k] = type(orig)(t.item()) if isinstance(orig, (int, float)) else t.item()


# --- DistributedOptimizer ---------------------------------------------------
class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer: per-parameter post-accumulate-grad hooks fire
    push_pull as soon as each grad is ready (comm/compute overlap), and
    ``step()`` synchronizes before applying the inner optimizer.

    Reference: byteps/torch DistributedOptimizer (grad-accumulator hooks →
    _push_pull_param_async; synchronize() in step)."""

    def __init__(self, optimizer: torch.optim.Optimizer,
                 named_parameters: Iterable[Tuple[str, torch.Tensor]],
                 compression: str = Compression.none,
                 backward_passes_per_step: int = 1):
        self._opt = optimizer
        self._compression = compression
        self._bpps = max(1, backward_passes_per_step)
        self._pass_count = 0
        self._handles: Dict[torch.Tensor, Handle] = {}
        self._names: Dict[torch.Tensor, str] = {}
        self._hooks = []
        named = list(named_parameters)
        bps_check(len({n for n, _ in named}) == len(named),
                  "parameter names must be unique")
        # declaration order = named_parameters order → priorities fixed
        # identically on every worker before any backward runs
        for pname, p in named:
            if p.requires_grad:
                name = f"byteps_push_pull.{pname}"
                self._names[p] = name
                _state.core.registry.declare(name, (p.numel(),), np.float32)
        for pname, p in named:
            if p.requires_grad:
                self._hooks.append(p.register_post_accumulate_grad_hook(
                    self._make_hook()
                ))

    # pass-throughs
    @property
    def param_groups(self):
        return self._opt.param_groups

    @param_groups.setter
    def param_groups(self, v):
        self._opt.param_groups = v

    @property
    def state(self):
        return self._opt.state

    def state_dict(self):
        return self._opt.state_dict()

    def load_state_dict(self, sd):
        return self._opt.load_state_dict(sd)

    def zero_grad(self, set_to_none: bool = True):
        return self._opt.zero_grad(set_to_none=set_to_none)

    def _make_hook(self):
        def hook(p: torch.Tensor) -> None:
            if (self._pass_count + 1) % self._bpps != 0:
                return  # accumulate locally this pass
            self._handles[p] = push_pull_async(
                p.grad, average=True, name=self._names[p],
                compression=self._compression,
            )
        return hook

    def synchronize(self) -> None:
        for p, h in self._handles.items():
            synchronize(h)
        self._handles.clear()

    def step(self, closure=None):
        self._pass_count += 1
        if self._pass_count % self._bpps != 0:
            return None  # mid-accumulation: no sync, no step
        self.synchronize()
        out = self._opt.step(closure)
        return out


def DistributedOptimizer(
    optimizer: torch.optim.Optimizer,
    named_parameters: Iterable[Tuple[str, torch.Tensor]],
    compression: str = Compression.none,
    backward_passes_per_step: int = 1,
) -> _DistributedOptimizer:
    return _DistributedOptimizer(optimizer, named_parameters, compression,
                                 backward_passes_per_step)
