"""Fused in-jit gradient aggregation + ``DistributedOptimizer``.

Reference analog: ``byteps/torch/__init__.py`` ``DistributedOptimizer``
(wraps the user's optimizer, intercepts gradients, push_pulls them, then
steps). The TPU-idiomatic form is an ``optax.GradientTransformation``
wrapper whose ``update`` runs **inside the user's shard_map/pmap'd train
step**: gradients are flattened, concatenated, partitioned into
``BYTEPS_PARTITION_BYTES`` chunks (declaration = pytree order, so chunk
issue order preserves the reference's priority semantics), and each chunk is
aggregated with a psum or the compressed collective. Error-feedback and
Nesterov-momentum state live in the optimizer state pytree (per-device,
sharded over dp — each device is a "worker" with its own residual), which is
the pure-functional replacement for the reference's C++ side buffers.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from byteps_tpu.common.config import get_config
from byteps_tpu.comm.ici import compressed_allreduce_local
from byteps_tpu.compression import from_params
from byteps_tpu.compression.error_feedback import CompressionSpec


def _flatten_concat(tree):
    leaves = jax.tree.leaves(tree)
    flats = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
    sizes = [f.shape[0] for f in flats]
    return jnp.concatenate(flats) if len(flats) > 1 else flats[0], sizes


def _unconcat_unflatten(flat, tree, sizes):
    leaves, treedef = jax.tree.flatten(tree)
    outs = []
    off = 0
    for leaf, s in zip(leaves, sizes):
        outs.append(flat[off:off + s].reshape(leaf.shape).astype(leaf.dtype))
        off += s
    return jax.tree.unflatten(treedef, outs)


def _chunk_bounds(total: int, chunk_elems: int):
    bounds = []
    off = 0
    while off < total:
        ln = min(chunk_elems, total - off)
        bounds.append((off, ln))
        off += ln
    return bounds or [(0, total)]


def push_pull_inside(
    grads,
    axis: Optional[str] = None,
    n: Optional[int] = None,
    average: bool = True,
    spec: Optional[CompressionSpec] = None,
    rng: Optional[jnp.ndarray] = None,
    ef_residual: Optional[jnp.ndarray] = None,
    partition_bytes: Optional[int] = None,
    two_way: bool = True,
):
    """Aggregate a gradient pytree across the dp axis, **inside** shard_map.

    Returns ``agg_grads`` (same structure as ``grads``), or
    ``(agg_grads, new_ef_residual)`` when ``ef_residual`` is given (a flat
    fp32 vector of the total parameter count).

    This is the fused analog of per-tensor ``push_pull`` calls: one trace,
    chunked collectives in declaration order, XLA overlaps them.
    """
    cfg = get_config()
    axis = axis or cfg.dp_axis
    if n is None:
        n = jax.lax.axis_size(axis)
    if spec is None:
        spec = from_params(None)
    partition_bytes = partition_bytes or cfg.partition_bytes
    chunk_elems = max(1, partition_bytes // 4)  # aggregation runs in fp32

    flat, sizes = _flatten_concat(grads)
    total = flat.shape[0]
    bounds = _chunk_bounds(total, chunk_elems)

    out_chunks = []
    new_e_chunks = [] if ef_residual is not None else None
    for ci, (off, ln) in enumerate(bounds):
        g = jax.lax.dynamic_slice_in_dim(flat, off, ln)
        if spec.enabled:
            if rng is None:
                if spec.compressor.stochastic:
                    raise ValueError(
                        f"{spec.compressor.name} requires an rng that advances "
                        "every step; pass rng= (DistributedOptimizer does this "
                        "automatically from its step count)"
                    )
                rng = jax.random.PRNGKey(0)
            crng = jax.random.fold_in(rng, ci)
            e = (
                jax.lax.dynamic_slice_in_dim(ef_residual, off, ln)
                if ef_residual is not None
                else None
            )
            res = compressed_allreduce_local(
                g, crng, spec.compressor, axis, n,
                average=average, two_way=two_way, ef_residual=e,
            )
            if e is not None:
                out, ne = res
                new_e_chunks.append(ne)
            else:
                out = res
        else:
            s = jax.lax.psum(g, axis)
            out = s / n if average else s
            if new_e_chunks is not None:
                new_e_chunks.append(jnp.zeros_like(g))
        out_chunks.append(out)

    agg_flat = jnp.concatenate(out_chunks) if len(out_chunks) > 1 else out_chunks[0]
    agg = _unconcat_unflatten(agg_flat, grads, sizes)
    if ef_residual is not None:
        new_e = (
            jnp.concatenate(new_e_chunks) if len(new_e_chunks) > 1 else new_e_chunks[0]
        )
        return agg, new_e
    return agg


class DistributedOptState(NamedTuple):
    inner: Any
    count: jnp.ndarray                      # step counter (rng derivation)
    ef: Optional[jnp.ndarray]               # flat EF residual or None
    momentum: Optional[jnp.ndarray]         # flat momentum buffer or None


def DistributedOptimizer(
    tx: optax.GradientTransformation,
    compression_params: Optional[Dict[str, Any]] = None,
    axis: Optional[str] = None,
    num_devices: Optional[int] = None,
    average: bool = True,
    partition_bytes: Optional[int] = None,
    seed: int = 0,
) -> optax.GradientTransformation:
    """Wrap an optax transformation with BytePS gradient aggregation.

    ``update`` MUST be called inside a shard_map/pmap context that defines
    the dp ``axis``. Gradients entering ``update`` are per-device; the
    wrapper aggregates them (compressed if configured), updates EF/momentum
    state, then applies the inner transformation to the aggregated grads.

    Reference: ``DistributedOptimizer(optimizer, named_parameters,
    compression, ...)`` in byteps/torch — same contract, functional form.
    """
    cfg = get_config()
    axis_name = axis or cfg.dp_axis
    spec = from_params(compression_params)

    def init_fn(params):
        flat, _ = _flatten_concat(params)
        total = flat.shape[0]
        # EF / momentum are PER-DEVICE worker state (each device is one
        # reference worker): globally (n * total,), sharded over the dp axis
        # so each device's shard_map block is its own (total,) buffer. Shard
        # with `dp_state_specs()`; see that helper's docstring.
        n = num_devices if num_devices is not None else len(jax.devices())
        ef = (
            jnp.zeros((n * total,), jnp.float32)
            if (spec.enabled and spec.ef)
            else None
        )
        mom = (
            jnp.zeros((n * total,), jnp.float32)
            if (spec.enabled and spec.momentum)
            else None
        )
        return DistributedOptState(
            inner=tx.init(params), count=jnp.zeros((), jnp.int32), ef=ef, momentum=mom
        )

    def update_fn(grads, state: DistributedOptState, params=None):
        n = num_devices if num_devices is not None else jax.lax.axis_size(axis_name)
        # spec.seed (reference compression_params 'seed') co-determines the
        # stream so configs differing in seed actually differ
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(seed), spec.seed), state.count
        )

        flat, sizes = _flatten_concat(grads)
        mom = state.momentum
        if spec.enabled and mom is not None:
            # Nesterov momentum before compression (reference:
            # nesterov_momentum.cc decorator)
            mom = spec.mu * mom + flat
            flat = flat + spec.mu * mom
            grads_in = _unconcat_unflatten(flat, grads, sizes)
        else:
            grads_in = grads

        if spec.enabled and state.ef is not None:
            agg, new_ef = push_pull_inside(
                grads_in, axis_name, n, average, spec, rng,
                ef_residual=state.ef, partition_bytes=partition_bytes,
                two_way=spec.two_way,
            )
        else:
            agg = push_pull_inside(
                grads_in, axis_name, n, average, spec, rng,
                partition_bytes=partition_bytes, two_way=spec.two_way,
            )
            new_ef = state.ef

        updates, new_inner = tx.update(agg, state.inner, params)
        return updates, DistributedOptState(
            inner=new_inner, count=state.count + 1, ef=new_ef, momentum=mom
        )

    return optax.GradientTransformation(init_fn, update_fn)


def dp_state_specs(axis: Optional[str] = None) -> DistributedOptState:
    """PartitionSpec prefix-tree for a ``DistributedOptState``.

    Use as the shard_map in/out spec for the optimizer state: the inner
    optax state and step count are replicated (every device applies the same
    aggregated update), while the EF/momentum buffers are sharded over the
    dp axis (per-device worker state)::

        spec = bps.dp_state_specs()
        step = jax.shard_map(per_device_step, mesh=mesh,
                             in_specs=(P(), spec, P("dp"), P("dp")),
                             out_specs=(P(), spec), check_vma=False)
    """
    from jax.sharding import PartitionSpec as P

    axis = axis or get_config().dp_axis
    return DistributedOptState(inner=P(), count=P(), ef=P(axis), momentum=P(axis))
